"""Batched-fit entry points: many fits as ONE vmap-across-jobs dispatch.

Two workloads share this machinery:

- **Hyperparameter sweeps** (``POST /models/sweep``): a λ grid over
  :mod:`~learningorchestra_tpu.ml.logistic` or a depth grid over
  :mod:`~learningorchestra_tpu.ml.trees`, fitted as one ``vmap`` over
  the grid axis with per-point metrics and the argmax checkpoint
  published through the same atomic ``os.replace`` path the builder
  uses — so the serving registry (serve/registry.py) picks the winner
  up like any other build. A scenario the reference never had.
- **Job coalescing** (sched/coalesce.py): a flood of small
  single-classifier builds from many users fuses into one dispatch —
  every member's (X, y, λ) tuple becomes one more slice on the same
  job axis a sweep uses for its grid points.

The fused program's job axis pads to the shared quarter-octave shape
grid (utils/shapegrid.py) with a fixed floor, then aligns to the mesh's
data-axis size so the axis always partitions evenly across devices (the
pjit idiom: jobs are embarrassingly parallel, so sharding the job axis
inserts ZERO collectives — matched in/out specs, no cross-slice
reduction anywhere). Dummy slots replicate slot 0 rather than holding
zeros (an all-zero member would drive 0/0 NaNs through its lanes).

Reproducibility contract (the coalescer's acceptance bar): a vmap
slice's result depends only on its own inputs, and two dispatches padded
to the SAME job-axis width run the SAME XLA program — so a job fused
into a batch of N is bit-identical to the same job run alone whenever
both land on one grid value (which the fixed pad floor guarantees for
small batches). Batched fits run their full iteration budget — the solo
path's plateau early-exit is per-member host control flow that would
make one member's stopping point depend on its neighbors'.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.core.store import ROW_ID, DocumentStore
from learningorchestra_tpu.ml.base import (
    infer_num_classes,
    resolve_mesh,
    segment_steps,
)
from learningorchestra_tpu.ml.binning import MAX_BINS, apply_bins, make_thresholds
from learningorchestra_tpu.ml.evaluation import masked_metrics
from learningorchestra_tpu.ml.logistic import (
    _LR_ROW_ITERS_BUDGET,
    _fit_segment,
    _forward,
    _lbfgs_state,
    scaler_stats,
)
from learningorchestra_tpu.ml.trees import (
    _dt_fit,
    _ensemble_forward,
    _heap_thresholds,
)
from learningorchestra_tpu.parallel.mesh import DATA_AXIS, data_size
from learningorchestra_tpu.parallel.sharding import pad_rows
from learningorchestra_tpu.sched.cancel import check_cancelled
from learningorchestra_tpu.telemetry import tracing as _tracing
from learningorchestra_tpu.utils.shapegrid import grid_size, padded_indices

SWEEP_CLASSIFIERS = ("lr", "dt")

# Fused job axes pad to grid_size(n, floor=_JOB_PAD_FLOOR), then align
# to the mesh's data-axis size. The floor is the MicroBatcher trick at
# job granularity: every batch of <= 8 jobs runs the ONE compiled
# 8-slot program (bit-reproducible across batch sizes), larger batches
# ride the quarter-octave grid.
_JOB_PAD_FLOOR = 8

# One fused dispatch's job axis is capped so a large grid over a large
# dataset cannot demand (points x rows x features) HBM in one program;
# grids past the cap chain through several fused dispatches (still one
# compile, ~points/cap executions — nothing like one dispatch per fit).
_MAX_FUSED_SLICES = 128

# Grids past this are a misuse of the synchronous sweep route, not a
# bigger batch (the job axis multiplies every member's arrays).
MAX_GRID_POINTS = 1024

_DEFAULT_MAX_ITER = 100  # MLlib maxIter default, like the solo LR path


# --------------------------------------------------------------------------
# Grid validation (the route's 406 surface)
# --------------------------------------------------------------------------

def validate_grid(kind: str, grid) -> list[dict]:
    """Normalize a sweep grid or raise ``ValueError`` with the offending
    entry. ``lr`` grids sweep ``reg_param`` (λ >= 0); ``dt`` grids sweep
    ``max_depth`` (int in [1, 12] — the tree heap is 2^depth arrays)."""
    if kind not in SWEEP_CLASSIFIERS:
        raise ValueError(
            f"classificator {kind!r} is not sweepable "
            f"(have: {SWEEP_CLASSIFIERS})"
        )
    if not isinstance(grid, list) or not grid:
        raise ValueError("grid must be a non-empty list of points")
    if len(grid) > MAX_GRID_POINTS:
        raise ValueError(
            f"grid has {len(grid)} points (max {MAX_GRID_POINTS})"
        )
    normalized: list[dict] = []
    for entry in grid:
        if not isinstance(entry, dict):
            raise ValueError(f"grid points must be objects, got {entry!r}")
        if kind == "lr":
            value = entry.get("reg_param")
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                raise ValueError(
                    f"lr grid points need a numeric reg_param, got {entry!r}"
                )
            if not np.isfinite(value) or value < 0:
                raise ValueError(f"reg_param must be finite and >= 0: {entry!r}")
            normalized.append({"reg_param": float(value)})
        else:
            value = entry.get("max_depth")
            if isinstance(value, bool) or not isinstance(value, int):
                raise ValueError(
                    f"dt grid points need an integer max_depth, got {entry!r}"
                )
            if not 1 <= value <= 12:
                raise ValueError(f"max_depth must be in [1, 12]: {entry!r}")
            normalized.append({"max_depth": int(value)})
    return normalized


# --------------------------------------------------------------------------
# Member preparation (host work, BEFORE the device queue)
# --------------------------------------------------------------------------

def prepare_member(
    kind: str,
    X_train: np.ndarray,
    y_train: np.ndarray,
    X_eval: np.ndarray,
    y_eval: np.ndarray,
    grid: list[dict],
    mesh: Optional[Mesh] = None,
    max_iter: int = _DEFAULT_MAX_ITER,
) -> tuple[tuple, dict]:
    """Host-side prep for one coalescible fit/sweep job: pad + dtype the
    arrays and derive the compatibility ``key`` — everything the fused
    program's shape depends on, so two members with equal keys stack on
    one job axis. Runs on the submitting thread (prep must precede the
    device queue: a leader can only stack payloads that already exist).

    Deliberately does NOT validate finiteness: a NaN-poisoned member
    must fail INSIDE the fused dispatch (alone, neighbors unaffected) —
    that isolation is part of the coalescer's contract and is tested.
    """
    mesh = resolve_mesh(mesh)
    grid = validate_grid(kind, grid)
    if not isinstance(max_iter, int) or max_iter < 1:
        raise ValueError(f"max_iter must be an integer >= 1, got {max_iter!r}")
    X_train = np.asarray(X_train)
    y_train = np.asarray(y_train)
    X_eval = np.asarray(X_eval)
    y_eval = np.asarray(y_eval)
    if X_train.ndim != 2 or X_eval.ndim != 2:
        raise ValueError("feature matrices must be 2-D")
    if X_train.shape[1] != X_eval.shape[1]:
        raise ValueError("train/eval feature widths differ")
    num_classes = max(infer_num_classes(y_train), infer_num_classes(y_eval))
    multiple = data_size(mesh)
    X_pad, mask = pad_rows(X_train, multiple)
    y_pad, _ = pad_rows(y_train, multiple)
    Xe_pad, mask_e = pad_rows(X_eval, multiple)
    ye_pad, _ = pad_rows(y_eval, multiple)
    payload = {
        "kind": kind,
        "grid": grid,
        # scanned HERE on the submitting thread (parallel across
        # requests), verdict carried to the fused dispatch where the
        # member fails ALONE (run_group) — scanning there instead would
        # serialize O(members x rows x features) host work on the
        # width-1 device lane
        "finite": bool(
            np.isfinite(X_train).all() and np.isfinite(X_eval).all()
        ),
        "X": X_pad.astype(np.float32),
        "y": y_pad.astype(np.int32),
        "mask": mask.astype(np.float32),
        "X_eval": Xe_pad.astype(np.float32),
        "y_eval": ye_pad.astype(np.int32),
        "mask_eval": mask_e.astype(np.float32),
        "rows": int(len(X_train)),
        "num_classes": num_classes,
        "max_iter": int(max_iter),
    }
    if kind == "lr":
        # the solo fit's scaler recipe (logistic.scaler_stats, shared
        # so the paths cannot drift) — λ never changes it, so it is
        # per-member, not per-point
        mean, scale = scaler_stats(X_train)
        payload["mean"] = mean.astype(np.float32)
        payload["scale"] = scale.astype(np.float32)
    else:
        payload["thresholds"] = make_thresholds(X_train, MAX_BINS).astype(
            np.float32
        )
    key = (
        "sweep",
        kind,
        int(X_pad.shape[0]),
        int(Xe_pad.shape[0]),
        int(X_pad.shape[1]),
        num_classes,
        int(max_iter) if kind == "lr" else MAX_BINS,
        "f32",
        _mesh_signature(mesh),
    )
    return key, payload


def _mesh_signature(mesh: Mesh) -> tuple:
    from learningorchestra_tpu.core.devcache import mesh_signature

    return mesh_signature(mesh)


# --------------------------------------------------------------------------
# The fused programs
# --------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("iters",))
def _lr_fused_segment(params, states, Xs, ys, masks, l2s, iters: int):
    """``iters`` L-BFGS iterations for EVERY slice of the job axis as
    one program — the solo fit's segment (ml/logistic.py) under vmap,
    optimizer state carried per slice across segment boundaries."""

    def one(p, s, X, y, m, l2):
        return _fit_segment(p, s, X, y, m, iters, l2)

    return jax.vmap(one)(params, states, Xs, ys, masks, l2s)


@partial(jax.jit, static_argnames=("num_classes",))
def _lr_fused_eval(params, Xe, means, scales, ye, masks_e, num_classes: int):
    """Per-slice forward + on-device confusion metrics: one dispatch
    yields every point's (accuracy, weighted F1). The forward IS the
    product path's (logistic._forward under vmap) — not a re-typed
    copy that could drift from what the checkpoint will serve."""

    def one(p, X, mean, scale, y, m):
        labels, _ = _forward(p, X, mean, scale)
        return masked_metrics(y, labels, m, num_classes)

    return jax.vmap(one)(params, Xe, means, scales, ye, masks_e)


@partial(jax.jit, static_argnames=("num_classes", "max_depth", "max_bins"))
def _dt_fused(
    Xs, ys, ws, thresholds, Xe, ye, we,
    num_classes: int, max_depth: int, max_bins: int,
):
    """Bin + grow + evaluate one decision tree PER SLICE — the whole
    histogram-tree pipeline (ml/trees.py) under vmap. Depth is a static
    program shape, so a depth grid groups points by depth and runs one
    fused program per distinct depth (each still a batch over the job
    axis, never one dispatch per point)."""

    def one(X, y, w, th, Xev, yev, wev):
        bins = apply_bins(X, th)
        features_heap, bins_heap, leaf_probs = _dt_fit(
            bins, y, w, num_classes, max_depth, max_bins
        )
        thresholds_heap = _heap_thresholds(features_heap, bins_heap, th)
        probs = _ensemble_forward(
            Xev,
            features_heap[None],
            thresholds_heap[None],
            leaf_probs[None],
            max_depth,
        )
        labels = jnp.argmax(probs, axis=1)
        accuracy, weighted_f1 = masked_metrics(yev, labels, wev, num_classes)
        return features_heap, thresholds_heap, leaf_probs, accuracy, weighted_f1

    return jax.vmap(one)(Xs, ys, ws, thresholds, Xe, ye, we)


def _job_axis(n: int, mesh: Mesh) -> tuple[int, NamedSharding]:
    """Padded slot count and sharding for a fused job axis: grid floor,
    then aligned to the data-axis size so the axis ALWAYS partitions
    evenly — slices are independent, so this is collective-free SPMD."""
    devices = data_size(mesh)
    target = grid_size(n, _JOB_PAD_FLOOR)
    target = ((target + devices - 1) // devices) * devices
    return target, NamedSharding(mesh, P(DATA_AXIS))


def _stack(arrays: list[np.ndarray], sharding) -> jax.Array:
    """Stack per-slice host arrays along a new job axis and place it
    job-sharded (callers build the padded slot list via
    ``padded_indices``: dummy slots replicate slot 0)."""
    return jax.device_put(np.stack(arrays), sharding)


# --------------------------------------------------------------------------
# The group runner (executed ONCE per fused batch by the coalescer leader)
# --------------------------------------------------------------------------

def group_runner(mesh: Optional[Mesh] = None):
    """The coalescer's runner for sweep/fit members: ``(payloads) ->
    [outcome, ...]`` with the per-member isolation contract from
    sched/coalesce.py (an outcome is ``("ok", result)`` or
    ``("error", exception)``)."""
    mesh = resolve_mesh(mesh)

    def run(payloads: list) -> list:
        return run_group(payloads, mesh)

    return run


def run_group(payloads: list, mesh: Mesh) -> list:
    outcomes: list = [None] * len(payloads)
    live: list[int] = []
    for index, payload in enumerate(payloads):
        # per-member validation verdict (computed at prepare_member on
        # the submitting thread): a poisoned member fails ALONE — NaN
        # features would otherwise silently NaN its fitted params
        if not payload.get("finite", True):
            outcomes[index] = (
                "error",
                ValueError(
                    "non-finite features in coalesced member "
                    f"{index} — member failed, neighbors unaffected"
                ),
            )
        else:
            live.append(index)
    if not live:
        return outcomes
    kind = payloads[live[0]]["kind"]
    # one flat slice list: (member, point) pairs — a 100-λ sweep is one
    # member with 100 slices, 64 coalesced small builds are 64 members
    # with one slice each; the fused program cannot tell the difference
    slices = [
        (member, point)
        for member in live
        for point in range(len(payloads[member]["grid"]))
    ]
    per_point: dict[tuple[int, int], dict] = {}
    for start in range(0, len(slices), _MAX_FUSED_SLICES):
        chunk = slices[start : start + _MAX_FUSED_SLICES]
        if kind == "lr":
            _run_lr_chunk(payloads, chunk, per_point, mesh)
        else:
            _run_dt_chunk(payloads, chunk, per_point, mesh)
        if len(payloads) == 1:
            # chunk boundary of a single-member (big-grid) sweep: the
            # executing leader IS that member, so its DELETE aborts
            # cleanly between fused programs. With multiple members
            # fused, the batch runs to completion instead — an abort
            # here would fail the leader's NEIGHBORS for the leader's
            # cancellation (the ambient token is the leader's)
            check_cancelled()
    for member in live:
        payload = payloads[member]
        points = []
        for point in range(len(payload["grid"])):
            entry = per_point[(member, point)]
            points.append({**entry, "grid": payload["grid"][point]})
        accuracies = [p["accuracy"] for p in points]
        best = int(np.argmax(accuracies))
        outcomes[member] = (
            "ok",
            {
                "kind": kind,
                "points": [
                    {
                        "grid": p["grid"],
                        "accuracy": p["accuracy"],
                        "weighted_f1": p["weighted_f1"],
                    }
                    for p in points
                ],
                "params": [p["params"] for p in points],
                "best": best,
                "_attribution": {
                    "rows": payload["rows"],
                    "bytes": int(
                        payload["X"].nbytes + payload["X_eval"].nbytes
                    ),
                    "points": len(points),
                },
            },
        )
    return outcomes


def _run_lr_chunk(payloads, chunk, per_point, mesh) -> None:
    first = payloads[chunk[0][0]]
    features = first["X"].shape[1]
    num_classes = first["num_classes"]
    max_iter = first["max_iter"]
    padded, sharding = _job_axis(len(chunk), mesh)
    # dummy slots replicate slot 0's (member, point) pair
    slots = [chunk[i] for i in padded_indices(len(chunk), padded)]
    members = [member for member, _ in slots]
    l2s = np.asarray(
        [payloads[member]["grid"][point]["reg_param"] for member, point in slots],
        np.float32,
    )
    with _tracing.span(
        "coalesce:lr_chunk", slices=len(chunk), padded=padded
    ):
        Xs = _stack([payloads[m]["X"] for m in members], sharding)
        ys = _stack([payloads[m]["y"] for m in members], sharding)
        # standardized per slice ON DEVICE from the per-member scaler
        # (λ shares one standardization; members each carry their own)
        means = _stack([payloads[m]["mean"] for m in members], sharding)
        scales = _stack([payloads[m]["scale"] for m in members], sharding)
        masks = _stack([payloads[m]["mask"] for m in members], sharding)
        Xs = (Xs - means[:, None, :]) / scales[:, None, :]
        params = jax.device_put(
            {
                "w": jnp.zeros((padded, features, num_classes), jnp.float32),
                "b": jnp.zeros((padded, num_classes), jnp.float32),
            },
            sharding,
        )
        l2_dev = jax.device_put(l2s, sharding)
        states = jax.vmap(_lbfgs_state)(params)
        # watchdog-safe segmentation, like the solo fit, with the job
        # axis multiplying the per-program row cost; NO plateau exit —
        # batched stopping must not couple members (module docstring)
        iters = segment_steps(
            max_iter, first["X"].shape[0] * padded, _LR_ROW_ITERS_BUDGET,
            features,
        )
        for _ in range(max(1, max_iter // iters)):
            params, states, _ = _lr_fused_segment(
                params, states, Xs, ys, masks, l2_dev, iters
            )
        Xe = _stack([payloads[m]["X_eval"] for m in members], sharding)
        ye = _stack([payloads[m]["y_eval"] for m in members], sharding)
        we = _stack([payloads[m]["mask_eval"] for m in members], sharding)
        accuracy, weighted_f1 = _lr_fused_eval(
            params, Xe, means, scales, ye, we, num_classes
        )
        # ONE host transfer for the whole chunk's params + metrics
        w_host, b_host, acc_host, f1_host = jax.device_get(
            (params["w"], params["b"], accuracy, weighted_f1)
        )
    for i, (member, point) in enumerate(chunk):
        per_point[(member, point)] = {
            "accuracy": float(acc_host[i]),
            "weighted_f1": float(f1_host[i]),
            "params": {
                "kind": "lr",
                "w": np.asarray(w_host[i]),
                "b": np.asarray(b_host[i]),
                "mean": payloads[member]["mean"],
                "scale": payloads[member]["scale"],
            },
        }


def _run_dt_chunk(payloads, chunk, per_point, mesh) -> None:
    first = payloads[chunk[0][0]]
    num_classes = first["num_classes"]
    # depth is a static program shape: group this chunk's slices by
    # depth and run one fused program per distinct depth — each still a
    # batched job axis, never one dispatch per grid point
    by_depth: dict[int, list[tuple[int, int]]] = {}
    for member, point in chunk:
        depth = payloads[member]["grid"][point]["max_depth"]
        by_depth.setdefault(depth, []).append((member, point))
    for depth, group in sorted(by_depth.items()):
        padded, sharding = _job_axis(len(group), mesh)
        members = [
            group[i][0] for i in padded_indices(len(group), padded)
        ]
        with _tracing.span(
            "coalesce:dt_chunk", slices=len(group), padded=padded,
            depth=depth,
        ):
            Xs = _stack([payloads[m]["X"] for m in members], sharding)
            ys = _stack([payloads[m]["y"] for m in members], sharding)
            ws = _stack([payloads[m]["mask"] for m in members], sharding)
            ths = _stack(
                [payloads[m]["thresholds"] for m in members], sharding
            )
            Xe = _stack([payloads[m]["X_eval"] for m in members], sharding)
            ye = _stack([payloads[m]["y_eval"] for m in members], sharding)
            we = _stack([payloads[m]["mask_eval"] for m in members], sharding)
            features_heap, thresholds_heap, leaf_probs, accuracy, f1 = (
                _dt_fused(
                    Xs, ys, ws, ths, Xe, ye, we,
                    num_classes, depth, MAX_BINS,
                )
            )
            fh, th, lp, acc_host, f1_host = jax.device_get(
                (features_heap, thresholds_heap, leaf_probs, accuracy, f1)
            )
        for i, (member, point) in enumerate(group):
            per_point[(member, point)] = {
                "accuracy": float(acc_host[i]),
                "weighted_f1": float(f1_host[i]),
                "params": {
                    "kind": "dt",
                    "features_heap": np.asarray(fh[i]),
                    "thresholds_heap": np.asarray(th[i]),
                    "leaf_probs": np.asarray(lp[i]),
                    "max_depth": depth,
                },
            }


# --------------------------------------------------------------------------
# Model reconstruction + the service-level sweep orchestration
# --------------------------------------------------------------------------

def model_from_params(params: dict, mesh: Optional[Mesh] = None):
    """A predict-ready model from one grid point's fitted params — the
    object the argmax checkpoint serializes."""
    mesh = resolve_mesh(mesh)
    if params["kind"] == "lr":
        from learningorchestra_tpu.ml.logistic import LogisticRegressionModel

        return LogisticRegressionModel(
            {"w": jnp.asarray(params["w"]), "b": jnp.asarray(params["b"])},
            jnp.asarray(params["mean"]),
            jnp.asarray(params["scale"]),
            mesh,
        )
    from learningorchestra_tpu.ml.trees import _TreeEnsembleModel

    return _TreeEnsembleModel(
        jnp.asarray(params["features_heap"])[None],
        jnp.asarray(params["thresholds_heap"])[None],
        jnp.asarray(params["leaf_probs"])[None],
        mesh,
        params["max_depth"],
    )


def run_sweep(
    store: DocumentStore,
    body: dict,
    *,
    jobs,
    coalescer,
    models_dir: Optional[str] = None,
    mesh: Optional[Mesh] = None,
) -> dict:
    """The ``POST /models/sweep`` flow: prep on the request thread, ONE
    coalescible device job for the whole grid (concurrent sweeps with
    compatible shapes fuse), argmax checkpoint published atomically,
    per-point metrics persisted as collection ``sweep_name``.

    Raises whatever the member job raises (the route maps
    ``QueueFullError`` to 429 and ``DuplicateJobError`` to 409)."""
    from learningorchestra_tpu.frame.pyspark_compat import run_preprocessor
    from learningorchestra_tpu.ml.builder import (
        FEATURES_COL,
        LABEL_COL,
        load_dataframe,
    )
    from learningorchestra_tpu.ml.checkpoint import checkpoint_path, save_model
    from learningorchestra_tpu.sched.cancel import CancelToken
    from learningorchestra_tpu.sched.scheduler import DEVICE_CLASS

    mesh = resolve_mesh(mesh)
    name = body["sweep_name"]
    training_df = load_dataframe(store, body["training_filename"])
    testing_df = load_dataframe(store, body["test_filename"])
    out = run_preprocessor(body["preprocessor_code"], training_df, testing_df)
    eval_df = (
        out["features_evaluation"]
        if out["features_evaluation"] is not None
        else out["features_testing"]
    )
    key, payload = prepare_member(
        body["classificator"],
        out["features_training"].feature_matrix(FEATURES_COL),
        out["features_training"].label_vector(LABEL_COL),
        eval_df.feature_matrix(FEATURES_COL),
        eval_df.label_vector(LABEL_COL),
        body["grid"],
        mesh=mesh,
        max_iter=int(body.get("max_iter", _DEFAULT_MAX_ITER)),
    )
    token = CancelToken()
    member = coalescer.register(
        key, payload, group_runner(mesh), token=token, name=f"sweep:{name}"
    )
    try:
        # collection=name opts the member into the journal (ISSUE: each
        # member keeps its own journal entry); store= is deliberately
        # NOT passed — the failure-marking write it enables targets a
        # collection that only exists after success, a guaranteed no-op
        jobs.run_sync(
            f"sweep:{name}",
            coalescer.run_member,
            member,
            job_class=DEVICE_CLASS,
            token=token,
            collection=name,
        )
    except BaseException:
        # a submission that never ran (429 queue cap, 409 duplicate)
        # must not leave a payload for some future leader to stack;
        # harmless no-op when the member already executed and failed
        coalescer.abandon(member)
        raise
    result = member.result
    best = result["best"]
    checkpoint = None
    if models_dir:
        os.makedirs(models_dir, exist_ok=True)
        checkpoint = checkpoint_path(models_dir, name)
        # atomic publication (temp + os.replace, ml/checkpoint.py): the
        # serving registry's rev stamp sees the winner, never a partial
        save_model(model_from_params(result["params"][best], mesh), checkpoint)
        # publish-time serve warmup (compile plane): feature width is
        # derivable from the winner's own params where the model
        # records it (lr/nb); the handler skips kinds that don't
        from learningorchestra_tpu import compile as lo_compile

        lo_compile.checkpoint_published(checkpoint)
    points = [
        {**p["grid"], "accuracy": p["accuracy"], "weighted_f1": p["weighted_f1"]}
        for p in result["points"]
    ]
    document = {
        ROW_ID: 0,
        "filename": name,
        "classificator": result["kind"],
        "points": points,
        "best": best,
        "model_checkpoint": checkpoint,
        "finished": True,
    }
    store.insert_one(name, document)
    return {
        "model": name,
        "classificator": result["kind"],
        "points": points,
        "best": best,
        "model_checkpoint": checkpoint,
    }
