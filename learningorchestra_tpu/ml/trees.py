"""Histogram-binned decision trees, random forest, and gradient boosting.

Replaces Spark MLlib's ``DecisionTreeClassifier`` / ``RandomForestClassifier``
/ ``GBTClassifier`` (reference: microservices/model_builder_image/
model_builder.py:8-12,153-155). Defaults mirror MLlib: ``maxDepth=5``,
``maxBins=32``; RF ``numTrees=20`` with sqrt feature subsets per node;
GBT ``maxIter=20``, ``stepSize=0.1``, binary logistic loss.

TPU-first design — no recursive node objects, no data-dependent control
flow:

- Features are quantile-binned once (``ml/binning.py``); a tree level is
  then ONE dense program: scatter-add per-row stat vectors into a
  ``(node, feature, bin, channel)`` histogram, cumulative-sum over bins,
  and an argmax — the classic LightGBM/XGBoost histogram method, which
  is exactly the shape of computation XLA tiles well.
- The tree is a static heap (arrays of size ``2^depth - 1``); rows carry
  an int32 node index and each level doubles it. Nodes that stop
  splitting get ``feature = -1`` and route everything left, so shapes
  never change.
- One generic ``channel`` dimension serves both worlds: class one-hots
  (gini splits, used by dt/rf) and Newton ``(g, h)`` pairs (logistic
  boosting, used by gb).
- Random forest is ``vmap`` over per-tree RNG keys — all 20 trees grow
  simultaneously on device, with Poisson(1) bootstrap weights and
  per-node feature subsets. Boosting is ``lax.scan`` over rounds.
- Row-sharded inputs: the scatter-adds reduce over the ``data`` mesh
  axis; XLA inserts the cross-chip psum from the sharding annotations.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.ml import progress as _progress
from learningorchestra_tpu.ml.base import (
    FittedModel,
    infer_num_classes,
    largest_divisor,
    prepare_xy,
    resolve_mesh,
)
from learningorchestra_tpu.ml.binning import MAX_BINS, apply_bins, make_thresholds
from learningorchestra_tpu.parallel.mesh import MODEL_AXIS, model_size

MAX_DEPTH = 5          # MLlib default maxDepth
NUM_TREES = 20         # MLlib default numTrees (RF)
GBT_ROUNDS = 20        # MLlib default maxIter (GBT)
GBT_STEP = 0.1         # MLlib default stepSize
EPS = 1e-12
# rows*features cap per histogram feature-block: bounds the f32 bin
# indicator transient at ~2 GB (rows*block*max_bins*4 with 32 bins)
_HIST_BLOCK_ROW_FEATURES = 16e6


# --------------------------------------------------------------------------
# Level primitives
# --------------------------------------------------------------------------

def _level_histograms(bins, node, channels, n_nodes: int, max_bins: int):
    """Accumulate per-row channel vectors into ``(node, feature, bin, K)``.

    The histogram-build hot loop: O(rows × features) accumulation, the
    tree analogue of the reference's distributed MLlib fit iterations
    (model_builder.py:199).

    MXU formulation: the scatter-add is algebraically
    ``one_hot(bin).T @ (one_hot(node) ⊗ channels)`` — two dense
    matmuls, which the systolic array executes at full tilt where a
    batched scatter (under the forest's tree-vmap) serializes. Measured
    on v5e at 1M×16, 20 trees: 0.26 s/level vs 2.55 s/level for the
    scatter — 10×. f32 operands keep the sums within 1e-4 of exact
    (matmul reassociation only). The scatter fallback guards the wide
    case (many classes at deep levels) where the ``(rows, nodes·K)``
    intermediate would not fit.
    """
    num_channels = channels.shape[1]
    num_features = bins.shape[1]
    rows = bins.shape[0]

    if n_nodes * num_channels <= 64:
        node_oh = jax.nn.one_hot(node, n_nodes, dtype=jnp.float32)
        fused = (node_oh[:, :, None] * channels[:, None, :]).reshape(
            channels.shape[0], n_nodes * num_channels
        )

        # Feature-BLOCKED contraction: one per-feature dot re-reads the
        # (rows, nodes*K) fused matrix from HBM once per feature — 16
        # features × 5 levels × 20 vmapped trees ≈ 400 GB of redundant
        # traffic per forest fit at 1M rows. Contracting a block of
        # features in ONE dot_general reads fused once per block; the
        # bin indicator is built in (block, rows, bins) layout and
        # contracted over rows directly (no transpose materializes).
        # Block size is HBM-capped: the indicator transient is
        # rows*block*max_bins*4 bytes (~2 GB cap).
        cap = max(1, int(_HIST_BLOCK_ROW_FEATURES // max(rows, 1)))
        block = largest_divisor(num_features, cap)
        blocked = bins.T.reshape(num_features // block, block, rows)
        iota = jnp.arange(max_bins, dtype=jnp.int32)

        def per_block_mm(bins_fb):
            # (block, rows, bins) exact 0/1 indicator
            indicator = (bins_fb[:, :, None] == iota).astype(jnp.float32)
            # HIGHEST: `fused` carries arbitrary f32 gradients on the
            # boosting path; the TPU's default bf16 matmul would shift
            # near-tie split gains (indicator operands alone are
            # bf16-exact, the channel side is not)
            return jax.lax.dot_general(
                indicator,
                fused,
                (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
            )                                    # (block, bins, nodes*K)

        hist = jax.lax.map(per_block_mm, blocked)  # (F/blk, blk, B, n*K)
        return hist.reshape(
            num_features, max_bins, n_nodes, num_channels
        ).transpose(2, 0, 1, 3)

    def per_feature(bins_f):
        index = node * max_bins + bins_f
        return (
            jnp.zeros((n_nodes * max_bins, num_channels), jnp.float32)
            .at[index]
            .add(channels)
        )

    # Sequential over features (lax.map), parallel over rows within each
    # scatter; keeps the transient at (rows, K) per step.
    hist = jax.lax.map(per_feature, bins.T)              # (F, nodes*B, K)
    return hist.reshape(num_features, n_nodes, max_bins, num_channels).transpose(
        1, 0, 2, 3
    )


def _leaf_sums(leaf_of_row, channels, n_leaves: int):
    """Per-leaf channel sums — the same MXU-vs-scatter choice as
    _level_histograms: one-hot matmul while the ``(rows, n_leaves)``
    intermediate stays small (default depth 5 → 32 leaves), guarded
    scatter for deep trees where it would not fit."""
    if n_leaves <= 64:
        return jnp.dot(
            jax.nn.one_hot(leaf_of_row, n_leaves, dtype=jnp.float32).T,
            channels,
            precision=jax.lax.Precision.HIGHEST,
        )
    return (
        jnp.zeros((n_leaves, channels.shape[1]), jnp.float32)
        .at[leaf_of_row]
        .add(channels)
    )


def _gini_gain(hist):
    """Split scores from class-count histograms ``(nodes, F, B, C)``.

    Maximizing ``Σ_c l_c²/n_l + Σ_c r_c²/n_r`` is minimizing weighted
    gini impurity; the parent term makes it a proper gain (> 0 required
    to split, MLlib ``minInfoGain=0``)."""
    left = jnp.cumsum(hist, axis=2)
    total = left[:, :, -1:, :]
    right = total - left
    n_left = left.sum(-1)
    n_right = right.sum(-1)
    score_left = (left**2).sum(-1) / jnp.maximum(n_left, EPS)
    score_right = (right**2).sum(-1) / jnp.maximum(n_right, EPS)
    parent = (total[:, :, 0, :] ** 2).sum(-1) / jnp.maximum(
        total[:, :, 0, :].sum(-1), EPS
    )
    gain = score_left + score_right - parent[:, :, None]
    valid = (n_left > 0) & (n_right > 0)
    return jnp.where(valid, gain, -jnp.inf)


def _newton_gain(hist, lam=1.0):
    """Split scores from ``(g, h)`` histograms ``(nodes, F, B, 2)`` —
    XGBoost-style second-order gain for logistic boosting."""
    left = jnp.cumsum(hist, axis=2)
    total = left[:, :, -1:, :]
    right = total - left
    g_left, h_left = left[..., 0], left[..., 1]
    g_right, h_right = right[..., 0], right[..., 1]
    score = g_left**2 / (h_left + lam) + g_right**2 / (h_right + lam)
    parent = total[:, :, 0, 0] ** 2 / (total[:, :, 0, 1] + lam)
    gain = score - parent[:, :, None]
    valid = (h_left > EPS) & (h_right > EPS)
    return jnp.where(valid, gain, -jnp.inf)


def _select_splits(gain, subset_key, subset_k: Optional[int]):
    """Best (feature, bin) per node from ``gain (nodes, F, B)``; nodes
    whose best gain is <= 0 get ``feature = -1`` (leaf). ``subset_k``
    restricts each node to a random feature subset (RF per-node
    sampling, MLlib featureSubsetStrategy="auto" → sqrt)."""
    n_nodes, num_features, max_bins = gain.shape
    if subset_k is not None and subset_k < num_features:
        scores = jax.random.uniform(subset_key, (n_nodes, num_features))
        kth = jnp.sort(scores, axis=1)[:, subset_k - 1]
        allowed = scores <= kth[:, None]
        gain = jnp.where(allowed[:, :, None], gain, -jnp.inf)
    flat = gain.reshape(n_nodes, -1)
    best = jnp.argmax(flat, axis=1).astype(jnp.int32)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    feature = best // max_bins
    bin_index = best % max_bins
    is_leaf = ~(best_gain > 0) | jnp.isinf(best_gain)
    feature = jnp.where(is_leaf, -1, feature)
    return feature, bin_index


def _indicator_lookup(indices, table, fill=0):
    """Gather-free ``table[indices]`` for small tables: an indicator
    select-sum on the VPU. Per-row gathers serialize on TPU and were
    the forest fit's dominant cost — an isolated 20-tree × 5-level
    routing probe on v5e at 1M×16 cost 1.9 s, the same order as the
    entire 1.66 s forest fit, vs 0.16 s for its histograms. A select
    (never a multiply) so 0·inf/0·NaN cannot poison the sum; exactly
    one indicator per row is set, so the sum is exact. Wide tables
    fall back to the native gather — the (rows, size) indicator would
    dwarf the gather it replaces (same ≤64 guard pattern as
    _level_histograms/_leaf_sums)."""
    size = table.shape[0]
    if size > 64:
        return table[indices]
    picked = indices[:, None] == jnp.arange(size, dtype=jnp.int32)
    return jnp.where(picked, table[None, :], fill).sum(axis=1)


def _route(bins, node, feature, bin_index):
    """Advance each row one level down: left iff its bin <= the node's
    split bin; ``feature = -1`` nodes send everything left. All
    per-row lookups are gather-free (see _indicator_lookup)."""
    row_feature = _indicator_lookup(node, feature)
    row_bin = _indicator_lookup(node, bin_index)
    feature_oh = jax.nn.one_hot(
        jnp.maximum(row_feature, 0), bins.shape[1], dtype=bins.dtype
    )
    x_bin = (bins * feature_oh).sum(axis=1)
    go_right = (x_bin > row_bin) & (row_feature >= 0)
    return node * 2 + go_right.astype(jnp.int32)


# --------------------------------------------------------------------------
# Single-tree fits (jit-composable; shapes static over levels)
# --------------------------------------------------------------------------

def _grow(bins, channels, gain_fn, max_depth, max_bins, subset_key, subset_k):
    """Grow one tree level-wise. Returns heap arrays (features, bins per
    internal node) and the per-row final leaf index."""
    n_rows = bins.shape[0]
    node = jnp.zeros(n_rows, jnp.int32)
    features_heap = []
    bins_heap = []
    for level in range(max_depth):
        hist = _level_histograms(bins, node, channels, 2**level, max_bins)
        gain = gain_fn(hist)
        level_key = (
            jax.random.fold_in(subset_key, level) if subset_key is not None else None
        )
        feature, bin_index = _select_splits(gain, level_key, subset_k)
        features_heap.append(feature)
        bins_heap.append(bin_index)
        node = _route(bins, node, feature, bin_index)
    return (
        jnp.concatenate(features_heap),
        jnp.concatenate(bins_heap),
        node,
    )


def _fit_classification_tree(
    bins, one_hot, max_depth, max_bins, subset_key=None, subset_k=None
):
    features_heap, bins_heap, leaf_of_row = _grow(
        bins, one_hot, _gini_gain, max_depth, max_bins, subset_key, subset_k
    )
    num_classes = one_hot.shape[1]
    leaf_counts = _leaf_sums(leaf_of_row, one_hot, 2**max_depth)
    leaf_probs = leaf_counts / jnp.maximum(leaf_counts.sum(1, keepdims=True), EPS)
    return features_heap, bins_heap, leaf_probs


def _fit_newton_tree(bins, g, h, max_depth, max_bins, lam=1.0):
    channels = jnp.stack([g, h], axis=1)
    features_heap, bins_heap, leaf_of_row = _grow(
        bins, channels, _newton_gain, max_depth, max_bins, None, None
    )
    sums = _leaf_sums(leaf_of_row, channels, 2**max_depth)
    leaf_values = -sums[:, 0] / (sums[:, 1] + lam)
    return features_heap, bins_heap, leaf_values, leaf_of_row


# --------------------------------------------------------------------------
# Prediction on raw (unbinned) features
# --------------------------------------------------------------------------

def _descend(X, features_heap, thresholds_heap, max_depth):
    """Walk the static heap: raw value <= float threshold goes left —
    identical routing to the binned training walk by construction
    (ml/binning.py bin semantics). ``~(x <= t)`` rather than ``x > t``
    so NaN goes right, matching searchsorted's NaN-to-last-bin policy at
    training time."""
    node = jnp.zeros(X.shape[0], jnp.int32)
    for level in range(max_depth):
        offset = 2**level - 1
        heap_pos = offset + node
        # gather-free heap and feature lookups (see _indicator_lookup;
        # constant features carry inf thresholds and unselected X
        # columns may be NaN — the selects keep them inert while a
        # SELECTED NaN still routes right, the missing-value policy)
        feature = _indicator_lookup(heap_pos, features_heap)
        threshold = _indicator_lookup(heap_pos, thresholds_heap, fill=0.0)
        picked = jnp.maximum(feature, 0)[:, None] == jnp.arange(
            X.shape[1], dtype=jnp.int32
        )
        x = jnp.where(picked, X, 0.0).sum(axis=1)
        go_right = ~(x <= threshold) & (feature >= 0)
        node = node * 2 + go_right.astype(jnp.int32)
    return node


def _heap_thresholds(features_heap, bins_heap, thresholds):
    """Float threshold per internal node: ``thresholds[f, b]``. A split
    at the last bin can never be selected (its right side is empty), so
    ``b`` is always a valid threshold index."""
    safe_feature = jnp.maximum(features_heap, 0)
    safe_bin = jnp.minimum(bins_heap, thresholds.shape[1] - 1)
    return thresholds[safe_feature, safe_bin]


# --------------------------------------------------------------------------
# Estimators
# --------------------------------------------------------------------------

class _TreeEnsembleModel(FittedModel):
    """Shared predict machinery: stacked heaps (T, 2^D-1) + leaf stats."""

    def __init__(self, features_heap, thresholds_heap, leaf_probs, mesh, max_depth):
        self.features_heap = features_heap        # (T, 2^D - 1)
        self.thresholds_heap = thresholds_heap    # (T, 2^D - 1)
        self.leaf_probs = leaf_probs              # (T, 2^D, C)
        self.mesh = mesh
        self.max_depth = max_depth

    def _device_eval(self, X):
        X_dev, _, mask = prepare_xy(X, None, self.mesh)
        probs = _ensemble_forward(
            X_dev,
            self.features_heap,
            self.thresholds_heap,
            self.leaf_probs,
            self.max_depth,
        )
        return jnp.argmax(probs, axis=1), probs, mask


@partial(jax.jit, static_argnames=("max_depth",))
def _ensemble_forward(X, features_heap, thresholds_heap, leaf_probs, max_depth):
    """Mean class distribution over trees, sequentially accumulated.

    NOT a vmap over trees: that materializes a ``(trees, rows, classes)``
    intermediate whose class-minor dimension pads to the 128-lane tile —
    at 20 trees × 10M rows that is ~100 GB of HBM for 1.6 GB of data.
    The scan keeps one ``(classes, rows)`` accumulator (rows minor → no
    padding) and one tree's gather live at a time."""
    num_classes = leaf_probs.shape[-1]

    def one_tree(acc, tree):
        features, thresholds, leaves = tree
        leaf = _descend(X, features, thresholds, max_depth)
        return acc + leaves.T[:, leaf], None

    acc, _ = jax.lax.scan(
        one_tree,
        jnp.zeros((num_classes, X.shape[0]), jnp.float32),
        (features_heap, thresholds_heap, leaf_probs),
    )
    if features_heap.shape[0] == 0:  # numTrees=0: uniform, not 0/0 NaN
        return jnp.full((X.shape[0], num_classes), 1.0 / num_classes)
    return (acc / features_heap.shape[0]).T


@partial(jax.jit, static_argnames=("num_classes", "max_depth", "max_bins"))
def _dt_fit(bins, y, weights, num_classes, max_depth, max_bins):
    one_hot = jax.nn.one_hot(y, num_classes, dtype=jnp.float32) * weights[:, None]
    return _fit_classification_tree(bins, one_hot, max_depth, max_bins)


def _rf_specs(mesh):
    return (
        NamedSharding(mesh, P(MODEL_AXIS, None)),       # features heap
        NamedSharding(mesh, P(MODEL_AXIS, None)),       # split-bin heap
        NamedSharding(mesh, P(MODEL_AXIS, None, None)), # leaf probs
    )


@partial(
    jax.jit,
    static_argnames=("num_classes", "max_depth", "max_bins", "subset_k", "mesh"),
)
def _rf_chunk(
    bins, y, weights, keys, num_classes, max_depth, max_bins, subset_k,
    mesh=None,
):
    base_one_hot = jax.nn.one_hot(y, num_classes, dtype=jnp.float32)

    def one_tree(tree_key):
        bootstrap_key, subset_key = jax.random.split(tree_key)
        bootstrap = jax.random.poisson(
            bootstrap_key, 1.0, (bins.shape[0],)
        ).astype(jnp.float32)
        one_hot = base_one_hot * (weights * bootstrap)[:, None]
        return _fit_classification_tree(
            bins, one_hot, max_depth, max_bins, subset_key, subset_k
        )

    # Tensor parallelism over TREES: the vmap axis is sharded on the
    # mesh's model axis (when it divides evenly), so a (data, model)
    # mesh grows trees 2D-parallel — each device builds the histograms
    # for its tree shard over its row shard, and XLA psums the
    # histograms over the data axis only. Uneven splits replicate, like
    # LR's class axis.
    specs = None
    if mesh is not None and keys.shape[0] % model_size(mesh) == 0:
        specs = _rf_specs(mesh)
        keys = jax.lax.with_sharding_constraint(
            keys, NamedSharding(mesh, P(MODEL_AXIS))
        )
    out = jax.vmap(one_tree)(keys)
    if specs is not None:
        out = tuple(
            jax.lax.with_sharding_constraint(array, spec)
            for array, spec in zip(out, specs)
        )
    return out


# Per-program budget in row*trees: one bootstrap tree costs about one
# boosting round (~0.3-0.7 s at 1M rows) — ~4 trees at 10M rows keeps a
# segment under the execution watchdog (see base.segment_steps).
_RF_ROW_TREES_BUDGET = 40e6

# HBM cap on the vmap width: a chunk's level-histogram transients are
# (chunk*rows_per_device, lanes) one-hots padded to the 128-lane tile
# (~512 B/row at f32) — 20M row*trees per device ≈ 10 GB transient,
# inside a 16 GB v5e alongside the binned matrix.
_RF_ROW_TREES_PER_DEVICE_HBM = 20e6


def _rf_fit(
    bins, y, weights, key, num_classes, max_depth, max_bins, num_trees,
    subset_k, mesh=None,
):
    """Forest fit in watchdog- and HBM-safe chunks of trees. Trees are
    independent, so chunking only splits the vmap width; the key fan-out
    matches the former single-program fit, and on a model-sharded mesh
    the chunk width stays a multiple of the model axis so every chunk
    keeps the 2D tree/row parallelism."""
    from learningorchestra_tpu.ml.base import largest_divisor, segment_steps
    from learningorchestra_tpu.parallel.mesh import data_size

    if num_trees <= 0:  # empty forest: empty heaps (vmap over no keys)
        return _rf_chunk(
            bins, y, weights, jax.random.split(key, 0), num_classes,
            max_depth, max_bins, subset_k, None,
        )
    chunk = segment_steps(
        num_trees, bins.shape[0], _RF_ROW_TREES_BUDGET, bins.shape[1]
    )
    rows_per_device = bins.shape[0] // (data_size(mesh) if mesh else 1)
    hbm_chunk = max(1, int(_RF_ROW_TREES_PER_DEVICE_HBM // max(rows_per_device, 1)))
    if hbm_chunk < chunk:
        chunk = largest_divisor(num_trees, hbm_chunk)
    sharded = mesh is not None and num_trees % model_size(mesh) == 0
    if sharded and chunk % model_size(mesh) != 0:
        width = model_size(mesh)
        chunk = largest_divisor(num_trees, max(chunk, width), multiple_of=width)
    keys = jax.random.split(key, num_trees)
    chunks = [
        _rf_chunk(
            bins, y, weights, keys[start : start + chunk], num_classes,
            max_depth, max_bins, subset_k, mesh,
        )
        for start in range(0, num_trees, chunk)
    ]
    if len(chunks) == 1:
        return chunks[0]
    out = tuple(jnp.concatenate(parts) for parts in zip(*chunks))
    if sharded:
        out = tuple(
            jax.device_put(array, spec)
            for array, spec in zip(out, _rf_specs(mesh))
        )
    return out


@jax.jit
def _gbt_init(y, weights):
    y_f = y.astype(jnp.float32)
    n_real = jnp.maximum(weights.sum(), 1.0)
    base_rate = jnp.clip((y_f * weights).sum() / n_real, 1e-6, 1 - 1e-6)
    f0 = jnp.log(base_rate / (1 - base_rate))
    return f0, jnp.full(y.shape[0], f0, jnp.float32)


def _gbt_rounds_impl(
    bins, y, weights, margins, max_depth, max_bins, rounds, step
):
    """``rounds`` boosting rounds as one program, margins in and out —
    chained by :func:`_gbt_fit` (see base.segment_steps)."""
    y_f = y.astype(jnp.float32)

    def one_round(margins, _):
        p = jax.nn.sigmoid(margins)
        g = (p - y_f) * weights
        h = jnp.maximum(p * (1 - p), 1e-6) * weights
        features, split_bins, leaf_values, leaf_of_row = _fit_newton_tree(
            bins, g, h, max_depth, max_bins
        )
        margins = margins + step * leaf_values[leaf_of_row]
        return margins, (features, split_bins, leaf_values)

    margins, (features_heap, bins_heap, leaf_values) = jax.lax.scan(
        one_round, margins, length=rounds
    )
    return margins, features_heap, bins_heap, leaf_values


_gbt_rounds = partial(
    jax.jit, static_argnames=("max_depth", "max_bins", "rounds")
)(_gbt_rounds_impl)


@lru_cache(maxsize=None)
def _donated_gbt_rounds():
    return jax.jit(
        _gbt_rounds_impl,
        static_argnames=("max_depth", "max_bins", "rounds"),
        donate_argnums=(3,),
    )


def _gbt_rounds_runner():
    """The segment program :func:`_gbt_fit` chains: the margin vector
    (argument 3) is DONATED — each segment's output margins rebind it,
    so XLA reuses that (rows,)-sized HBM buffer across boosting
    segments instead of holding two generations per boundary
    (``donate_argnums``, SNIPPETS.md [3]). bins/y/weights are re-read
    every segment and stay undonated. CPU backends don't implement
    donation and use the shared undonated program, read as the MODULE
    attribute at call time (so tests can script it; resolving lazily
    also means importing this module never initializes the device
    backend)."""
    if jax.default_backend() == "cpu":
        return _gbt_rounds
    return _donated_gbt_rounds()


# Per-program budget in row*rounds: one boosting round builds a whole
# depth-5 tree (~0.3-0.6 s at 1M rows), so ~4 rounds at 10M rows keeps
# a segment under the execution watchdog (see base.segment_steps).
_GB_ROW_ROUNDS_BUDGET = 40e6


def _gbt_fit(bins, y, weights, max_depth, max_bins, rounds, step):
    """Sequential boosting in watchdog-safe segments; the margin vector
    carries across programs, so the round sequence matches the former
    single-scan program."""
    from learningorchestra_tpu.ml.base import segment_steps

    f0, margins = _gbt_init(y, weights)
    if rounds <= 0:  # zero rounds: empty heaps, base-rate-only model
        _, features_heap, bins_heap, leaf_values = _gbt_rounds(
            bins, y, weights, margins, max_depth, max_bins, 0, step
        )
        return f0, features_heap, bins_heap, leaf_values
    chunk = segment_steps(
        rounds, bins.shape[0], _GB_ROW_ROUNDS_BUDGET, bins.shape[1]
    )
    heaps = []
    rounds_chunk = _gbt_rounds_runner()
    total_chunks = rounds // chunk
    # Crash resume (see ml/progress.py): margins + the heaps built so
    # far are enough to replay the remaining chunks bit-identically —
    # f0 is recomputed deterministically from y/weights above. The
    # artifact must match this call's chunking and hyperparameters on
    # top of the sink's rev/dtype/mesh key, else restart clean.
    scalars = {
        "chunk": chunk,
        "rounds": rounds,
        "max_depth": max_depth,
        "max_bins": max_bins,
        "step": float(np.asarray(step)),
    }
    start = 0
    sink = _progress.current_sink()
    if sink is not None:
        restored = sink.load("gbt")
        if restored is not None:
            done, arrays, saved = restored
            state = None
            if (
                all(saved.get(key) == scalars[key] for key in scalars)
                and 0 < done <= total_chunks
                and len(arrays) == 4
                and all(a.shape[0] == done * chunk for a in arrays[1:])
            ):
                state = _progress.device_restore(margins, [arrays[0]])
            if state is None:
                sink.discard()
            else:
                margins = state
                heaps.append(tuple(jnp.asarray(a) for a in arrays[1:]))
                start = done
                _progress.segments_skipped(done)
    for index in range(start, total_chunks):
        margins, features_heap, bins_heap, leaf_values = rounds_chunk(
            bins, y, weights, margins, max_depth, max_bins, chunk, step
        )
        heaps.append((features_heap, bins_heap, leaf_values))
        if sink is not None:
            sink.save(
                "gbt",
                index + 1,
                [np.asarray(margins)]
                + [
                    np.concatenate([np.asarray(h[i]) for h in heaps])
                    for i in range(3)
                ],
                scalars,
            )
    if len(heaps) == 1:
        features_heap, bins_heap, leaf_values = heaps[0]
    else:
        features_heap, bins_heap, leaf_values = (
            jnp.concatenate(parts) for parts in zip(*heaps)
        )
    return f0, features_heap, bins_heap, leaf_values


@partial(jax.jit, static_argnames=("max_depth",))
def _gbt_forward(X, f0, features_heap, thresholds_heap, leaf_values, step, max_depth):
    """Boosted margins, sequentially accumulated over rounds — like
    :func:`_ensemble_forward`, NOT a vmap over trees: the batched
    ``(rounds, rows)`` descend intermediates pad ~6x on TPU tile
    boundaries (25 GB at 20×10M rows); the scan keeps one margin
    vector and one round's gather live at a time."""

    def one_tree(margins, tree):
        features, thresholds, leaves = tree
        leaf = _descend(X, features, thresholds, max_depth)
        return margins + step * leaves[leaf], None

    margins, _ = jax.lax.scan(
        one_tree,
        jnp.full(X.shape[0], f0, jnp.float32),
        (features_heap, thresholds_heap, leaf_values),
    )
    p = jax.nn.sigmoid(margins)
    return jnp.stack([1 - p, p], axis=1)


class DecisionTreeClassifier:
    def __init__(
        self,
        max_depth: int = MAX_DEPTH,
        max_bins: int = MAX_BINS,
        mesh: Optional[Mesh] = None,
    ):
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.mesh = resolve_mesh(mesh)

    def fit(self, X: np.ndarray, y: np.ndarray) -> _TreeEnsembleModel:
        num_classes = infer_num_classes(y)
        thresholds = make_thresholds(X, self.max_bins)
        X_dev, y_dev, mask = prepare_xy(X, y, self.mesh)
        bins = apply_bins(X_dev, jnp.asarray(thresholds, jnp.float32))
        features_heap, bins_heap, leaf_probs = _dt_fit(
            bins,
            y_dev,
            mask.astype(jnp.float32),
            num_classes,
            self.max_depth,
            self.max_bins,
        )
        thresholds_heap = _heap_thresholds(
            features_heap, bins_heap, jnp.asarray(thresholds, jnp.float32)
        )
        return _TreeEnsembleModel(
            features_heap[None],
            thresholds_heap[None],
            leaf_probs[None],
            self.mesh,
            self.max_depth,
        )


class RandomForestClassifier:
    def __init__(
        self,
        num_trees: int = NUM_TREES,
        max_depth: int = MAX_DEPTH,
        max_bins: int = MAX_BINS,
        seed: int = 0,
        mesh: Optional[Mesh] = None,
    ):
        self.num_trees = num_trees
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.seed = seed
        self.mesh = resolve_mesh(mesh)

    def fit(self, X: np.ndarray, y: np.ndarray) -> _TreeEnsembleModel:
        num_classes = infer_num_classes(y)
        num_features = np.asarray(X).shape[1]
        subset_k = max(1, int(np.ceil(np.sqrt(num_features))))
        thresholds = make_thresholds(X, self.max_bins)
        X_dev, y_dev, mask = prepare_xy(X, y, self.mesh)
        bins = apply_bins(X_dev, jnp.asarray(thresholds, jnp.float32))
        features_heap, bins_heap, leaf_probs = _rf_fit(
            bins,
            y_dev,
            mask.astype(jnp.float32),
            jax.random.key(self.seed),
            num_classes,
            self.max_depth,
            self.max_bins,
            self.num_trees,
            subset_k,
            mesh=self.mesh,
        )
        thresholds_heap = _heap_thresholds(
            features_heap, bins_heap, jnp.asarray(thresholds, jnp.float32)
        )
        return _TreeEnsembleModel(
            features_heap, thresholds_heap, leaf_probs, self.mesh, self.max_depth
        )


class GBTModel(FittedModel):
    def __init__(self, f0, features_heap, thresholds_heap, leaf_values, step, mesh, max_depth):
        self.f0 = f0
        self.features_heap = features_heap
        self.thresholds_heap = thresholds_heap
        self.leaf_values = leaf_values
        self.step = step
        self.mesh = mesh
        self.max_depth = max_depth

    def _device_eval(self, X):
        X_dev, _, mask = prepare_xy(X, None, self.mesh)
        probs = _gbt_forward(
            X_dev,
            self.f0,
            self.features_heap,
            self.thresholds_heap,
            self.leaf_values,
            jnp.float32(self.step),
            self.max_depth,
        )
        return jnp.argmax(probs, axis=1), probs, mask


class GBTClassifier:
    """Binary gradient-boosted trees (MLlib GBTClassifier is binary-only)."""

    def __init__(
        self,
        rounds: int = GBT_ROUNDS,
        step: float = GBT_STEP,
        max_depth: int = MAX_DEPTH,
        max_bins: int = MAX_BINS,
        mesh: Optional[Mesh] = None,
    ):
        self.rounds = rounds
        self.step = step
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.mesh = resolve_mesh(mesh)

    def fit(self, X: np.ndarray, y: np.ndarray) -> GBTModel:
        if infer_num_classes(y) > 2:
            raise ValueError("GBTClassifier supports binary labels only (MLlib contract)")
        thresholds = make_thresholds(X, self.max_bins)
        X_dev, y_dev, mask = prepare_xy(X, y, self.mesh)
        bins = apply_bins(X_dev, jnp.asarray(thresholds, jnp.float32))
        f0, features_heap, bins_heap, leaf_values = _gbt_fit(
            bins,
            y_dev,
            mask.astype(jnp.float32),
            self.max_depth,
            self.max_bins,
            self.rounds,
            jnp.float32(self.step),
        )
        thresholds_heap = _heap_thresholds(
            features_heap, bins_heap, jnp.asarray(thresholds, jnp.float32)
        )
        return GBTModel(
            f0,
            features_heap,
            thresholds_heap,
            leaf_values,
            self.step,
            self.mesh,
            self.max_depth,
        )
