"""Shared estimator contract and device data preparation.

Design notes (TPU-first):

- Features travel as one dense ``(rows, features)`` float32 matrix —
  the MXU wants large batched matmuls, not per-row documents.
- Rows are padded to the mesh's ``data``-axis size and carried with a
  validity mask (static shapes; XLA compiles one program per padded
  shape). Every reduction in every estimator is mask-weighted, so
  padding never biases a fit.
- ``mesh=None`` means "all visible devices on the data axis" via the
  same code path: single-chip is just a 1-wide mesh.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from learningorchestra_tpu.parallel.mesh import default_mesh
from learningorchestra_tpu.parallel.sharding import shard_rows

# The model-builder request contract (reference:
# microservices/model_builder_image/model_builder.py:151-157,287-291).
CLASSIFIER_NAMES = ("lr", "dt", "rf", "gb", "nb")


def resolve_mesh(mesh: Optional[Mesh]) -> Mesh:
    return mesh if mesh is not None else default_mesh()


# Multiplier on every per-estimator segment budget, read ONCE at import:
# per-request env reads could desynchronize SPMD dispatch counts across
# a multi-host mesh, so the knob is process-lifetime constant and must
# be set identically on every host (deploy/README.md env contract).
try:
    _PROGRAM_BUDGET_SCALE = float(
        # lo: allow[LO305] module-level read-once by design (see above)
        os.environ.get("LO_PROGRAM_ROW_STEPS", "1") or "1"
    )
except ValueError as error:
    raise ValueError(
        "LO_PROGRAM_ROW_STEPS must be a number, got "
        # lo: allow[LO305] error-message echo of the same knob
        f"{os.environ.get('LO_PROGRAM_ROW_STEPS')!r}"
    ) from error


def largest_divisor(total: int, cap: int, multiple_of: int = 1) -> int:
    """Largest divisor of ``total`` that is <= ``cap`` and a multiple of
    ``multiple_of``; falls back to ``multiple_of`` (assumed to divide
    ``total``) when no divisor fits under the cap."""
    best = 0
    for candidate in range(multiple_of, total + 1, multiple_of):
        if total % candidate == 0 and candidate <= cap:
            best = candidate
    return best or multiple_of


def segment_steps(
    total: int, rows: int, row_steps_budget: float, features: int = 16
) -> int:
    """Steps per device program so one XLA execution stays short.

    Iterative fits (L-BFGS iterations, boosting rounds, forest trees)
    are dispatched as a handful of medium programs instead of one long
    one: remotely-attached chips (and any fleet runtime with an
    execution watchdog) kill single executions that run for minutes —
    observed as "TPU worker process crashed" at ~2 min on a tunneled
    v5e for a 100-iteration 10M-row L-BFGS scan — and shorter programs
    also bound how much work a preempted job loses. ``row_steps_budget``
    is the per-program budget in row*steps at a 16-feature reference
    width (per-step cost scales with the feature count for both matmul
    and histogram passes, so ``features`` rescales the budget); the
    result is the largest divisor of ``total`` within budget, so every
    segment has the same static shape and compiles exactly once.
    ``LO_PROGRAM_ROW_STEPS`` multiplies all budgets (e.g. raise it on
    directly-attached chips without an execution watchdog); it is read
    once per process so every host of a multi-host mesh computes the
    same segmentation.
    """
    row_steps_budget *= _PROGRAM_BUDGET_SCALE
    if total <= 1 or rows <= 0:
        return max(total, 1)
    cost_rows = rows * max(features, 1) / 16
    target = max(1, int(row_steps_budget / cost_rows))
    if target >= total:
        return total
    return largest_divisor(total, target)


class DeviceMatrix:
    """A feature matrix already padded + row-sharded on the mesh.

    The builder shards the shared test/eval matrices ONCE and every
    classifier predicts against the same device buffers —
    ``prepare_xy`` passes them straight through, so N models cost one
    host→device transfer, not N (the tail the reference pays per
    evaluator, model_builder.py:205-224)."""

    __slots__ = ("data", "mask", "rows", "mesh")

    def __init__(self, data: jax.Array, mask: jax.Array, rows: int, mesh: Mesh):
        self.data = data
        self.mask = mask
        self.rows = rows
        self.mesh = mesh

    def __len__(self) -> int:
        return self.rows


def shard_matrix(X: np.ndarray, mesh: Optional[Mesh] = None) -> DeviceMatrix:
    """Pad + row-shard a feature matrix once, for reuse across models."""
    mesh = resolve_mesh(mesh)
    X = np.asarray(X)
    X_dev, mask = shard_rows(X, mesh, dtype=np.float32)
    return DeviceMatrix(X_dev, mask, len(X), mesh)


class DeviceLabels:
    """A label vector already padded + row-sharded, with its class count
    captured host-side (the scatter in the device metrics needs a static
    bound). Shared across classifier threads like :class:`DeviceMatrix`."""

    __slots__ = ("data", "num_classes", "mesh")

    def __init__(self, data: jax.Array, num_classes: int, mesh: Mesh):
        self.data = data
        self.num_classes = num_classes
        self.mesh = mesh


def shard_labels(y: np.ndarray, mesh: Optional[Mesh] = None) -> DeviceLabels:
    mesh = resolve_mesh(mesh)
    y = np.asarray(y)
    y_dev, _ = shard_rows(y, mesh, dtype=np.int32)
    return DeviceLabels(y_dev, infer_num_classes(y), mesh)


def prepare_xy(
    X, y: Optional[np.ndarray], mesh: Mesh
) -> tuple[jax.Array, Optional[jax.Array], jax.Array]:
    """Pad + row-shard features (float32), labels (int32) and the
    validity mask over the mesh's data axis. A :class:`DeviceMatrix`
    sharded on the same mesh passes through without any transfer."""
    if isinstance(X, DeviceMatrix):
        if X.mesh is mesh:
            y_dev = None
            if y is not None:
                y_dev, _ = shard_rows(np.asarray(y), mesh, dtype=np.int32)
            return X.data, y_dev, X.mask
        # mesh mismatch: fall back through host memory
        X = np.asarray(jax.device_get(X.data))[: X.rows]
    X_dev, mask = shard_rows(np.asarray(X), mesh, dtype=np.float32)
    y_dev = None
    if y is not None:
        y_dev, _ = shard_rows(np.asarray(y), mesh, dtype=np.int32)
    return X_dev, y_dev, mask


def infer_num_classes(y: np.ndarray) -> int:
    """Labels are class indices 0..C-1 (the MLlib convention: label is a
    double holding an index, reference docs/model_builder.md)."""
    return int(np.max(y)) + 1 if len(y) else 1


class FittedModel:
    """Base for fitted models: numpy (or :class:`DeviceMatrix`) in,
    numpy out, device inside.

    Subclasses implement ``_device_eval(X) -> (labels, probs, mask)``
    (all padded, device-resident); the base class provides host-facing
    predict/evaluate built on it with the minimum number of device
    round trips — one forward pass serves labels, probabilities AND
    on-device metrics (the reference runs two JVM evaluators plus a
    collect over the same predictions, model_builder.py:205-247)."""

    mesh: "Mesh"

    def _device_eval(self, X):
        raise NotImplementedError

# Every current model's labels are argmax(probs) (softmax/posterior/
# ensemble-mean are all argmax-monotonic), so the host can rebuild them
# from the probabilities and the label buffer never has to travel.
    labels_from_probs = True

    def _transfer(
        self, labels, probs, n: int, scalars: tuple = ()
    ) -> tuple[np.ndarray, np.ndarray, tuple]:
        """ONE blocking device→host transfer of a forward pass, plus any
        ``scalars`` batched into the same trip — transfers on a remote
        chip are latency-bound, so every entry point funnels through
        here. Labels are rebuilt host-side when they are argmax(probs)
        (``labels_from_probs``), so the label buffer never travels.
        Multi-host arrays gather via ``fetch``. The blocking transfer is
        a ``d2h`` span in the active trace (a no-op outside one), so the
        device→host tail shows up in ``/jobs/<name>/trace`` next to the
        ``h2d`` spans the data plane emits."""
        from learningorchestra_tpu.telemetry import profile as _profile
        from learningorchestra_tpu.telemetry import span as _span

        with _span("d2h:predictions", rows=n):
            if jax.process_count() > 1:
                from learningorchestra_tpu.parallel.multihost import fetch

                probs_np = np.asarray(fetch(probs))[:n]
                labels_np = (
                    np.argmax(probs_np, axis=1)
                    if self.labels_from_probs
                    else np.asarray(fetch(labels))[:n]
                )
                fetched = jax.device_get(tuple(scalars)) if scalars else ()
                _profile.account_d2h(probs_np.nbytes + labels_np.nbytes)
                return labels_np, probs_np, tuple(fetched)
            if self.labels_from_probs:
                out = jax.device_get((probs,) + tuple(scalars))
                probs_np = np.asarray(out[0])[:n]
                _profile.account_d2h(probs_np.nbytes)
                return np.argmax(probs_np, axis=1), probs_np, tuple(out[1:])
            out = jax.device_get((labels, probs) + tuple(scalars))
            _profile.account_d2h(out[0].nbytes + out[1].nbytes)
            return (
                np.asarray(out[0])[:n],
                np.asarray(out[1])[:n],
                tuple(out[2:]),
            )

    def _eval(self, X) -> tuple[np.ndarray, np.ndarray]:
        labels, probs, _ = self._device_eval(X)
        labels_np, probs_np, _ = self._transfer(labels, probs, len(X))
        return labels_np, probs_np

    def predict(self, X) -> np.ndarray:
        return self._eval(X)[0]

    def predict_proba(self, X) -> np.ndarray:
        return self._eval(X)[1]

    def predict_both(self, X) -> tuple[np.ndarray, np.ndarray]:
        """``(labels, probabilities)`` from ONE forward pass — calling
        predict then predict_proba would run the program twice."""
        return self._eval(X)

    def _device_metrics(self, X, y_true):
        """Dispatch forward + on-device confusion metrics; returns the
        unfetched ``(accuracy, weighted_f1)`` device scalars plus the
        forward outputs so callers can batch the host transfer."""
        from learningorchestra_tpu.ml.evaluation import masked_metrics
        from learningorchestra_tpu.parallel.sharding import shard_rows

        labels, probs, mask = self._device_eval(X)
        if isinstance(y_true, DeviceLabels):  # pre-sharded by the builder
            y_dev = y_true.data
            num_classes = max(int(probs.shape[-1]), y_true.num_classes)
        else:
            num_classes = max(int(probs.shape[-1]), infer_num_classes(y_true))
            y_dev, _ = shard_rows(np.asarray(y_true), self.mesh, dtype=np.int32)
        accuracy, weighted_f1 = masked_metrics(y_dev, labels, mask, num_classes)
        return accuracy, weighted_f1, labels, probs

    def evaluate(self, X, y_true: np.ndarray) -> tuple[float, float]:
        """``(accuracy, weighted_f1)`` with the confusion matrix built
        ON DEVICE from the forward pass — one dispatch, two scalars
        back; predictions never round-trip through host memory."""
        accuracy, weighted_f1, _, _ = self._device_metrics(X, y_true)
        # one transfer for both scalars
        accuracy, weighted_f1 = jax.device_get((accuracy, weighted_f1))
        return float(accuracy), float(weighted_f1)

    def evaluate_predict(
        self, X_eval, y_eval, X_test
    ) -> tuple[float, float, np.ndarray, np.ndarray]:
        """Metrics on the eval split AND ``(labels, probabilities)`` on
        the test split in ONE blocking device→host transfer — the
        builder's per-classifier tail collapsed from three round trips
        (evaluate scalars, predict labels, predict probs) to one. When
        ``X_test is X_eval`` (the documented product path evaluates on
        the test frame, reference model_builder.py:205-224 runs its two
        evaluators AND collect() over that same frame) the forward pass
        itself runs once."""
        accuracy, weighted_f1, labels_e, probs_e = self._device_metrics(
            X_eval, y_eval
        )
        if X_test is X_eval:
            labels_t, probs_t = labels_e, probs_e
        else:
            labels_t, probs_t, _ = self._device_eval(X_test)
        labels_np, probs_np, (accuracy, weighted_f1) = self._transfer(
            labels_t, probs_t, len(X_test), (accuracy, weighted_f1)
        )
        return float(accuracy), float(weighted_f1), labels_np, probs_np

    def device_state(self) -> list:
        """The fitted model's device arrays (for block_until_ready —
        honest fit-phase attribution under async dispatch)."""
        leaves = jax.tree.leaves(vars(self))
        return [leaf for leaf in leaves if isinstance(leaf, jax.Array)]


def make_classifier(name: str, mesh: Optional[Mesh] = None):
    """The classifier switcher (reference model_builder.py:151-157)."""
    from learningorchestra_tpu.ml.logistic import LogisticRegression
    from learningorchestra_tpu.ml.naive_bayes import NaiveBayes
    from learningorchestra_tpu.ml.trees import (
        DecisionTreeClassifier,
        GBTClassifier,
        RandomForestClassifier,
    )

    switcher = {
        "lr": LogisticRegression,
        "dt": DecisionTreeClassifier,
        "rf": RandomForestClassifier,
        "gb": GBTClassifier,
        "nb": NaiveBayes,
    }
    if name not in switcher:
        raise KeyError(name)
    return switcher[name](mesh=mesh)
