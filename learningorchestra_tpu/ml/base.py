"""Shared estimator contract and device data preparation.

Design notes (TPU-first):

- Features travel as one dense ``(rows, features)`` float32 matrix —
  the MXU wants large batched matmuls, not per-row documents.
- Rows are padded to the mesh's ``data``-axis size and carried with a
  validity mask (static shapes; XLA compiles one program per padded
  shape). Every reduction in every estimator is mask-weighted, so
  padding never biases a fit.
- ``mesh=None`` means "all visible devices on the data axis" via the
  same code path: single-chip is just a 1-wide mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from learningorchestra_tpu.parallel.mesh import default_mesh
from learningorchestra_tpu.parallel.sharding import shard_rows

# The model-builder request contract (reference:
# microservices/model_builder_image/model_builder.py:151-157,287-291).
CLASSIFIER_NAMES = ("lr", "dt", "rf", "gb", "nb")


def resolve_mesh(mesh: Optional[Mesh]) -> Mesh:
    return mesh if mesh is not None else default_mesh()


def prepare_xy(
    X: np.ndarray, y: Optional[np.ndarray], mesh: Mesh
) -> tuple[jax.Array, Optional[jax.Array], jax.Array]:
    """Pad + row-shard features (float32), labels (int32) and the
    validity mask over the mesh's data axis."""
    X_dev, mask = shard_rows(np.asarray(X), mesh, dtype=np.float32)
    y_dev = None
    if y is not None:
        y_dev, _ = shard_rows(np.asarray(y), mesh, dtype=np.int32)
    return X_dev, y_dev, mask


def infer_num_classes(y: np.ndarray) -> int:
    """Labels are class indices 0..C-1 (the MLlib convention: label is a
    double holding an index, reference docs/model_builder.md)."""
    return int(np.max(y)) + 1 if len(y) else 1


class FittedModel:
    """Base for fitted models: numpy in, numpy out, device inside."""

    def predict(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError


def make_classifier(name: str, mesh: Optional[Mesh] = None):
    """The classifier switcher (reference model_builder.py:151-157)."""
    from learningorchestra_tpu.ml.logistic import LogisticRegression
    from learningorchestra_tpu.ml.naive_bayes import NaiveBayes
    from learningorchestra_tpu.ml.trees import (
        DecisionTreeClassifier,
        GBTClassifier,
        RandomForestClassifier,
    )

    switcher = {
        "lr": LogisticRegression,
        "dt": DecisionTreeClassifier,
        "rf": RandomForestClassifier,
        "gb": GBTClassifier,
        "nb": NaiveBayes,
    }
    if name not in switcher:
        raise KeyError(name)
    return switcher[name](mesh=mesh)
