"""Fit-progress artifacts: segment-granular crash resume.

The fit loops are already segmented (L-BFGS segments in ml/logistic.py,
boosting-round chunks in ml/trees.py) so a process that dies mid-fit has
well-defined resume points — this module persists them. After each
segment the fit saves a compact artifact (params + optimizer state +
segment index) next to the model checkpoints, written with the same
atomic temp-file + ``os.replace`` discipline as ml/checkpoint.py, and
stamped with a devcache-style validation key (input-collection content
fingerprints, dtype policy, mesh signature — fingerprints, not revs:
revs reseed per boot and must survive a restart here). A restarted
build loads the artifact,
validates the key — ANY mismatch deletes it and restarts the fit from
scratch, never a silently-wrong model — and re-enters the segment loop
at the saved index. The segment programs re-seed their derived state
(value/grad, margins' f0) at entry, so a resumed sequence is
bit-identical to an uninterrupted one.

The sink rides a contextvar: ml/builder.py binds one per classifier
around ``classifier.fit`` and the fit loops pick it up with
:func:`current_sink` — zero signature churn through the model classes,
and library callers without a sink pay one contextvar read.

Persistence is best-effort: a full disk loses resume granularity, not
the fit. Telemetry: ``lo_build_segments_saved_total`` (artifact writes)
and ``lo_build_segments_skipped_total`` (segments NOT re-run thanks to
a restored artifact — the chaos drill's "resumed run performed only the
remaining work" evidence).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import traceback
import zipfile
from typing import Any, Callable, Optional

import numpy as np

_HEADER = "__progress__.json"

# One artifact per in-flight fit: <progress_dir>/<output_name>.progress
PROGRESS_SUFFIX = ".progress"

_SINK: contextvars.ContextVar[Optional["ProgressSink"]] = (
    contextvars.ContextVar("lo_progress_sink", default=None)
)


def progress_path(progress_dir: str, name: str) -> str:
    return os.path.join(progress_dir, name + PROGRESS_SUFFIX)


def _counter(name: str, help_text: str):
    from learningorchestra_tpu.telemetry import metrics as _metrics

    return _metrics.global_registry().counter(name, help_text)


def _saved_counter():
    return _counter(
        "lo_build_segments_saved_total",
        "Fit-progress artifacts persisted at segment boundaries",
    )


def _skipped_counter():
    return _counter(
        "lo_build_segments_skipped_total",
        "Fit segments skipped by resuming from a progress artifact",
    )


def collection_fingerprint(store, collection: str) -> str:
    """Restart-stable content identity for an input collection, for the
    artifact validation key. The store's in-memory collection revs
    (core/devcache.py) reseed from a random base every boot, so an
    artifact stamped with a rev could never validate on a restarted
    process — which is exactly the process that needs it. Hashing the
    documents themselves survives the WAL round trip: same content,
    same key. One streaming pass per build input, and the build is
    about to read every one of these rows anyway."""
    import hashlib

    digest = hashlib.sha256()
    for document in store.find(collection, {}):
        digest.update(
            json.dumps(document, sort_keys=True, default=repr).encode()
        )
        digest.update(b"\x00")
    return digest.hexdigest()[:16]


def segments_skipped(count: int) -> None:
    """Record ``count`` segments restored-not-recomputed. Called by the
    fit loops AFTER they accept a restored artifact (the sink cannot
    count at load time — the loop still validates segmentation/shape
    compatibility and may reject)."""
    if count > 0:
        _skipped_counter().inc(count)


@contextlib.contextmanager
def bind_sink(sink: Optional["ProgressSink"]):
    """Bind ``sink`` (or None) as the ambient progress sink for the
    fit running on this thread."""
    token = _SINK.set(sink)
    try:
        yield sink
    finally:
        _SINK.reset(token)


def current_sink() -> Optional["ProgressSink"]:
    return _SINK.get()


class ProgressSink:
    """One in-flight fit's progress artifact.

    ``meta`` is the validation key (JSON-safe dict: input content
    fingerprints, dtype policy, mesh signature — whatever makes a stale
    artifact detectable); :meth:`load` returns None unless the on-disk header
    matches it exactly. ``every`` throttles saves to every Nth segment
    (``LO_RESUME_EVERY_SEGMENTS``). ``on_segment`` fires after each
    durable save — the builder journals a ``progress`` event there.
    """

    def __init__(
        self,
        path: str,
        meta: dict,
        every: int = 1,
        on_segment: Optional[Callable[[int], None]] = None,
    ):
        self.path = path
        self.meta = meta
        self.every = max(1, int(every))
        self.on_segment = on_segment

    def load(self, kind: str) -> Optional[tuple[int, list, dict]]:
        """→ ``(segment, host_arrays, scalars)`` or None. A corrupt,
        wrong-kind, or stale-key artifact is DELETED and ignored: the
        fit restarts clean rather than resuming against data that
        changed underneath it."""
        if not os.path.isfile(self.path):
            return None
        try:
            with zipfile.ZipFile(self.path) as archive:
                header = json.loads(archive.read(_HEADER))
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            self.discard()
            return None
        if header.get("kind") != kind or header.get("meta") != self.meta:
            self.discard()
            return None
        try:
            data = np.load(self.path)
            arrays = [data[f"a{i}"] for i in range(int(header["leaves"]))]
            segment = int(header["segment"])
            scalars = dict(header.get("scalars") or {})
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            self.discard()
            return None
        return segment, arrays, scalars


    def save(
        self, kind: str, segment: int, arrays: list, scalars: dict
    ) -> None:
        """Persist segment state atomically (temp + ``os.replace``, the
        ml/checkpoint.py discipline: a reader never sees a partial
        archive, a crash mid-save never corrupts the published path).
        Segments not on the ``every`` grid are skipped. Best-effort: a
        failed write costs resume granularity, never the fit."""
        if segment % self.every != 0:
            return
        try:
            header = json.dumps(
                {
                    "kind": kind,
                    "meta": self.meta,
                    "segment": int(segment),
                    "leaves": len(arrays),
                    "scalars": scalars,
                }
            )
            tmp_path = self.path + ".tmp"
            # through a file object: np.savez given a NAME appends .npz
            with open(tmp_path, "wb") as handle:
                np.savez(
                    handle,
                    **{
                        f"a{i}": np.asarray(array)
                        for i, array in enumerate(arrays)
                    },
                )
            with zipfile.ZipFile(tmp_path, "a") as archive:
                archive.writestr(_HEADER, header)
            os.replace(tmp_path, self.path)
        except OSError:
            traceback.print_exc()
            return
        _saved_counter().inc()
        if self.on_segment is not None:
            try:
                self.on_segment(int(segment))
            except Exception:  # noqa: BLE001 — journaling is best-effort
                traceback.print_exc()

    def discard(self) -> None:
        """Remove the artifact (fit finished, or validation failed)."""
        try:
            os.remove(self.path)
        except OSError:
            pass


def device_restore(template: Any, host_arrays: list) -> Optional[Any]:
    """Rebuild a device pytree from saved host arrays: each leaf is
    ``device_put`` with the corresponding TEMPLATE leaf's sharding, so
    a restored fit lands on the same mesh layout the fresh init would
    have used. Returns None on any structure/shape/dtype mismatch (the
    caller restarts clean)."""
    import jax

    leaves, treedef = jax.tree.flatten(template)
    if len(leaves) != len(host_arrays):
        return None
    restored = []
    for leaf, host in zip(leaves, host_arrays):
        host = np.asarray(host)
        if tuple(host.shape) != tuple(leaf.shape) or host.dtype != leaf.dtype:
            return None
        restored.append(jax.device_put(host, leaf.sharding))
    return jax.tree.unflatten(treedef, restored)
