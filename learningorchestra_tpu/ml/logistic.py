"""Multinomial logistic regression, fitted with L-BFGS on device.

Replaces Spark MLlib's ``LogisticRegression`` (reference:
microservices/model_builder_image/model_builder.py:7,152 — MLlib also
optimizes with L-BFGS on the JVM). Defaults mirror MLlib: ``maxIter=100``,
``regParam=0.0``, fit-intercept, internal feature standardization.

TPU shape: the whole optimization is ONE jitted program — ``lax.scan``
over L-BFGS iterations, each iteration a fused (rows, features) ×
(features, classes) matmul on row-sharded data; the mean-loss reduction
is the only cross-chip collective and XLA inserts it from the sharding
annotations (no hand-written NCCL/allreduce as in torch-style ports).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.parallel.mesh import MODEL_AXIS, model_size
from learningorchestra_tpu.ml import progress as _progress
from learningorchestra_tpu.ml.base import (
    FittedModel,
    infer_num_classes,
    prepare_xy,
    resolve_mesh,
)


def _loss_fn(params, X, y, mask, l2):
    logits = X @ params["w"] + params["b"]
    log_probs = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(log_probs, y[:, None], axis=1)[:, 0]
    data_term = (nll * mask).sum() / mask.sum()
    return data_term + 0.5 * l2 * (params["w"] ** 2).sum()


# Hand-rolled L-BFGS (two-loop recursion, Armijo backtracking) instead
# of optax.lbfgs: profiled in round 4, the optax update chain cost
# ~20-25 ms of device time per iteration against a 1.5 ms full-data
# gradient pass at 1M×16 — the optimizer bookkeeping, not the math, was
# 90%+ of the LR fit (VERDICT r4 weak #6). The minimal implementation
# keeps the round-3/4 line-search decisions (Armijo instead of
# strong-Wolfe zoom: 18.9 s -> ~6 s in round 3; 4 backtracking halvings
# max, step floor 1/16: features are standardized so the unit step is
# almost always accepted — caps 3/4/5/15 measured identical losses to
# 5 decimals in round 4). One value_and_grad per ACCEPTED point (its
# gradient is reused as the next iteration's), plus loss-only passes
# for rejected trial steps. Quality is gated by the sklearn-oracle and
# Titanic-golden accuracy tests.
_LBFGS_MEMORY = 10
_BACKTRACK_STEPS = 4
_ARMIJO_C1 = 1e-4
# consecutive sub-tol loss deltas required before an early exit (see
# the history window in _fit)
_LR_STOP_DELTAS = 3


def _tree_dot(a, b):
    """Pytree inner product — one replicated scalar; on a sharded mesh
    XLA inserts the psums from the leaves' shardings."""
    return sum(
        jnp.vdot(x, y)
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def _tree_axpy(alpha, x, y):
    """``y + alpha * x`` leaf-wise (alpha a scalar)."""
    return jax.tree.map(lambda xi, yi: yi + alpha * xi, x, y)


def _tree_at(history, slot):
    return jax.tree.map(lambda h: h[slot], history)


def _lbfgs_state(params):
    """Curvature memory as fixed ``(m, *leaf.shape)`` ring buffers —
    static shapes, and every buffer inherits its leaf's sharding (the
    tensor-parallel class axis of W survives, unlike a flattened
    vector)."""
    history = jax.tree.map(
        lambda p: jnp.zeros((_LBFGS_MEMORY,) + p.shape, p.dtype), params
    )
    return {
        "S": history,
        "Y": jax.tree.map(jnp.copy, history),
        "rho": jnp.zeros((_LBFGS_MEMORY,), jnp.float32),
        "head": jnp.int32(0),       # next ring slot to write
        "filled": jnp.int32(0),     # valid pair count (<= m)
        "value": jnp.float32(0.0),  # f(x) at the current point
        "grad": jax.tree.map(jnp.zeros_like, params),
    }
    # value/grad are (re)seeded at each segment's entry (_fit_segment)
    # rather than lazily via a lax.cond inside the first iteration: the
    # cond read nicely but under the sweep module's vmap-across-jobs a
    # BATCHED cond executes both branches, paying a full extra
    # value_and_grad pass every L-BFGS step; one seeding pass per
    # segment (25+ iterations) costs ~4% instead


def _two_loop(state):
    """Search direction -H·g via the standard two-loop recursion over
    the ring buffers; unfilled slots are masked out (their alpha/beta
    contributions are zeroed)."""
    m = _LBFGS_MEMORY
    # newest-first order: slot (head-1-k) mod m
    order = jnp.mod(state["head"] - 1 - jnp.arange(m), m)
    valid = (jnp.arange(m) < state["filled"]).astype(jnp.float32)

    q = state["grad"]
    alphas = []
    for k in range(m):  # static unroll: m tiny
        s_k = _tree_at(state["S"], order[k])
        y_k = _tree_at(state["Y"], order[k])
        alpha = valid[k] * state["rho"][order[k]] * _tree_dot(s_k, q)
        q = _tree_axpy(-alpha, y_k, q)
        alphas.append(alpha)
    s_new = _tree_at(state["S"], order[0])
    y_new = _tree_at(state["Y"], order[0])
    y_dot = _tree_dot(y_new, y_new)
    gamma = jnp.where(
        (state["filled"] > 0) & (y_dot > 0.0),
        _tree_dot(s_new, y_new) / jnp.maximum(y_dot, 1e-20),
        1.0,
    )
    r = jax.tree.map(lambda qi: gamma * qi, q)
    for k in range(m - 1, -1, -1):  # oldest of the valid window first
        s_k = _tree_at(state["S"], order[k])
        y_k = _tree_at(state["Y"], order[k])
        beta = valid[k] * state["rho"][order[k]] * _tree_dot(y_k, r)
        r = _tree_axpy(alphas[k] - beta, s_k, r)
    return jax.tree.map(jnp.negative, r)


def _fit_segment_impl(params, opt_state, X, y, mask, iters: int, l2):
    """``iters`` L-BFGS iterations as ONE program, optimizer state in
    and out — chained by :func:`_fit` so arbitrarily long optimizations
    never exceed a single execution's wall-clock budget while the
    L-BFGS curvature memory carries across segment boundaries — the
    same iteration sequence as the former single-scan program."""
    loss = partial(_loss_fn, X=X, y=y, mask=mask, l2=l2)
    value_and_grad = jax.value_and_grad(loss)
    # seed (value, grad) at the segment's entry point: recomputing the
    # carried pair is redundant-but-identical work once per segment,
    # and it keeps every scan iteration branch-free (see _lbfgs_state)
    value0, grad0 = value_and_grad(params)
    opt_state = {**opt_state, "value": value0, "grad": grad0}

    def step(carry, _):
        x, state = carry
        # (value, grad) at x: seeded above for the first iteration,
        # then carried from each accepted point's value_and_grad below
        value, grad = state["value"], state["grad"]
        direction = _two_loop(state)
        slope = _tree_dot(grad, direction)
        # safeguard: a non-descent direction (stale curvature) falls
        # back to steepest descent
        descent = slope < 0.0
        direction = jax.tree.map(
            lambda d, g: jnp.where(descent, d, -g), direction, grad
        )
        slope = jnp.where(descent, slope, -_tree_dot(grad, grad))

        # Armijo backtracking as a while_loop that EXITS on acceptance —
        # standardized features accept the unit step almost always, so
        # the typical iteration pays ONE loss pass here (a static unroll
        # would pay all four trial passes every iteration), then ONE
        # value_and_grad at the accepted point (its gradient is reused
        # as the next iteration's).
        def ls_cond(carry):
            _, _, accepted, k = carry
            return (~accepted) & (k < _BACKTRACK_STEPS)

        def ls_body(carry):
            t, best_t, _, k = carry
            trial = loss(_tree_axpy(t, direction, x))
            ok = trial <= value + _ARMIJO_C1 * t * slope
            return (
                t * 0.5,
                jnp.where(ok, t, best_t),
                ok,
                k + 1,
            )

        _, best_t, _, _ = jax.lax.while_loop(
            ls_cond,
            ls_body,
            (
                jnp.float32(1.0),
                jnp.float32(1.0 / (1 << _BACKTRACK_STEPS)),  # step floor
                jnp.bool_(False),
                jnp.int32(0),
            ),
        )
        x_new = _tree_axpy(best_t, direction, x)
        value_new, grad_new = value_and_grad(x_new)

        # curvature pair; the update is skipped when s·y is not positive
        s = jax.tree.map(jnp.subtract, x_new, x)
        y_vec = jax.tree.map(jnp.subtract, grad_new, grad)
        sy = _tree_dot(s, y_vec)
        keep = sy > 1e-10
        head = state["head"]

        def ring_write(history, pair):
            return jax.tree.map(
                lambda h, p: h.at[head].set(jnp.where(keep, p, h[head])),
                history,
                pair,
            )

        state = {
            **state,
            "S": ring_write(state["S"], s),
            "Y": ring_write(state["Y"], y_vec),
            "rho": state["rho"].at[head].set(
                jnp.where(
                    keep,
                    1.0 / jnp.maximum(sy, 1e-20),
                    state["rho"][head],
                )
            ),
            "head": jnp.where(keep, (head + 1) % _LBFGS_MEMORY, head),
            "filled": jnp.where(
                keep,
                jnp.minimum(state["filled"] + 1, _LBFGS_MEMORY),
                state["filled"],
            ),
            "value": value_new,
            "grad": grad_new,
        }
        return (x_new, state), value

    (params, opt_state), losses = jax.lax.scan(
        step, (params, opt_state), length=iters
    )
    return params, opt_state, losses


# The shared, undonated program: what ml/sweep.py vmaps (donation inside
# an outer trace would be inert) and what CPU backends run.
_fit_segment = partial(jax.jit, static_argnames=("iters",))(_fit_segment_impl)


@lru_cache(maxsize=None)
def _donated_fit_segment():
    return jax.jit(
        _fit_segment_impl,
        static_argnames=("iters",),
        donate_argnums=(0, 1),
    )


def _fit_segment_runner():
    """The segment program :func:`_fit` chains: (params, opt_state) are
    DONATED — each segment's outputs rebind exactly those arguments, so
    XLA reuses their HBM across L-BFGS segments instead of holding two
    generations of curvature ring buffers live per boundary (the
    ``donate_argnums`` discipline, SNIPPETS.md [3]). X/y/mask are NOT
    donated: every segment re-reads them. CPU backends don't implement
    donation — they fall back to the shared undonated program, read as
    the MODULE attribute at call time (tests script `_fit_segment`;
    resolving lazily also means importing this module never initializes
    the device backend)."""
    if jax.default_backend() == "cpu":
        return _fit_segment
    return _donated_fit_segment()


# Per-program budget in row*iterations: ~18 iterations at 10M rows
# (~1.6 s/iteration on one tunneled v5e) keeps a segment under ~30 s.
_LR_ROW_ITERS_BUDGET = 180e6
# Convergence-check granularity: segments are capped at 25 iterations
# so the tol check below fires within a quarter of the default budget.
_LR_CHECK_ITERS = 25
# MLlib LogisticRegression default convergence tolerance (the reference
# engine stops when the objective stalls, model_builder.py:152 uses
# MLlib defaults); a fixed 100 iterations would do MORE work than the
# reference semantics.
_LR_TOL = 1e-6


def _plateaued(history: list[float], tol: float, window: int) -> bool:
    """True when the trailing ``window`` pre-step losses form a genuine
    plateau: EVERY consecutive delta is under the (relative) tolerance
    AND so is the total improvement across the window. A single
    floor-step Armijo iteration (step clamped to 1/16, objective barely
    moves once) produces one tiny delta inside an otherwise-descending
    run and must NOT stop the fit (ADVICE r5); ``window - 1``
    consecutive sub-tol deltas that also sum to nothing is a stall, not
    noise."""
    if len(history) < window:
        return False
    recent = history[-window:]
    threshold = tol * max(abs(recent[-1]), 1.0)
    return abs(recent[-1] - recent[0]) <= threshold and all(
        abs(recent[i + 1] - recent[i]) <= threshold
        for i in range(len(recent) - 1)
    )


def _fit(params, X, y, mask, max_iter: int, l2, tol: float = _LR_TOL):
    """L-BFGS fit in watchdog-safe segments (see base.segment_steps),
    stopping once the objective's per-iteration improvement stays under
    ``tol`` for several consecutive iterations (crossing segment
    boundaries) — MLlib's tol semantics made robust to a single stalled
    line-search step, checked at segment granularity so only one loss
    array crosses the wire per segment."""
    from learningorchestra_tpu.ml.base import largest_divisor, segment_steps

    if max_iter <= 0:  # MLlib allows maxIter=0: the initial model
        return params, jnp.zeros((0,), jnp.float32)
    iters = segment_steps(
        max_iter, X.shape[0], _LR_ROW_ITERS_BUDGET, X.shape[1]
    )
    if tol > 0:
        # cap segments for convergence-check granularity — but never
        # below 5 iterations (a prime max_iter would otherwise shatter
        # into per-iteration dispatches, each with a host sync)
        capped = largest_divisor(max_iter, min(iters, _LR_CHECK_ITERS))
        if capped >= min(iters, 5):
            iters = capped
    opt_state = _lbfgs_state(params)
    losses = []
    # Trailing pre-step losses across segment boundaries: convergence
    # requires EVERY delta in this window to be small, not just the
    # final two — a single floor-step Armijo iteration (step clamped to
    # 1/16, objective barely moves once) used to match the two-point
    # check and stop a fit mid-descent (ADVICE r5). Window of 3 deltas:
    # three consecutive sub-tol improvements is a plateau, one is noise.
    history: list[float] = []
    window = _LR_STOP_DELTAS + 1
    segment = _fit_segment_runner()
    total_segments = max_iter // iters
    # Crash resume: a sink bound by ml/builder.py means this fit should
    # persist per-segment progress and pick up any prior run's artifact.
    # The artifact must match this call's segmentation exactly (iters /
    # max_iter / l2) on top of the sink's own rev/dtype/mesh key — any
    # drift restarts the fit clean.
    sink = _progress.current_sink()
    start = 0
    if sink is not None:
        restored = sink.load("logistic")
        if restored is not None:
            done, arrays, scalars = restored
            state = None
            if (
                scalars.get("iters") == iters
                and scalars.get("max_iter") == max_iter
                and scalars.get("l2") == float(np.asarray(l2))
                and 0 < done <= total_segments
                and len(arrays) >= 1
            ):
                state = _progress.device_restore(
                    (params, opt_state), arrays[:-1]
                )
            if state is None:
                sink.discard()
            else:
                params, opt_state = state
                losses.append(jnp.asarray(arrays[-1]))
                history.extend(
                    float(v) for v in scalars.get("history") or []
                )
                del history[:-window]
                start = done
                _progress.segments_skipped(done)
    for index in range(start, total_segments):
        # plateau check at the TOP so a resumed fit that had already
        # converged (crash between progress save and checkpoint write)
        # stops exactly where the uninterrupted run did — running one
        # more segment here would break bit-identity
        if tol > 0 and _plateaued(history, tol, window):
            break
        params, opt_state, segment_losses = segment(
            params, opt_state, X, y, mask, iters, l2
        )
        losses.append(segment_losses)
        if tol > 0:
            # One host transfer either way: the losses come back as one
            # array.
            history.extend(float(v) for v in np.asarray(segment_losses))
            del history[:-window]
        if sink is not None:
            sink.save(
                "logistic",
                index + 1,
                [
                    np.asarray(leaf)
                    for leaf in jax.tree.leaves((params, opt_state))
                ]
                + [np.concatenate([np.asarray(l) for l in losses])],
                {
                    "iters": iters,
                    "max_iter": max_iter,
                    "l2": float(np.asarray(l2)),
                    "history": list(history),
                },
            )
    return params, (
        jnp.concatenate(losses) if len(losses) > 1 else losses[0]
    )


@jax.jit
def _masked_stats(X, mask):
    """Per-feature mean/scale from a row-sharded matrix + validity mask —
    the standardization step computed ON DEVICE, so a fit can start from
    per-host-fed shards without any host ever holding the full dataset.
    The reductions cross the data axis; XLA inserts the psums."""
    weights = mask.astype(X.dtype)
    count = weights.sum()
    mean = (X * weights[:, None]).sum(axis=0) / count
    var = ((X - mean) ** 2 * weights[:, None]).sum(axis=0) / count
    std = jnp.sqrt(var)
    return mean, jnp.where(std > 0, std, 1.0)


@jax.jit
def _standardize(X, mean, scale, weights):
    return ((X - mean) / scale) * weights[:, None]


@jax.jit
def _forward(params, X, mean, scale):
    logits = ((X - mean) / scale) @ params["w"] + params["b"]
    probs = jax.nn.softmax(logits)
    return jnp.argmax(logits, axis=1), probs


def scaler_stats(X: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """The host-side standardization scaler (float64 mean, std with
    zero-variance features pinned to 1) — ONE recipe shared by
    :meth:`LogisticRegression.fit` and the batched sweep prep
    (ml/sweep.py), so the solo and fused paths can never drift."""
    mean = np.asarray(X, np.float64).mean(axis=0)
    std = np.asarray(X, np.float64).std(axis=0)
    return mean, np.where(std > 0, std, 1.0)


class LogisticRegressionModel(FittedModel):
    def __init__(self, params, mean, scale, mesh: Mesh):
        self.params = params
        self.mean = mean
        self.scale = scale
        self.mesh = mesh

    def _device_eval(self, X):
        X_dev, _, mask = prepare_xy(X, None, self.mesh)
        labels, probs = _forward(self.params, X_dev, self.mean, self.scale)
        return labels, probs, mask


class LogisticRegression:
    def __init__(
        self,
        max_iter: int = 100,
        reg_param: float = 0.0,
        mesh: Optional[Mesh] = None,
        tol: float = _LR_TOL,
    ):
        self.max_iter = max_iter
        self.reg_param = reg_param
        self.tol = tol  # MLlib's user-settable convergence tolerance
        self.mesh = resolve_mesh(mesh)

    def fit(self, X: np.ndarray, y: np.ndarray) -> LogisticRegressionModel:
        num_classes = infer_num_classes(y)
        # Standardize for conditioning (MLlib standardizes internally
        # too); the scaler is part of the fitted model.
        mean, scale = scaler_stats(X)
        X_std = (np.asarray(X) - mean) / scale
        X_dev, y_dev, mask = prepare_xy(X_std, y, self.mesh)
        return self._fit_prepared(
            X_dev,
            y_dev,
            mask,
            num_classes,
            jnp.asarray(mean, jnp.float32),
            jnp.asarray(scale, jnp.float32),
        )

    def fit_sharded(
        self,
        X_dev: jax.Array,
        y_dev: jax.Array,
        mask: jax.Array,
        num_classes: int,
    ) -> LogisticRegressionModel:
        """Fit from already row-sharded device arrays — the per-host
        feeding entry: pair with ``parallel.shard_rows_local`` so on a
        multi-host mesh each host loads only its ``host_row_range`` row
        slice and NO process ever materializes the full dataset (the
        100M-row ingestion story; reference workers instead each read
        their Mongo partitions). Standardization happens on device from
        the shards (:func:`_masked_stats`); ``num_classes`` must be given
        since no host can scan all labels.
        """
        mean, scale = _masked_stats(X_dev, mask)
        X_std = _standardize(X_dev, mean, scale, mask.astype(X_dev.dtype))
        return self._fit_prepared(
            X_std,
            y_dev,
            mask,
            num_classes,
            mean.astype(jnp.float32),
            scale.astype(jnp.float32),
        )

    def _fit_prepared(
        self, X_dev, y_dev, mask, num_classes, mean, scale
    ) -> LogisticRegressionModel:
        # Tensor parallelism: the class dimension of W/b is sharded over
        # the mesh's model axis (init sharding propagates through the
        # whole L-BFGS scan), so X @ W partitions its output columns and
        # log_softmax's normalizer is the only model-axis collective.
        num_features = X_dev.shape[1]
        # Replicate when classes don't divide the axis (NamedSharding
        # needs even splits); the data axis still carries the rows. The
        # fallback is explicit: silent replication looked like tensor
        # parallelism without being it (VERDICT r2 weak #3).
        shardable = num_classes % model_size(self.mesh) == 0
        if not shardable and model_size(self.mesh) > 1:
            import warnings

            warnings.warn(
                f"LogisticRegression: {num_classes} classes do not divide "
                f"the model axis ({model_size(self.mesh)} devices); W/b "
                "replicate and the model axis adds no parallelism for "
                "this fit",
                stacklevel=3,
            )
        class_spec = P(None, MODEL_AXIS) if shardable else P()
        bias_spec = P(MODEL_AXIS) if shardable else P()
        params0 = {
            "w": jax.device_put(
                jnp.zeros((num_features, num_classes), jnp.float32),
                NamedSharding(self.mesh, class_spec),
            ),
            "b": jax.device_put(
                jnp.zeros((num_classes,), jnp.float32),
                NamedSharding(self.mesh, bias_spec),
            ),
        }
        params, _ = _fit(
            params0,
            X_dev,
            y_dev,
            mask.astype(jnp.float32),
            max_iter=self.max_iter,
            l2=jnp.float32(self.reg_param),
            tol=self.tol,
        )
        return LogisticRegressionModel(params, mean, scale, self.mesh)
