"""Multinomial logistic regression, fitted with L-BFGS on device.

Replaces Spark MLlib's ``LogisticRegression`` (reference:
microservices/model_builder_image/model_builder.py:7,152 — MLlib also
optimizes with L-BFGS on the JVM). Defaults mirror MLlib: ``maxIter=100``,
``regParam=0.0``, fit-intercept, internal feature standardization.

TPU shape: the whole optimization is ONE jitted program — ``lax.scan``
over L-BFGS iterations, each iteration a fused (rows, features) ×
(features, classes) matmul on row-sharded data; the mean-loss reduction
is the only cross-chip collective and XLA inserts it from the sharding
annotations (no hand-written NCCL/allreduce as in torch-style ports).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.parallel.mesh import MODEL_AXIS, model_size
from learningorchestra_tpu.ml.base import (
    FittedModel,
    infer_num_classes,
    prepare_xy,
    resolve_mesh,
)
from learningorchestra_tpu.parallel.multihost import fetch


def _loss_fn(params, X, y, mask, l2):
    logits = X @ params["w"] + params["b"]
    log_probs = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(log_probs, y[:, None], axis=1)[:, 0]
    data_term = (nll * mask).sum() / mask.sum()
    return data_term + 0.5 * l2 * (params["w"] ** 2).sum()


def _optimizer():
    # Backtracking (Armijo) line search instead of optax's default zoom:
    # zoom's strong-Wolfe bracketing re-evaluates loss+grad many times
    # per iteration, and on a 1M-row fit it was 94% of the wall-clock
    # (18.9 s -> ~6 s on one v5e chip, identical accuracy, monotone
    # convergence; measured in round 3). store_grad stays False: its
    # value-fn transpose uses a Python-float cotangent that trips a
    # dtype mismatch under x64 (optax linesearch.py:363), and the price
    # is just one value_and_grad per accepted step.
    #
    # max_backtracking_steps=4 (step floor 1/16): the fit standardizes
    # features, so the L-BFGS unit step is almost always accepted and
    # deeper brackets only pay while_loop time — measured in round 4 at
    # 1M×16 and on an ill-conditioned correlated/imbalanced set, caps
    # of 3/4/5/15 converge to identical loss (5 decimals) while the
    # wall-clock per 100-iteration fit is 3.4/4.1/6.5/7.0 s; the
    # sklearn-oracle and Titanic-golden accuracy tests gate quality.
    return optax.lbfgs(
        learning_rate=1.0,
        linesearch=optax.scale_by_backtracking_linesearch(
            max_backtracking_steps=4
        ),
    )


@jax.jit
def _opt_init(params):
    return _optimizer().init(params)


@partial(jax.jit, static_argnames=("iters",))
def _fit_segment(params, opt_state, X, y, mask, iters: int, l2):
    """``iters`` L-BFGS iterations as ONE program, optimizer state in
    and out — chained by :func:`_fit` so arbitrarily long optimizations
    never exceed a single execution's wall-clock budget while the
    L-BFGS curvature memory carries across segment boundaries — the
    same iteration sequence as the former single-scan program."""
    loss = partial(_loss_fn, X=X, y=y, mask=mask, l2=l2)
    optimizer = _optimizer()
    value_and_grad = jax.value_and_grad(loss)

    def step(carry, _):
        params, state = carry
        value, grad = value_and_grad(params)
        updates, state = optimizer.update(
            grad, state, params, value=value, grad=grad, value_fn=loss
        )
        params = optax.apply_updates(params, updates)
        return (params, state), value

    (params, opt_state), losses = jax.lax.scan(
        step, (params, opt_state), length=iters
    )
    return params, opt_state, losses


# Per-program budget in row*iterations: ~18 iterations at 10M rows
# (~1.6 s/iteration on one tunneled v5e) keeps a segment under ~30 s.
_LR_ROW_ITERS_BUDGET = 180e6
# Convergence-check granularity: segments are capped at 25 iterations
# so the tol check below fires within a quarter of the default budget.
_LR_CHECK_ITERS = 25
# MLlib LogisticRegression default convergence tolerance (the reference
# engine stops when the objective stalls, model_builder.py:152 uses
# MLlib defaults); a fixed 100 iterations would do MORE work than the
# reference semantics.
_LR_TOL = 1e-6


def _fit(params, X, y, mask, max_iter: int, l2, tol: float = _LR_TOL):
    """L-BFGS fit in watchdog-safe segments (see base.segment_steps),
    stopping once the objective improves by less than ``tol`` across a
    whole segment — MLlib's tol semantics at segment granularity (at
    most one segment of extra iterations vs a per-iteration check, and
    only one scalar crosses the wire per segment)."""
    from learningorchestra_tpu.ml.base import largest_divisor, segment_steps

    if max_iter <= 0:  # MLlib allows maxIter=0: the initial model
        return params, jnp.zeros((0,), jnp.float32)
    iters = segment_steps(
        max_iter, X.shape[0], _LR_ROW_ITERS_BUDGET, X.shape[1]
    )
    if tol > 0:
        # cap segments for convergence-check granularity — but never
        # below 5 iterations (a prime max_iter would otherwise shatter
        # into per-iteration dispatches, each with a host sync)
        capped = largest_divisor(max_iter, min(iters, _LR_CHECK_ITERS))
        if capped >= min(iters, 5):
            iters = capped
    opt_state = _opt_init(params)
    losses = []
    previous = None
    for _ in range(max_iter // iters):
        params, opt_state, segment_losses = _fit_segment(
            params, opt_state, X, y, mask, iters, l2
        )
        losses.append(segment_losses)
        if tol <= 0:  # explicit "run every iteration"
            continue
        # The MOST RECENT per-iteration improvement, like Breeze's
        # per-iteration check (a segment-endpoint delta can stop early
        # on an oscillating objective whose endpoints happen to match).
        # One host transfer either way: the losses come back as one
        # array.
        segment_host = np.asarray(segment_losses)
        last = float(segment_host[-1])
        before_last = (
            float(segment_host[-2]) if len(segment_host) > 1 else previous
        )
        if before_last is not None and abs(before_last - last) <= (
            tol * max(abs(last), 1.0)
        ):
            break
        previous = last
    return params, (
        jnp.concatenate(losses) if len(losses) > 1 else losses[0]
    )


@jax.jit
def _masked_stats(X, mask):
    """Per-feature mean/scale from a row-sharded matrix + validity mask —
    the standardization step computed ON DEVICE, so a fit can start from
    per-host-fed shards without any host ever holding the full dataset.
    The reductions cross the data axis; XLA inserts the psums."""
    weights = mask.astype(X.dtype)
    count = weights.sum()
    mean = (X * weights[:, None]).sum(axis=0) / count
    var = ((X - mean) ** 2 * weights[:, None]).sum(axis=0) / count
    std = jnp.sqrt(var)
    return mean, jnp.where(std > 0, std, 1.0)


@jax.jit
def _standardize(X, mean, scale, weights):
    return ((X - mean) / scale) * weights[:, None]


@jax.jit
def _forward(params, X, mean, scale):
    logits = ((X - mean) / scale) @ params["w"] + params["b"]
    probs = jax.nn.softmax(logits)
    return jnp.argmax(logits, axis=1), probs


class LogisticRegressionModel(FittedModel):
    def __init__(self, params, mean, scale, mesh: Mesh):
        self.params = params
        self.mean = mean
        self.scale = scale
        self.mesh = mesh

    def _device_eval(self, X):
        X_dev, _, mask = prepare_xy(X, None, self.mesh)
        labels, probs = _forward(self.params, X_dev, self.mean, self.scale)
        return labels, probs, mask


class LogisticRegression:
    def __init__(
        self,
        max_iter: int = 100,
        reg_param: float = 0.0,
        mesh: Optional[Mesh] = None,
        tol: float = _LR_TOL,
    ):
        self.max_iter = max_iter
        self.reg_param = reg_param
        self.tol = tol  # MLlib's user-settable convergence tolerance
        self.mesh = resolve_mesh(mesh)

    def fit(self, X: np.ndarray, y: np.ndarray) -> LogisticRegressionModel:
        num_classes = infer_num_classes(y)
        # Standardize for conditioning (MLlib standardizes internally
        # too); the scaler is part of the fitted model.
        mean = np.asarray(X, np.float64).mean(axis=0)
        std = np.asarray(X, np.float64).std(axis=0)
        scale = np.where(std > 0, std, 1.0)
        X_std = (np.asarray(X) - mean) / scale
        X_dev, y_dev, mask = prepare_xy(X_std, y, self.mesh)
        return self._fit_prepared(
            X_dev,
            y_dev,
            mask,
            num_classes,
            jnp.asarray(mean, jnp.float32),
            jnp.asarray(scale, jnp.float32),
        )

    def fit_sharded(
        self,
        X_dev: jax.Array,
        y_dev: jax.Array,
        mask: jax.Array,
        num_classes: int,
    ) -> LogisticRegressionModel:
        """Fit from already row-sharded device arrays — the per-host
        feeding entry: pair with ``parallel.shard_rows_local`` so on a
        multi-host mesh each host loads only its ``host_row_range`` row
        slice and NO process ever materializes the full dataset (the
        100M-row ingestion story; reference workers instead each read
        their Mongo partitions). Standardization happens on device from
        the shards (:func:`_masked_stats`); ``num_classes`` must be given
        since no host can scan all labels.
        """
        mean, scale = _masked_stats(X_dev, mask)
        X_std = _standardize(X_dev, mean, scale, mask.astype(X_dev.dtype))
        return self._fit_prepared(
            X_std,
            y_dev,
            mask,
            num_classes,
            mean.astype(jnp.float32),
            scale.astype(jnp.float32),
        )

    def _fit_prepared(
        self, X_dev, y_dev, mask, num_classes, mean, scale
    ) -> LogisticRegressionModel:
        # Tensor parallelism: the class dimension of W/b is sharded over
        # the mesh's model axis (init sharding propagates through the
        # whole L-BFGS scan), so X @ W partitions its output columns and
        # log_softmax's normalizer is the only model-axis collective.
        num_features = X_dev.shape[1]
        # Replicate when classes don't divide the axis (NamedSharding
        # needs even splits); the data axis still carries the rows. The
        # fallback is explicit: silent replication looked like tensor
        # parallelism without being it (VERDICT r2 weak #3).
        shardable = num_classes % model_size(self.mesh) == 0
        if not shardable and model_size(self.mesh) > 1:
            import warnings

            warnings.warn(
                f"LogisticRegression: {num_classes} classes do not divide "
                f"the model axis ({model_size(self.mesh)} devices); W/b "
                "replicate and the model axis adds no parallelism for "
                "this fit",
                stacklevel=3,
            )
        class_spec = P(None, MODEL_AXIS) if shardable else P()
        bias_spec = P(MODEL_AXIS) if shardable else P()
        params0 = {
            "w": jax.device_put(
                jnp.zeros((num_features, num_classes), jnp.float32),
                NamedSharding(self.mesh, class_spec),
            ),
            "b": jax.device_put(
                jnp.zeros((num_classes,), jnp.float32),
                NamedSharding(self.mesh, bias_spec),
            ),
        }
        params, _ = _fit(
            params0,
            X_dev,
            y_dev,
            mask.astype(jnp.float32),
            max_iter=self.max_iter,
            l2=jnp.float32(self.reg_param),
            tol=self.tol,
        )
        return LogisticRegressionModel(params, mean, scale, self.mesh)
