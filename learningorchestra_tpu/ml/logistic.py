"""Multinomial logistic regression, fitted with L-BFGS on device.

Replaces Spark MLlib's ``LogisticRegression`` (reference:
microservices/model_builder_image/model_builder.py:7,152 — MLlib also
optimizes with L-BFGS on the JVM). Defaults mirror MLlib: ``maxIter=100``,
``regParam=0.0``, fit-intercept, internal feature standardization.

TPU shape: the whole optimization is ONE jitted program — ``lax.scan``
over L-BFGS iterations, each iteration a fused (rows, features) ×
(features, classes) matmul on row-sharded data; the mean-loss reduction
is the only cross-chip collective and XLA inserts it from the sharding
annotations (no hand-written NCCL/allreduce as in torch-style ports).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.parallel.mesh import MODEL_AXIS, model_size
from learningorchestra_tpu.ml.base import (
    FittedModel,
    infer_num_classes,
    prepare_xy,
    resolve_mesh,
)


def _loss_fn(params, X, y, mask, l2):
    logits = X @ params["w"] + params["b"]
    log_probs = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(log_probs, y[:, None], axis=1)[:, 0]
    data_term = (nll * mask).sum() / mask.sum()
    return data_term + 0.5 * l2 * (params["w"] ** 2).sum()


@partial(jax.jit, static_argnames=("max_iter",))
def _fit(params, X, y, mask, max_iter: int, l2):
    loss = partial(_loss_fn, X=X, y=y, mask=mask, l2=l2)
    optimizer = optax.lbfgs()
    value_and_grad = optax.value_and_grad_from_state(loss)

    def step(carry, _):
        params, state = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = optimizer.update(
            grad, state, params, value=value, grad=grad, value_fn=loss
        )
        params = optax.apply_updates(params, updates)
        return (params, state), value

    (params, _), losses = jax.lax.scan(
        step, (params, optimizer.init(params)), length=max_iter
    )
    return params, losses


@jax.jit
def _forward(params, X, mean, scale):
    logits = ((X - mean) / scale) @ params["w"] + params["b"]
    probs = jax.nn.softmax(logits)
    return jnp.argmax(logits, axis=1), probs


class LogisticRegressionModel(FittedModel):
    def __init__(self, params, mean, scale, mesh: Mesh):
        self.params = params
        self.mean = mean
        self.scale = scale
        self.mesh = mesh

    def _eval(self, X: np.ndarray):
        X_dev, _, mask = prepare_xy(X, None, self.mesh)
        labels, probs = _forward(self.params, X_dev, self.mean, self.scale)
        n = len(X)
        return np.asarray(labels)[:n], np.asarray(probs)[:n]

    def predict(self, X: np.ndarray) -> np.ndarray:
        return self._eval(X)[0]

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        return self._eval(X)[1]


class LogisticRegression:
    def __init__(
        self,
        max_iter: int = 100,
        reg_param: float = 0.0,
        mesh: Optional[Mesh] = None,
    ):
        self.max_iter = max_iter
        self.reg_param = reg_param
        self.mesh = resolve_mesh(mesh)

    def fit(self, X: np.ndarray, y: np.ndarray) -> LogisticRegressionModel:
        num_classes = infer_num_classes(y)
        # Standardize for conditioning (MLlib standardizes internally
        # too); the scaler is part of the fitted model.
        mean = np.asarray(X, np.float64).mean(axis=0)
        std = np.asarray(X, np.float64).std(axis=0)
        scale = np.where(std > 0, std, 1.0)
        X_std = (np.asarray(X) - mean) / scale
        X_dev, y_dev, mask = prepare_xy(X_std, y, self.mesh)
        # Tensor parallelism: the class dimension of W/b is sharded over
        # the mesh's model axis (init sharding propagates through the
        # whole L-BFGS scan), so X @ W partitions its output columns and
        # log_softmax's normalizer is the only model-axis collective.
        num_features = X_std.shape[1]
        # Replicate when classes don't divide the axis (NamedSharding
        # needs even splits); the data axis still carries the rows.
        shardable = num_classes % model_size(self.mesh) == 0
        class_spec = P(None, MODEL_AXIS) if shardable else P()
        bias_spec = P(MODEL_AXIS) if shardable else P()
        params0 = {
            "w": jax.device_put(
                jnp.zeros((num_features, num_classes), jnp.float32),
                NamedSharding(self.mesh, class_spec),
            ),
            "b": jax.device_put(
                jnp.zeros((num_classes,), jnp.float32),
                NamedSharding(self.mesh, bias_spec),
            ),
        }
        params, _ = _fit(
            params0,
            X_dev,
            y_dev,
            mask.astype(jnp.float32),
            max_iter=self.max_iter,
            l2=jnp.float32(self.reg_param),
        )
        return LogisticRegressionModel(
            params,
            jnp.asarray(mean, jnp.float32),
            jnp.asarray(scale, jnp.float32),
            self.mesh,
        )
