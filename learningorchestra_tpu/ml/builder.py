"""Model builder: the flagship pipeline — preprocess, fit N classifiers
concurrently, evaluate, persist predictions.

Reference behaviour (microservices/model_builder_image/model_builder.py:
133-247): load train+test dataframes, ``exec`` user preprocessing, fan
out one thread per requested classifier onto the shared Spark cluster
(FAIR scheduler), time the fit, evaluate weighted-F1/accuracy when an
evaluation split exists, then ``collect()`` predictions to the driver and
insert them row-by-row.

TPU-native differences: classifiers fit as jitted programs on the shared
device mesh (threads overlap host prep and keep the reference's
task-parallel shape, reference model_builder.py:94,159-175); predictions
are written back in batched columnar writes, not 1 RPC per row.
"""

from __future__ import annotations

import itertools
import os
import threading
import traceback
from concurrent.futures import ThreadPoolExecutor, wait
from typing import Callable, Optional

import numpy as np
from jax.sharding import Mesh

from learningorchestra_tpu.core.columns import Column
from learningorchestra_tpu.core.store import DocumentStore, ROW_ID
from learningorchestra_tpu.core.table import insert_columns_batched
from learningorchestra_tpu.frame.dataframe import DataFrame
from learningorchestra_tpu.frame.pyspark_compat import run_preprocessor
from learningorchestra_tpu.ml import progress as _progress
from learningorchestra_tpu.ml.base import CLASSIFIER_NAMES, make_classifier
from learningorchestra_tpu.sched import cancel as _cancel
from learningorchestra_tpu.sched import config as _sched_config
from learningorchestra_tpu.sched.cancel import JobCancelledError, check_cancelled
from learningorchestra_tpu.telemetry import tracing as _tracing
from learningorchestra_tpu.testing import faults as _faults
from learningorchestra_tpu.utils.dtypepolicy import dtype_policy
from learningorchestra_tpu.utils.profiling import PhaseTimer, trace

FEATURES_COL = "features"
LABEL_COL = "label"

# Guards the process-global JAX profiler (see build_model's trace note).
_TRACE_LOCK = threading.Lock()

# Serializes collective device dispatches on a single-process CPU
# backend (the KNOWN LATENT from PR 8, now guarded): with
# --xla_force_host_platform_device_count=N the "devices" are threads of
# one host pool, and XLA's CPU collective rendezvous can deadlock when
# two already-compiled collective programs execute concurrently — each
# program's participants grab part of the pool and wait for peers that
# the other program's participants are occupying. Real accelerator
# backends serialize dispatches through the device queue, and the
# scheduler's width-1 device class protects the product path; this lock
# protects direct library/test callers running concurrent builds. It is
# a no-op (never taken) off CPU or under multi-process SPMD.
_CPU_RENDEZVOUS_LOCK = threading.Lock()


def _collective_dispatch_guard():
    """The context manager for one collective dispatch+fetch: the CPU
    rendezvous lock when the backend is single-process CPU with
    virtual devices, else a free pass."""
    import contextlib

    import jax

    if (
        jax.process_count() == 1
        and jax.default_backend() == "cpu"
        and jax.local_device_count() > 1
    ):
        return _CPU_RENDEZVOUS_LOCK
    return contextlib.nullcontext()

# Capture directories are named from the JOB (dataset name + build
# sequence number), never the wall clock: this line once used
# ``int(time.time() * 1000)``, which on a multi-host mesh computes a
# DIFFERENT name on every process — the bug class that motivated the
# analyzer's LO102 broadcast-determinism rule (analysis/rules.py; the
# rule itself checks broadcast/dispatch payloads, not artifact paths).
# Tracing is also coordinator-only now, but the deterministic name
# keeps captures correlatable with their request across hosts and runs.
_TRACE_SEQ = itertools.count()


def _next_trace_dir(trace_root: str, test_filename: str) -> str:
    for seq in _TRACE_SEQ:
        path = os.path.join(trace_root, f"build_{test_filename}_{seq:03d}")
        try:
            # makedirs IS the reservation: an exists() probe would let
            # two server processes sharing LO_TRACE_DIR claim the same
            # name before either profiler writes it
            os.makedirs(path)
        except FileExistsError:  # taken by an earlier run or a peer
            continue
        return path
    raise AssertionError("unreachable: itertools.count is infinite")


def load_dataframe(store: DocumentStore, filename: str) -> DataFrame:
    """Dataset → DataFrame, metadata row/fields excluded (the reference
    drops the metadata document and its fields, model_builder.py:96-116).

    Reads through the device cache's host tier (core/devcache.py): the
    second build/predict over the same collection revision skips the
    wire read and frame decode — the reference re-reads Mongo per
    request instead (model_builder.py:96-116)."""
    from learningorchestra_tpu.core.devcache import dataset_table

    return DataFrame.from_table(dataset_table(store, filename))


class PredictionWriter:
    """Overlapped prediction write-back: one background thread drains
    per-classifier store writes while the NEXT classifier fits — the
    write tail leaves the build's critical path (the reference's
    untimed collect()+insert tail, model_builder.py:232-247, was ours
    too, just batched).

    One writer thread, not a pool: per-collection write order is
    preserved (rows before the metadata document — the contract
    write_documents states), and the shared store sees at most one bulk
    writer per build. ``barrier()`` is the end-of-job fence build_model
    runs before returning: every submitted write has finished (or its
    exception re-raises and fails the job), so the 201/finished
    contract and the persisted per-phase timings stay honest — the
    "write" phase is measured on the writer thread around the actual
    store calls."""

    def __init__(self):
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="lo-writeback"
        )
        self._futures: list = []
        self._lock = threading.Lock()

    def submit(self, fn, name: Optional[str] = None) -> None:
        context = _tracing.capture()

        def run():
            with _tracing.attach(context):
                return fn()

        with self._lock:
            self._futures.append((name, self._pool.submit(run)))

    def barrier(self) -> list:
        """Drain every pending write; returns ``[(name, exception)]``
        for the writes that failed instead of raising — a failed
        write-back fails THAT classifier's outcome (the partial-results
        contract), not the whole build."""
        self._pool.shutdown(wait=True)
        with self._lock:
            futures, self._futures = self._futures, []
        failures = []
        for name, future in futures:
            error = future.exception()
            if error is not None:
                failures.append((name, error))
        return failures


def _prediction_columns(predicted_df: DataFrame) -> dict[str, Column]:
    """Column-major view of a prediction frame as typed columns: every
    column except the assembled ``features`` vector (the reference also
    deletes ``rawPrediction``, which we never materialize),
    ``probability`` as a fixed-width ``vec`` column — the (rows, classes)
    matrix goes to the store as one float64 buffer and materializes as
    per-row plain lists only at document reads (reference
    model_builder.py:232-247 boxes it per row at driver collect time).
    Numeric columns hand their buffers to the store directly — no
    per-value float()/isnan loops (the tail the reference never fixed,
    model_builder.py:237-247)."""
    out: dict[str, Column] = {}
    for name in predicted_df.columns:
        if name == FEATURES_COL:
            continue
        column = predicted_df._column(name)
        if column.ndim > 1:
            out[name] = Column.from_numpy(
                np.asarray(column, dtype=np.float64)
            )
        elif column.dtype == object:
            out[name] = Column.from_values(column.tolist())
        else:
            out[name] = Column.from_numpy(
                np.asarray(column, dtype=np.float64)
            )
    return out


def train_one(
    store: DocumentStore,
    classificator_name: str,
    features_training: DataFrame,
    features_testing: DataFrame,
    features_evaluation: Optional[DataFrame],
    prediction_filename: str,
    mesh: Optional[Mesh] = None,
    write_outputs: bool = True,
    models_dir: Optional[str] = None,
    writer: Optional[PredictionWriter] = None,
    sink: Optional[_progress.ProgressSink] = None,
    on_durable: Optional[Callable[[dict], None]] = None,
) -> dict:
    """Fit + evaluate + persist one classifier (the reference's
    ``classificator_handler``, model_builder.py:178-230). Returns the
    prediction collection's metadata document — complete only after the
    build's write barrier when a ``writer`` is given (build_model hands
    each classifier the shared background writer so this classifier's
    store writes overlap the next one's fit; None = write synchronously,
    the contract for direct callers).

    ``write_outputs=False`` runs the full compute path (fit, evaluate,
    predict — all of which enter cross-host collectives and must run on
    every process of a multi-host mesh) but skips the store writes: SPMD
    worker processes pass False so the shared store sees exactly one
    writer (parallel/spmd.py).

    ``models_dir`` (or ``LO_MODELS_DIR``) persists the fitted model as a
    checkpoint named after the prediction collection, recorded in the
    metadata as ``model_checkpoint`` — the durability the reference
    lacks (its models die with the request, model_builder.py:232-247;
    SURVEY.md §5 flags this); :func:`predict_with_model` serves
    predictions from the artifact without refitting.

    ``sink`` makes the fit crash-resumable: it is bound as the ambient
    progress sink around the fit, so the segment loops persist progress
    artifacts (ml/progress.py). ``on_durable(metadata)`` fires once this
    classifier's outputs have durably landed (after the metadata insert
    — on the writer thread when writes overlap); build_model journals
    the per-classifier completion there."""
    output_name = f"{prediction_filename}_prediction_{classificator_name}"
    metadata = {
        "filename": output_name,
        "classificator": classificator_name,
        ROW_ID: 0,
    }
    timer = PhaseTimer()

    # Cooperative cancellation (DELETE /jobs/<name>): phase boundaries
    # are the abort points — no-op outside a scheduled job and on SPMD
    # worker processes (they carry no token; a coordinator-side abort
    # mid-collective-stream poisons the dispatcher like any mid-job
    # failure, and the supervisor restarts the runtime).
    check_cancelled()
    X_train = features_training.feature_matrix(FEATURES_COL)
    y_train = features_training.label_vector(LABEL_COL)

    classifier = make_classifier(classificator_name, mesh=mesh)
    _faults.fire(
        "builder.phase", phase="fit", classificator=classificator_name
    )
    # dtype rides the phase attrs so a trace says which LO_DTYPE_POLICY
    # (f32 vs bf16 feature matrices) produced these numbers
    with timer.phase("fit", rows=len(X_train), dtype=dtype_policy()):
        # the rendezvous guard serializes the whole dispatch+drain on a
        # single-process CPU backend (see _CPU_RENDEZVOUS_LOCK); a
        # no-op on real accelerators and under multi-process SPMD
        with _collective_dispatch_guard(), _progress.bind_sink(sink):
            model = classifier.fit(X_train, y_train)
            # drain the async dispatch queue inside the fit phase:
            # without this the device time lands on whichever later
            # call blocks first, and "evaluate"/"predict" report the
            # fit's tail (VERDICT r4 weak #5 — the phase numbers must
            # mean something)
            import jax

            jax.block_until_ready(model.device_state())
    metadata["fit_time"] = timer.timings["fit"]
    check_cancelled()  # phase boundary: fit done, before checkpoint/eval

    # None = "no caller preference" → env fallback; "" = explicitly
    # disabled. The distinction matters on a multi-host mesh: the SPMD
    # payload carries one resolved value to every process, so whether
    # the (collective) checkpoint gather runs is decided identically
    # everywhere — a per-host env fallback on "" would desynchronize.
    if models_dir is None:
        # free-form volume path: no numeric domain to preflight, and
        # lo: allow[LO305] — read here so every process resolves one
        models_dir = os.environ.get("LO_MODELS_DIR")  # lo: allow[LO301]
    if models_dir:
        from learningorchestra_tpu.ml.checkpoint import (
            checkpoint_path,
            gather_model,
            write_checkpoint,
        )

        artifact = checkpoint_path(models_dir, output_name)
        _faults.fire(
            "builder.phase",
            phase="checkpoint",
            classificator=classificator_name,
        )
        with timer.phase("checkpoint"):
            # the gather may be a cross-host collective (model-axis
            # sharded params): ALL processes enter it; only the
            # coordinator touches the filesystem
            with _collective_dispatch_guard():
                gathered = gather_model(model)
            if write_outputs:
                os.makedirs(models_dir, exist_ok=True)
                write_checkpoint(gathered, artifact)
        if write_outputs:
            metadata["model_checkpoint"] = artifact
            # publish-time serve warmup (compile plane): hand the serve
            # path the chance to precompile this artifact's fixed
            # dispatch shape before the first POST /predict asks for
            # it. Feature width rides along — tree checkpoints don't
            # record it. No-op unless a service registered a handler;
            # never raises into the build.
            from learningorchestra_tpu import compile as lo_compile

            lo_compile.checkpoint_published(
                artifact, features=int(X_train.shape[1])
            )

    prediction = None
    if features_evaluation is not None:
        # Sharded once, shared across all classifier threads (cached on
        # the frame) — N models, one host→device transfer. build_model
        # aliases features_evaluation to features_testing when their
        # content matches (the documented product path), so X_eval IS
        # X_test below and evaluate+predict share one forward pass and
        # one device→host transfer.
        X_eval = features_evaluation.device_matrix(FEATURES_COL, model.mesh)
        y_eval = features_evaluation.device_labels(LABEL_COL, model.mesh)
        X_test = features_testing.device_matrix(FEATURES_COL, model.mesh)
        _faults.fire(
            "builder.phase",
            phase="evaluate",
            classificator=classificator_name,
        )
        with timer.phase("evaluate", rows=features_evaluation.count()):
            # the collective eval is THE dispatch the PR 8 latent
            # deadlock fired on: two warm builds' evals interleaving
            # on the virtual-device CPU pool (regression-tested by
            # test_builder.test_two_warm_builds_complete_concurrently)
            with _collective_dispatch_guard():
                accuracy, weighted_f1, labels, probs = (
                    model.evaluate_predict(X_eval, y_eval, X_test)
                )
            prediction = (labels, probs)
            # Stored as strings, matching the reference's metadata document
            # (model_builder.py:223-224, values shown in docs/database_api.md).
            metadata["F1"] = str(weighted_f1)
            metadata["accuracy"] = str(accuracy)

    return _predict_and_write(
        store,
        model,
        features_testing,
        output_name,
        metadata,
        timer,
        write_outputs,
        prediction=prediction,
        writer=writer,
        on_durable=on_durable,
    )


def _predict_and_write(
    store: DocumentStore,
    model,
    features_testing: DataFrame,
    output_name: str,
    metadata: dict,
    timer: PhaseTimer,
    write_outputs: bool,
    prediction: Optional[tuple] = None,
    writer: Optional[PredictionWriter] = None,
    on_durable: Optional[Callable[[dict], None]] = None,
) -> dict:
    """Predict over the test frame and persist the prediction
    collection + its metadata document — the shared tail of
    :func:`train_one` and :func:`predict_with_model`.

    Written directly (not via write_documents): prediction metadata has
    no ``finished`` flag in the reference either (model_builder.py:
    191-196; document shape shown in docs/database_api.md:76-83). The
    bulk prediction write is timed as its own phase — it is the
    reference's wall-clock tail (driver collect() + row-wise inserts,
    model_builder.py:232-247) and the number the benchmark reports.

    With a ``writer``, the store writes run on the build's background
    writer thread overlapped with the next classifier's fit; the host
    column prep stays on THIS thread (it reads the predicted frame),
    the ``write`` phase is timed around the actual store calls on the
    writer thread, and the metadata document — including the timings —
    still lands strictly after the rows. build_model's barrier
    guarantees the returned metadata is complete before the job
    reports finished.
    """
    if prediction is None:  # no eval split: predict is its own pass
        X_test = features_testing.device_matrix(FEATURES_COL, model.mesh)
        _faults.fire(
            "builder.phase",
            phase="predict",
            classificator=metadata.get("classificator"),
        )
        with timer.phase("predict", rows=features_testing.count()):
            # one forward pass yields labels AND probabilities
            with _collective_dispatch_guard():
                prediction = model.predict_both(X_test)
    labels, probability = prediction
    predicted_df = features_testing.withColumn(
        "prediction", labels.astype(np.float64)
    ).withColumn("probability", probability)

    if not write_outputs:
        metadata["timings"] = timer.as_metadata()
        return metadata

    columns = _prediction_columns(predicted_df)
    write_rows = predicted_df.count()
    write_bytes = sum(
        int(column.resident_nbytes()) for column in columns.values()
    )

    def flush() -> None:
        _faults.fire(
            "builder.phase",
            phase="write",
            classificator=metadata.get("classificator"),
        )
        store.drop(output_name)
        with timer.phase("write", rows=write_rows, bytes=write_bytes):
            insert_columns_batched(store, output_name, columns)
        metadata["timings"] = timer.as_metadata()
        store.insert_one(output_name, metadata)
        # the metadata document is the durability proof (it lands
        # strictly after the rows): only now is this classifier's
        # completion journal-worthy
        if on_durable is not None:
            on_durable(metadata)

    if writer is None:
        flush()
    else:
        writer.submit(flush, metadata.get("classificator"))
    return metadata


def _alias_if_equal(
    features_evaluation: Optional[DataFrame], features_testing: DataFrame
) -> Optional[DataFrame]:
    """The documented preprocessor evaluates on the test frame
    (reference docs/model_builder.md: ``features_evaluation =
    assembler.transform(testing_df)``) but builds it as a SEPARATE
    transform, so the frames are distinct objects with identical
    content. Aliasing them lets the per-frame device cache share one
    host→device transfer and evaluate_predict share one forward pass.
    The content check is a host-side array compare — microseconds next
    to a transfer."""
    if features_evaluation is None or features_evaluation is features_testing:
        return features_evaluation
    try:
        eval_X = features_evaluation.feature_matrix(FEATURES_COL)
        test_X = features_testing.feature_matrix(FEATURES_COL)
        eval_y = features_evaluation.label_vector(LABEL_COL)
        test_y = features_testing.label_vector(LABEL_COL)
    except (KeyError, TypeError, ValueError):
        return features_evaluation
    if (
        eval_X.shape == test_X.shape
        and np.array_equal(eval_X, test_X)
        and np.array_equal(eval_y, test_y)
    ):
        return features_testing
    return features_evaluation


class _ResumedMemberFailure(RuntimeError):
    """A classifier the pre-crash run already journaled as permanently
    failed: the resumed build records the original error without
    re-running the member."""


def _fold_resume(resume: Optional[list]) -> dict[str, dict]:
    """Journaled ``progress`` events → per-classifier terminal status.
    Later events win (a ``failed`` member re-journaled ``finished`` by
    a later resume attempt is finished). Segment events carry no
    ``status`` and fold to nothing — the fits read their own progress
    artifacts, which hold strictly more than the journal line."""
    done: dict[str, dict] = {}
    for event in resume or []:
        name = event.get("classificator")
        status = event.get("status")
        if name and status in ("finished", "failed"):
            done[name] = {"status": status, "error": event.get("error")}
    return done


def _mesh_key(mesh: Optional[Mesh]) -> str:
    """The (resolved) mesh's structural signature as a string — the
    progress artifact's mesh-layout validation component."""
    from learningorchestra_tpu.core.devcache import mesh_signature
    from learningorchestra_tpu.ml.base import resolve_mesh

    return str(mesh_signature(resolve_mesh(mesh)))


def build_model(
    store: DocumentStore,
    training_filename: str,
    test_filename: str,
    preprocessor_code: str,
    classificators_list: list[str],
    mesh: Optional[Mesh] = None,
    write_outputs: bool = True,
    models_dir: Optional[str] = None,
    resume: Optional[list] = None,
) -> list[dict]:
    """The reference's ``build_model`` (model_builder.py:133-176):
    preprocess once, then one thread per classifier.

    ``resume`` is the journaled ``progress`` event list recovery hands
    a re-enqueued build (sched/recovery.py): classifiers it records as
    durably finished are skipped (their stored metadata is returned),
    ones it records as permanently failed stay failed without a re-run,
    and everything else refits — each fit picking up its own progress
    artifact, so only the remaining segments execute."""
    import jax

    unknown = [n for n in classificators_list if n not in CLASSIFIER_NAMES]
    if unknown:
        raise KeyError(f"invalid classificator names {unknown}")

    # Captured ONCE on the job worker thread (contextvars do not cross
    # the per-classifier pool below): the handle is how the build
    # journals per-classifier completions and attaches the partial-
    # results detail to its own record. None for library callers.
    from learningorchestra_tpu.core.jobs import current_job_handle

    handle = current_job_handle()

    # Span-per-stage: with phase spans from each train_one's PhaseTimer
    # these cover the build end to end, so /jobs/<name>/trace accounts
    # for (nearly) the whole job wall-clock — the 61%-dtype-cast class
    # of fact becomes a one-request diagnosis.
    _faults.fire("builder.phase", phase="load_data")
    with _tracing.span("load_data"):
        training_df = load_dataframe(store, training_filename)
        testing_df = load_dataframe(store, test_filename)
        _tracing.annotate(rows=training_df.count() + testing_df.count())
    _faults.fire("builder.phase", phase="preprocess")
    with _tracing.span("preprocess"):
        out = run_preprocessor(preprocessor_code, training_df, testing_df)
        _tracing.annotate(rows=out["features_training"].count())
        out["features_evaluation"] = _alias_if_equal(
            out["features_evaluation"], out["features_testing"]
        )

    # Multi-host SPMD: every process must dispatch the classifiers'
    # device programs in the SAME order, and thread scheduling is not
    # deterministic across hosts — serialize the fan-out. Single-host
    # keeps the reference's thread-per-classifier shape
    # (model_builder.py:159-175). LO_BUILD_WORKERS caps the fan-out:
    # N concurrent fits hold N models' device working sets at once, and
    # past ~1M rows per classifier that can exceed one chip's HBM (the
    # fits are device-queue-serialized anyway, so capping costs little
    # wall-clock; the 10M-row scale proof runs with LO_BUILD_WORKERS=1).
    # span(devices): the first jax.process_count() call of a process
    # initializes the device backend — ~100 ms on CPU, whole seconds on
    # a cold TPU runtime — a real, otherwise-invisible chunk of the
    # first build's wall-clock that belongs in the trace.
    with _tracing.span("devices"):
        multi_process = jax.process_count() > 1
    if multi_process:
        max_workers = 1
    else:
        max_workers = len(classificators_list) or 1
        # lo: allow[LO305] validated in place with its own error below
        cap = os.environ.get("LO_BUILD_WORKERS", "").strip()
        if cap:
            try:
                max_workers = max(1, min(max_workers, int(cap)))
            except ValueError:
                raise ValueError(
                    f"LO_BUILD_WORKERS must be an integer, got {cap!r}"
                ) from None
    # LO_TRACE_DIR: device-level tracing of the whole fan-out (fits,
    # predictions, writes) into a TensorBoard/Perfetto profile dir —
    # one capture per build, named after the test dataset. The JAX
    # profiler is process-global and non-reentrant, so a build that
    # overlaps an active capture runs untraced rather than failing:
    # tracing is observability, never a reason to 500 a request.
    # Coordinator-only (write_outputs), like every other host-side
    # artifact (parallel/spmd.py:19-21): worker processes run the same
    # compute but must not write to the trace volume.
    # lo: allow[LO301,LO305] free-form profile-dir path, per-build read
    trace_root = os.environ.get("LO_TRACE_DIR")
    trace_dir = None
    tracing = (
        trace_root and write_outputs and _TRACE_LOCK.acquire(blocking=False)
    )
    if tracing:
        try:
            trace_dir = _next_trace_dir(trace_root, test_filename)
        except OSError:  # unwritable/full trace volume: run untraced
            _TRACE_LOCK.release()
            tracing = False
    # Crash resume needs a durable home for progress artifacts: they
    # live beside the model checkpoints. Resolve the env fallback here
    # so the sink and train_one agree on one directory (train_one keeps
    # its own fallback for direct callers). Coordinator-only, single-
    # host only: a resumed in-process build cannot rejoin a multi-host
    # collective stream, so workers never persist progress.
    if models_dir is None:
        # lo: allow[LO305] same env fallback the sink and train_one use
        models_dir = os.environ.get("LO_MODELS_DIR")
    make_sink: Optional[Callable] = None
    if (
        write_outputs
        and models_dir
        and not multi_process
        and _sched_config.resume_enabled()
    ):
        # The devcache-style validation key: a progress artifact is
        # only resumable against the SAME input content, dtype policy,
        # and mesh layout that produced it — anything else is a clean
        # restart, never a silently-wrong model. Content fingerprints,
        # not collection revs: revs reseed per boot, and the restarted
        # process is the one that needs the artifact to validate.
        sink_meta = {
            "training_fp": _progress.collection_fingerprint(
                store, training_filename
            ),
            "test_fp": _progress.collection_fingerprint(
                store, test_filename
            ),
            "dtype_policy": dtype_policy(),
            "mesh": _mesh_key(mesh),
        }
        every = _sched_config.resume_every_segments()
        os.makedirs(models_dir, exist_ok=True)

        def make_sink(name: str) -> _progress.ProgressSink:
            output_name = f"{test_filename}_prediction_{name}"
            on_segment = None
            if handle is not None:
                def on_segment(seg: int, _name=name) -> None:
                    handle.progress(
                        classificator=_name, kind="segment", segment=seg
                    )
            return _progress.ProgressSink(
                _progress.progress_path(models_dir, output_name),
                dict(sink_meta),
                every=every,
                on_segment=on_segment,
            )

    try:
        return _build_model_traced(
            store,
            out,
            classificators_list,
            test_filename,
            mesh,
            write_outputs,
            models_dir,
            max_workers,
            trace_dir,
            resume_done=_fold_resume(resume),
            make_sink=make_sink,
            handle=handle,
        )
    finally:
        if tracing:
            _TRACE_LOCK.release()


def _build_model_traced(
    store,
    out,
    classificators_list,
    test_filename,
    mesh,
    write_outputs,
    models_dir,
    max_workers,
    trace_dir,
    resume_done=None,
    make_sink=None,
    handle=None,
) -> list[dict]:
    # contextvars don't cross pool threads: hand each worker the ambient
    # (trace, span) so its train span — and the PhaseTimer phases inside
    # — nest under the request/job trace, and the ambient cancel token
    # so DELETE /jobs/<name> reaches the per-classifier threads.
    context = _tracing.capture()
    cancel_token = _cancel.current_token()
    # Overlapped write-back (LO_WRITE_OVERLAP=0 restores synchronous
    # writes): coordinator-only host work — the writer thread touches
    # the store, never the device, so it cannot reorder SPMD dispatch.
    overlap = (
        # lo: allow[LO305] — per-build read: a mid-flight flip only
        # affects the NEXT build, never a writer already draining
        write_outputs
        and os.environ.get("LO_WRITE_OVERLAP", "1") != "0"  # lo: allow[LO305]
    )
    writer = PredictionWriter() if overlap else None
    resume_done = resume_done or {}

    def run_train(name: str) -> dict:
        with _tracing.attach(context), _cancel.bind(cancel_token):
            # a cancelled build stops launching classifiers: fits
            # already in flight run to their own next check inside
            # train_one, queued ones never start
            check_cancelled()
            sink = make_sink(name) if make_sink is not None else None
            prior = resume_done.get(name)
            if prior is not None and prior.get("status") == "failed":
                # journaled as permanently failed before the crash:
                # resume skips the member, keeping the original error
                if sink is not None:
                    sink.discard()
                raise _ResumedMemberFailure(
                    prior.get("error") or "failed before service restart"
                )
            if prior is not None and prior.get("status") == "finished":
                stored = store.find_one(
                    f"{test_filename}_prediction_{name}", {ROW_ID: 0}
                )
                if stored is not None:
                    # durably completed before the crash (the journal
                    # line lands only after the metadata insert): skip
                    # the refit, return the stored outcome
                    if sink is not None:
                        sink.discard()
                    return stored
                # journaled finished but the outputs are gone (dropped
                # collection): fall through and rebuild

            def durable(metadata, _name=name, _sink=sink) -> None:
                if handle is not None:
                    handle.progress(classificator=_name, status="finished")
                if _sink is not None:
                    _sink.discard()

            with _tracing.span(f"train:{name}", classificator=name):
                return train_one(
                    store,
                    name,
                    out["features_training"],
                    out["features_testing"],
                    out["features_evaluation"],
                    test_filename,
                    mesh,
                    write_outputs,
                    models_dir,
                    writer=writer,
                    sink=sink,
                    on_durable=durable,
                )

    try:
        with trace(trace_dir), ThreadPoolExecutor(
            max_workers=max_workers
        ) as pool:
            futures = [
                (name, pool.submit(run_train, name))
                for name in classificators_list
            ]
            wait([future for _, future in futures])
    finally:
        # End-of-job barrier: no build returns (or fails) with writes
        # still in flight; a failed write-back fails that MEMBER.
        write_failures = writer.barrier() if writer is not None else []
    return _collect_outcomes(
        classificators_list, futures, write_failures, handle
    )


def _collect_outcomes(
    classificators_list, futures, write_failures, handle
) -> list[dict]:
    """Fold per-classifier futures + write-back failures into the
    build's result — the partial-results contract: ONE failed member
    no longer fails the whole job. Outcomes:

    - all succeeded → the metadata list, as ever;
    - any cancelled → the cancellation re-raises (job CANCELLED);
    - all failed → the single member's exception re-raises verbatim
      (single-classifier builds keep their reference-parity 500
      bodies), several failures raise one aggregate;
    - mixed → the successes return, the job FINISHES, and the record
      carries ``detail.result = "finished_partial"`` with a per-name
      status map (surfaced by GET /jobs/<name> and the /wait body).

    Failed members are journaled (``status="failed"``) so a resumed
    run skips them instead of re-running a permanent failure."""
    succeeded: list[dict] = []
    errors: dict[str, BaseException] = {}
    cancelled: Optional[BaseException] = None
    write_failed = dict(write_failures)
    for name, future in futures:
        try:
            result = future.result()
        except JobCancelledError as interruption:
            cancelled = interruption
            continue
        except BaseException as error:  # noqa: BLE001 — folded below
            errors[name] = error
            continue
        if name in write_failed:
            # compute finished, but the overlapped write-back failed:
            # this member's outputs never landed
            errors[name] = write_failed[name]
            continue
        succeeded.append(result)
    if cancelled is not None:
        raise cancelled
    for name, error in errors.items():
        if isinstance(error, _ResumedMemberFailure):
            continue  # already journaled by the pre-crash run
        traceback.print_exception(type(error), error, error.__traceback__)
        if handle is not None:
            handle.progress(
                classificator=name,
                status="failed",
                error=_member_error(error),
            )
    if not errors:
        return succeeded
    statuses = {
        name: (
            {"status": "failed", "error": _member_error(errors[name])}
            if name in errors
            else {"status": "finished"}
        )
        for name in classificators_list
    }
    if not succeeded:
        if len(errors) == 1:
            raise next(iter(errors.values()))
        raise RuntimeError(
            "all classifiers failed: "
            + "; ".join(
                f"{name}: {_member_error(error)}"
                for name, error in errors.items()
            )
        )
    if handle is not None:
        handle.annotate(result="finished_partial", classifiers=statuses)
    return succeeded


def _member_error(error: BaseException) -> str:
    if isinstance(error, _ResumedMemberFailure):
        return str(error)  # already formatted by the pre-crash run
    return f"{type(error).__name__}: {error}"


def predict_with_model(
    store: DocumentStore,
    checkpoint_path: str,
    training_filename: str,
    test_filename: str,
    preprocessor_code: str,
    prediction_filename: str,
    mesh: Optional[Mesh] = None,
    write_outputs: bool = True,
) -> dict:
    """Serve predictions from a saved checkpoint — no refit.

    Loads the artifact :func:`train_one` persisted, re-runs the same
    preprocessor over the same (training, test) frames — the training
    frame is required because preprocessor state is derived from it
    (StringIndexer category order, assembler column lists, imputation
    stats); feeding the test frame in its place would silently permute
    or reshape features. Then predicts and writes the prediction
    collection in the same shape build_model produces. This is the
    resume path the reference cannot offer: its fitted models die with
    the request (model_builder.py:232-247)."""
    from learningorchestra_tpu.ml.checkpoint import load_model

    model = load_model(checkpoint_path, mesh=mesh)
    training_df = load_dataframe(store, training_filename)
    testing_df = load_dataframe(store, test_filename)
    out = run_preprocessor(preprocessor_code, training_df, testing_df)

    metadata = {
        "filename": prediction_filename,
        "model_checkpoint": checkpoint_path,
        ROW_ID: 0,
    }
    return _predict_and_write(
        store,
        model,
        out["features_testing"],
        prediction_filename,
        metadata,
        PhaseTimer(),
        write_outputs,
    )
