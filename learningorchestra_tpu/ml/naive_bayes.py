"""Multinomial naive Bayes — a single fused matmul fit.

Replaces Spark MLlib's ``NaiveBayes`` (reference:
microservices/model_builder_image/model_builder.py:13,156; MLlib default
``modelType="multinomial"``, ``smoothing=1.0``). Requires non-negative
features, like MLlib.

TPU shape: the entire fit is ``one_hot(y)ᵀ @ X`` — one (classes, rows) ×
(rows, features) matmul on the MXU — plus two log-normalizations. On a
row-sharded mesh the matmul's row contraction IS the cross-chip
reduction; XLA lowers it to a psum over ICI. This is the op the
reference spent 41.87 s of Spark JVM time on for 891 Titanic rows
(BASELINE.md).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from learningorchestra_tpu.ml.base import (
    FittedModel,
    infer_num_classes,
    prepare_xy,
    resolve_mesh,
)


@partial(jax.jit, static_argnames=("num_classes",))
def _fit(X, y, mask, num_classes: int, smoothing):
    one_hot = jax.nn.one_hot(y, num_classes, dtype=jnp.float32) * mask[:, None]
    class_feature_sums = one_hot.T @ X                      # (C, F) on the MXU
    class_counts = one_hot.sum(axis=0)                      # (C,)
    smoothed = class_feature_sums + smoothing
    theta = jnp.log(smoothed) - jnp.log(smoothed.sum(axis=1, keepdims=True))
    prior = jnp.log(class_counts) - jnp.log(mask.sum())
    return theta, prior


@jax.jit
def _forward(theta, prior, X):
    joint = X @ theta.T + prior                             # (N, C)
    probs = jax.nn.softmax(joint)
    return jnp.argmax(joint, axis=1), probs


class NaiveBayesModel(FittedModel):
    def __init__(self, theta, prior, mesh: Mesh):
        self.theta = theta
        self.prior = prior
        self.mesh = mesh

    def _device_eval(self, X):
        X_dev, _, mask = prepare_xy(X, None, self.mesh)
        labels, probs = _forward(self.theta, self.prior, X_dev)
        return labels, probs, mask


class NaiveBayes:
    def __init__(self, smoothing: float = 1.0, mesh: Optional[Mesh] = None):
        self.smoothing = smoothing
        self.mesh = resolve_mesh(mesh)

    def fit(self, X: np.ndarray, y: np.ndarray) -> NaiveBayesModel:
        X = np.asarray(X)
        if np.nanmin(X) < 0:
            raise ValueError(
                "NaiveBayes requires non-negative features (MLlib contract)"
            )
        num_classes = infer_num_classes(y)
        X_dev, y_dev, mask = prepare_xy(X, y, self.mesh)
        theta, prior = _fit(
            X_dev,
            y_dev,
            mask.astype(jnp.float32),
            num_classes=num_classes,
            smoothing=jnp.float32(self.smoothing),
        )
        return NaiveBayesModel(theta, prior, self.mesh)
