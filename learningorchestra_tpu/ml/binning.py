"""Feature binning: quantile thresholds + on-device bin assignment.

Trees on TPU want histogram-binned features: exact split search over raw
floats is data-dependent control flow, but binned split search is a dense
scatter/cumsum program with static shapes. Same trick Spark MLlib itself
uses (``maxBins=32`` default) and the reason its trees scale; here the
binning keeps every tree op on the MXU/VPU.

Bin semantics: ``bin b`` holds values ``thresholds[b-1] < x <=
thresholds[b]``; a split "at bin b" sends ``x <= thresholds[b]`` left, so
raw-feature prediction only needs the float threshold, never the bins.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

MAX_BINS = 32


def make_thresholds(X: np.ndarray, max_bins: int = MAX_BINS) -> np.ndarray:
    """Per-feature quantile thresholds, shape ``(features, max_bins - 1)``.

    Duplicate quantiles (constant-ish features) are harmless: empty bins
    simply never win a split. NaNs are ignored when computing quantiles
    and land in the last bin at assignment (searchsorted sends NaN right),
    a one-sided missing-value policy like LightGBM's default.
    """
    quantiles = np.linspace(0, 1, max_bins + 1)[1:-1]
    with np.errstate(all="ignore"):
        thresholds = np.nanquantile(np.asarray(X, np.float64), quantiles, axis=0).T
    return np.nan_to_num(thresholds, nan=np.inf)


@jax.jit
def apply_bins(X: jax.Array, thresholds: jax.Array) -> jax.Array:
    """Assign each value its bin index in ``[0, max_bins)``: one
    vmapped ``searchsorted`` per feature, on device.

    int8 result (when the bin count fits): the binned matrix is the
    tree fits' largest long-lived buffer, and TPU tiling pads the
    feature-minor dimension to the 128-lane boundary — at 10M×16 an
    int32 binned matrix occupies ~5 GB of HBM after padding, int8 ~1.3
    GB. Index arithmetic downstream promotes to int32 as needed.
    """

    def one_feature(column, feature_thresholds):
        return jnp.searchsorted(feature_thresholds, column, side="left")

    bins = jax.vmap(one_feature, in_axes=(1, 0), out_axes=1)(X, thresholds)
    max_bins = thresholds.shape[1] + 1
    return bins.astype(jnp.int8 if max_bins <= 127 else jnp.int32)
