"""Sharding helpers: rows over the ``data`` axis, replication, padding.

Replaces the reference's RDD partitioning (reference:
microservices/projection_image/projection.py:104-111 reads a Mongo
collection as Spark partitions). A table's row dimension is sharded over
the mesh's ``data`` axis with ``jax.device_put``; XLA then inserts ICI
collectives for any cross-shard reduction instead of a shuffle.

TPU note: row counts are padded to a multiple of the data-axis size
(static shapes — XLA compiles one program per padded shape, and
estimators carry an explicit validity mask rather than using dynamic
shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.parallel.mesh import DATA_AXIS


def pad_rows(array: np.ndarray, multiple: int) -> tuple[np.ndarray, np.ndarray]:
    """Pad axis 0 to a multiple; returns (padded, validity mask)."""
    n = array.shape[0]
    padded_n = ((n + multiple - 1) // multiple) * multiple
    mask = np.zeros(padded_n, dtype=bool)
    mask[:n] = True
    if padded_n == n:
        return array, mask
    pad_width = [(0, padded_n - n)] + [(0, 0)] * (array.ndim - 1)
    return np.pad(array, pad_width), mask


def row_sharded(mesh: Mesh) -> NamedSharding:
    """Rows over ``data``, everything else replicated."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_rows(
    array: np.ndarray, mesh: Mesh, dtype=None
) -> tuple[jax.Array, jax.Array]:
    """Pad + device_put an array row-sharded over the mesh.

    Returns ``(device_array, device_mask)`` where the boolean mask marks
    real (non-padding) rows; both are sharded identically so masked
    reductions stay local until the final psum.
    """
    n_shards = mesh.shape[DATA_AXIS]
    padded, mask = pad_rows(np.asarray(array), n_shards)
    if dtype is not None:
        padded = padded.astype(dtype)
    sharding = row_sharded(mesh)
    return (
        jax.device_put(padded, sharding),
        jax.device_put(mask, sharding),
    )


def put_replicated(value, mesh: Mesh) -> jax.Array:
    return jax.device_put(jnp.asarray(value), replicated(mesh))
