"""Sharding helpers: rows over the ``data`` axis, replication, padding.

Replaces the reference's RDD partitioning (reference:
microservices/projection_image/projection.py:104-111 reads a Mongo
collection as Spark partitions). A table's row dimension is sharded over
the mesh's ``data`` axis with ``jax.device_put``; XLA then inserts ICI
collectives for any cross-shard reduction instead of a shuffle.

TPU note: row counts are padded to a multiple of the data-axis size
(static shapes — XLA compiles one program per padded shape, and
estimators carry an explicit validity mask rather than using dynamic
shapes). Padded counts are additionally BUCKETED to a quarter-octave
geometric grid (1/1.25/1.5/1.75 × powers of two) so nearby dataset
sizes share one padded shape: without the grid every distinct row count
recompiles every estimator program, which at 10M rows made XLA
compilation — not compute — the wall-clock (SCALE_r04: a 273 s NB fit
whose kernel runs in 27 ms). Worst-case padding waste is 25% of rows on
kernels that are memory-bound anyway; masks keep the math exact.
``LO_SHAPE_BUCKETS=0`` restores minimal padding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.parallel.mesh import DATA_AXIS
from learningorchestra_tpu.utils.dtypepolicy import dtype_policy
from learningorchestra_tpu.utils.shapegrid import bucket_count, grid_size


def bucket_rows(n: int) -> int:
    """Smallest quarter-octave grid value >= n: {4,5,6,7} x 2^k.

    THE padded-shape grid, shared with the serving MicroBatcher and the
    job coalescer — one copy of the math (utils/shapegrid.py) so the
    padding paths cannot drift apart.
    """
    return bucket_count(n)


def padded_row_count(n: int, multiple: int) -> int:
    """Rows after bucket-then-align padding — THE padded-shape rule.

    Shared by :func:`pad_rows` and the per-host feeder
    (``multihost.shard_rows_local``) so single-host and per-host-fed
    arrays land on identical global shapes.
    """
    # grid_size honors LO_SHAPE_BUCKETS (read once in utils/shapegrid —
    # the one copy of both the math and the knob)
    target = grid_size(n)
    return ((target + multiple - 1) // multiple) * multiple


def pad_rows(array: np.ndarray, multiple: int) -> tuple[np.ndarray, np.ndarray]:
    """Pad axis 0 to the bucketed grid; returns (padded, validity mask)."""
    n = array.shape[0]
    padded_n = padded_row_count(n, multiple)
    mask = np.zeros(padded_n, dtype=bool)
    mask[:n] = True
    if padded_n == n:
        return array, mask
    pad_width = [(0, padded_n - n)] + [(0, 0)] * (array.ndim - 1)
    return np.pad(array, pad_width), mask


def row_sharded(mesh: Mesh) -> NamedSharding:
    """Rows over ``data``, everything else replicated."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def policy_dtype(dtype):
    """The dtype a float buffer actually ships in under
    ``LO_DTYPE_POLICY``: ``bf16`` maps float requests to bfloat16 —
    halving the H2D transfer and the HBM-resident matrix — while int,
    bool, and mask buffers are never touched. Identity under ``f32``."""
    if dtype is None:
        return None
    if dtype_policy() == "bf16" and np.issubdtype(
        np.dtype(dtype), np.floating
    ):
        return jnp.bfloat16
    return dtype


def shard_rows(
    array: np.ndarray, mesh: Mesh, dtype=None
) -> tuple[jax.Array, jax.Array]:
    """Pad + device_put an array row-sharded over the mesh.

    Returns ``(device_array, device_mask)`` where the boolean mask marks
    real (non-padding) rows; both are sharded identically so masked
    reductions stay local until the final psum. Float ``dtype`` requests
    flow through :func:`policy_dtype`, so ``LO_DTYPE_POLICY=bf16``
    halves every feature-matrix transfer at THE H2D funnel without any
    caller opting in per site.
    """
    n_shards = mesh.shape[DATA_AXIS]
    padded, mask = pad_rows(np.asarray(array), n_shards)
    if dtype is not None:
        padded = padded.astype(policy_dtype(dtype))
    sharding = row_sharded(mesh)
    # Flight-recorder byte accounting at THE H2D funnel (every matrix/
    # label transfer in the product path comes through here): counts
    # into lo_h2d_bytes_total and the ambient span. Host-side only —
    # identical on every process, no collective, SPMD-safe.
    from learningorchestra_tpu.telemetry import profile

    profile.account_h2d(int(padded.nbytes) + int(mask.nbytes))
    return (
        jax.device_put(padded, sharding),
        jax.device_put(mask, sharding),
    )


def put_replicated(value, mesh: Mesh) -> jax.Array:
    return jax.device_put(jnp.asarray(value), replicated(mesh))
