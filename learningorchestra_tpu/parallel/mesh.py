"""Device mesh construction.

The framework's standard mesh has two axes:

- ``data`` — batch/row parallelism (the Spark-worker analogue; scaling
  this axis is the equivalent of ``docker service scale
  microservice_sparkworker=N`` in the reference, README.md:94);
- ``model`` — feature/class/tree parallelism for estimators whose inner
  dimension is worth sharding (tensor-parallel axis).

Single-chip runs get a 1×1 mesh and the same code path: everything is
written mesh-relative so multi-chip is a deployment knob, not a code
change.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    data: Optional[int] = None,
    model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(data, model)`` mesh over ``devices``.

    ``data=None`` takes every remaining device after ``model`` is carved
    out. Device order follows ``jax.devices()`` so the data axis maps to
    contiguous ICI neighbours on a TPU slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    if model < 1:
        raise ValueError(f"model axis must be >= 1, got {model}")
    if data is None:
        if len(devices) % model:
            raise ValueError(
                f"{len(devices)} devices not divisible by model={model}"
            )
        data = len(devices) // model
    if data * model > len(devices):
        raise ValueError(
            f"mesh {data}x{model} needs {data * model} devices, have {len(devices)}"
        )
    grid = np.array(devices[: data * model]).reshape(data, model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def default_mesh() -> Mesh:
    """All visible devices on the ``data`` axis."""
    return make_mesh()


def data_size(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS]


def model_size(mesh: Mesh) -> int:
    return mesh.shape[MODEL_AXIS]
