"""SPMD job dispatch: the coordinator receives a job, every host runs it.

A jitted program over a cross-host mesh must be entered by EVERY process
(SPMD) — one host cannot run a global-mesh fit alone; its devices would
wait forever in the first cross-host collective. The reference gets the
same property from Spark: the driver receives one REST request and the
cluster scheduler launches the job's stages on every worker
(reference: docker-compose.yml:123-163 master/worker overlay).

Here the coordinator (process 0) serves REST. Worker processes run
:meth:`SpmdDispatcher.run_worker_loop`, blocked in a broadcast. Each
compute job the coordinator accepts is serialized to JSON, broadcast
through the device runtime (``broadcast_one_to_all`` — a length prefix,
then the payload bytes), and then executed by all processes at once; the
collectives inside the job line up because every process enters the same
handler with the same arguments in the same order (the dispatcher lock
serializes jobs, and the broadcast itself is the cross-host barrier).

Host-side effects (store writes, PNG rendering) stay coordinator-only:
handlers receive ``coordinator=`` so workers run the compute path but
skip the writes — compute is global, the product surface is not.

Single-process runs skip all of this: ``submit`` calls the handler
directly.
"""

from __future__ import annotations

import json
import threading
import traceback
from typing import Any, Callable

import jax
import numpy as np

_SHUTDOWN_OP = "__shutdown__"


def _broadcast_json(obj: Any = None) -> Any:
    """Broadcast a JSON-serializable object from process 0 to all.

    Every process must call this at the same point; process 0 passes the
    object, the rest pass nothing and receive it. Variable length rides
    a two-phase broadcast: a scalar length, then the padded byte buffer.
    """
    from jax.experimental import multihost_utils

    payload = b""
    if jax.process_index() == 0:
        payload = json.dumps(obj).encode()
    length = multihost_utils.broadcast_one_to_all(
        np.array([len(payload)], np.int32)
    )
    n = int(length[0])
    buf = np.zeros(n, np.uint8)
    if jax.process_index() == 0:
        buf[:] = np.frombuffer(payload, np.uint8)
    buf = multihost_utils.broadcast_one_to_all(buf)
    return json.loads(bytes(buf).decode())


class SpmdDispatcher:
    """Routes compute jobs to every process in the multi-host runtime."""

    def __init__(self) -> None:
        self._handlers: dict[str, Callable[[dict], Any]] = {}
        self._lock = threading.Lock()

    def register(self, op: str, handler: Callable[[dict], Any]) -> None:
        self._handlers[op] = handler

    def submit(self, op: str, payload: dict) -> Any:
        """Run ``op`` on all hosts; returns the coordinator's result.

        Only the coordinator calls this (workers sit in
        :meth:`run_worker_loop`). The lock serializes jobs so the
        broadcast order — and therefore the collective order inside the
        handlers — is identical on every process.
        """
        handler = self._handlers[op]
        if jax.process_count() == 1:
            return handler(payload)
        with self._lock:
            _broadcast_json({"op": op, "payload": payload})
            return handler(payload)

    def run_worker_loop(self) -> None:
        """Worker-process main loop: execute broadcast jobs until
        shutdown. A failed job is fatal for the worker: it may have
        aborted between two collectives, and rejoining the loop with a
        desynchronized collective stream would hang or corrupt every
        later job — crashing instead tears down the distributed runtime
        so the coordinator surfaces an error (the reference's Spark
        stages likewise fail the job when an executor dies mid-stage).
        The deployment's restart policy brings the worker back."""
        while True:
            job = _broadcast_json()
            if job["op"] == _SHUTDOWN_OP:
                return
            try:
                self._handlers[job["op"]](job["payload"])
            except Exception:
                print(
                    f"[spmd worker {jax.process_index()}] job "
                    f"{job['op']!r} failed:\n{traceback.format_exc()}",
                    flush=True,
                )
                raise

    def shutdown_workers(self) -> None:
        if jax.process_count() > 1 and jax.process_index() == 0:
            with self._lock:
                _broadcast_json({"op": _SHUTDOWN_OP})
