"""SPMD job dispatch: the coordinator receives a job, every host runs it.

A jitted program over a cross-host mesh must be entered by EVERY process
(SPMD) — one host cannot run a global-mesh fit alone; its devices would
wait forever in the first cross-host collective. The reference gets the
same property from Spark: the driver receives one REST request and the
cluster scheduler launches the job's stages on every worker
(reference: docker-compose.yml:123-163 master/worker overlay).

Here the coordinator (process 0) serves REST. Worker processes run
:meth:`SpmdDispatcher.run_worker_loop`, blocked in a broadcast. Each
compute job the coordinator accepts is serialized to JSON, broadcast
through the device runtime (``broadcast_one_to_all`` — a length prefix,
then the payload bytes), and then executed by all processes at once; the
collectives inside the job line up because every process enters the same
handler with the same arguments in the same order (the dispatcher lock
serializes jobs, and the broadcast itself is the cross-host barrier).

Host-side effects (store writes, PNG rendering) stay coordinator-only:
handlers receive ``coordinator=`` so workers run the compute path but
skip the writes — compute is global, the product surface is not.

Single-process runs skip all of this: ``submit`` calls the handler
directly.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from typing import Any, Callable, Optional

import jax
import numpy as np

from learningorchestra_tpu.telemetry import metrics as _metrics
from learningorchestra_tpu.telemetry import tracing as _tracing

_SHUTDOWN_OP = "__shutdown__"
_PING_OP = "__ping__"


_METRICS_CACHE: Optional[dict] = None


def _registry_metrics():
    """Declare-once, cached: _broadcast_json runs on every dispatch AND
    every idle heartbeat ping — it must not take the registry lock for
    five re-declarations each time."""
    global _METRICS_CACHE
    if _METRICS_CACHE is not None:
        return _METRICS_CACHE
    registry = _metrics.global_registry()
    _METRICS_CACHE = _build_metrics(registry)
    return _METRICS_CACHE


def _build_metrics(registry):
    return {
        "jobs": registry.counter(
            "lo_spmd_jobs_total",
            "SPMD jobs dispatched, by op and outcome",
            labels=("op", "outcome"),
        ),
        "seconds": registry.histogram(
            "lo_spmd_job_duration_seconds",
            "Coordinator-observed SPMD job wall-clock",
            labels=("op",),
        ),
        "broadcast_bytes": registry.counter(
            "lo_spmd_broadcast_bytes_total",
            "Payload bytes broadcast from the coordinator",
        ),
        "watchdog_trips": registry.counter(
            "lo_spmd_watchdog_trips_total",
            "Jobs killed by the dispatch watchdog",
        ),
        "poisoned": registry.gauge(
            "lo_spmd_poisoned",
            "1 once the collective stream is desynchronized",
        ),
    }


class SpmdJobError(RuntimeError):
    """A multi-host compute job failed."""


class SpmdTimeoutError(SpmdJobError):
    """The job did not complete within the watchdog window — the usual
    cause is a worker process dying mid-job, leaving the coordinator
    blocked in a cross-host collective that can never complete."""


class SpmdRuntimePoisonedError(SpmdJobError):
    """A previous job timed out or failed mid-collective: the collective
    stream across processes is desynchronized and no further SPMD job
    can run safely. Recovery = restart the runtime (the supervisor's
    restart policy, deploy/stack.py — the analogue of Spark restarting
    the application when executors are lost)."""


def _broadcast_json(obj: Any = None) -> Any:
    """Broadcast a JSON-serializable object from process 0 to all.

    Every process must call this at the same point; process 0 passes the
    object, the rest pass nothing and receive it. Variable length rides
    a two-phase broadcast: a scalar length, then the padded byte buffer.
    """
    from jax.experimental import multihost_utils

    payload = b""
    if jax.process_index() == 0:
        payload = json.dumps(obj).encode()
        _registry_metrics()["broadcast_bytes"].inc(len(payload))
    length = multihost_utils.broadcast_one_to_all(
        np.array([len(payload)], np.int32)
    )
    n = int(length[0])
    buf = np.zeros(n, np.uint8)
    if jax.process_index() == 0:
        buf[:] = np.frombuffer(payload, np.uint8)
    buf = multihost_utils.broadcast_one_to_all(buf)
    return json.loads(bytes(buf).decode())


class SpmdDispatcher:
    """Routes compute jobs to every process in the multi-host runtime."""

    def __init__(self) -> None:
        self._handlers: dict[str, Callable[[dict], Any]] = {
            _PING_OP: lambda payload: None
        }
        self._lock = threading.Lock()
        self._poisoned: Optional[str] = None  # reason, once broken
        self._stop_heartbeat = threading.Event()
        self._metrics = _registry_metrics()

    def _poison_locked(self, reason: str) -> None:
        # every caller sits inside `with self._lock:` (submit's job
        # serialization) — the _locked suffix is the analyzer-checked
        # contract (LO203) that keeps it that way
        self._poisoned = reason
        self._metrics["poisoned"].set(1)

    def _observe(self, op: str, outcome: str, started: float) -> None:
        if op == _PING_OP:  # keepalives would swamp the job series
            return
        self._metrics["jobs"].labels(op, outcome).inc()
        self._metrics["seconds"].labels(op).observe(
            time.perf_counter() - started
        )

    def start_heartbeat(self, interval: Optional[float] = None) -> None:
        """Coordinator-side idle keepalive. A waiting worker is not
        passively parked: its pending ``_broadcast_json`` is a live
        collective that the transport TIMES OUT if the coordinator stays
        idle past the collective deadline (~30 s under gloo) — the
        worker then crashes and the supervisor restart-loops a healthy
        deployment. A no-op ping broadcast inside that window keeps the
        stream alive; pings also double as worker-liveness probes (a
        dead worker fails the ping, poisoning the dispatcher early
        instead of at the next real job)."""
        if jax.process_count() == 1 or jax.process_index() != 0:
            return
        if interval is None:
            # lo: allow[LO305] deliberate per-start read (test knob)
            interval = float(os.environ.get("LO_SPMD_HEARTBEAT_S", "10"))

        def beat() -> None:
            while not self._stop_heartbeat.wait(interval):
                if self._poisoned:
                    return
                try:
                    self.submit(_PING_OP, {}, timeout=max(interval * 4, 60))
                except SpmdJobError:
                    return  # poisoned: the supervisor owns recovery

        threading.Thread(target=beat, name="spmd-heartbeat", daemon=True).start()

    def register(self, op: str, handler: Callable[[dict], Any]) -> None:
        self._handlers[op] = handler

    def submit(
        self, op: str, payload: dict, timeout: Optional[float] = None
    ) -> Any:
        """Run ``op`` on all hosts; returns the coordinator's result.

        Only the coordinator calls this (workers sit in
        :meth:`run_worker_loop`). The lock serializes jobs so the
        broadcast order — and therefore the collective order inside the
        handlers — is identical on every process.

        Failure model (the coordinator half of the worker-death story —
        run_worker_loop documents the worker half): the job runs under a
        watchdog (``timeout``, default ``LO_SPMD_TIMEOUT_S``, 3600 s; 0
        disables). If a worker dies mid-job the coordinator blocks in a
        cross-host collective that can never complete — the watchdog
        turns that into :class:`SpmdTimeoutError` so the REST request
        FAILS with an error payload instead of hanging forever (the
        reference gets task retry from Spark and restart from swarm,
        docker-compose.yml:14-15,145). After a timeout or an in-job
        exception the dispatcher is POISONED: the collective stream is
        desynchronized, later submits fail fast with
        :class:`SpmdRuntimePoisonedError`, and the supervisor's restart
        policy rebuilds the runtime.
        """
        handler = self._handlers[op]
        # The request's correlation ID rides the broadcast envelope so
        # worker-side spans/logs are attributable to the REST request
        # that caused them. It is read ONCE here on the coordinator and
        # broadcast — every process sees the same value (LO102-safe).
        envelope = {
            "op": op,
            "payload": payload,
            "cid": _tracing.current_correlation_id(),
        }
        started = time.perf_counter()
        if jax.process_count() == 1:
            with _tracing.span(f"spmd:{op}"):
                try:
                    result = handler(payload)
                except BaseException:
                    self._observe(op, "error", started)
                    raise
            self._observe(op, "ok", started)
            return result
        if timeout is None:
            # lo: allow[LO305] deliberate per-dispatch read (test knob)
            timeout = float(os.environ.get("LO_SPMD_TIMEOUT_S", "3600") or 0)
        # deliberate lock-free fast path: _poisoned is a monotonic
        # latch (None -> reason, never back), so a stale read here only
        # delays the failure to the authoritative re-check below — and
        # taking the lock would park this request behind the job that
        # is busy poisoning the stream.
        if self._poisoned:  # lo: allow[LO203]
            raise SpmdRuntimePoisonedError(self._poisoned)
        with self._lock:
            if self._poisoned:
                raise SpmdRuntimePoisonedError(self._poisoned)
            if not timeout:
                with _tracing.span(f"spmd:{op}"):
                    _broadcast_json(envelope)
                    try:
                        result = handler(payload)
                    except BaseException as error:
                        # same poisoning as the watchdog path: workers die
                        # on in-job exceptions, the stream is broken
                        self._poison_locked(
                            f"SPMD job {op!r} failed mid-collective: {error}"
                        )
                        self._observe(op, "error", started)
                        raise
                self._observe(op, "ok", started)
                return result
            box: dict[str, Any] = {}
            done = threading.Event()
            context = _tracing.capture()

            def run() -> None:
                try:
                    # the broadcast is inside the watchdog too: with a
                    # dead worker it can block just like the collectives
                    with _tracing.attach(context), _tracing.span(
                        f"spmd:{op}"
                    ):
                        _broadcast_json(envelope)
                        box["result"] = handler(payload)
                except BaseException as error:  # noqa: BLE001 — re-raised
                    box["error"] = error
                finally:
                    done.set()

            thread = threading.Thread(
                target=run, name=f"spmd-{op}", daemon=True
            )
            thread.start()
            if not done.wait(timeout):
                self._metrics["watchdog_trips"].inc()
                self._poison_locked(
                    f"SPMD job {op!r} timed out after {timeout:.0f}s — a "
                    "worker likely died mid-job; the runtime must be "
                    "restarted (supervisor restart policy)"
                )
                self._observe(op, "timeout", started)
                raise SpmdTimeoutError(self._poisoned)
            if "error" in box:
                # an exception mid-job kills the workers by design
                # (run_worker_loop): the runtime is no longer usable
                self._poison_locked(
                    f"SPMD job {op!r} failed mid-collective: {box['error']}"
                )
                self._observe(op, "error", started)
                raise box["error"]
            self._observe(op, "ok", started)
            return box["result"]

    def run_worker_loop(self) -> None:
        """Worker-process main loop: execute broadcast jobs until
        shutdown. A failed job is fatal for the worker: it may have
        aborted between two collectives, and rejoining the loop with a
        desynchronized collective stream would hang or corrupt every
        later job — crashing instead tears down the distributed runtime
        so the coordinator surfaces an error (the reference's Spark
        stages likewise fail the job when an executor dies mid-stage).
        The deployment's restart policy brings the worker back."""
        while True:
            job = _broadcast_json()
            if job["op"] == _SHUTDOWN_OP:
                return
            # Worker-side spans carry the COORDINATOR's correlation ID
            # (from the broadcast envelope): one request, one ID, across
            # every host. The finished trace parks in the in-process
            # ring (tracing.remember_trace) and the ID is logged so
            # worker stdout lines correlate with the coordinator's
            # /jobs/<name>/trace output.
            trace = _tracing.Trace(job.get("cid"), name=f"spmd:{job['op']}")
            try:
                with _tracing.activate(trace), _tracing.span(
                    f"spmd:{job['op']}", process=jax.process_index()
                ):
                    self._handlers[job["op"]](job["payload"])
            except Exception:
                print(
                    f"[spmd worker {jax.process_index()}] job "
                    f"{job['op']!r} (cid {trace.correlation_id}) failed:\n"
                    f"{traceback.format_exc()}",
                    flush=True,
                )
                raise
            finally:
                if job["op"] != _PING_OP:
                    _tracing.remember_trace(trace)
                    # worker spans join the cid-keyed export buffer so
                    # a stitched trace shows the SPMD side too
                    _tracing.export_trace(trace, service="spmd")

    def shutdown_workers(self) -> None:
        self._stop_heartbeat.set()
        if jax.process_count() > 1 and jax.process_index() == 0:
            with self._lock:
                # Not a divergence bug: the workers' matching half of
                # this collective is the _broadcast_json they are parked
                # in at the top of run_worker_loop.
                _broadcast_json({"op": _SHUTDOWN_OP})  # lo: allow[LO101]
