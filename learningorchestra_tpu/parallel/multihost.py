"""Multi-host runtime: coordinator bootstrap, per-host feeding, gather.

The reference scales by adding Spark workers to a master/worker overlay
(reference: docker-compose.yml:123-163; README.md:94 ``docker service
scale microservice_sparkworker=3``). The TPU equivalent is a multi-host
slice: every host runs the SAME program, ``jax.distributed`` wires the
hosts into one runtime, ``jax.devices()`` returns the global device
list, and the existing ``(data, model)`` mesh simply spans hosts — XLA
routes data-axis collectives over ICI within a host and DCN across
hosts. No worker protocol is written here; the sharding annotations are
the protocol.

Three pieces:

- :func:`initialize_from_env` — process bootstrap from ``LO_COORDINATOR``
  / ``LO_NUM_PROCESSES`` / ``LO_PROCESS_ID`` (the deployment knob; on
  Cloud TPU the args can be omitted and jax autodetects).
- :func:`host_row_range` / :func:`shard_rows_local` — per-host feeding:
  each host loads ONLY its row slice and
  ``jax.make_array_from_process_local_data`` assembles the global array
  without any host ever materializing the full dataset (the 100M-row
  ingestion story; the reference instead relies on every Spark worker
  reading its partitions from Mongo).
- :func:`fetch` — host-side view of results: replicated or
  single-host arrays come back with ``np.asarray``; row-sharded
  multi-host arrays are ``process_allgather``-ed so every host sees the
  same global result (the ``collect()`` analogue).

Single-process runs hit none of this machinery: ``fetch`` degrades to
``np.asarray`` and ``shard_rows_local`` to a plain ``device_put``.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learningorchestra_tpu.parallel.mesh import DATA_AXIS

_ENV_COORDINATOR = "LO_COORDINATOR"
_ENV_NUM_PROCESSES = "LO_NUM_PROCESSES"
_ENV_PROCESS_ID = "LO_PROCESS_ID"


def _distributed_client_active() -> bool:
    """Whether this process already joined a distributed runtime.
    ``jax.distributed.is_initialized`` only exists from jax 0.5; on
    older runtimes the client handle lives on the (internal)
    global_state singleton."""
    is_initialized = getattr(jax.distributed, "is_initialized", None)
    if is_initialized is not None:
        return bool(is_initialized())
    try:
        from jax._src import distributed as _distributed

        return _distributed.global_state.client is not None
    except (ImportError, AttributeError):
        return False


def initialize_from_env() -> bool:
    """Join the multi-host runtime if the environment asks for one.

    Reads ``LO_COORDINATOR`` (host:port), ``LO_NUM_PROCESSES`` and
    ``LO_PROCESS_ID``; when all are present, calls
    ``jax.distributed.initialize`` so this process's devices join the
    global runtime. Idempotent; returns True when running multi-host.

    On CPU (the virtual-mesh test rig) cross-process collectives need
    the gloo transport, which must be configured before the backend
    initializes — done here, gated to the CPU platform.
    """
    if _distributed_client_active():
        return jax.process_count() > 1
    coordinator = os.environ.get(_ENV_COORDINATOR)
    num_processes = os.environ.get(_ENV_NUM_PROCESSES)
    process_id = os.environ.get(_ENV_PROCESS_ID)
    if not (coordinator and num_processes and process_id):
        return False
    if jax.config.jax_platforms and "cpu" in jax.config.jax_platforms:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(num_processes),
        process_id=int(process_id),
    )
    return jax.process_count() > 1


def _local_data_coords(mesh: Mesh) -> list[int]:
    """Sorted data-axis coordinates owned by this process, verified
    contiguous (guaranteed when the mesh is built from ``jax.devices()``
    order, parallel/mesh.py)."""
    data_axis_index = mesh.axis_names.index(DATA_AXIS)
    coords = sorted(
        {
            idx[data_axis_index]
            for idx, dev in np.ndenumerate(mesh.devices)
            if dev.process_index == jax.process_index()
        }
    )
    if coords and coords != list(range(coords[0], coords[-1] + 1)):
        raise ValueError(
            "this host's data-axis coordinates are not contiguous; "
            "build the mesh from jax.devices() order"
        )
    return coords


def host_row_range(n_rows: int, mesh: Mesh) -> tuple[int, int]:
    """Global row range this host must feed for an ``n_rows`` dataset
    row-sharded over ``mesh``'s data axis.

    Rows are dealt in contiguous blocks along the data axis, so every
    host owns one contiguous slice of the (padded) row space. The stop
    is clamped to ``n_rows``; padding rows are synthesized by
    :func:`shard_rows_local`, never loaded.
    """
    from learningorchestra_tpu.parallel.sharding import padded_row_count

    data_size = mesh.shape[DATA_AXIS]
    # padded rows per data-axis coord — the bucketed rule, so per-host
    # feeding matches sharding.pad_rows's global shapes exactly
    block = padded_row_count(n_rows, data_size) // data_size
    coords = _local_data_coords(mesh)
    if not coords:
        return 0, 0
    return min(coords[0] * block, n_rows), min((coords[-1] + 1) * block, n_rows)


def shard_rows_local(
    local_rows: np.ndarray,
    mesh: Mesh,
    n_rows: int,
    dtype=None,
) -> tuple[jax.Array, jax.Array]:
    """Assemble a global row-sharded array from this host's slice.

    ``local_rows`` must be exactly the ``host_row_range(n_rows, mesh)``
    slice of the global dataset. Rows are padded (per host) up to the
    block boundary and returned with the matching global validity mask,
    mirroring ``sharding.shard_rows``'s contract — the two are
    interchangeable from the estimators' point of view.
    """
    from learningorchestra_tpu.parallel.sharding import padded_row_count

    local_rows = np.asarray(local_rows)
    if dtype is not None:
        local_rows = local_rows.astype(dtype)
    data_size = mesh.shape[DATA_AXIS]
    block = padded_row_count(n_rows, data_size) // data_size
    padded_n = block * data_size
    start, stop = host_row_range(n_rows, mesh)
    if len(local_rows) != stop - start:
        raise ValueError(
            f"expected rows [{start}, {stop}) = {stop - start} rows, "
            f"got {len(local_rows)}"
        )
    # Pad this host's slice out to its share of the padded row space.
    local_padded_n = len(_local_data_coords(mesh)) * block
    pad = local_padded_n - len(local_rows)
    local_mask = np.zeros(local_padded_n, dtype=bool)
    local_mask[: len(local_rows)] = True
    if pad:
        pad_width = [(0, pad)] + [(0, 0)] * (local_rows.ndim - 1)
        local_rows = np.pad(local_rows, pad_width)
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    global_shape = (padded_n,) + local_rows.shape[1:]
    arr = jax.make_array_from_process_local_data(
        sharding, local_rows, global_shape=global_shape
    )
    mask = jax.make_array_from_process_local_data(
        sharding, local_mask, global_shape=(padded_n,)
    )
    return arr, mask


def fetch(arr: jax.Array) -> np.ndarray:
    """Host numpy view of a device array, multi-host safe.

    Fully-addressable arrays (single process, or replicated outputs)
    convert directly; row-sharded arrays spanning hosts are gathered
    with ``process_allgather`` so every host returns the same global
    value — the TPU-native ``collect()``.
    """
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
