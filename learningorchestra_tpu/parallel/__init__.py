"""Parallelism: device meshes, shardings, distributed runtime.

This package replaces the reference's Spark standalone cluster
(reference: microservices/spark_image/, docker-compose.yml:123-163): rows
of a dataset are sharded over the ``data`` axis of a
``jax.sharding.Mesh`` the way Spark partitions RDDs over workers, and
cross-device reductions ride XLA collectives over ICI instead of RDD
shuffles.
"""

from learningorchestra_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    default_mesh,
    make_mesh,
)
from learningorchestra_tpu.parallel.sharding import (  # noqa: F401
    pad_rows,
    replicated,
    row_sharded,
    shard_rows,
)
from learningorchestra_tpu.parallel.multihost import (  # noqa: F401
    fetch,
    host_row_range,
    initialize_from_env,
    shard_rows_local,
)
