"""model_builder service (port 5002) — the flagship.

Reference: microservices/model_builder_image/server.py:52-115. The
request is synchronous: 201 only after ALL classifiers finish
(server.py:112-115 — SURVEY.md §3.2 notes this is the one synchronous
job in the reference).

Beyond reference parity, fitted models persist as checkpoints
(``LO_MODELS_DIR``/``models_dir``) and are served back over REST:
``GET /models`` lists artifacts, ``GET /models/<name>`` describes one,
``POST /models/<name>/predictions`` predicts from the artifact without
refitting — the durability the reference lacks (its fitted models die
with the request, model_builder.py:232-247; SURVEY.md §5)."""

from __future__ import annotations

import json
import os
import zipfile
from typing import Optional

from jax.sharding import Mesh

from learningorchestra_tpu.core.store import DocumentStore
from learningorchestra_tpu.ml.base import CLASSIFIER_NAMES
from learningorchestra_tpu.ml.builder import build_model, predict_with_model
from learningorchestra_tpu.ml.checkpoint import (
    CHECKPOINT_SUFFIX,
    checkpoint_path as _checkpoint_path,
)
from learningorchestra_tpu.sched import DEVICE_CLASS, QueueFullError
from learningorchestra_tpu.services import validators
from learningorchestra_tpu.telemetry import register_store
from learningorchestra_tpu.utils.web import WebApp, too_many_requests

MESSAGE_RESULT = "result"
MESSAGE_CREATED_FILE = "created_file"


def create_app(
    store: DocumentStore,
    mesh: Optional[Mesh] = None,
    build=None,
    models_dir: Optional[str] = None,
    predict=None,
    jobs: "JobManager | None" = None,
) -> WebApp:
    """``build``/``predict`` override how a validated request body
    becomes a build_model / predict_with_model call — the multi-host
    runner injects an SPMD dispatch (parallel/spmd.py) so every process
    enters the fit; default is the in-process call. ``models_dir``
    (default ``LO_MODELS_DIR``) is where checkpoints live.

    Long builds: the reference keeps ``POST /models`` synchronous (201
    only after ALL fits, server.py:112-115) and that stays the default
    for parity — but a request carrying ``"async": true`` returns 201
    immediately and runs the build as a tracked job instead, so one
    multi-minute build no longer pins a WSGI worker invisibly;
    ``GET /jobs`` on this service reports its state
    (PENDING/RUNNING/FINISHED/FAILED + error payload)."""
    import itertools

    from learningorchestra_tpu.core.jobs import DuplicateJobError, JobManager

    app = WebApp("model_builder")
    # Reference parity allows a concurrent SAME-NAME sync build/predict
    # to run too (racy allow-both, reference server.py:112-115). The
    # duplicate still goes through the device queue — just under a
    # uniquified job name — so "two SPMD dispatches never contend for
    # the mesh" holds even for the parity path.
    duplicate_seq = itertools.count(1)
    models_dir = models_dir or os.environ.get("LO_MODELS_DIR")
    jobs = jobs or JobManager()
    register_store(store)
    # GET /jobs (+ /trace, DELETE): a build's state and span tree —
    # per-classifier train spans nesting the PhaseTimer fit/evaluate/
    # predict/write phases under the request's correlation ID — plus
    # cooperative cancellation of queued/running builds.
    app.register_job_routes(jobs)

    def checkpoint_path(name: str) -> str:
        return _checkpoint_path(models_dir, name)

    if build is None:

        def build(body: dict) -> None:
            build_model(
                store,
                body["training_filename"],
                body["test_filename"],
                body["preprocessor_code"],
                body["classificators_list"],
                mesh=mesh,
                models_dir=models_dir,
            )

    if predict is None:

        def predict(model_name: str, body: dict) -> None:
            predict_with_model(
                store,
                checkpoint_path(model_name),
                body["training_filename"],
                body["test_filename"],
                body["preprocessor_code"],
                body["prediction_filename"],
                mesh=mesh,
            )

    @app.route("/models", methods=("POST",))
    def create_model(request):
        body = request.get_json()
        try:
            validators.filename_exists(
                store,
                body["training_filename"],
                validators.MESSAGE_INVALID_TRAINING_FILENAME,
            )
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        try:
            validators.filename_exists(
                store,
                body["test_filename"],
                validators.MESSAGE_INVALID_TEST_FILENAME,
            )
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        for name in body["classificators_list"]:
            if name not in CLASSIFIER_NAMES:
                return {
                    MESSAGE_RESULT: validators.MESSAGE_INVALID_CLASSIFICATOR
                }, 406
        job_name = (
            f"build:{body['test_filename']}:"
            f"{'+'.join(body['classificators_list'])}"
        )
        if body.get("async"):
            try:
                jobs.submit(job_name, build, body, job_class=DEVICE_CLASS)
            except QueueFullError as error:  # device queue at its cap
                return too_many_requests(error)
            except ValueError as error:  # same job already active
                return {MESSAGE_RESULT: str(error)}, 409
            return {
                MESSAGE_RESULT: MESSAGE_CREATED_FILE,
                "job": job_name,
            }, 201
        # Synchronous stays the reference contract (201 after ALL fits)
        # but runs as a TRACKED job through the scheduler's DEVICE
        # class, so concurrent builds queue for the mesh instead of
        # contending on it (the request thread blocks; a scheduler
        # worker executes) and the build still gets a correlated span
        # tree at /jobs/<name>/trace. A concurrent same-name sync build
        # falls back to untracked execution rather than changing the
        # reference's (racy) allow-both behaviour.
        try:
            jobs.run_sync(job_name, build, body, job_class=DEVICE_CLASS)
        except QueueFullError as error:
            return too_many_requests(error)
        except DuplicateJobError:  # already active: reference parity.
            # NOT a bare ValueError — run_sync re-raises the build's
            # OWN exceptions, and a build that failed with ValueError
            # must surface, not silently run a second time. The rerun
            # keeps the allow-both behaviour but STAYS on the device
            # queue (unique name) so it cannot overlap the first on
            # the mesh.
            try:
                jobs.run_sync(
                    f"{job_name}#dup{next(duplicate_seq)}",
                    build,
                    body,
                    job_class=DEVICE_CLASS,
                )
            except QueueFullError as error:
                return too_many_requests(error)
        # response body stays the verbatim reference payload (clients
        # and the golden tests compare it whole); the job name is
        # derivable and /jobs lists it
        return {MESSAGE_RESULT: MESSAGE_CREATED_FILE}, 201

    @app.route("/models", methods=("GET",))
    def list_models(request):
        if not models_dir or not os.path.isdir(models_dir):
            return {MESSAGE_RESULT: []}, 200
        names = sorted(
            name[: -len(CHECKPOINT_SUFFIX)]
            for name in os.listdir(models_dir)
            if name.endswith(CHECKPOINT_SUFFIX)
        )
        return {MESSAGE_RESULT: names}, 200

    @app.route("/models/<model_name>", methods=("GET",))
    def get_model(request, model_name):
        if (
            not models_dir
            or not validators.safe_filename(model_name)
            or not os.path.isfile(checkpoint_path(model_name))
        ):
            return {MESSAGE_RESULT: validators.MESSAGE_NOT_FOUND}, 404
        path = checkpoint_path(model_name)
        with zipfile.ZipFile(path) as archive:
            header = json.loads(archive.read("__model__.json"))
        return {
            MESSAGE_RESULT: {
                "name": model_name,
                "kind": header["kind"],
                "size_bytes": os.path.getsize(path),
            }
        }, 200

    @app.route("/models/<model_name>/predictions", methods=("POST",))
    def predict_model(request, model_name):
        body = request.get_json(silent=True)
        required = (
            "training_filename",
            "test_filename",
            "preprocessor_code",
            "prediction_filename",
        )
        if not isinstance(body, dict) or any(k not in body for k in required):
            return {MESSAGE_RESULT: validators.MESSAGE_MISSING_FIELDS}, 406
        if (
            not models_dir
            or not validators.safe_filename(model_name)
            or not os.path.isfile(checkpoint_path(model_name))
        ):
            return {MESSAGE_RESULT: validators.MESSAGE_NOT_FOUND}, 404
        try:
            validators.filename_exists(
                store,
                body["training_filename"],
                validators.MESSAGE_INVALID_TRAINING_FILENAME,
            )
            validators.filename_exists(
                store,
                body["test_filename"],
                validators.MESSAGE_INVALID_TEST_FILENAME,
            )
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        if not validators.safe_filename(body["prediction_filename"]):
            return {MESSAGE_RESULT: validators.MESSAGE_INVALID_FILENAME}, 406
        try:
            validators.filename_free(store, body["prediction_filename"])
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 409
        # checkpoint predictions run a forward pass on the mesh: same
        # device-class queue as builds, so they never overlap an SPMD fit
        try:
            jobs.run_sync(
                f"predict:{body['prediction_filename']}",
                predict,
                model_name,
                body,
                job_class=DEVICE_CLASS,
            )
        except QueueFullError as error:
            return too_many_requests(error)
        except DuplicateJobError:
            # same parity rule as builds: the concurrent duplicate runs,
            # but through the device queue, never inline on the mesh
            try:
                jobs.run_sync(
                    f"predict:{body['prediction_filename']}#dup"
                    f"{next(duplicate_seq)}",
                    predict,
                    model_name,
                    body,
                    job_class=DEVICE_CLASS,
                )
            except QueueFullError as error:
                return too_many_requests(error)
        return {MESSAGE_RESULT: MESSAGE_CREATED_FILE}, 201

    return app
