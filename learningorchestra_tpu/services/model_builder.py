"""model_builder service (port 5002) — the flagship.

Reference: microservices/model_builder_image/server.py:52-115. The
request is synchronous: 201 only after ALL classifiers finish
(server.py:112-115 — SURVEY.md §3.2 notes this is the one synchronous
job in the reference)."""

from __future__ import annotations

from typing import Optional

from jax.sharding import Mesh

from learningorchestra_tpu.core.store import DocumentStore
from learningorchestra_tpu.ml.base import CLASSIFIER_NAMES
from learningorchestra_tpu.ml.builder import build_model
from learningorchestra_tpu.services import validators
from learningorchestra_tpu.utils.web import WebApp

MESSAGE_RESULT = "result"
MESSAGE_CREATED_FILE = "created_file"


def create_app(
    store: DocumentStore,
    mesh: Optional[Mesh] = None,
    build=None,
) -> WebApp:
    """``build`` overrides how a validated request body becomes a
    build_model call — the multi-host runner injects an SPMD dispatch
    (parallel/spmd.py) so every process enters the fit; default is the
    in-process call."""
    app = WebApp("model_builder")

    if build is None:

        def build(body: dict) -> None:
            build_model(
                store,
                body["training_filename"],
                body["test_filename"],
                body["preprocessor_code"],
                body["classificators_list"],
                mesh=mesh,
            )

    @app.route("/models", methods=("POST",))
    def create_model(request):
        body = request.get_json()
        try:
            validators.filename_exists(
                store,
                body["training_filename"],
                validators.MESSAGE_INVALID_TRAINING_FILENAME,
            )
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        try:
            validators.filename_exists(
                store,
                body["test_filename"],
                validators.MESSAGE_INVALID_TEST_FILENAME,
            )
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        for name in body["classificators_list"]:
            if name not in CLASSIFIER_NAMES:
                return {
                    MESSAGE_RESULT: validators.MESSAGE_INVALID_CLASSIFICATOR
                }, 406
        build(body)
        return {MESSAGE_RESULT: MESSAGE_CREATED_FILE}, 201

    return app
