"""model_builder service (port 5002) — the flagship.

Reference: microservices/model_builder_image/server.py:52-115. The
request is synchronous: 201 only after ALL classifiers finish
(server.py:112-115 — SURVEY.md §3.2 notes this is the one synchronous
job in the reference).

Beyond reference parity, fitted models persist as checkpoints
(``LO_MODELS_DIR``/``models_dir``) and are served back over REST:
``GET /models`` lists artifacts, ``GET /models/<name>`` describes one,
``POST /models/<name>/predictions`` predicts from the artifact without
refitting — the durability the reference lacks (its fitted models die
with the request, model_builder.py:232-247; SURVEY.md §5).

``POST /models/<name>/predict`` is the ONLINE lane (docs/serving.md):
rows in the request body, labels + probabilities in the synchronous
response — no job record, no store round-trip, no polling. Requests run
through the serving plane (``serve/``): the model's params stay pinned
in device memory (rev-keyed against the artifact, so a rebuild is never
served stale) and concurrent requests coalesce into one padded forward
dispatch per model. The lane bypasses the scheduler's device queue but
keeps its admission contract: a full batcher inbox answers 429 +
``Retry-After`` exactly like a full job queue."""

from __future__ import annotations

import json
import os
import time
import zipfile
from typing import Optional

import numpy as np
from jax.sharding import Mesh

from learningorchestra_tpu.core.store import DocumentStore
from learningorchestra_tpu.ml.base import CLASSIFIER_NAMES
from learningorchestra_tpu.ml.builder import build_model, predict_with_model
from learningorchestra_tpu.ml.checkpoint import (
    CHECKPOINT_SUFFIX,
    checkpoint_path as _checkpoint_path,
)
from learningorchestra_tpu.ml import sweep as lo_sweep
from learningorchestra_tpu.sched import (
    DEVICE_CLASS,
    QueueFullError,
    global_coalescer,
)
from learningorchestra_tpu.serve import ModelNotFoundError, global_serve_plane
from learningorchestra_tpu.serve.batcher import LATENCY_BUCKETS
from learningorchestra_tpu.services import validators
from learningorchestra_tpu.telemetry import register_store
from learningorchestra_tpu.utils.web import WebApp, too_many_requests

MESSAGE_RESULT = "result"
MESSAGE_CREATED_FILE = "created_file"
MESSAGE_INVALID_ROWS = "invalid_rows"
MESSAGE_SERVE_TIMEOUT = "predict_timeout"
MESSAGE_TOO_MANY_ROWS = "too_many_rows"


def create_app(
    store: DocumentStore,
    mesh: Optional[Mesh] = None,
    build=None,
    models_dir: Optional[str] = None,
    predict=None,
    jobs: "JobManager | None" = None,
    serve=None,
    coalescer=None,
) -> WebApp:
    """``build``/``predict`` override how a validated request body
    becomes a build_model / predict_with_model call — the multi-host
    runner injects an SPMD dispatch (parallel/spmd.py) so every process
    enters the fit; default is the in-process call. ``models_dir``
    (default ``LO_MODELS_DIR``) is where checkpoints live. ``serve``
    injects a :class:`~learningorchestra_tpu.serve.ServePlane` (tests
    pin knobs); default is the process-wide plane — safe across apps
    because registry entries key on absolute checkpoint paths.

    Long builds: the reference keeps ``POST /models`` synchronous (201
    only after ALL fits, server.py:112-115) and that stays the default
    for parity — but a request carrying ``"async": true`` returns 201
    immediately and runs the build as a tracked job instead, so one
    multi-minute build no longer pins a WSGI worker invisibly;
    ``GET /jobs`` on this service reports its state
    (PENDING/RUNNING/FINISHED/FAILED + error payload)."""
    import itertools

    from learningorchestra_tpu.core.jobs import DuplicateJobError, JobManager

    app = WebApp("model_builder")
    # Reference parity allows a concurrent SAME-NAME sync build/predict
    # to run too (racy allow-both, reference server.py:112-115). The
    # duplicate still goes through the device queue — just under a
    # uniquified job name — so "two SPMD dispatches never contend for
    # the mesh" holds even for the parity path.
    duplicate_seq = itertools.count(1)
    # lo: allow[LO305] app-factory boot wiring, same fallback as runner
    models_dir = models_dir or os.environ.get("LO_MODELS_DIR")
    jobs = jobs or JobManager()
    # the coalescing stage (sched/coalesce.py): process-wide by default
    # so sweep jobs submitted through different apps in one process
    # still fuse; tests inject one with pinned knobs
    coalescer = coalescer or global_coalescer()
    register_store(store)
    # GET /jobs (+ /trace, DELETE): a build's state and span tree —
    # per-classifier train spans nesting the PhaseTimer fit/evaluate/
    # predict/write phases under the request's correlation ID — plus
    # cooperative cancellation of queued/running builds.
    app.register_job_routes(jobs)
    app.register_observability(store)

    def checkpoint_path(name: str) -> str:
        return _checkpoint_path(models_dir, name)

    # The online-serving plane (docs/serving.md). Constructed lazily so
    # apps that never see predict traffic cost nothing; the default is
    # process-wide (registry keys are absolute artifact paths).
    plane_box: list = [serve]

    def serve_plane():
        if plane_box[0] is None:
            plane_box[0] = global_serve_plane()
        return plane_box[0]

    from learningorchestra_tpu.serve import config as serve_config

    # Fail-fast: resolve EVERY serving knob now, not at first request —
    # a typo'd LO_SERVE_BYTES must break app construction (the posture
    # deploy/run.sh preflights; library embedders get it here), never
    # surface as a 500 on a live route.
    serve_knobs = serve_config.validate_all()
    serve_timeout_s = serve_knobs["request_timeout_s"]
    serve_max_rows = serve_knobs["max_rows"]

    # Publish-time serve warmup (docs/compile.md): when a build or
    # sweep publishes a checkpoint, ride a LOW-priority device job
    # that loads it through the serve registry and executes the fixed
    # dispatch shape — the first POST /models/<name>/predict then hits
    # a compiled program. Low priority (the scheduler's heap prefers
    # larger values) keeps warmups behind every real build/predict;
    # no store/collection binding, so a warmup never shows up as a
    # dataset job. Process-wide handler, latest app wins — registry
    # entries key on absolute paths, so any live plane can warm any
    # artifact.
    from learningorchestra_tpu import compile as lo_compile

    def on_checkpoint_published(path: str, features) -> None:
        def warm() -> None:
            from learningorchestra_tpu.compile.warmup import warm_artifact

            warm_artifact(path, features=features, serve=serve_plane())

        try:
            jobs.submit(
                f"warmup:{os.path.basename(path)}",
                warm,
                job_class=DEVICE_CLASS,
                priority=-5,
            )
        except (DuplicateJobError, QueueFullError):
            # a republish racing its own warmup, or a saturated device
            # queue: warmup is opportunistic — the publication stands,
            # the first predict just pays the compile it always did
            pass

    lo_compile.set_publish_handler(on_checkpoint_published)
    serve_seconds = app.registry.histogram(
        "lo_serve_request_seconds",
        "End-to-end predict latency (admission to response build)",
        buckets=LATENCY_BUCKETS,
    )

    if build is None:

        def build(body: dict) -> None:
            build_model(
                store,
                body["training_filename"],
                body["test_filename"],
                body["preprocessor_code"],
                body["classificators_list"],
                mesh=mesh,
                models_dir=models_dir,
            )

    if predict is None:

        def predict(model_name: str, body: dict) -> None:
            predict_with_model(
                store,
                checkpoint_path(model_name),
                body["training_filename"],
                body["test_filename"],
                body["preprocessor_code"],
                body["prediction_filename"],
                mesh=mesh,
            )

    @app.route("/models", methods=("POST",))
    def create_model(request):
        body = request.get_json()
        try:
            validators.filename_exists(
                store,
                body["training_filename"],
                validators.MESSAGE_INVALID_TRAINING_FILENAME,
            )
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        try:
            validators.filename_exists(
                store,
                body["test_filename"],
                validators.MESSAGE_INVALID_TEST_FILENAME,
            )
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        for name in body["classificators_list"]:
            if name not in CLASSIFIER_NAMES:
                return {
                    MESSAGE_RESULT: validators.MESSAGE_INVALID_CLASSIFICATOR
                }, 406
        job_name = (
            f"build:{body['test_filename']}:"
            f"{'+'.join(body['classificators_list'])}"
        )
        # The journaled replay payload: everything recovery needs to
        # re-run (or RESUME — build_model is in the resume registry,
        # sched/recovery.py) this build after a crash, without the
        # closure. models_dir rides along because the restarted process
        # resolves no request-scoped state.
        replay = (
            "build_model",
            {
                "training_filename": body["training_filename"],
                "test_filename": body["test_filename"],
                "preprocessor_code": body["preprocessor_code"],
                "classificators_list": list(body["classificators_list"]),
                "models_dir": models_dir,
            },
        )
        if body.get("async"):
            try:
                jobs.submit(
                    job_name,
                    build,
                    body,
                    job_class=DEVICE_CLASS,
                    replay=replay,
                )
            except QueueFullError as error:  # device queue at its cap
                return too_many_requests(error)
            except ValueError as error:  # same job already active
                return {MESSAGE_RESULT: str(error)}, 409
            return {
                MESSAGE_RESULT: MESSAGE_CREATED_FILE,
                "job": job_name,
            }, 201
        # Synchronous stays the reference contract (201 after ALL fits)
        # but runs as a TRACKED job through the scheduler's DEVICE
        # class, so concurrent builds queue for the mesh instead of
        # contending on it (the request thread blocks; a scheduler
        # worker executes) and the build still gets a correlated span
        # tree at /jobs/<name>/trace. A concurrent same-name sync build
        # falls back to untracked execution rather than changing the
        # reference's (racy) allow-both behaviour.
        try:
            jobs.run_sync(
                job_name, build, body, job_class=DEVICE_CLASS, replay=replay
            )
        except QueueFullError as error:
            return too_many_requests(error)
        except DuplicateJobError:  # already active: reference parity.
            # NOT a bare ValueError — run_sync re-raises the build's
            # OWN exceptions, and a build that failed with ValueError
            # must surface, not silently run a second time. The rerun
            # keeps the allow-both behaviour but STAYS on the device
            # queue (unique name) so it cannot overlap the first on
            # the mesh.
            try:
                jobs.run_sync(
                    f"{job_name}#dup{next(duplicate_seq)}",
                    build,
                    body,
                    job_class=DEVICE_CLASS,
                    replay=replay,
                )
            except QueueFullError as error:
                return too_many_requests(error)
        # response body stays the verbatim reference payload (clients
        # and the golden tests compare it whole); the job name is
        # derivable and /jobs lists it
        return {MESSAGE_RESULT: MESSAGE_CREATED_FILE}, 201

    @app.route("/models/sweep", methods=("POST",))
    def sweep_models(request):
        """Hyperparameter sweep as ONE device job: a λ grid over ``lr``
        or a depth grid over ``dt`` fits as one vmap-across-jobs
        dispatch (ml/sweep.py) — per-point metrics in the response and
        persisted as collection ``sweep_name``, the argmax checkpoint
        published atomically so ``POST /models/<sweep_name>/predict``
        serves the winner immediately. Concurrent sweeps (and
        single-point "small builds") with compatible shapes coalesce
        into one dispatch via the scheduler's coalescing stage."""
        body = request.get_json(silent=True)
        required = (
            "training_filename",
            "test_filename",
            "preprocessor_code",
            "classificator",
            "grid",
            "sweep_name",
        )
        if not isinstance(body, dict) or any(k not in body for k in required):
            return {MESSAGE_RESULT: validators.MESSAGE_MISSING_FIELDS}, 406
        try:
            validators.filename_exists(
                store,
                body["training_filename"],
                validators.MESSAGE_INVALID_TRAINING_FILENAME,
            )
            validators.filename_exists(
                store,
                body["test_filename"],
                validators.MESSAGE_INVALID_TEST_FILENAME,
            )
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        if not validators.safe_filename(body["sweep_name"]):
            return {MESSAGE_RESULT: validators.MESSAGE_INVALID_FILENAME}, 406
        max_iter = body.get("max_iter", 100)
        if isinstance(max_iter, bool) or not isinstance(max_iter, int) or (
            max_iter < 1
        ):
            return {MESSAGE_RESULT: "invalid_max_iter"}, 406
        try:
            lo_sweep.validate_grid(body["classificator"], body["grid"])
        except ValueError as error:
            return {MESSAGE_RESULT: f"invalid_grid: {error}"}, 406
        try:
            validators.filename_free(store, body["sweep_name"])
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 409
        try:
            result = lo_sweep.run_sweep(
                store,
                body,
                jobs=jobs,
                coalescer=coalescer,
                models_dir=models_dir,
                mesh=mesh,
            )
        except QueueFullError as error:  # device queue at its cap
            return too_many_requests(error)
        except DuplicateJobError as error:  # same sweep already running
            return {MESSAGE_RESULT: str(error)}, 409
        return {MESSAGE_RESULT: result}, 201

    @app.route("/models", methods=("GET",))
    def list_models(request):
        # "result" stays the plain name list (clients and tests index
        # it); registry occupancy rides alongside as "serving"
        serving = serve_plane().stats()
        if not models_dir or not os.path.isdir(models_dir):
            return {MESSAGE_RESULT: [], "serving": serving}, 200
        names = sorted(
            name[: -len(CHECKPOINT_SUFFIX)]
            for name in os.listdir(models_dir)
            if name.endswith(CHECKPOINT_SUFFIX)
        )
        return {MESSAGE_RESULT: names, "serving": serving}, 200

    @app.route("/models/<model_name>", methods=("GET",))
    def get_model(request, model_name):
        if (
            not models_dir
            or not validators.safe_filename(model_name)
            or not os.path.isfile(checkpoint_path(model_name))
        ):
            return {MESSAGE_RESULT: validators.MESSAGE_NOT_FOUND}, 404
        path = checkpoint_path(model_name)
        with zipfile.ZipFile(path) as archive:
            header = json.loads(archive.read("__model__.json"))
        return {
            MESSAGE_RESULT: {
                "name": model_name,
                "kind": header["kind"],
                "size_bytes": os.path.getsize(path),
                "serving": serve_plane().registry.status(path),
            }
        }, 200

    @app.route("/models/<model_name>/predict", methods=("POST",))
    def predict_rows(request, model_name):
        """The online lane: rows in, labels + probabilities out, one
        synchronous response. Never a job record, never a traceback —
        every failure mode maps to a JSON error body (404 unknown or
        not-yet-built model, 406 malformed rows, 429 inbox full, 503
        timed out, 500 a forward-pass failure with its message)."""
        started = time.perf_counter()
        if (
            not models_dir
            or not validators.safe_filename(model_name)
            or not os.path.isfile(checkpoint_path(model_name))
        ):
            return {MESSAGE_RESULT: validators.MESSAGE_NOT_FOUND}, 404
        body = request.get_json(silent=True)
        if not isinstance(body, dict) or "rows" not in body:
            return {MESSAGE_RESULT: validators.MESSAGE_MISSING_FIELDS}, 406
        try:
            rows = np.asarray(body["rows"], dtype=np.float32)
        except (TypeError, ValueError):  # ragged / non-numeric
            return {MESSAGE_RESULT: MESSAGE_INVALID_ROWS}, 406
        if rows.ndim == 1 and rows.size:  # one bare row is one request
            rows = rows.reshape(1, -1)
        # np.isfinite also rejects JSON nulls: asarray converts None to
        # NaN without raising, which would otherwise slip past the 406
        # and come back as a 200 full of NaN "probabilities"
        if rows.ndim != 2 or rows.size == 0 or not np.isfinite(rows).all():
            return {MESSAGE_RESULT: MESSAGE_INVALID_ROWS}, 406
        if len(rows) > serve_max_rows:
            # the online lane is for low-latency scoring; bulk bodies
            # belong on the batch lane (POST /models/<name>/predictions)
            return {MESSAGE_RESULT: MESSAGE_TOO_MANY_ROWS}, 413
        try:
            pending = serve_plane().submit(checkpoint_path(model_name), rows)
        except QueueFullError as error:  # bounded inbox: 429 parity
            return too_many_requests(error)
        done = pending.wait(serve_timeout_s)
        # every post-dispatch exit is observed: a p99 that excluded the
        # timed-out and failed requests would read healthy during the
        # exact overload it exists to expose
        serve_seconds.observe(time.perf_counter() - started)
        if not done:
            # tell the batcher not to run the forward for a client that
            # stopped listening — the backlog drains instead of growing
            pending.abandon()
            return {MESSAGE_RESULT: MESSAGE_SERVE_TIMEOUT}, 503
        if pending.error is not None:
            if isinstance(pending.error, ModelNotFoundError):
                # artifact deleted between the check above and dispatch
                return {MESSAGE_RESULT: validators.MESSAGE_NOT_FOUND}, 404
            return {
                MESSAGE_RESULT: (
                    "prediction_failed: "
                    f"{type(pending.error).__name__}: {pending.error}"
                )
            }, 500
        return {
            MESSAGE_RESULT: {
                "model": model_name,
                "predictions": pending.labels.tolist(),
                "probabilities": pending.probs.tolist(),
            }
        }, 200

    @app.route("/models/<model_name>/predictions", methods=("POST",))
    def predict_model(request, model_name):
        body = request.get_json(silent=True)
        required = (
            "training_filename",
            "test_filename",
            "preprocessor_code",
            "prediction_filename",
        )
        if not isinstance(body, dict) or any(k not in body for k in required):
            return {MESSAGE_RESULT: validators.MESSAGE_MISSING_FIELDS}, 406
        if (
            not models_dir
            or not validators.safe_filename(model_name)
            or not os.path.isfile(checkpoint_path(model_name))
        ):
            return {MESSAGE_RESULT: validators.MESSAGE_NOT_FOUND}, 404
        try:
            validators.filename_exists(
                store,
                body["training_filename"],
                validators.MESSAGE_INVALID_TRAINING_FILENAME,
            )
            validators.filename_exists(
                store,
                body["test_filename"],
                validators.MESSAGE_INVALID_TEST_FILENAME,
            )
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        if not validators.safe_filename(body["prediction_filename"]):
            return {MESSAGE_RESULT: validators.MESSAGE_INVALID_FILENAME}, 406
        try:
            validators.filename_free(store, body["prediction_filename"])
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 409
        # checkpoint predictions run a forward pass on the mesh: same
        # device-class queue as builds, so they never overlap an SPMD fit
        try:
            jobs.run_sync(
                f"predict:{body['prediction_filename']}",
                predict,
                model_name,
                body,
                job_class=DEVICE_CLASS,
            )
        except QueueFullError as error:
            return too_many_requests(error)
        except DuplicateJobError:
            # same parity rule as builds: the concurrent duplicate runs,
            # but through the device queue, never inline on the mesh
            try:
                jobs.run_sync(
                    f"predict:{body['prediction_filename']}#dup"
                    f"{next(duplicate_seq)}",
                    predict,
                    model_name,
                    body,
                    job_class=DEVICE_CLASS,
                )
            except QueueFullError as error:
                return too_many_requests(error)
        return {MESSAGE_RESULT: MESSAGE_CREATED_FILE}, 201

    return app
