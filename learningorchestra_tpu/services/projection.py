"""projection service (port 5001).

Reference: microservices/projection_image/server.py:50-115 — validator
order matters (duplicate output name → 409 first, then parent existence
→ 406, then fields → 406), and the reference appends ``_id`` to the
requested fields before submitting (server.py:104-106)."""

from __future__ import annotations

from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.core.store import DocumentStore
from learningorchestra_tpu.ops.projection import project
from learningorchestra_tpu.sched import HOST_CLASS, QueueFullError
from learningorchestra_tpu.services import validators
from learningorchestra_tpu.telemetry import register_store, span
from learningorchestra_tpu.utils.web import WebApp, too_many_requests

MESSAGE_RESULT = "result"
MESSAGE_CREATED_FILE = "created_file"


def create_app(store: DocumentStore, jobs: JobManager | None = None) -> WebApp:
    app = WebApp("projection")
    jobs = jobs or JobManager()
    register_store(store)
    app.register_job_routes(jobs)
    app.register_observability(store)

    @app.route("/projections/<parent_filename>", methods=("POST",))
    def create_projection(request, parent_filename):
        body = request.get_json()
        projection_filename = body["projection_filename"]
        fields = body["fields"]
        try:
            validators.filename_free(store, projection_filename)
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 409
        try:
            validators.filename_exists(store, parent_filename)
            validators.fields_in_metadata(store, parent_filename, fields)
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        # Atomic claim: concurrent duplicate creates get exactly one 201,
        # the loser a 409 (the check-then-act race SURVEY §5 flags).
        if not store.create_collection(projection_filename):
            return {MESSAGE_RESULT: validators.MESSAGE_DUPLICATE_FILE}, 409

        def work() -> None:
            with span("projection:project", parent=parent_filename):
                project(
                    store, parent_filename, projection_filename, list(fields)
                )

        # The response stays synchronous (reference parity) but the
        # work runs through the scheduler's host class: bounded
        # concurrency under load, 429 + Retry-After past the queue cap.
        try:
            jobs.run_sync(
                f"projection:{projection_filename}", work, job_class=HOST_CLASS
            )
        except QueueFullError as error:
            store.drop(projection_filename)  # release the name claim
            return too_many_requests(error)
        except BaseException:
            store.drop(projection_filename)
            raise
        return {MESSAGE_RESULT: MESSAGE_CREATED_FILE}, 201

    return app
