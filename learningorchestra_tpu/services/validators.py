"""Request validators shared by the services — the ``*RequestValidator``
classes of the reference, with identical error strings.

Error-string constants are copied from the reference interfaces (e.g.
projection_image/projection.py:27-30, histogram_image/histogram.py:22-25
— note histogram's deliberately different ``duplicated_filename``)."""

from __future__ import annotations

from learningorchestra_tpu.core.store import DocumentStore
from learningorchestra_tpu.utils.paths import safe_filename  # noqa: F401 — REST-layer re-export

MESSAGE_INVALID_FIELDS = "invalid_fields"
MESSAGE_INVALID_FILENAME = "invalid_filename"
MESSAGE_DUPLICATE_FILE = "duplicate_file"
MESSAGE_MISSING_FIELDS = "missing_fields"
MESSAGE_HISTOGRAM_DUPLICATE = "duplicated_filename"
MESSAGE_INVALID_TRAINING_FILENAME = "invalid_training_filename"
MESSAGE_INVALID_TEST_FILENAME = "invalid_test_filename"
MESSAGE_INVALID_CLASSIFICATOR = "invalid_classificator_name"
MESSAGE_INVALID_LABEL = "invalid_field"
MESSAGE_NOT_FOUND = "file_not_found"

STRING_TYPE = "string"
NUMBER_TYPE = "number"


class ValidationError(Exception):
    """Carries the reference's error string as ``args[0]``."""


def filename_exists(
    store: DocumentStore, filename: str, message: str = MESSAGE_INVALID_FILENAME
) -> None:
    if filename not in store.list_collections():
        raise ValidationError(message)


def filename_free(
    store: DocumentStore, filename: str, message: str = MESSAGE_DUPLICATE_FILE
) -> None:
    if filename in store.list_collections():
        raise ValidationError(message)


def metadata_fields(store: DocumentStore, filename: str) -> list:
    metadata = store.find_one(filename, {"filename": filename})
    if metadata is None or not isinstance(metadata.get("fields"), list):
        return []
    return metadata["fields"]


def fields_in_metadata(store: DocumentStore, filename: str, fields) -> None:
    """Empty → missing_fields; unknown field → invalid_fields (reference
    projection.py:157-167, histogram.py:123-133)."""
    if not fields:
        raise ValidationError(MESSAGE_MISSING_FIELDS)
    known = metadata_fields(store, filename)
    for field in fields:
        if field not in known:
            raise ValidationError(MESSAGE_INVALID_FIELDS)


def field_types_valid(store: DocumentStore, filename: str, fields: dict) -> None:
    """data_type_handler's variant: also validates the requested type
    names (reference data_type_handler.py:117-130)."""
    if not fields:
        raise ValidationError(MESSAGE_MISSING_FIELDS)
    known = metadata_fields(store, filename)
    for field, field_type in fields.items():
        if field not in known:
            raise ValidationError(MESSAGE_INVALID_FIELDS)
        if field_type not in (STRING_TYPE, NUMBER_TYPE):
            raise ValidationError(MESSAGE_INVALID_FIELDS)


def label_in_metadata(store: DocumentStore, filename: str, label) -> None:
    """tsne/pca label validator: None allowed (reference tsne.py:177-186)."""
    if label is None:
        return
    if label not in metadata_fields(store, filename):
        raise ValidationError(MESSAGE_INVALID_LABEL)
