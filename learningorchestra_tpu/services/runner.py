"""Serve the full service stack in one process.

The reference deploys seven Flask containers wired to a shared MongoDB
(docker-compose.yml); here the equivalent single-host bring-up is seven
WSGI servers over one shared (WAL-backed) store. ``python -m
learningorchestra_tpu.services.runner`` is the deployment entrypoint;
``start_all`` is the programmatic/integration-test form.

Environment:
- ``LO_DATA_DIR`` — store WAL directory (default ``./lo_data``)
- ``LO_IMAGES_DIR`` — PNG volume root (default ``<data>/images``)
- ``LO_HOST`` — bind host. Defaults to ``127.0.0.1``: the model-builder
  service executes request-supplied preprocessor code (reference parity),
  so exposing the stack beyond localhost must be an explicit opt-in
  (``LO_HOST=0.0.0.0``) behind whatever sandboxing the deployment adds —
  see deploy/README.md.
"""

from __future__ import annotations

import os
from typing import Optional

from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.core.store import DocumentStore, InMemoryStore
from learningorchestra_tpu.services import (
    DATA_TYPE_HANDLER_PORT,
    DATABASE_API_PORT,
    HISTOGRAM_PORT,
    MODEL_BUILDER_PORT,
    PCA_PORT,
    PROJECTION_PORT,
    TSNE_PORT,
)
from learningorchestra_tpu.services import (
    data_type_handler,
    database_api,
    histogram,
    images,
    model_builder,
    projection,
)
from learningorchestra_tpu.utils.web import ServerThread


def build_apps(store: DocumentStore, images_dir: str) -> dict[int, object]:
    return {
        DATABASE_API_PORT: database_api.create_app(store, JobManager()),
        PROJECTION_PORT: projection.create_app(store),
        MODEL_BUILDER_PORT: model_builder.create_app(store),
        DATA_TYPE_HANDLER_PORT: data_type_handler.create_app(store),
        HISTOGRAM_PORT: histogram.create_app(store),
        TSNE_PORT: images.create_app(
            store, os.path.join(images_dir, "tsne"), "tsne"
        ),
        PCA_PORT: images.create_app(
            store, os.path.join(images_dir, "pca"), "pca"
        ),
    }


def start_all(
    store: Optional[DocumentStore] = None,
    images_dir: Optional[str] = None,
    host: str = "127.0.0.1",
    ephemeral: bool = False,
) -> tuple[DocumentStore, list[ServerThread]]:
    """Start all seven services on their reference ports; returns the
    shared store and the server threads (callers stop() them).

    ``ephemeral=True`` binds OS-assigned ports instead (tests can't
    assume 5000-5006 are free); each server's ``canonical_port`` records
    which reference port it stands in for, its ``port`` the actual bind.
    """
    store = store if store is not None else InMemoryStore()
    images_dir = images_dir or os.path.join(os.getcwd(), "lo_images")
    servers = []
    for port, app in build_apps(store, images_dir).items():
        server = ServerThread(app, host, 0 if ephemeral else port)
        server.canonical_port = port
        servers.append(server.start())
    return store, servers


def main() -> None:
    data_dir = os.environ.get("LO_DATA_DIR", os.path.join(os.getcwd(), "lo_data"))
    images_dir = os.environ.get(
        "LO_IMAGES_DIR", os.path.join(data_dir, "images")
    )
    host = os.environ.get("LO_HOST", "127.0.0.1")
    store = InMemoryStore(data_dir=data_dir)
    _, servers = start_all(store, images_dir, host)
    print(
        f"learningorchestra_tpu serving on ports 5000-5006 (host {host}); "
        f"data in {data_dir}",
        flush=True,
    )
    try:
        for server in servers:
            server._thread.join()
    except KeyboardInterrupt:
        for server in servers:
            server.stop()


if __name__ == "__main__":
    main()
