"""Serve the service stack: all seven in one process, or one per process.

The reference deploys seven Flask containers wired to a shared MongoDB
(docker-compose.yml:173-330). Both topologies exist here:

- **single process** (default): seven WSGI servers over one in-process
  WAL-backed store — ``python -m learningorchestra_tpu.services.runner``.
- **one service per process** (the reference's microservice shape):
  set ``LO_STORE_URL`` to a store server
  (``python -m learningorchestra_tpu.core.store_service``) and launch
  each service with ``LO_SERVICE=<name>`` — every process talks to the
  shared store over its wire protocol, exactly as the reference
  containers share Mongo via ``DATABASE_URL``.

Environment:
- ``LO_SERVICE`` — serve only this service (``database_api``,
  ``projection``, ``model_builder``, ``data_type_handler``,
  ``histogram``, ``tsne``, ``pca``); unset = all seven
- ``LO_PORT`` — bind port for single-service mode (default: the
  service's reference port; ``0`` = OS-assigned, printed on stdout)
- ``LO_STORE_URL`` — store server base URL (the reference's
  ``DATABASE_URL`` analogue); unset = in-process store
- ``LO_DATA_DIR`` — store WAL directory for the in-process store
  (default ``./lo_data``)
- ``LO_IMAGES_DIR`` — PNG volume root (default ``<data>/images``)
- ``LO_HOST`` — bind host. Defaults to ``127.0.0.1``: the model-builder
  service executes request-supplied preprocessor code (reference parity),
  so exposing the stack beyond localhost must be an explicit opt-in
  (``LO_HOST=0.0.0.0``) behind whatever sandboxing the deployment adds —
  see deploy/README.md.
"""

from __future__ import annotations

import os
from typing import Optional

from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.core.store import DocumentStore, InMemoryStore
from learningorchestra_tpu.services import (
    DATA_TYPE_HANDLER_PORT,
    DATABASE_API_PORT,
    HISTOGRAM_PORT,
    MODEL_BUILDER_PORT,
    PCA_PORT,
    PROJECTION_PORT,
    TSNE_PORT,
)
from learningorchestra_tpu.services import (
    data_type_handler,
    database_api,
    histogram,
    images,
    model_builder,
    projection,
)
from learningorchestra_tpu.utils.web import ServerThread


SERVICES: dict[str, int] = {
    "database_api": DATABASE_API_PORT,
    "projection": PROJECTION_PORT,
    "model_builder": MODEL_BUILDER_PORT,
    "data_type_handler": DATA_TYPE_HANDLER_PORT,
    "histogram": HISTOGRAM_PORT,
    "tsne": TSNE_PORT,
    "pca": PCA_PORT,
}


def build_app(name: str, store: DocumentStore, images_dir: str):
    if name == "database_api":
        return database_api.create_app(store, JobManager())
    if name == "projection":
        return projection.create_app(store)
    if name == "model_builder":
        return model_builder.create_app(store)
    if name == "data_type_handler":
        return data_type_handler.create_app(store)
    if name == "histogram":
        return histogram.create_app(store)
    if name in ("tsne", "pca"):
        return images.create_app(store, os.path.join(images_dir, name), name)
    raise KeyError(f"unknown service {name!r}")


def build_apps(store: DocumentStore, images_dir: str) -> dict[int, object]:
    return {
        port: build_app(name, store, images_dir)
        for name, port in SERVICES.items()
    }


def start_all(
    store: Optional[DocumentStore] = None,
    images_dir: Optional[str] = None,
    host: str = "127.0.0.1",
    ephemeral: bool = False,
) -> tuple[DocumentStore, list[ServerThread]]:
    """Start all seven services on their reference ports; returns the
    shared store and the server threads (callers stop() them).

    ``ephemeral=True`` binds OS-assigned ports instead (tests can't
    assume 5000-5006 are free); each server's ``canonical_port`` records
    which reference port it stands in for, its ``port`` the actual bind.
    """
    store = store if store is not None else InMemoryStore()
    images_dir = images_dir or os.path.join(os.getcwd(), "lo_images")
    servers = []
    for port, app in build_apps(store, images_dir).items():
        server = ServerThread(app, host, 0 if ephemeral else port)
        server.canonical_port = port
        servers.append(server.start())
    return store, servers


def main() -> None:
    from learningorchestra_tpu.core.store_service import connect

    data_dir = os.environ.get("LO_DATA_DIR", os.path.join(os.getcwd(), "lo_data"))
    images_dir = os.environ.get(
        "LO_IMAGES_DIR", os.path.join(data_dir, "images")
    )
    host = os.environ.get("LO_HOST", "127.0.0.1")
    store_url = os.environ.get("LO_STORE_URL")
    service = os.environ.get("LO_SERVICE")

    if store_url:
        store = connect(store_url)
    else:
        store = InMemoryStore(data_dir=data_dir)

    if service:
        port = int(os.environ.get("LO_PORT", SERVICES[service]))
        server = ServerThread(build_app(service, store, images_dir), host, port)
        server.start()
        print(f"service {service} on {host}:{server.port}", flush=True)
        servers = [server]
    else:
        _, servers = start_all(store, images_dir, host)
        print(
            f"learningorchestra_tpu serving on ports 5000-5006 (host {host}); "
            f"data in {data_dir}",
            flush=True,
        )
    try:
        for server in servers:
            server._thread.join()
    except KeyboardInterrupt:
        for server in servers:
            server.stop()


if __name__ == "__main__":
    main()
