"""Serve the service stack: all seven in one process, or one per process.

The reference deploys seven Flask containers wired to a shared MongoDB
(docker-compose.yml:173-330). Both topologies exist here:

- **single process** (default): seven WSGI servers over one in-process
  WAL-backed store — ``python -m learningorchestra_tpu.services.runner``.
- **one service per process** (the reference's microservice shape):
  set ``LO_STORE_URL`` to a store server
  (``python -m learningorchestra_tpu.core.store_service``) and launch
  each service with ``LO_SERVICE=<name>`` — every process talks to the
  shared store over its wire protocol, exactly as the reference
  containers share Mongo via ``DATABASE_URL``.

Environment:
- ``LO_SERVICE`` — serve only this service (``database_api``,
  ``projection``, ``model_builder``, ``data_type_handler``,
  ``histogram``, ``tsne``, ``pca``); unset = all seven
- ``LO_PORT`` — bind port for single-service mode (default: the
  service's reference port; ``0`` = OS-assigned, printed on stdout)
- ``LO_STORE_URL`` — store server base URL (the reference's
  ``DATABASE_URL`` analogue); unset = in-process store
- ``LO_DATA_DIR`` — store WAL directory for the in-process store
  (default ``./lo_data``)
- ``LO_IMAGES_DIR`` — PNG volume root (default ``<data>/images``)
- ``LO_MODELS_DIR`` — model checkpoint volume (default
  ``<data>/models``; empty string disables checkpointing). In
  multi-host mode this must be a volume shared by every host.
- ``LO_COORDINATOR`` / ``LO_NUM_PROCESSES`` / ``LO_PROCESS_ID`` —
  join a multi-host device runtime (parallel/multihost.py): process 0
  serves REST and broadcasts compute jobs, the rest run SPMD worker
  loops (parallel/spmd.py). Requires ``LO_STORE_URL`` and a shared
  ``LO_MODELS_DIR``. One jax process per host.
- ``LO_JOB_WORKERS`` / ``LO_SCHED_DEVICE_WIDTH`` / ``LO_SCHED_QUEUE_CAP``
  — scheduler knobs (sched/config.py has the full table): host-class
  concurrency width (default 8, replacing the old hardcoded pool),
  device-class width (default 1 — SPMD dispatches never contend for the
  mesh), and the per-class queue cap past which submissions get HTTP
  429 + ``Retry-After``. All seven services submit through ONE
  process-wide scheduler whose journal (in the store) lets a restarted
  process re-enqueue never-started jobs and terminate pollers of
  orphaned ones — docs/scheduler.md.
- ``LO_HOST`` — bind host. Defaults to ``127.0.0.1``: the model-builder
  service executes request-supplied preprocessor code (reference parity),
  so exposing the stack beyond localhost must be an explicit opt-in
  (``LO_HOST=0.0.0.0``) behind whatever sandboxing the deployment adds —
  see deploy/README.md.
- ``LO_BUILD_WORKERS`` — cap the model builder's thread-per-classifier
  fan-out (ml/builder.py). N concurrent fits hold N device working sets;
  past ~1M rows/classifier on one chip set 1 to stay inside HBM.
- ``LO_PROGRAM_ROW_STEPS`` — scale the per-program row*steps budget that
  segments long fits into short XLA executions (ml/base.segment_steps);
  raise it on directly-attached chips with no execution watchdog.
- ``LO_JIT_CACHE`` — persistent XLA compilation cache directory
  (default ``<data>/jit_cache``; empty disables). Shared safely between
  processes; turns minutes of per-process estimator compiles into
  second-scale cache loads (utils/jitcache.py).
- ``LO_SHAPE_BUCKETS`` — ``0`` disables the quarter-octave padded-shape
  grid (parallel/sharding.bucket_rows); default on, so nearby dataset
  sizes reuse one compiled program per estimator.
- ``LO_SPILL_BYTES`` / ``LO_SPILL_DIR`` — out-of-core column budget for
  the in-process store (core/store.py): past the budget, cold column
  payloads move to disk-backed mappings. Applies to the store SERVER
  process in the microservice topology.
- ``LO_DEVCACHE_BYTES`` / ``LO_STORE_COMPRESS`` / ``LO_WRITE_OVERLAP``
  — data-plane knobs (docs/dataplane.md): the rev-keyed device cache's
  capacity (core/devcache.py; 0 disables), zlib compression on the
  binary store wire, and the builder's overlapped prediction
  write-back (0 restores synchronous writes).
- ``LO_SERVE_BYTES`` / ``LO_SERVE_BATCH_WINDOW_MS`` / ``LO_SERVE_MAX_BATCH``
  / ``LO_SERVE_MAX_ROWS`` / ``LO_SERVE_QUEUE_CAP`` / ``LO_SERVE_TIMEOUT_S``
  — online-serving knobs (docs/serving.md): the model registry's
  pinned-parameter byte budget (0 = host-only fallback), the
  micro-batch collection window, the per-dispatch request cap, the
  per-request row cap (413 past it), the bounded batcher inbox (429 +
  Retry-After past it), and the per-request wait bound.
- ``LO_FLEET_REPLICAS`` / ``LO_FLEET_RF`` / ``LO_FLEET_MODEL_QPS`` /
  ``LO_FLEET_DOWN_S`` — the replicated serving fleet (docs/serving.md
  "Fleet"): replica count, owners per model on the consistent-hash
  placement ring, the router's per-model admission quota, and the
  heartbeat age past which a replica is routed around. A replica
  process additionally carries ``LO_FLEET_REPLICA=<index>`` (set by
  the supervisor — deploy/stack.py — not by operators), which arms the
  per-process :class:`~learningorchestra_tpu.serve.fleet.ReplicaAgent`;
  the router itself is ``LO_SERVICE=router`` (default port 5007).
- ``LO_COALESCE_WINDOW_MS`` / ``LO_COALESCE_MAX_JOBS`` — the job
  coalescer (docs/scheduler.md): shape-compatible device jobs arriving
  within the window fuse into ONE vmap-across-jobs dispatch (0 =
  passthrough); max_jobs caps a fused batch's job axis.
- ``LO_INGEST_SLAB_BYTES`` — CSVs past this size parse as bounded slabs
  (core/ingest.py), keeping ingest's transient working set slab-sized.
- ``LO_AUTO_PROMOTE_S`` / ``LO_PEERS`` / ``LO_FAILOVER_TIMEOUT_S`` —
  store HA: follower self-promotion, term fencing, and the client-side
  re-point window (core/store_service.py; see deploy/README.md).

Observability: every service (and the store server) answers
``GET /metrics`` in Prometheus text format, and every request carries an
``X-Correlation-Id`` that threads REST → job → SPMD broadcast → phase
spans (``GET /jobs/<name>/trace``) — docs/observability.md has the
metric catalog and scrape examples.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.core.store import DocumentStore, InMemoryStore
from learningorchestra_tpu.sched import (
    JobJournal,
    Scheduler,
    recover_jobs,
    shard_scope,
)
from learningorchestra_tpu.services import (
    DATA_TYPE_HANDLER_PORT,
    DATABASE_API_PORT,
    HISTOGRAM_PORT,
    MODEL_BUILDER_PORT,
    PCA_PORT,
    PROJECTION_PORT,
    ROUTER_PORT,
    TSNE_PORT,
)
from learningorchestra_tpu.services import (
    data_type_handler,
    database_api,
    histogram,
    images,
    model_builder,
    projection,
)
from learningorchestra_tpu.ml.checkpoint import checkpoint_path as _ckpt
from learningorchestra_tpu.utils.web import ServerThread


# Deployment-knob readers (sched/config.py pattern): the runner's LO_*
# env reads funnel through these so the boot surface stays greppable
# and the contract analyzer (LO305) can verify the read-once
# discipline. deploy/run.sh's preflight validates the numeric domains
# before boot; unset/empty means "use the default".


def _str_env(name: str, default: str | None = None) -> str | None:
    return os.environ.get(name, default)


def _int_env(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError as error:
        raise ValueError(f"{name} must be an integer, got {raw!r}") from error


def _flag_env(name: str, default: bool = False) -> bool:
    """Strict 0/1 flags (the domain deploy/run.sh's preflight
    enforces): unset/empty -> ``default``, else ``raw == "1"``."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    return raw == "1"


SERVICES: dict[str, int] = {
    "database_api": DATABASE_API_PORT,
    "projection": PROJECTION_PORT,
    "model_builder": MODEL_BUILDER_PORT,
    "data_type_handler": DATA_TYPE_HANDLER_PORT,
    "histogram": HISTOGRAM_PORT,
    "tsne": TSNE_PORT,
    "pca": PCA_PORT,
}


def make_dispatcher(store: DocumentStore, images_dir: str):
    """SPMD dispatcher for the compute jobs (model fits, embeddings):
    the coordinator's REST handler submits, every process executes, only
    the coordinator writes to the store / images volume."""
    import jax

    from learningorchestra_tpu.ml.builder import build_model, predict_with_model
    from learningorchestra_tpu.ops.images import create_embedding_image
    from learningorchestra_tpu.parallel.spmd import SpmdDispatcher

    coordinator = jax.process_index() == 0
    dispatcher = SpmdDispatcher()

    def handle_build_model(payload: dict) -> None:
        # models_dir comes from the BROADCAST payload on every process —
        # never from per-host env — so the decision to enter the
        # checkpoint gather collective is identical across the mesh
        # (write_outputs still keeps filesystem writes coordinator-only)
        build_model(
            store,
            payload["training_filename"],
            payload["test_filename"],
            payload["preprocessor_code"],
            payload["classificators_list"],
            write_outputs=coordinator,
            models_dir=payload.get("models_dir"),
        )

    def handle_predict_model(payload: dict) -> None:
        predict_with_model(
            store,
            payload["checkpoint_path"],
            payload["training_filename"],
            payload["test_filename"],
            payload["preprocessor_code"],
            payload["prediction_filename"],
            write_outputs=coordinator,
        )

    def handle_embedding_image(payload: dict) -> None:
        create_embedding_image(
            store,
            payload["parent_filename"],
            payload["label_name"],
            payload["output_filename"],
            os.path.join(images_dir, payload["method"]),
            payload["method"],
            render=coordinator,
        )

    dispatcher.register("build_model", handle_build_model)
    dispatcher.register("predict_model", handle_predict_model)
    dispatcher.register("embedding_image", handle_embedding_image)
    return dispatcher


def make_job_manager(store: DocumentStore, scope: str = "all") -> JobManager:
    """One JobManager for the whole process: every service submits
    through a single scheduler, so the DEVICE class serializes builds
    and embeddings against each other process-wide, and every submit is
    journaled in the shared store for crash recovery."""
    return JobManager(
        # the scope gains the store's shard-topology suffix so recovery
        # replays stay shard-local (sched/journal.py shard_scope);
        # unsharded stores keep their scope strings byte-identical
        scheduler=Scheduler(
            journal=JobJournal(store, scope=shard_scope(scope, store))
        )
    )


def build_app(
    name: str,
    store: DocumentStore,
    images_dir: str,
    dispatcher=None,
    models_dir: str = "",
    jobs: "JobManager | None" = None,
):
    if name == "database_api":
        return database_api.create_app(store, jobs or JobManager())
    if name == "projection":
        return projection.create_app(store, jobs)
    if name == "model_builder":
        # Opt-in (LO_MODELS_DIR / models_dir): library and test callers
        # of start_all don't silently grow a checkpoint directory.
        models_dir = models_dir or _str_env("LO_MODELS_DIR", "")
        build = None
        predict = None
        if dispatcher is not None:
            def build(body: dict) -> None:
                payload = {
                    key: body[key]
                    for key in (
                        "training_filename",
                        "test_filename",
                        "preprocessor_code",
                        "classificators_list",
                    )
                }
                payload["models_dir"] = models_dir
                dispatcher.submit("build_model", payload)

            def predict(model_name: str, body: dict) -> None:
                dispatcher.submit(
                    "predict_model",
                    {
                        "checkpoint_path": _ckpt(models_dir, model_name),
                        "training_filename": body["training_filename"],
                        "test_filename": body["test_filename"],
                        "preprocessor_code": body["preprocessor_code"],
                        "prediction_filename": body["prediction_filename"],
                    },
                )
        return model_builder.create_app(
            store, build=build, models_dir=models_dir, predict=predict,
            jobs=jobs,
        )
    if name == "data_type_handler":
        return data_type_handler.create_app(store, jobs)
    if name == "histogram":
        return histogram.create_app(store, jobs)
    if name == "router":
        # The fleet router (serve/router.py): placement-aware predict
        # proxy + residency view, launched as its own LO_SERVICE —
        # never part of the all-in-one seven (start_all), because a
        # router in front of zero replicas routes nothing.
        from learningorchestra_tpu.serve import router as _router

        return _router.create_app(store)
    if name in ("tsne", "pca"):
        create = None
        if dispatcher is not None:
            def create(parent_filename, label_name, output_filename):
                dispatcher.submit(
                    "embedding_image",
                    {
                        "parent_filename": parent_filename,
                        "label_name": label_name,
                        "output_filename": output_filename,
                        "method": name,
                    },
                )
        return images.create_app(
            store, os.path.join(images_dir, name), name, create=create,
            jobs=jobs,
        )
    raise KeyError(f"unknown service {name!r}")


def build_apps(
    store: DocumentStore,
    images_dir: str,
    dispatcher=None,
    models_dir: str = "",
    jobs: "JobManager | None" = None,
) -> dict[int, object]:
    # One shared JobManager unless the caller brings their own: the
    # seven services must share a scheduler or the device class cannot
    # serialize builds against embeddings.
    jobs = jobs or make_job_manager(store)
    return {
        port: build_app(name, store, images_dir, dispatcher, models_dir, jobs)
        for name, port in SERVICES.items()
    }


# One fallback collector per (process, store): main() starts it before
# start_all, and start_all starts it for embedded callers (tests, the
# verify drive) — whoever gets there first wins, the other is a no-op.
_COLLECTORS: dict[int, object] = {}
_COLLECTORS_LOCK = threading.Lock()


def maybe_start_collector(
    store: DocumentStore, instance: str = "runner", service: str = "runner"
):
    """Start the single-process fallback TSDB collector for ``store``
    unless one is already running, collection is disabled
    (``LO_TSDB_COLLECT=0`` — the cluster driver owns the scrape), or the
    interval is zero. Returns the Collector, or None when gated off."""
    from learningorchestra_tpu.telemetry import metrics as _metrics
    from learningorchestra_tpu.telemetry import tsdb as _tsdb

    if not (_tsdb.collect_enabled() and _tsdb.metrics_interval_s() > 0):
        return None
    with _COLLECTORS_LOCK:
        collector = _COLLECTORS.get(id(store))
        if collector is None:
            collector = _tsdb.Collector(
                store,
                _metrics.global_registry(),
                instance=instance,
                service=service,
            ).start()
            _COLLECTORS[id(store)] = collector
    return collector


def start_all(
    store: Optional[DocumentStore] = None,
    images_dir: Optional[str] = None,
    host: str = "127.0.0.1",
    ephemeral: bool = False,
    dispatcher=None,
    models_dir: str = "",
    jobs: "JobManager | None" = None,
) -> tuple[DocumentStore, list[ServerThread]]:
    """Start all seven services on their reference ports; returns the
    shared store and the server threads (callers stop() them).

    ``ephemeral=True`` binds OS-assigned ports instead (tests can't
    assume 5000-5006 are free); each server's ``canonical_port`` records
    which reference port it stands in for, its ``port`` the actual bind.
    """
    store = store if store is not None else InMemoryStore()
    images_dir = images_dir or os.path.join(os.getcwd(), "lo_images")
    maybe_start_collector(store)
    servers = []
    apps = build_apps(store, images_dir, dispatcher, models_dir, jobs)
    for port, app in apps.items():
        server = ServerThread(app, host, 0 if ephemeral else port)
        server.canonical_port = port
        servers.append(server.start())
    return store, servers


def main() -> None:
    # An explicit JAX_PLATFORMS in the deployment env is binding. Some
    # hosts carry an accelerator-registration sitecustomize that
    # force-overrides the jax_platforms CONFIG at interpreter start
    # (after env capture), silently putting a remote accelerator first;
    # re-assert the operator's choice through the config API.
    if os.environ.get("JAX_PLATFORMS"):
        import jax as _jax

        _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from learningorchestra_tpu.core.store_service import connect
    from learningorchestra_tpu.parallel.multihost import initialize_from_env

    # Join the multi-host device runtime first if the deployment asks for
    # one (LO_COORDINATOR/LO_NUM_PROCESSES/LO_PROCESS_ID): the compute
    # services then see the global mesh — the reference's "add spark
    # workers" knob (README.md:94) as an environment setting. One jax
    # process per host: run the all-in-one runner (or one compute
    # service) per host, not seven LO_SERVICE processes each trying to
    # join as the same process_id.
    print(
        "runner starting: "
        # boot banner; name-set knobs checked by runner/multihost at
        # boot, not range-checkable by the preflight
        f"LO_SERVICE={_str_env('LO_SERVICE')!r} "  # lo: allow[LO301]
        f"LO_COORDINATOR={_str_env('LO_COORDINATOR')!r} "  # lo: allow[LO301]
        f"LO_PROCESS_ID={_str_env('LO_PROCESS_ID')!r} "  # lo: allow[LO301]
        f"JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS')!r}",
        flush=True,
    )
    multi_host = initialize_from_env()

    # Fail fast on a malformed device-cache budget — the same startup
    # posture as the scheduler knobs: a typo'd LO_DEVCACHE_BYTES must
    # not silently run at the default capacity.
    from learningorchestra_tpu.core.devcache import capacity_bytes

    print(f"devcache capacity: {capacity_bytes()} bytes", flush=True)

    # Same fail-fast posture for the serving knobs: a typo'd
    # LO_SERVE_BYTES must not silently serve at the default budget.
    from learningorchestra_tpu.serve import config as serve_config

    print(f"serving config: {serve_config.validate_all()}", flush=True)

    # ...and the fleet knobs (docs/serving.md "Fleet"): an operator
    # should see at boot whether this process is a fleet replica (and
    # which index) or a plain single serving plane, and a typo'd
    # LO_FLEET_RF must refuse bring-up, never silently place models
    # with the wrong replication
    from learningorchestra_tpu.serve import fleet as serve_fleet

    print(f"fleet config: {serve_fleet.validate_env()}", flush=True)

    # ...and the coalescing knobs (docs/scheduler.md): window 0 means
    # passthrough, which an operator should see stated at boot
    from learningorchestra_tpu.sched import config as sched_config

    print(
        "coalescing config: "
        f"window_s={sched_config.coalesce_window_s()} "
        f"max_jobs={sched_config.coalesce_max_jobs()}",
        flush=True,
    )

    # ...and the crash-resume knobs (docs/robustness.md): an operator
    # should see at boot whether orphaned builds will resume or fail,
    # and a typo'd LO_RESUME must refuse bring-up, never silently pick
    # a side
    print(
        "resume config: "
        f"enabled={sched_config.resume_enabled()} "
        f"every_segments={sched_config.resume_every_segments()}",
        flush=True,
    )

    # ...and the zero-copy wire knobs (docs/dataplane.md): shm_bytes 0
    # means frames ride the HTTP body — an operator expecting the ring
    # should see that stated at boot, and a typo'd LO_DTYPE_POLICY
    # must refuse bring-up, never silently fit at the wrong precision
    from learningorchestra_tpu.core import shmring
    from learningorchestra_tpu.utils.dtypepolicy import dtype_policy

    print(
        f"wire config: shm_bytes={shmring.shm_bytes()} "
        f"dtype_policy={dtype_policy()} "
        f"v2={_flag_env('LO_WIRE_V2', default=True)}",
        flush=True,
    )

    # ...and the sharding knobs (docs/dataplane.md): an operator should
    # see at boot how many shard groups this process routes across (the
    # ';' groups of LO_STORE_URL — 1 means the unsharded wire path) and
    # which stripe geometry a first write would seed; a typo'd
    # LO_SHARD_STRIPE_ROWS must refuse bring-up, never silently seed an
    # unintended placement into the fleet's shard map
    from learningorchestra_tpu.core import shardmap

    store_url = _str_env("LO_STORE_URL")  # lo: allow[LO301] free-form URL
    shard_groups = len([g for g in store_url.split(";") if g.strip()]) or 1
    print(
        f"shard config: groups={shard_groups} "
        f"stripe_rows={shardmap.stripe_rows()} "
        f"map_ttl_s={shardmap.map_ttl_s()}",
        flush=True,
    )

    # ...and the web-serving knobs (docs/web.md): LO_WEB_ASYNC=0 is the
    # threaded escape hatch — an operator should see at boot which
    # serving core is live, and a typo'd LO_WEB_HANDLERS must refuse
    # bring-up, never silently serve at the default width
    from learningorchestra_tpu.utils import webloop

    print(f"web config: {webloop.validate_env()}", flush=True)

    # ...and the AOT compile-plane knobs (docs/compile.md): whether the
    # boot precompile pass runs, how much of the manifest it covers,
    # and whether executables publish to the fleet — a typo'd LO_AOT
    # must refuse bring-up, never silently boot cold
    from learningorchestra_tpu.compile import config as compile_config

    print(f"compile config: {compile_config.validate_env()}", flush=True)

    data_dir = _str_env("LO_DATA_DIR", os.path.join(os.getcwd(), "lo_data"))
    from learningorchestra_tpu.utils.jitcache import enable_compile_cache

    enable_compile_cache(os.path.join(data_dir, "jit_cache"))  # data_dir may predate env read
    # lo: allow[LO301] free-form volume path, no domain to preflight
    images_dir = _str_env(
        "LO_IMAGES_DIR", os.path.join(data_dir, "images")
    )
    models_dir = _str_env(
        "LO_MODELS_DIR", os.path.join(data_dir, "models")
    )
    host = _str_env("LO_HOST", "127.0.0.1")
    store_url = _str_env("LO_STORE_URL")
    service = _str_env("LO_SERVICE")

    if store_url:
        store = connect(store_url)
    else:
        store = InMemoryStore(data_dir=data_dir)

    dispatcher = None
    if multi_host:
        import jax

        if not store_url:
            # Every process of the mesh must see the SAME datasets; a
            # per-process InMemoryStore would leave workers reading an
            # empty store and the coordinator waiting forever in its
            # first cross-host collective. Refuse to start.
            raise SystemExit(
                "multi-host mode requires LO_STORE_URL: all processes "
                "must share one store server "
                "(python -m learningorchestra_tpu.core.store_service)"
            )
        if _str_env("LO_MODELS_DIR") is None:
            # Same reasoning for checkpoints: predict-from-checkpoint
            # broadcasts the artifact path to every process, so the
            # models dir must be a volume all hosts mount — not each
            # host's local disk. Make the choice explicit.
            raise SystemExit(
                "multi-host mode requires LO_MODELS_DIR pointing at a "
                "volume shared by all hosts (set it to '' to disable "
                "checkpointing)"
            )
        print(
            f"multi-host runtime: process {jax.process_index()}/"
            f"{jax.process_count()}, {jax.device_count()} global devices",
            flush=True,
        )
        dispatcher = make_dispatcher(store, images_dir)
        # keep idle workers' pending broadcast inside the transport's
        # collective deadline (see SpmdDispatcher.start_heartbeat)
        dispatcher.start_heartbeat()
        if jax.process_index() > 0:
            # Worker host: no REST surface — execute the jobs the
            # coordinator broadcasts (the spark-worker role,
            # reference docker-compose.yml:123-163).
            print("spmd worker: waiting for jobs", flush=True)
            dispatcher.run_worker_loop()
            return

    # One scheduler + journal for every service this process runs.
    # Scope the journal to the service in the one-process-per-service
    # topology so each restarted process recovers only its own jobs
    # from the shared store. Recovery runs BEFORE the REST surface
    # accepts traffic: never-started jobs re-enqueue, orphaned RUNNING
    # jobs go FAILED with finished:true so pollers terminate — the
    # crash the reference hangs on (docs/scheduler.md).
    # ...and the fleet-observability knobs (docs/observability.md): a
    # typo'd LO_SLO_* threshold must refuse bring-up, and an operator
    # should see at boot whether this process self-scrapes into the
    # store-backed TSDB ring or defers to a cluster driver
    # (deploy/cluster.py sets LO_TSDB_COLLECT=0 and collects centrally
    # through POST /metrics/ingest).
    from learningorchestra_tpu.telemetry import slo as _slo
    from learningorchestra_tpu.telemetry import tracing as _tracing
    from learningorchestra_tpu.telemetry import tsdb as _tsdb

    print(
        "observability config: "
        f"collect={_tsdb.collect_enabled()} "
        f"interval_s={_tsdb.metrics_interval_s()} "
        f"points={_tsdb.tsdb_points()} "
        f"trace_ring={_tracing.trace_ring()} "
        f"slo={_slo.validate_env()}",
        flush=True,
    )
    maybe_start_collector(
        store, instance=service or "runner", service=service or "runner"
    )

    # The AOT compile plane (docs/compile.md): fleet-fetch serialized
    # executables into the local jit cache, precompile the manifest in
    # the background (a daemon thread — compilation is host CPU work,
    # it never occupies a device-class scheduler slot), publish fresh
    # entries back. Gated on LO_AOT; the kill -9 restart drill rides
    # this — a restarted runner pulls its own previously published
    # programs and replays with zero compile misses.
    from learningorchestra_tpu.compile import boot_compile_plane

    if boot_compile_plane(store=store, models_dir=models_dir or ""):
        print("aot compile plane: precompiling in background", flush=True)

    jobs = make_job_manager(store, scope=service or "all")
    recovered = recover_jobs(store, jobs)
    if recovered["requeued"] or recovered["orphaned"]:
        print(
            "job recovery: "
            f"{len(recovered['requeued'])} re-enqueued, "
            f"{len(recovered['orphaned'])} orphaned jobs marked failed",
            flush=True,
        )

    if service:
        port = _int_env(
            "LO_PORT",
            ROUTER_PORT if service == "router" else SERVICES[service],
        )
        server = ServerThread(
            build_app(service, store, images_dir, dispatcher, models_dir, jobs),
            host,
            port,
        )
        server.start()
        print(f"service {service} on {host}:{server.port}", flush=True)
        servers = [server]
        if (
            service == "model_builder"
            and serve_fleet.replica_index() is not None
        ):
            # This process is a fleet replica: run the agent that pins
            # this replica's placement-assigned checkpoints (warming
            # them at the serve shape) and heartbeats residency into
            # the store the router reads. Uses the process-wide plane —
            # the same one create_app serves predicts from.
            from learningorchestra_tpu.serve import global_serve_plane

            agent = serve_fleet.ReplicaAgent(
                store,
                models_dir or "",
                global_serve_plane(),
                url=f"http://{host}:{server.port}",
            ).start()
            print(
                f"fleet replica {agent.index}: agent started "
                f"(interval {agent.interval_s}s)",
                flush=True,
            )
    else:
        _, servers = start_all(
            store,
            images_dir,
            host,
            ephemeral=_flag_env("LO_EPHEMERAL"),
            dispatcher=dispatcher,
            models_dir=models_dir,
            jobs=jobs,
        )
        port_names = {port: name for name, port in SERVICES.items()}
        for server in servers:
            name = port_names[server.canonical_port]
            print(f"service {name} on {host}:{server.port}", flush=True)
        print(
            f"learningorchestra_tpu serving all services (host {host}); "
            f"data in {data_dir}",
            flush=True,
        )
    try:
        for server in servers:
            server._thread.join()
    except KeyboardInterrupt:
        for server in servers:
            server.stop()


if __name__ == "__main__":
    main()
