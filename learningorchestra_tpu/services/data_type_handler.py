"""data_type_handler service (port 5003).

Reference: microservices/data_type_handler_image/server.py:46-76. The
request body IS the field→type dict; success message is ``file_changed``
with status 200."""

from __future__ import annotations

import itertools

from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.core.store import DocumentStore
from learningorchestra_tpu.ops.dtype import convert_field_types
from learningorchestra_tpu.sched import HOST_CLASS, QueueFullError
from learningorchestra_tpu.services import validators
from learningorchestra_tpu.telemetry import register_store, span
from learningorchestra_tpu.utils.web import WebApp, too_many_requests

MESSAGE_RESULT = "result"
MESSAGE_CHANGED_FILE = "file_changed"


def create_app(store: DocumentStore, jobs: JobManager | None = None) -> WebApp:
    app = WebApp("data_type_handler")
    jobs = jobs or JobManager()
    register_store(store)
    app.register_job_routes(jobs)
    app.register_observability(store)
    # fieldtypes passes are legitimately repeatable on one dataset (the
    # reference allows back-to-back casts), so job names take a sequence
    # suffix instead of colliding as duplicates
    conversion_seq = itertools.count()

    @app.route("/fieldtypes/<filename>", methods=("PATCH",))
    def change_data_type(request, filename):
        fields = request.get_json()
        try:
            validators.filename_exists(store, filename)
            validators.field_types_valid(store, filename, fields)
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406

        def work() -> None:
            # the 61%-of-pipeline cast (VERDICT r5) now shows up as its
            # own span in any trace that includes a fieldtypes pass
            with span("dtype:convert", filename=filename):
                convert_field_types(store, filename, fields)

        try:
            jobs.run_sync(
                f"dtype:{filename}#{next(conversion_seq)}",
                work,
                job_class=HOST_CLASS,
            )
        except QueueFullError as error:
            return too_many_requests(error)
        return {MESSAGE_RESULT: MESSAGE_CHANGED_FILE}, 200

    return app
