"""data_type_handler service (port 5003).

Reference: microservices/data_type_handler_image/server.py:46-76. The
request body IS the field→type dict; success message is ``file_changed``
with status 200."""

from __future__ import annotations

from learningorchestra_tpu.core.store import DocumentStore
from learningorchestra_tpu.ops.dtype import convert_field_types
from learningorchestra_tpu.services import validators
from learningorchestra_tpu.telemetry import register_store, span
from learningorchestra_tpu.utils.web import WebApp

MESSAGE_RESULT = "result"
MESSAGE_CHANGED_FILE = "file_changed"


def create_app(store: DocumentStore) -> WebApp:
    app = WebApp("data_type_handler")
    register_store(store)

    @app.route("/fieldtypes/<filename>", methods=("PATCH",))
    def change_data_type(request, filename):
        fields = request.get_json()
        try:
            validators.filename_exists(store, filename)
            validators.field_types_valid(store, filename, fields)
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        # the 61%-of-pipeline cast (VERDICT r5) now shows up as its own
        # span in any trace that includes a fieldtypes pass
        with span("dtype:convert", filename=filename):
            convert_field_types(store, filename, fields)
        return {MESSAGE_RESULT: MESSAGE_CHANGED_FILE}, 200

    return app
