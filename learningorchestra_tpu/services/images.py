"""tsne (port 5005) and pca (port 5006) services — one parametrized app.

Reference: microservices/tsne_image/server.py:57-163 and
pca_image/server.py (identical shape; only the request key differs:
``tsne_filename`` vs ``pca_filename``). Image-existence validation is
filesystem-based, like the reference (tsne.py:162-175): duplicates → 409,
missing on GET/DELETE → 404 with ``file_not_found``."""

from __future__ import annotations

import os

from learningorchestra_tpu.core.store import DocumentStore
from learningorchestra_tpu.ops.images import IMAGE_FORMAT, create_embedding_image
from learningorchestra_tpu.services import validators
from learningorchestra_tpu.utils.web import WebApp, send_file

MESSAGE_RESULT = "result"
MESSAGE_CREATED_FILE = "created_file"
MESSAGE_DELETED_FILE = "deleted_file"


def create_app(store: DocumentStore, images_path: str, method: str) -> WebApp:
    """``method`` is "tsne" or "pca"; the request filename key follows it."""
    app = WebApp(method)
    filename_key = f"{method}_filename"
    os.makedirs(images_path, exist_ok=True)

    def image_exists(name: str) -> bool:
        return (name + IMAGE_FORMAT) in os.listdir(images_path)

    @app.route("/images/<parent_filename>", methods=("POST",))
    def create_image(request, parent_filename):
        body = request.get_json()
        output_filename = body[filename_key]
        label_name = body.get("label_name")
        if image_exists(output_filename):
            return {MESSAGE_RESULT: validators.MESSAGE_DUPLICATE_FILE}, 409
        try:
            validators.filename_exists(store, parent_filename)
            validators.label_in_metadata(store, parent_filename, label_name)
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        create_embedding_image(
            store, parent_filename, label_name, output_filename, images_path, method
        )
        return {MESSAGE_RESULT: MESSAGE_CREATED_FILE}, 201

    @app.route("/images", methods=("GET",))
    def get_images(request):
        return {MESSAGE_RESULT: os.listdir(images_path)}, 200

    @app.route("/images/<filename>", methods=("GET",))
    def get_image(request, filename):
        if not image_exists(filename):
            return {MESSAGE_RESULT: validators.MESSAGE_NOT_FOUND}, 404
        return send_file(
            os.path.join(images_path, filename + IMAGE_FORMAT), "image/png"
        )

    @app.route("/images/<filename>", methods=("DELETE",))
    def delete_image(request, filename):
        if not image_exists(filename):
            return {MESSAGE_RESULT: validators.MESSAGE_NOT_FOUND}, 404
        os.remove(os.path.join(images_path, filename + IMAGE_FORMAT))
        return {MESSAGE_RESULT: MESSAGE_DELETED_FILE}, 200

    return app
