"""tsne (port 5005) and pca (port 5006) services — one parametrized app.

Reference: microservices/tsne_image/server.py:57-163 and
pca_image/server.py (identical shape; only the request key differs:
``tsne_filename`` vs ``pca_filename``). Image-existence validation is
filesystem-based, like the reference (tsne.py:162-175): duplicates → 409,
missing on GET/DELETE → 404 with ``file_not_found``."""

from __future__ import annotations

import contextlib
import os

from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.core.store import DocumentStore
from learningorchestra_tpu.ops.images import IMAGE_FORMAT, create_embedding_image
from learningorchestra_tpu.sched import DEVICE_CLASS, QueueFullError
from learningorchestra_tpu.services import validators
from learningorchestra_tpu.telemetry import register_store, span
from learningorchestra_tpu.utils.web import WebApp, send_file, too_many_requests

MESSAGE_RESULT = "result"
MESSAGE_CREATED_FILE = "created_file"
MESSAGE_DELETED_FILE = "deleted_file"

# In-flight create claims are `<name>.png.part` markers: atomic (O_EXCL)
# duplicate gating without ever exposing a 0-byte PNG to GET/DELETE. A
# crash can leave a stale marker blocking the name; DELETE on the name
# clears it once the PNG exists, a stale-only marker needs operator
# cleanup (the reference has no equivalent safeguard at all).
CLAIM_SUFFIX = ".part"


def create_app(
    store: DocumentStore,
    images_path: str,
    method: str,
    create=None,
    jobs: JobManager | None = None,
) -> WebApp:
    """``method`` is "tsne" or "pca"; the request filename key follows it.

    ``create`` overrides how a validated request becomes a
    create_embedding_image call — the multi-host runner injects an SPMD
    dispatch (parallel/spmd.py) so every process enters the embedding;
    default is the in-process call. Embeddings are device-bound (the
    t-SNE/PCA solvers own the mesh while they iterate), so creates run
    through the scheduler's DEVICE class and serialize against model
    builds instead of contending with them."""
    app = WebApp(method)
    jobs = jobs or JobManager()
    register_store(store)
    app.register_job_routes(jobs)
    app.register_observability(store)

    if create is None:

        def create(parent_filename, label_name, output_filename):
            create_embedding_image(
                store,
                parent_filename,
                label_name,
                output_filename,
                images_path,
                method,
            )
    filename_key = f"{method}_filename"
    os.makedirs(images_path, exist_ok=True)

    def image_path(name: str) -> str:
        return os.path.join(images_path, name + IMAGE_FORMAT)

    def image_exists(name: str) -> bool:
        """The finished PNG exists — what GET/DELETE see."""
        return (name + IMAGE_FORMAT) in os.listdir(images_path)

    def name_taken(name: str) -> bool:
        """Finished PNG *or* an in-flight claim — the duplicate gate."""
        listing = os.listdir(images_path)
        return (name + IMAGE_FORMAT) in listing or (
            name + IMAGE_FORMAT + CLAIM_SUFFIX
        ) in listing

    def claim_image(name: str) -> bool:
        """Atomically claim the name with a ``.part`` marker; False if a
        concurrent create won. The marker — not the PNG — carries the
        claim, so an in-progress image is never visible to GET/DELETE."""
        try:
            fd = os.open(
                image_path(name) + CLAIM_SUFFIX,
                os.O_CREAT | os.O_EXCL | os.O_WRONLY,
            )
        except FileExistsError:
            return False
        os.close(fd)
        return True

    def release_claim(name: str, keep_png: bool) -> None:
        with contextlib.suppress(FileNotFoundError):
            os.remove(image_path(name) + CLAIM_SUFFIX)
        if not keep_png:
            with contextlib.suppress(FileNotFoundError):
                os.remove(image_path(name))  # partially rendered output

    @app.route("/images/<parent_filename>", methods=("POST",))
    def create_image(request, parent_filename):
        body = request.get_json()
        output_filename = body[filename_key]
        label_name = body.get("label_name")
        if not validators.safe_filename(output_filename):
            return {MESSAGE_RESULT: validators.MESSAGE_INVALID_FILENAME}, 406
        if name_taken(output_filename):
            return {MESSAGE_RESULT: validators.MESSAGE_DUPLICATE_FILE}, 409
        try:
            validators.filename_exists(store, parent_filename)
            validators.label_in_metadata(store, parent_filename, label_name)
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        if not claim_image(output_filename):
            return {MESSAGE_RESULT: validators.MESSAGE_DUPLICATE_FILE}, 409
        if os.path.exists(image_path(output_filename)):
            # A concurrent create finished between name_taken() and our
            # marker acquisition; the marker alone isn't the whole claim —
            # marker + absent PNG is. Never overwrite a finished image.
            release_claim(output_filename, keep_png=True)
            return {MESSAGE_RESULT: validators.MESSAGE_DUPLICATE_FILE}, 409
        def work() -> None:
            with span(f"{method}:embed", parent=parent_filename):
                create(parent_filename, label_name, output_filename)

        try:
            jobs.run_sync(
                f"{method}:{output_filename}", work, job_class=DEVICE_CLASS
            )
        except QueueFullError as error:
            release_claim(output_filename, keep_png=False)
            return too_many_requests(error)
        except BaseException:
            release_claim(output_filename, keep_png=False)
            raise
        release_claim(output_filename, keep_png=True)
        return {MESSAGE_RESULT: MESSAGE_CREATED_FILE}, 201

    @app.route("/images", methods=("GET",))
    def get_images(request):
        # Only finished PNGs — in-flight `.part` claim markers are an
        # implementation detail the client never sees (the reference
        # lists only rendered images, tsne_image/server.py:110-118).
        listing = [
            name
            for name in os.listdir(images_path)
            if not name.endswith(CLAIM_SUFFIX)
        ]
        return {MESSAGE_RESULT: listing}, 200

    @app.route("/images/<filename>", methods=("GET",))
    def get_image(request, filename):
        if not validators.safe_filename(filename) or not image_exists(filename):
            return {MESSAGE_RESULT: validators.MESSAGE_NOT_FOUND}, 404
        return send_file(image_path(filename), "image/png")

    @app.route("/images/<filename>", methods=("DELETE",))
    def delete_image(request, filename):
        if not validators.safe_filename(filename) or not image_exists(filename):
            return {MESSAGE_RESULT: validators.MESSAGE_NOT_FOUND}, 404
        os.remove(image_path(filename))
        with contextlib.suppress(FileNotFoundError):
            os.remove(image_path(filename) + CLAIM_SUFFIX)
        return {MESSAGE_RESULT: MESSAGE_DELETED_FILE}, 200

    return app
