"""database_api service (port 5000) — dataset CRUD.

Reference: microservices/database_api_image/server.py:33-96. Same
routes, payloads, status codes and messages; ingestion stays
asynchronous (201 immediately, rows land on a background job, the
``finished`` flag flips at the end — reference database.py:199-216) but
runs through the batched columnar ingest and a real job manager whose
failures also terminate pollers (core/jobs.py)."""

from __future__ import annotations

from learningorchestra_tpu.core.ingest import (
    DUPLICATE_FILE,
    INVALID_URL,
    IngestError,
    ingest_csv,
    validate_csv_url,
    write_ingest_metadata,
)
from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.core.store import (
    METADATA_ID,
    ROW_ID,
    DocumentStore,
    UnsupportedQueryError,
    parse_query,
)
from learningorchestra_tpu.sched import HOST_CLASS, QueueFullError
from learningorchestra_tpu.telemetry import register_store
from learningorchestra_tpu.utils.web import WebApp, too_many_requests

MESSAGE_RESULT = "result"
MESSAGE_CREATED_FILE = "file_created"
MESSAGE_DELETED_FILE = "deleted_file"
PAGINATE_FILE_LIMIT = 20


def create_app(store: DocumentStore, jobs: JobManager | None = None) -> WebApp:
    app = WebApp("database_api")
    jobs = jobs or JobManager()
    register_store(store)
    # GET /jobs (+ /trace, DELETE): every async job's state — PENDING/
    # RUNNING/FINISHED/FAILED/CANCELLED, class, attempts, timings,
    # error — inspectable and cancellable over REST instead of only via
    # each collection's metadata row.
    app.register_job_routes(jobs)
    app.register_observability(store)

    @app.route("/files", methods=("POST",))
    def create_file(request):
        body = request.get_json()
        url = body["url"]
        filename = body["filename"]
        try:
            validate_csv_url(url)
        except IngestError:
            return {MESSAGE_RESULT: INVALID_URL}, 406
        try:
            write_ingest_metadata(store, filename, url)
        except KeyError:
            return {MESSAGE_RESULT: DUPLICATE_FILE}, 409
        try:
            jobs.submit(
                f"ingest:{filename}",
                ingest_csv,
                store,
                filename,
                url,
                store=store,
                collection=filename,
                job_class=HOST_CLASS,
                # the journaled lineage: a restart that finds this job
                # admitted-but-never-started re-runs the ingest from
                # (filename, url) alone (sched/recovery.py)
                replay=("ingest", {"filename": filename, "url": url}),
            )
        except QueueFullError as error:
            # admission refused: undo the name claim so the client can
            # simply resubmit after Retry-After
            store.drop(filename)
            return too_many_requests(error)
        return {MESSAGE_RESULT: MESSAGE_CREATED_FILE}, 201

    @app.route("/files/<filename>", methods=("GET",))
    def read_file(request, filename):
        try:
            limit = int(request.args.get("limit", PAGINATE_FILE_LIMIT))
            skip = int(request.args.get("skip", 0))
        except ValueError:
            return {MESSAGE_RESULT: "invalid skip/limit"}, 400
        limit = min(limit, PAGINATE_FILE_LIMIT)
        try:
            query = parse_query(request.args.get("query"))
            documents = list(store.find(filename, query, skip=skip, limit=limit))
        except UnsupportedQueryError as error:
            return {MESSAGE_RESULT: str(error)}, 400
        except (ValueError, SyntaxError):  # unparseable query string
            return {MESSAGE_RESULT: "invalid query"}, 400
        return {MESSAGE_RESULT: documents}, 200

    @app.route("/files", methods=("GET",))
    def read_files_descriptor(request):
        result = []
        for filename in store.list_collections():
            metadata = store.find_one(filename, {ROW_ID: METADATA_ID})
            if metadata is None:
                continue
            metadata.pop(ROW_ID, None)
            result.append(metadata)
        return {MESSAGE_RESULT: result}, 200

    @app.route("/files/<filename>", methods=("DELETE",))
    def delete_file(request, filename):
        store.drop(filename)
        return {MESSAGE_RESULT: MESSAGE_DELETED_FILE}, 200

    return app
