"""histogram service (port 5004).

Reference: microservices/histogram_image/server.py:35-83. Duplicate
output name → 409 with ``duplicated_filename`` (this service's string
differs from projection's ``duplicate_file`` — reference
histogram.py:25)."""

from __future__ import annotations

from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.core.store import DocumentStore
from learningorchestra_tpu.ops.histogram import create_histogram
from learningorchestra_tpu.sched import HOST_CLASS, QueueFullError
from learningorchestra_tpu.services import validators
from learningorchestra_tpu.telemetry import register_store, span
from learningorchestra_tpu.utils.web import WebApp, too_many_requests

MESSAGE_RESULT = "result"
MESSAGE_CREATED_FILE = "created_file"


def create_app(store: DocumentStore, jobs: JobManager | None = None) -> WebApp:
    app = WebApp("histogram")
    jobs = jobs or JobManager()
    register_store(store)
    app.register_job_routes(jobs)
    app.register_observability(store)

    @app.route("/histograms/<parent_filename>", methods=("POST",))
    def create_histogram_route(request, parent_filename):
        body = request.get_json()
        histogram_filename = body["histogram_filename"]
        fields = body["fields"]
        try:
            validators.filename_free(
                store, histogram_filename, validators.MESSAGE_HISTOGRAM_DUPLICATE
            )
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 409
        try:
            validators.filename_exists(store, parent_filename)
            validators.fields_in_metadata(store, parent_filename, fields)
        except validators.ValidationError as error:
            return {MESSAGE_RESULT: error.args[0]}, 406
        # Atomic claim closes the duplicate-create race (SURVEY §5).
        if not store.create_collection(histogram_filename):
            return {MESSAGE_RESULT: validators.MESSAGE_HISTOGRAM_DUPLICATE}, 409

        def work() -> None:
            with span("histogram:compute", parent=parent_filename):
                create_histogram(
                    store, parent_filename, histogram_filename, list(fields)
                )

        # Synchronous response, scheduled execution: host-class width
        # bounds concurrent aggregations, the queue cap backpressures.
        try:
            jobs.run_sync(
                f"histogram:{histogram_filename}", work, job_class=HOST_CLASS
            )
        except QueueFullError as error:
            store.drop(histogram_filename)  # release the name claim
            return too_many_requests(error)
        except BaseException:
            store.drop(histogram_filename)
            raise
        return {MESSAGE_RESULT: MESSAGE_CREATED_FILE}, 201

    return app
