"""The seven REST services, same surface as the reference microservices.

Route table (identical paths, methods, ports, status codes and error
strings — reference files cited per module):

| port | service           | routes                                   |
|------|-------------------|------------------------------------------|
| 5000 | database_api      | POST/GET /files, GET/DELETE /files/<f>   |
| 5001 | projection        | POST /projections/<parent>               |
| 5002 | model_builder     | POST /models                             |
| 5003 | data_type_handler | PATCH /fieldtypes/<f>                    |
| 5004 | histogram         | POST /histograms/<parent>                |
| 5005 | tsne              | POST/GET/DELETE /images[...]             |
| 5006 | pca               | POST/GET/DELETE /images[...]             |

Each module exposes ``create_app(store, ...) -> WebApp``; the reference's
per-service Flask processes map to ``services.runner`` which serves any
subset against a shared store.

Beyond the reference surface, model_builder also serves the ONLINE
prediction lane (``POST /models/<name>/predict`` — synchronous labels +
probabilities from a device-resident model registry with request
micro-batching, docs/serving.md), and every service answers ``GET /metrics``
(Prometheus text exposition — request counts/latency, job states,
jitcache hit/miss, store occupancy; see docs/observability.md) and the
job surface (``GET /jobs``, ``GET /jobs/<name>/trace``,
``DELETE /jobs/<name>`` for cooperative cancellation): since the
scheduler subsystem (docs/scheduler.md) every service's work runs as a
tracked job through class-aware priority queues — device-bound jobs
serialize so SPMD dispatches never contend for the mesh, and a full
queue answers 429 + ``Retry-After``.
"""

DATABASE_API_PORT = 5000
PROJECTION_PORT = 5001
MODEL_BUILDER_PORT = 5002
DATA_TYPE_HANDLER_PORT = 5003
HISTOGRAM_PORT = 5004
TSNE_PORT = 5005
PCA_PORT = 5006
# Beyond the reference table: the fleet router (serve/router.py) — the
# one client-facing URL in front of N serving replicas. Launched as
# LO_SERVICE=router, never part of the all-in-one seven.
ROUTER_PORT = 5007
