"""Crash recovery: replay the journal, re-enqueue or terminate.

The reference's failure mode — the exact one this exists to close — is
a worker that dies with ``finished: false`` on a dataset's metadata,
leaving every client polling forever (reference database.py:199-216,
client __init__.py:24-32). On restart, :func:`recover_jobs`:

1. **Re-enqueues jobs that never started** (last journal event
   ``submitted``/``retry``) whose operation is in the replay registry —
   the submit document carries the op name and payload, so the work
   reconstructs without the original closure (the lineage idea from
   Ray, reduced to named idempotent operations).
2. **Resumes orphaned RUNNING jobs whose op is resumable** (last event
   ``started``, op in the resume registry, ``LO_RESUME`` enabled):
   re-enqueues the work under the same name with the journaled
   ``progress`` events — per-classifier completions, fit-segment saves
   — so the resumed run performs only the remaining work. Parked
   waiters never noticed: same name, same record map, the push hook
   fires when the resumed run finishes.
3. **Marks the rest of the orphaned RUNNING jobs FAILED**: appends a
   terminal ``orphaned`` event and flips the tracked dataset's
   metadata to ``finished: true`` with an error, so pollers terminate.
   Never-started jobs with no replay handler get the same terminal
   treatment — no journal entry is ever left able to hang a client.

Replayable ops are registered by name. ``ingest`` ships built in: it is
idempotent-by-construction here because only never-STARTED ingests
replay (a started one may have written partial rows; it is orphaned
instead). ``build_model`` registers as BOTH replayable and resumable —
its outputs are whole-collection drops + atomic checkpoint/progress
artifacts, so a half-dead build re-runs safely at any point. Register
more with :func:`register_replay` / :func:`register_resumable`.
"""

from __future__ import annotations

from typing import Callable

from learningorchestra_tpu.core.store import METADATA_ID, ROW_ID
from learningorchestra_tpu.sched import config as _config
from learningorchestra_tpu.sched.journal import JobJournal
from learningorchestra_tpu.sched.scheduler import QueueFullError
from learningorchestra_tpu.telemetry import metrics as _metrics

ORPHAN_ERROR = "orphaned by service restart"

# op name -> handler(store, payload). Handlers re-run the work from the
# journaled payload alone.
_REPLAY_REGISTRY: dict[str, Callable] = {}

# op name -> handler(store, payload, progress). Handlers re-run the
# work from the journaled payload plus the run's ``progress`` events —
# ops here declare that a STARTED run is safe to re-execute (atomic
# outputs, journaled completions).
_RESUME_REGISTRY: dict[str, Callable] = {}


def register_replay(op: str, handler: Callable) -> None:
    _REPLAY_REGISTRY[op] = handler


def register_resumable(op: str, handler: Callable) -> None:
    _RESUME_REGISTRY[op] = handler


def _replay_ingest(store, payload: dict) -> None:
    from learningorchestra_tpu.core.ingest import ingest_csv

    ingest_csv(store, payload["filename"], payload["url"])


register_replay("ingest", _replay_ingest)


def _build_model_replay(store, payload: dict, progress=None) -> None:
    """Re-run (or resume) a model build from its journaled submit
    payload. Registered at module import — recovery runs BEFORE the
    web app exists, so this cannot live in a create_app closure."""
    import jax

    if jax.process_count() > 1:
        # An in-process resume on one host of a multi-host runner would
        # enter collective programs the other hosts never join — a
        # hang, not a recovery. Multi-host builds restart client-side.
        raise RuntimeError(
            "build_model replay is single-host only "
            f"(process_count={jax.process_count()})"
        )
    from learningorchestra_tpu.ml.builder import build_model

    build_model(
        store,
        payload["training_filename"],
        payload["test_filename"],
        payload["preprocessor_code"],
        list(payload["classificators_list"]),
        models_dir=payload.get("models_dir"),
        resume=list(progress or []),
    )


register_replay("build_model", _build_model_replay)
register_resumable("build_model", _build_model_replay)


def _recovered_counter():
    return _metrics.global_registry().counter(
        "lo_sched_recovered_total",
        "Journal-replay outcomes at service restart",
        labels=("outcome",),
    )


def _resumed_counter():
    return _metrics.global_registry().counter(
        "lo_sched_resumed_total",
        "Orphaned RUNNING jobs re-enqueued with journaled progress",
    )


def _terminate_poller(store, collection: str, error: str) -> None:
    """Flip the tracked dataset's metadata so clients polling
    ``finished`` stop — the crash the reference hangs on."""
    try:
        store.update_one(
            collection,
            {ROW_ID: METADATA_ID},
            {"finished": True, "error": error},
        )
    except Exception:  # noqa: BLE001 — collection may be gone
        pass


def recover_jobs(store, jobs, journal: JobJournal | None = None) -> dict:
    """Replay ``journal`` (default: ``jobs``'s own, else a fresh
    scope-"all" one over ``store``) and reconcile every non-terminal
    entry. Returns ``{"requeued": [names], "orphaned": [names]}``.

    Call once at process start, before the REST surface accepts
    traffic and after the store has replayed its WAL. ``jobs`` is the
    process's JobManager: requeued work becomes ordinary tracked jobs
    (records, traces, fresh journal entries).
    """
    journal = journal or getattr(jobs, "journal", None) or JobJournal(store)
    histories = journal.replay()
    counter = _recovered_counter()
    requeued: list[str] = []
    orphaned: list[str] = []
    live = [h for h in histories.values() if not h.terminal]
    if not live:
        # Nothing to reconcile. If replay also proved the journal holds
        # no other scope's events, the whole collection is dead weight:
        # drop it — this clean-restart compaction is what bounds
        # journal growth across restart cycles.
        if not journal.saw_foreign_scope:
            journal.compact()
        return {"requeued": requeued, "orphaned": orphaned}
    # Live histories exist: recovery stays strictly APPEND-ONLY.
    # Compacting first would open a window where a crash between the
    # drop and the re-submits loses every pending job — the exact
    # hung-poller bug this subsystem exists to close. The extra
    # documents cost a little store space until the next clean restart
    # compacts them.

    def orphan(name: str, collection, outcome: str) -> None:
        """Terminate one unrecoverable history: journal the terminal
        event and flip the tracked dataset so pollers stop."""
        orphaned.append(name)
        counter.labels(outcome).inc()
        journal.append(name, "orphaned", error=ORPHAN_ERROR)
        if collection:
            _terminate_poller(store, collection, ORPHAN_ERROR)

    for name, history in histories.items():
        if history.terminal:
            continue
        submit = history.submit
        collection = submit.get("collection")
        if history.started:
            # Orphaned RUNNING job: the process died mid-flight. An op
            # in the resume registry declared a started run safe to
            # re-execute (atomic outputs, journaled completions) — it
            # re-enqueues under the SAME name with its progress events,
            # so the resumed run performs only the remaining work and
            # parked waiters resolve on its completion. Everything else
            # may have half-written output: it fails, visibly, and its
            # pollers terminate.
            resume_handler = _RESUME_REGISTRY.get(submit.get("op"))
            if resume_handler is not None and _config.resume_enabled():
                payload = submit.get("payload") or {}
                try:
                    jobs.submit(
                        name,
                        resume_handler,
                        store,
                        payload,
                        list(history.progress),
                        store=store if collection else None,
                        collection=collection,
                        job_class=submit.get("job_class") or "host",
                        priority=int(submit.get("priority") or 0),
                        replay=(submit["op"], payload),
                    )
                except QueueFullError:
                    orphan(name, collection, "dropped")
                    continue
                requeued.append(name)
                counter.labels("resumed").inc()
                _resumed_counter().inc()
                continue
            orphan(name, collection, "orphaned")
            continue
        handler = _REPLAY_REGISTRY.get(submit.get("op"))
        if handler is None:
            # Admitted but never started, and not replayable: terminal,
            # for the same no-hung-pollers reason.
            orphan(name, collection, "unreplayable")
            continue
        payload = submit.get("payload") or {}
        try:
            jobs.submit(
                name,
                handler,
                store,
                payload,
                store=store if collection else None,
                collection=collection,
                job_class=submit.get("job_class") or "host",
                priority=int(submit.get("priority") or 0),
                replay=(submit["op"], payload),
            )
        except QueueFullError:
            # a backlog larger than the queue cap must not crash the
            # restart: past the cap, the remainder terminates like
            # unreplayable work (clients resubmit) instead of wedging
            # bring-up
            orphan(name, collection, "dropped")
            continue
        requeued.append(name)
        counter.labels("requeued").inc()
    return {"requeued": requeued, "orphaned": orphaned}
