"""Retry policy: transient-failure classification and seeded backoff.

Transient failures re-enqueue with exponential backoff plus
deterministic jitter up to a budget; terminal failures keep the
``finished: true`` + error contract (core/jobs.py). Determinism
matters twice: the jitter sequence is golden-testable, and a journal
replay after a crash re-derives the same delays the crashed process
would have used.
"""

from __future__ import annotations

import random

from learningorchestra_tpu.sched import config


class TransientJobError(RuntimeError):
    """Raise from job code for failures worth retrying — a store
    failover window, a flaky download, a briefly-contended device
    runtime. Anything else (bad input, a bug) is terminal and keeps
    today's ``finished: true`` + error contract."""


# Exception type names (checked by name so this module never imports
# jax or the store client: parallel/spmd.py defines SpmdTimeoutError
# and core/store_service.py defines StoreUnavailableError, but
# importing either pulls heavy deps into every client process) that
# classify as transient alongside TransientJobError subclasses.
_TRANSIENT_TYPE_NAMES = frozenset(
    {"SpmdTimeoutError", "StoreUnavailableError"}
)


def is_transient(error: BaseException) -> bool:
    """Should this failure re-enqueue (budget permitting)?

    ``TransientJobError`` by contract; ``SpmdTimeoutError`` because the
    watchdog fires for worker-death *and* for overlong collectives —
    after the supervisor restarts the runtime the same job usually
    succeeds, so the retry rides out the restart window; the store
    client's ``StoreUnavailableError`` because a 503 is the replicated
    store's *transient* degraded state by contract — a read-only
    follower mid-takeover or a quorum-suspended minority primary
    answering 503 + Retry-After (docs/replication.md) — and the job
    usually succeeds once failover completes or the partition heals.
    Subclass checks are by type name to keep the heavy imports out of
    the graph.
    """
    if isinstance(error, TransientJobError):
        return True
    return any(
        cls.__name__ in _TRANSIENT_TYPE_NAMES
        for cls in type(error).__mro__
    )


def backoff_delay(
    name: str,
    attempt: int,
    base_s: float | None = None,
    cap_s: float | None = None,
    seed: int | None = None,
) -> float:
    """Delay before re-enqueueing ``name``'s attempt ``attempt`` (1 is
    the first retry): ``min(cap, base * 2**(attempt-1))`` scaled by a
    deterministic jitter in [0.75, 1.25] derived from (seed, name,
    attempt) — the same job retries on the same schedule on every
    process and every replay, while distinct jobs decorrelate instead
    of thundering back in lockstep."""
    if attempt < 1:
        raise ValueError(f"attempt must be >= 1, got {attempt}")
    base_s = config.backoff_base_s() if base_s is None else base_s
    cap_s = config.backoff_cap_s() if cap_s is None else cap_s
    seed = config.jitter_seed() if seed is None else seed
    raw = min(cap_s, base_s * (2.0 ** (attempt - 1)))
    jitter = random.Random(f"{seed}:{name}:{attempt}").uniform(0.75, 1.25)
    return raw * jitter
