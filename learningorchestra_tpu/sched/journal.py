"""Durable job journal: one submit document, transitions appended.

Every scheduler admission writes a ``submitted`` document into the
``__lo_jobs__`` collection of the :class:`DocumentStore`; every state
transition (``started``, ``retry``, ``finished``, ``failed``,
``cancelled``, ``rejected``, ``orphaned``) appends another. Running
work may additionally append ``progress`` documents (JobHandle.progress
— per-classifier completions, fit-segment saves); these are NOT state
transitions, they are the resume payload recovery hands back to a
resumable op after a crash (docs/robustness.md). The store's
WAL makes the journal survive a crash, which is what recovery
(sched/recovery.py) replays — task lineage in the Ray sense, scoped to
what this system needs: enough to re-enqueue work that never started
and to terminate pollers of work that died mid-flight.

Append-only by design: transitions are separate documents, not in-place
updates, so a crash can never leave a half-written state and replay is
a pure fold over ``_id`` order. ``scope`` labels which process owns a
job ("all" for the single-process runner, the service name in the
one-process-per-service topology) so each restarted process recovers
only its own jobs from the shared store.

Journal writes are best-effort: a store hiccup loses an audit line, not
the job — availability over perfect lineage, the same call Ray makes
for its event log.
"""

from __future__ import annotations

import time
import traceback
from typing import Iterator, Optional

from learningorchestra_tpu.testing import faults as _faults

JOURNAL_COLLECTION = "__lo_jobs__"

TERMINAL_EVENTS = frozenset(
    {"finished", "failed", "cancelled", "rejected", "orphaned"}
)


def shard_scope(scope: str, store) -> str:
    """The journal scope suffixed with the store's shard topology
    (``all`` → ``all#sh4x8192`` on a four-group fleet) so recovery
    replays stay shard-local in two senses: the journal lives whole on
    the META group (``insert_one`` routes there — no cross-group fold),
    and a RE-SHARDED fleet sees its old entries as foreign scopes
    instead of replaying job lineage whose block ids meant a different
    placement. Resharding in place is a declared non-goal
    (docs/dataplane.md): drain, then re-ingest. Unsharded stores carry
    no signature and keep their scopes byte-identical."""
    signature = getattr(store, "shard_signature", "")
    return f"{scope}#{signature}" if signature else scope


class JobHistory:
    """One job's folded journal: its submit document, the last event
    seen, and any ``progress`` events the run appended — all recovery
    needs."""

    __slots__ = ("name", "submit", "last_event", "last_error", "progress")

    def __init__(self, name: str, submit: dict):
        self.name = name
        self.submit = submit
        self.last_event = "submitted"
        self.last_error: Optional[str] = None
        # ``progress`` event documents in append order (per-classifier
        # completions, segment saves) — the resume payload for an
        # orphaned RUNNING job. Not a state transition: folding one
        # must NOT touch last_event, or a started job would stop
        # looking started.
        self.progress: list[dict] = []

    @property
    def terminal(self) -> bool:
        return self.last_event in TERMINAL_EVENTS

    @property
    def started(self) -> bool:
        return self.last_event == "started"


class JobJournal:
    def __init__(self, store, scope: str = "all"):
        self.store = store
        self.scope = scope
        # set by replay(): did the journal hold events of OTHER scopes?
        # Compaction drops the whole collection, so it is only safe
        # when this journal provably owns everything in it.
        self.saw_foreign_scope = False

    def append(self, job: str, event: str, **fields) -> None:
        document = {"job": job, "event": event, "scope": self.scope,
                    "ts": time.time()}
        document.update(
            {key: value for key, value in fields.items() if value is not None}
        )
        try:
            # chaos point: an injected error here must cost an audit
            # line, never the job — the same contract as a real store
            # hiccup (testing/faults.py)
            _faults.fire("sched.journal.append", job=job, event=event)
            self.store.insert_one(JOURNAL_COLLECTION, document)
        except Exception:  # noqa: BLE001 — journaling must not fail jobs
            traceback.print_exc()

    def _events(self) -> Iterator[dict]:
        try:
            yield from self.store.find(JOURNAL_COLLECTION)
        except Exception:  # noqa: BLE001 — no journal yet / store down
            return

    def replay(self) -> dict[str, JobHistory]:
        """Fold the journal (``_id`` order = append order) into one
        history per job name in this scope. A resubmit of a name whose
        previous run reached a terminal state starts a fresh history —
        the newest submit wins, like JobManager's record map."""
        histories: dict[str, JobHistory] = {}
        self.saw_foreign_scope = False
        for event in self._events():
            if event.get("scope") != self.scope:
                self.saw_foreign_scope = True
                continue
            name = event.get("job")
            kind = event.get("event")
            if name is None or kind is None:
                continue
            history = histories.get(name)
            if kind == "submitted":
                histories[name] = JobHistory(name, event)
                continue
            if history is None:
                # transition without a submit (partial WAL): synthesize
                # an op-less submit so recovery can still terminate it
                history = histories[name] = JobHistory(name, event)
            if kind == "progress":
                history.progress.append(event)
                continue
            history.last_event = kind
            history.last_error = event.get("error", history.last_error)
        return histories

    def compact(self) -> None:
        """Drop the journal wholesale — called by recovery ONLY when
        replay proved every entry belongs to this scope AND every
        history is terminal (nothing live to lose if the process dies
        right here). Consequence: in the one-process-per-service
        topology, once TWO scopes have written into the shared
        collection neither ever satisfies the ownership proof, so the
        journal grows until a maintenance pass with the store quiesced
        (or a store-level delete-by-query primitive, which the
        DocumentStore API does not have yet) reclaims it — the
        documented trade-off for crash-safe, coordination-free
        recovery; docs/scheduler.md covers the operational angle."""
        try:
            self.store.drop(JOURNAL_COLLECTION)
        except Exception:  # noqa: BLE001
            traceback.print_exc()
