"""Durable job scheduler: admission control, priorities, retries,
deadlines, cancellation, and crash-recovery for every async workload.

The reference's only job abstraction is a ``finished`` boolean a crashed
worker leaves ``false`` forever (reference database.py:199-216); our
``JobManager`` fixed the poll-hang but still threw every request
straight onto an unbounded thread pool. This package is the scheduling
layer between the REST surface and execution — the substrate systems
like Ray (Moritz et al., OSDI '18) put at their core:

- :class:`~learningorchestra_tpu.sched.scheduler.Scheduler` — a
  priority queue per **concurrency class**. Device-bound jobs (model
  builds, t-SNE/PCA embeddings) serialize at ``LO_SCHED_DEVICE_WIDTH``
  (default 1) so two SPMD dispatches never contend for the mesh;
  host-bound jobs (projections, histograms, field-type scans, ingests)
  run at ``LO_JOB_WORKERS``. Per-class queue caps
  (``LO_SCHED_QUEUE_CAP``) surface as HTTP 429 + ``Retry-After``.
- :mod:`~learningorchestra_tpu.sched.policy` — typed transient-failure
  classification (:class:`TransientJobError`, plus the SPMD watchdog's
  ``SpmdTimeoutError``) and exponential backoff with deterministic
  seeded jitter up to a retry budget.
- :class:`~learningorchestra_tpu.sched.journal.JobJournal` — one
  document per submit in the :class:`DocumentStore`, state transitions
  appended, so a restarted service replays the journal
  (:func:`~learningorchestra_tpu.sched.recovery.recover_jobs`),
  re-enqueues jobs that never started, and marks orphaned RUNNING jobs
  FAILED with ``finished: true`` so pollers terminate — the exact crash
  the reference hangs on.
- :mod:`~learningorchestra_tpu.sched.cancel` — cooperative cancellation
  tokens with per-job deadlines, wired to ``DELETE /jobs/<name>`` and
  checked in the builder's phase loop.
- :class:`~learningorchestra_tpu.sched.coalesce.Coalescer` — the
  coalescing stage in front of the device class: shape-compatible
  device jobs arriving within ``LO_COALESCE_WINDOW_MS`` fuse into ONE
  ``vmap``-across-jobs dispatch (each member keeps its own record,
  journal entry, and cancellation token; a cancelled member is masked
  out, not a reason to abort its neighbors).

``core/jobs.py`` executes what this package admits; ``docs/scheduler.md``
is the operator guide.
"""

from learningorchestra_tpu.sched.cancel import (
    CancelToken,
    JobCancelledError,
    JobTimeoutError,
    check_cancelled,
    current_token,
)
from learningorchestra_tpu.sched.coalesce import Coalescer, global_coalescer
from learningorchestra_tpu.sched.journal import (
    JOURNAL_COLLECTION,
    JobJournal,
    shard_scope,
)
from learningorchestra_tpu.sched.policy import (
    TransientJobError,
    backoff_delay,
    is_transient,
)
from learningorchestra_tpu.sched.recovery import recover_jobs
from learningorchestra_tpu.sched.scheduler import (
    DEVICE_CLASS,
    HOST_CLASS,
    QueueFullError,
    Scheduler,
    Task,
)

__all__ = [
    "CancelToken",
    "Coalescer",
    "DEVICE_CLASS",
    "HOST_CLASS",
    "JOURNAL_COLLECTION",
    "JobCancelledError",
    "JobJournal",
    "JobTimeoutError",
    "QueueFullError",
    "Scheduler",
    "Task",
    "TransientJobError",
    "backoff_delay",
    "check_cancelled",
    "current_token",
    "global_coalescer",
    "is_transient",
    "recover_jobs",
    "shard_scope",
]
