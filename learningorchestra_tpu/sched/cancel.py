"""Cooperative cancellation tokens with per-job deadlines.

A scheduler worker cannot preempt a thread mid-fit; cancellation is
cooperative, like Ray's ``ray.cancel`` on actor tasks: the token flips,
and the job notices at its next :func:`check_cancelled` — the builder's
phase loop checks between classifier fits and phases (ml/builder.py).
Deadlines ride the same token: a queued job past its deadline fails at
dequeue without ever starting; a running one fails at its next check.

The ambient token is a ``contextvars`` binding (like telemetry tracing),
so library code calls :func:`check_cancelled` unconditionally — it is a
no-op outside a scheduled job, and on SPMD *worker* processes, which
never carry a token; only the coordinator's job raises. Note the
consequence on a multi-host mesh: cancelling a RUNNING device job aborts
the coordinator mid-collective-stream, which poisons the dispatcher
exactly like any other mid-job failure (parallel/spmd.py) and hands
recovery to the supervisor's restart policy. Cancelling a QUEUED device
job is always clean.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Iterator, Optional


class JobCancelledError(Exception):
    """The job was cancelled (``DELETE /jobs/<name>``)."""


class JobTimeoutError(JobCancelledError):
    """The job exceeded its deadline. A :class:`JobCancelledError`
    subclass so one ``check()`` call covers both; the manager maps it
    to FAILED (the job did not do what was asked) while an explicit
    cancel maps to CANCELLED."""


class CancelToken:
    """One job's cancellation state. ``cancel()`` may be called from
    any thread; ``check()`` raises on the job's own thread."""

    __slots__ = ("deadline", "_reason")

    def __init__(self, deadline: Optional[float] = None):
        # monotonic-clock deadline; None = no deadline
        self.deadline = deadline
        self._reason: Optional[str] = None

    @property
    def cancelled(self) -> bool:
        return self._reason is not None

    def cancel(self, reason: str = "cancelled") -> None:
        self._reason = reason

    def check(self) -> None:
        if self._reason is not None:
            raise JobCancelledError(self._reason)
        if self.deadline is not None and time.monotonic() > self.deadline:
            raise JobTimeoutError("job deadline exceeded")


_TOKEN: contextvars.ContextVar[Optional[CancelToken]] = (
    contextvars.ContextVar("lo_cancel_token", default=None)
)


def current_token() -> Optional[CancelToken]:
    return _TOKEN.get()


@contextlib.contextmanager
def bind(token: Optional[CancelToken]) -> Iterator[None]:
    """Make ``token`` the ambient token for the executing job."""
    reset = _TOKEN.set(token)
    try:
        yield
    finally:
        _TOKEN.reset(reset)


def check_cancelled() -> None:
    """Raise if the ambient job was cancelled or passed its deadline;
    no-op without a token (library code outside a scheduled job, SPMD
    worker processes)."""
    token = _TOKEN.get()
    if token is not None:
        token.check()
