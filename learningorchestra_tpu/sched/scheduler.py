"""Priority queues with concurrency classes — the admission layer.

Two classes, two queues, two worker pools:

- ``device`` — jobs that own the accelerator (model builds, t-SNE/PCA
  embeddings, checkpoint predictions). Width defaults to 1 so two SPMD
  dispatches never contend for the mesh: on a multi-host runtime a
  second concurrent dispatch would interleave collectives and deadlock
  (the invariant the analyzer's LO101 guards statically; this queue
  guards it dynamically).
- ``host`` — everything CPU/store-bound (ingests, projections,
  histograms, field-type scans), width ``LO_JOB_WORKERS``.

Each queue is a max-priority heap (larger ``priority`` first, FIFO
within a priority) with a depth cap: past it :meth:`Scheduler.enqueue`
raises :class:`QueueFullError` carrying a ``Retry-After`` estimate, and
the REST layer turns that into HTTP 429 (utils/web.py) — bounded
admission instead of the reference's unbounded daemon-thread spawn.
Retries re-enter through the same heap after their backoff timer but
bypass the cap (the work was already admitted once).

The scheduler runs opaque :class:`Task` objects; all job bookkeeping
(records, traces, journal events, retry classification) lives in the
``run`` closure the :class:`~learningorchestra_tpu.core.jobs.JobManager`
builds, so this module stays importable without jax, the store, or the
job manager.
"""

from __future__ import annotations

import heapq
import itertools
import math
import threading
import time
from typing import Callable, Optional

from learningorchestra_tpu.sched import config
from learningorchestra_tpu.sched.cancel import CancelToken
from learningorchestra_tpu.telemetry import metrics as _metrics

DEVICE_CLASS = "device"
HOST_CLASS = "host"


class QueueFullError(RuntimeError):
    """Admission refused: the class's queue is at its depth cap.

    Deliberately NOT a ValueError: service handlers catch ValueError
    for duplicate-job 409s and must not mistake backpressure for a
    duplicate. ``retry_after_s`` is the depth-and-throughput-derived
    hint the REST layer sends as ``Retry-After``.
    """

    def __init__(self, job_class: str, depth: int, retry_after_s: int):
        super().__init__(
            f"{job_class} queue full ({depth} queued); "
            f"retry in ~{retry_after_s}s"
        )
        self.job_class = job_class
        self.depth = depth
        self.retry_after_s = retry_after_s


class Task:
    """One schedulable unit. ``run`` executes the job and returns
    ``None`` when the job reached a terminal state, or a delay in
    seconds to re-enqueue after (a transient failure within budget).
    ``wait_s`` is stamped by the worker at dequeue so ``run`` can
    record queue time."""

    __slots__ = (
        "name",
        "job_class",
        "priority",
        "token",
        "run",
        "attempt",
        "enqueued_at",
        "wait_s",
    )

    def __init__(
        self,
        name: str,
        job_class: str,
        priority: int,
        run: Callable[["Task"], Optional[float]],
        token: Optional[CancelToken] = None,
    ):
        self.name = name
        self.job_class = job_class
        self.priority = priority
        self.run = run
        self.token = token or CancelToken()
        self.attempt = 1
        self.enqueued_at = 0.0
        self.wait_s = 0.0


class _ClassQueue:
    """One concurrency class: heap + worker pool + throughput EWMA."""

    def __init__(self, name: str, width: int, cap: int):
        self.name = name
        self.width = width
        self.cap = cap
        self.cond = threading.Condition()
        self.heap: list[tuple[int, int, Task]] = []
        self.seq = itertools.count()
        self.workers = 0
        self.idle = 0
        self.running = 0
        # EWMA of execution seconds, seeding Retry-After estimates
        # before any job has completed
        self.avg_run_s = 1.0


class Scheduler:
    """Admission + ordering + workers for both concurrency classes.

    Worker threads spawn lazily per class up to its width (a scheduler
    constructed for a test that never submits costs zero threads) and
    are daemons; :meth:`close` exists so tests can park them.
    """

    def __init__(
        self,
        host_width: Optional[int] = None,
        device_width: Optional[int] = None,
        queue_cap: Optional[int] = None,
        journal=None,
    ):
        cap = config.queue_cap() if queue_cap is None else queue_cap
        self.journal = journal
        self._classes = {
            HOST_CLASS: _ClassQueue(
                HOST_CLASS,
                config.host_width() if host_width is None else host_width,
                cap,
            ),
            DEVICE_CLASS: _ClassQueue(
                DEVICE_CLASS,
                config.device_width() if device_width is None else device_width,
                cap,
            ),
        }
        self._closed = False
        registry = _metrics.global_registry()
        self._depth_gauge = registry.gauge(
            "lo_sched_queue_depth",
            "Jobs queued (admitted, not yet running) per class",
            labels=("job_class",),
        )
        self._running_gauge = registry.gauge(
            "lo_sched_running",
            "Jobs executing per class",
            labels=("job_class",),
        )
        self._wait_seconds = registry.histogram(
            "lo_sched_queue_wait_seconds",
            "Seconds between admission and execution start",
            labels=("job_class",),
        )
        self._rejected_total = registry.counter(
            "lo_sched_rejected_total",
            "Submissions refused at the queue cap (HTTP 429)",
            labels=("job_class",),
        )
        self._retries_total = registry.counter(
            "lo_sched_retries_total",
            "Transient failures re-enqueued with backoff",
            labels=("job_class",),
        )

    def class_width(self, job_class: str) -> int:
        return self._classes[job_class].width

    def check_admission(self, job_class: str) -> None:
        """Raise :class:`QueueFullError` if ``job_class`` is at its cap
        right now. A best-effort pre-check for submit paths that would
        otherwise do durable work (journal writes, name claims) before
        :meth:`enqueue` rejects — exactly when the system is overloaded
        and every spare store round-trip hurts. The admit/reject race
        this leaves open is still closed authoritatively by enqueue."""
        cls = self._classes[job_class]
        with cls.cond:
            depth = len(cls.heap)
            if depth >= cls.cap:
                self._rejected_total.labels(cls.name).inc()
                raise QueueFullError(
                    cls.name, depth, self._retry_after_locked(cls)
                )

    def enqueue(self, task: Task, requeue: bool = False) -> None:
        """Admit ``task``. Raises :class:`QueueFullError` at the cap
        (unless ``requeue`` — a backoff re-entry of admitted work) and
        ``KeyError`` for an unknown class."""
        cls = self._classes[task.job_class]
        with cls.cond:
            if self._closed:
                raise RuntimeError("scheduler is closed")
            depth = len(cls.heap)
            if not requeue and depth >= cls.cap:
                self._rejected_total.labels(cls.name).inc()
                raise QueueFullError(
                    cls.name, depth, self._retry_after_locked(cls)
                )
            task.enqueued_at = time.monotonic()
            # max-heap on priority, FIFO within: heapq is a min-heap,
            # so negate priority and tie-break on the admission seq
            heapq.heappush(
                cls.heap, (-task.priority, next(cls.seq), task)
            )
            self._depth_gauge.labels(cls.name).set(len(cls.heap))
            # lazy spawn up to the width, but only when the backlog
            # exceeds the workers already waiting for it — a burst of N
            # submits grows the pool, a trickle reuses the idle worker
            if cls.workers < cls.width and len(cls.heap) > cls.idle:
                cls.workers += 1
                threading.Thread(
                    target=self._worker,
                    args=(cls,),
                    daemon=True,
                    name=f"lo-sched-{cls.name}-{cls.workers}",
                ).start()
            cls.cond.notify()

    def _retry_after_locked(self, cls: _ClassQueue) -> int:
        """Deterministic Retry-After: the backlog drained at the
        class's observed (EWMA) per-job seconds across its width,
        clamped to [1, 60]."""
        estimate = cls.avg_run_s * (len(cls.heap) + 1) / max(1, cls.width)
        return max(1, min(60, math.ceil(estimate)))

    def _worker(self, cls: _ClassQueue) -> None:
        while True:
            with cls.cond:
                while not cls.heap and not self._closed:
                    cls.idle += 1
                    # timed wait (LO204): a lost notify — close() racing
                    # the wait, a worker dying mid-critical-section —
                    # degrades to a 1 s predicate re-check, not a
                    # parked-forever worker
                    cls.cond.wait(1.0)
                    cls.idle -= 1
                if self._closed:
                    cls.workers -= 1
                    return
                _, _, task = heapq.heappop(cls.heap)
                self._depth_gauge.labels(cls.name).set(len(cls.heap))
                cls.running += 1
                self._running_gauge.labels(cls.name).set(cls.running)
            task.wait_s = time.monotonic() - task.enqueued_at
            self._wait_seconds.labels(cls.name).observe(task.wait_s)
            started = time.monotonic()
            try:
                retry_delay = task.run(task)
            except Exception:  # noqa: BLE001 — run() owns job errors;
                # anything escaping is a scheduler bug and must not
                # kill the worker thread
                import traceback

                traceback.print_exc()
                retry_delay = None
            finally:
                with cls.cond:
                    cls.running -= 1
                    self._running_gauge.labels(cls.name).set(cls.running)
                    cls.avg_run_s = (
                        0.8 * cls.avg_run_s
                        + 0.2 * (time.monotonic() - started)
                    )
            if retry_delay is not None:
                self._retries_total.labels(cls.name).inc()
                self._schedule_requeue(task, retry_delay)

    def _schedule_requeue(self, task: Task, delay: float) -> None:
        task.attempt += 1

        def requeue() -> None:
            try:
                self.enqueue(task, requeue=True)
            except RuntimeError:
                # closed mid-backoff (test teardown / shutdown): the
                # journal's non-terminal tail makes the next process
                # re-enqueue it (recovery), so dropping here is safe
                pass

        timer = threading.Timer(delay, requeue)
        timer.daemon = True
        timer.start()

    def close(self) -> None:
        """Stop workers after the current job (tests; production
        relies on daemon threads dying with the process). Tasks still
        queued are NOT silently stranded: each is cancelled and run
        once — the cancelled token short-circuits execution into the
        job's terminal bookkeeping, so run_sync/wait callers wake with
        a CANCELLED record instead of blocking forever."""
        stranded: list[Task] = []
        for cls in self._classes.values():
            with cls.cond:
                # set under EACH class's lock (LO203): enqueue and the
                # workers read _closed under their class lock, so the
                # flag must be published under the same locks — after
                # this loop every class has observed it
                self._closed = True
                while cls.heap:
                    _, _, task = heapq.heappop(cls.heap)
                    stranded.append(task)
                self._depth_gauge.labels(cls.name).set(0)
                cls.cond.notify_all()
        for task in stranded:
            task.token.cancel("scheduler closed")
            try:
                task.run(task)
            except Exception:  # noqa: BLE001 — drain must not abort
                import traceback

                traceback.print_exc()
