"""Scheduler knobs, env-configurable with validation.

Every knob is read at Scheduler/JobManager construction (not import), so
tests monkeypatch the environment and deployments restart to change
them. A malformed value raises immediately with the offending text —
a scheduler silently running at a default width after a typo'd
``LO_JOB_WORKERS=eight`` is exactly the misconfiguration that only
shows up as mystery queueing under load.

Knob table (documented in docs/scheduler.md):

===========================  =======  =====================================
env var                      default  meaning
===========================  =======  =====================================
``LO_JOB_WORKERS``           8        host-class concurrency width
``LO_SCHED_DEVICE_WIDTH``    1        device-class concurrency width
``LO_SCHED_QUEUE_CAP``       64       per-class queued-job cap (429 past it)
``LO_SCHED_RETRIES``         3        max attempts for transient failures
``LO_SCHED_BACKOFF_S``       0.5      backoff base (doubles per attempt)
``LO_SCHED_BACKOFF_CAP_S``   60       backoff ceiling before jitter
``LO_SCHED_SEED``            0        jitter seed (deterministic replay)
``LO_SCHED_TIMEOUT_S``       0        default per-job deadline (0 = none)
``LO_JOB_HISTORY``           512      terminal job records kept in memory
``LO_JOB_TTL_S``             3600     terminal record retention seconds
``LO_COALESCE_WINDOW_MS``    2.0      job-coalescing collection window in
                                      milliseconds (``0`` = passthrough:
                                      every device job dispatches alone)
``LO_COALESCE_MAX_JOBS``     32       max member jobs fused into one
                                      vmap-across-jobs dispatch
``LO_RESUME``                1        crash resume: orphaned RUNNING jobs
                                      with a resumable op re-enqueue with
                                      their journaled progress instead of
                                      going FAILED (strict 0/1)
``LO_RESUME_EVERY_SEGMENTS`` 1        persist a fit-progress artifact every
                                      N segments (integral >= 1; higher =
                                      less checkpoint I/O, more recompute
                                      after a crash)
===========================  =======  =====================================
"""

from __future__ import annotations

import os


def _int_env(name: str, default: int, minimum: int = 1) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"{name} must be an integer, got {raw!r}"
        ) from None
    if value < minimum:
        raise ValueError(f"{name} must be >= {minimum}, got {value}")
    return value


def _float_env(name: str, default: float, minimum: float = 0.0) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}") from None
    if value < minimum:
        raise ValueError(f"{name} must be >= {minimum}, got {value}")
    return value


def host_width() -> int:
    """Concurrency width for host-bound jobs — replaces the hardcoded
    ``ThreadPoolExecutor(max_workers=8)`` the JobManager used to own."""
    return _int_env("LO_JOB_WORKERS", 8)


def device_width() -> int:
    """Concurrency width for device-bound jobs. Default 1: two SPMD
    dispatches must never contend for the mesh."""
    return _int_env("LO_SCHED_DEVICE_WIDTH", 1)


def queue_cap() -> int:
    """Max queued (not yet running) jobs per class before admission
    control rejects with 429 + Retry-After."""
    return _int_env("LO_SCHED_QUEUE_CAP", 64)


def retry_budget() -> int:
    """Max attempts (first run + retries) for transient failures."""
    return _int_env("LO_SCHED_RETRIES", 3)


def backoff_base_s() -> float:
    return _float_env("LO_SCHED_BACKOFF_S", 0.5)


def backoff_cap_s() -> float:
    return _float_env("LO_SCHED_BACKOFF_CAP_S", 60.0)


def jitter_seed() -> int:
    return _int_env("LO_SCHED_SEED", 0, minimum=-(2**62))


def default_timeout_s() -> float:
    """Default per-job deadline; 0 disables."""
    return _float_env("LO_SCHED_TIMEOUT_S", 0.0)


def job_history() -> int:
    """Terminal JobRecords kept in the manager's in-memory map."""
    return _int_env("LO_JOB_HISTORY", 512)


def job_ttl_s() -> float:
    """Terminal JobRecord retention before TTL eviction."""
    return _float_env("LO_JOB_TTL_S", 3600.0)


def coalesce_window_s() -> float:
    """The job-coalescing collection window, converted to seconds.
    ``0`` disables coalescing entirely (passthrough: every coalescible
    device job runs as its own dispatch)."""
    return _float_env("LO_COALESCE_WINDOW_MS", 2.0, 0.0) / 1000.0


def resume_enabled() -> bool:
    """Crash resume for device jobs (docs/robustness.md). Strict 0/1:
    ``LO_RESUME=yes`` silently meaning "off" (or "on") is exactly the
    ambiguity the deploy preflight exists to refuse."""
    raw = os.environ.get("LO_RESUME", "").strip()
    if not raw:
        return True
    if raw not in ("0", "1"):
        raise ValueError(f"LO_RESUME must be 0 or 1, got {raw!r}")
    return raw == "1"


def resume_every_segments() -> int:
    """Persist a fit-progress artifact every N segments. Strictly
    integral >= 1 — ``1.5`` silently truncating would double the
    recompute window an operator thought they configured."""
    return _int_env("LO_RESUME_EVERY_SEGMENTS", 1)


def coalesce_max_jobs() -> int:
    """Max member jobs fused into one vmap-across-jobs dispatch.
    Strictly integral (``1.5`` must not silently truncate) and >= 1;
    also bounds the fused dispatch's device working set — the job axis
    multiplies every member's arrays."""
    return _int_env("LO_COALESCE_MAX_JOBS", 32)
