"""Job coalescing: many shape-compatible device jobs, one fused dispatch.

The device class is deliberately width-1 (two SPMD dispatches must never
contend for the mesh), which turns a flood of small builds from many
users into a serial queue: one dispatch per job, the accelerator mostly
idle between them. The serving lane already proved the fix for
predictions (serve/batcher.py: 64 concurrent clients → mean batch 35.6,
ONE padded forward). This module lifts that pattern to whole device
JOBS: shape-compatible jobs arriving within ``LO_COALESCE_WINDOW_MS``
fuse into ONE ``vmap``-across-jobs dispatch, with the job axis padded to
the shared quarter-octave shape grid (utils/shapegrid.py) so coalesced
batch sizes share compiled programs instead of causing a compile storm.

How it rides the existing scheduler — no second queue, no second worker
pool:

1. A coalescible job registers a :class:`Member` (its prepared payload +
   compatibility key) and then submits through the JobManager into the
   DEVICE class exactly like any other job — its own
   :class:`~learningorchestra_tpu.core.jobs.JobRecord`, journal entry,
   cancellation token, 429 admission, everything.
2. The first member task to reach a device worker claims LEADERSHIP of
   its key: it collects every registered-but-not-yet-executed compatible
   member (waiting up to the window for stragglers, exactly like the
   MicroBatcher — and while a fused dispatch runs, the next burst piles
   into the pending set, which is what makes the next dispatch a batch),
   masks out cancelled members, and runs the group's batched runner
   ONCE.
3. When a collected member's own task later drains from the queue, its
   result is already delivered: the task consumes it instantly —
   returning the member's own result, raising the member's OWN error
   (a mid-batch failure never touches its neighbors; per-member host
   prep failures are isolated by the runner contract below), or raising
   its cancellation. Per-member record/journal/trace semantics from the
   scheduler subsystem are therefore completely unchanged.

Keying reuses the devcache discipline (core/devcache.py): a key is a
hashable tuple covering everything that must match for two jobs to share
one compiled program — job kind, feature width, padded row counts (the
quarter-octave row grid makes nearby dataset sizes land on one padded
shape, so compatibility is common, not lucky), class count, dtype
policy, hyperparameter schedule, and the mesh signature.

Runner contract: ``runner(payloads) -> [outcome, ...]`` (same order),
where each outcome is ``("ok", result)`` or ``("error", exception)`` —
one per payload, so a member whose data fails host-side validation
fails ALONE while its batch-mates proceed. A runner that raises
wholesale fails every live member of that batch with the same error
(the fused program itself died — there is no per-member verdict to
give). Results may carry a ``"_attribution"`` dict (rows/bytes); the
consuming member task re-emits it as a span on its OWN job trace, so
the flight recorder splits the fused dispatch back into per-job
rows/bytes accounting.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from learningorchestra_tpu.sched import config
from learningorchestra_tpu.sched.cancel import CancelToken, JobCancelledError
from learningorchestra_tpu.telemetry import tracing as _tracing
from learningorchestra_tpu.testing import faults as _faults

# Member lifecycle (all transitions under the coalescer's condition
# lock). PENDING → LEADER when the member's own task reaches a worker
# first; PENDING → CLAIMED when another leader collects it into a fused
# batch; PENDING → ABANDONED when its submission failed after
# registration (queue cap, duplicate name) and no task will ever run.
PENDING = "pending"
LEADER = "leader"
CLAIMED = "claimed"
ABANDONED = "abandoned"

_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)


class Member:
    """One coalescible job's slot in the stage: payload in, exactly one
    of result / error / skipped out, handed across threads via the done
    event (delivery writes happen-before the event set; only ``state``
    needs the lock)."""

    __slots__ = (
        "key", "payload", "runner", "token", "name",
        "state", "result", "error", "skipped", "_done",
    )

    def __init__(
        self,
        key: tuple,
        payload: Any,
        runner: Callable,
        token: Optional[CancelToken],
        name: str,
    ):
        self.key = key
        self.payload = payload
        self.runner = runner
        self.token = token
        self.name = name
        self.state = PENDING
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.skipped = False
        self._done = threading.Event()

    def deliver(self) -> None:
        self._done.set()

    @property
    def delivered(self) -> bool:
        return self._done.is_set()


class Coalescer:
    """The coalescing stage in front of the device class.

    Holds no threads of its own: leaders are whichever scheduler worker
    reaches a member task first, so the device class's width discipline
    (and its admission control) is untouched. ``window_s == 0`` is pure
    passthrough — members skip the pending set entirely and every task
    runs its own single-job dispatch through the same runner, which
    keeps the passthrough and fused paths on identical code (and, with
    the job axis padded to one grid value, identical numerics).
    """

    def __init__(
        self,
        window_s: Optional[float] = None,
        max_jobs: Optional[int] = None,
    ):
        self.window_s = (
            config.coalesce_window_s() if window_s is None else window_s
        )
        self.max_jobs = (
            config.coalesce_max_jobs() if max_jobs is None else max_jobs
        )
        self._cond = threading.Condition()
        self._pending: dict[tuple, list[Member]] = {}
        # instance counters for stats(); the process-wide prometheus
        # families are module-level (one registry entry per process)
        self._fused = 0
        self._members = 0
        self._masked = 0
        self._metrics = _coalesce_metrics()

    # --- registration (request/submit threads) -------------------------------
    def register(
        self,
        key: tuple,
        payload: Any,
        runner: Callable,
        token: Optional[CancelToken] = None,
        name: str = "",
    ) -> Member:
        """Make a member visible to leaders. Call BEFORE submitting its
        job (prep must precede the device queue: a leader can only stack
        payloads that already exist), then hand ``run_member`` to the
        JobManager as the job function with the SAME token."""
        member = Member(key, payload, runner, token, name)
        if self.window_s > 0 and self.max_jobs > 1:
            with self._cond:
                self._pending.setdefault(key, []).append(member)
                self._cond.notify_all()
        return member

    def abandon(self, member: Member) -> None:
        """The member's submission failed after registration (queue cap
        429, duplicate 409): drop it so no leader stacks work nobody
        will consume. Harmless if a leader already claimed it — the
        delivered result is simply never read."""
        with self._cond:
            if member.state == PENDING:
                member.state = ABANDONED
                peers = self._pending.get(member.key)
                if peers is not None:
                    try:
                        peers.remove(member)
                    except ValueError:
                        pass
                    if not peers:
                        del self._pending[member.key]

    # --- execution (scheduler device workers) --------------------------------
    def run_member(self, member: Member) -> Any:
        """THE job function for a coalescible job. Exactly one of three
        paths: lead a fused dispatch, consume a result a leader already
        delivered, or (cancelled and masked) surface the cancellation
        through the scheduler's standard terminal path."""
        with self._cond:
            if member.state == PENDING:
                member.state = LEADER
                peers = self._pending.get(member.key)
                if peers is not None:
                    try:
                        peers.remove(member)
                    except ValueError:
                        pass
                    if not peers:
                        del self._pending[member.key]
                lead = True
            else:
                lead = False
        if lead:
            self._dispatch(self._collect(member))
        else:
            # a follower's result is normally delivered before its task
            # even dequeues (width-1 serializes leader before follower);
            # the timeout loop is defensive — the leader's finally
            # guarantees delivery, so this only spins on a genuine bug
            # instead of wedging a device worker forever
            while not member._done.wait(timeout=1.0):
                if member.token is not None:
                    member.token.check()
        return self._consume(member)

    def _collect(self, leader: Member) -> list[Member]:
        """Fill the batch from the pending set until the window closes
        or ``max_jobs`` is reached (the MicroBatcher's collection loop,
        at job granularity). Registration notifies the condition, so a
        burst arriving mid-window is picked up without polling."""
        batch = [leader]
        if self.window_s <= 0 or self.max_jobs <= 1:
            return batch
        deadline = time.monotonic() + self.window_s
        with self._cond:
            while True:
                peers = self._pending.get(leader.key)
                while peers and len(batch) < self.max_jobs:
                    peer = peers.pop(0)
                    peer.state = CLAIMED
                    batch.append(peer)
                if peers is not None and not peers:
                    del self._pending[leader.key]
                if len(batch) >= self.max_jobs:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                # predicate loop with timeout (LO204): a missed notify
                # degrades to closing the window, never a parked worker
                self._cond.wait(remaining)
        return batch

    def _dispatch(self, batch: list[Member]) -> None:
        """Run the fused dispatch for ``batch`` on the calling (device
        worker) thread and deliver every member's outcome. Cancelled
        members are masked OUT of the fused batch — a cancellation is
        never a reason to abort its neighbors."""
        live: list[Member] = []
        masked = 0
        for member in batch:
            if member.token is not None and member.token.cancelled:
                member.skipped = True
                member.deliver()
                masked += 1
            else:
                live.append(member)
        if masked:
            self._metrics["masked"].inc(masked)
        with self._cond:
            self._masked += masked
        if not live:  # every member cancelled before the window closed
            return  # counted as masked members, never as a dispatch
        self._metrics["batch_size"].observe(len(batch))
        self._metrics["fused"].inc()
        self._metrics["members"].inc(len(batch))
        with self._cond:
            self._fused += 1
            self._members += len(batch)
        try:
            # chaos point: an injected error here must land as
            # per-member failures through the delivery path below, never
            # a wedged batch (testing/faults.py)
            _faults.fire("coalesce.dispatch", jobs=len(live))
            with _tracing.span(
                "coalesce:dispatch", jobs=len(live), masked=masked
            ):
                outcomes = live[0].runner([m.payload for m in live])
            if len(outcomes) != len(live):
                raise RuntimeError(
                    f"coalesce runner returned {len(outcomes)} outcomes "
                    f"for {len(live)} members"
                )
            for member, outcome in zip(live, outcomes):
                status, value = outcome
                if status == "ok":
                    member.result = value
                else:
                    member.error = value
                    self._metrics["failed_members"].inc()
                member.deliver()
        except BaseException as error:  # noqa: BLE001 — the fused program
            # (or a malformed runner outcome mid-delivery) died: every
            # live member not already delivered fails, each through its
            # OWN record. An undelivered member would otherwise park its
            # follower task forever — on the width-1 device class that
            # wedges the mesh's only dispatch lane.
            for member in live:
                if member.delivered:
                    continue
                member.error = _per_member_error(error)
                member.deliver()

    def _consume(self, member: Member) -> Any:
        """Surface this member's delivered outcome on its own task (its
        own record, trace, and journal): result, error, or
        cancellation."""
        if member.skipped:
            # masked out of the fused batch; the standard CANCELLED
            # terminal path takes it from here
            if member.token is not None:
                member.token.check()  # raises with the cancel reason
            raise JobCancelledError("coalesced member cancelled")
        if member.error is not None:
            raise member.error
        attribution = {}
        if isinstance(member.result, dict):
            attribution = member.result.get("_attribution") or {}
        # per-job flight-recorder attribution: the member's share of the
        # fused dispatch (rows/bytes) lands on ITS job trace, so
        # /jobs/<name>/trace and /profile split the fused span per job
        with _tracing.span("coalesce:member", **attribution):
            pass
        return member.result

    # --- introspection ---------------------------------------------------------
    def depth(self) -> int:
        with self._cond:
            return sum(len(peers) for peers in self._pending.values())

    def stats(self) -> dict:
        with self._cond:
            return {
                "pending": sum(
                    len(peers) for peers in self._pending.values()
                ),
                "fused_dispatches": self._fused,
                "members": self._members,
                "masked": self._masked,
                "mean_batch_size": (
                    round(self._members / self._fused, 3)
                    if self._fused
                    else None
                ),
            }


def _per_member_error(error: BaseException) -> BaseException:
    """A fresh exception instance per member for a batch-wide failure:
    up to max_jobs threads re-raise their member's error concurrently,
    and raising ONE shared instance from many threads interleaves the
    mutations of its ``__traceback__`` — garbling exactly the
    diagnostics needed to debug the fused-program death."""
    try:
        clone = type(error)(*error.args)
        clone.__cause__ = error
        return clone
    except BaseException:  # noqa: BLE001 — exotic constructor signature:
        # fall back to sharing the instance rather than masking the error
        return error


_COALESCER: Optional[Coalescer] = None
_COALESCER_LOCK = threading.Lock()


def global_coalescer() -> Coalescer:
    """The process-wide stage (knobs read once at first use); services
    share it like they share the runner's scheduler, so jobs submitted
    through different apps in one process still coalesce."""
    global _COALESCER
    with _COALESCER_LOCK:
        if _COALESCER is None:
            _COALESCER = Coalescer()
        return _COALESCER


_METRICS: Optional[dict] = None
_METRICS_LOCK = threading.Lock()


def _coalesce_metrics() -> dict:
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            from learningorchestra_tpu.telemetry import global_registry

            registry = global_registry()
            _METRICS = {
                "batch_size": registry.histogram(
                    "lo_sched_coalesce_batch_size",
                    "Member jobs per fused device dispatch",
                    buckets=_BATCH_BUCKETS,
                ),
                "fused": registry.counter(
                    "lo_sched_coalesce_fused_total",
                    "Fused vmap-across-jobs dispatches run",
                ),
                "members": registry.counter(
                    "lo_sched_coalesce_members_total",
                    "Member jobs riding fused dispatches",
                ),
                "masked": registry.counter(
                    "lo_sched_coalesce_masked_total",
                    "Cancelled members masked out of fused dispatches",
                ),
                "failed_members": registry.counter(
                    "lo_sched_coalesce_failed_members_total",
                    "Members failing alone inside a fused dispatch",
                ),
            }
        return _METRICS
