"""Online serving: device-resident model registry + request micro-batching.

The reference system is batch-only — a model lives and dies inside one
build job and the only way to get a prediction is to submit another job
and poll (SURVEY §1). This package turns the checkpoints the builder
already persists (``ml/checkpoint.py``) into an interactive surface:

- :class:`~learningorchestra_tpu.serve.registry.ModelRegistry` pins
  predict-ready models in device memory, rev-keyed against the artifact
  on disk and byte-budgeted like the data plane's devcache
  (``LO_SERVE_BYTES``, LRU; 0 = host-only fallback).
- :class:`~learningorchestra_tpu.serve.batcher.MicroBatcher` coalesces
  predict requests arriving within ``LO_SERVE_BATCH_WINDOW_MS`` into one
  padded forward dispatch per model and scatters results back to the
  waiting request threads, honoring the scheduler's 429 + Retry-After
  admission contract at its bounded inbox.
- :class:`ServePlane` owns one of each — the unit the model_builder
  service wires behind ``POST /models/<name>/predict``
  (docs/serving.md).
- :mod:`~learningorchestra_tpu.serve.fleet` and
  :mod:`~learningorchestra_tpu.serve.router` scale the plane OUT:
  consistent-hash model placement over N replicas, residency gossip on
  the store, and a placement-aware proxy riding the event-loop server
  (docs/serving.md "Fleet").

One process-wide plane (:func:`global_serve_plane`) serves production;
tests construct standalone planes with explicit knobs.
"""

from __future__ import annotations

import threading
from typing import Optional

from learningorchestra_tpu.serve.batcher import SERVE_CLASS, MicroBatcher
from learningorchestra_tpu.serve.registry import (
    ModelNotFoundError,
    ModelRegistry,
    artifact_rev,
)


class ServePlane:
    """Registry + batcher, constructed together so their knobs resolve
    at the same instant and tests can swap the whole plane."""

    def __init__(
        self,
        capacity: Optional[int] = None,
        window_s: Optional[float] = None,
        max_batch: Optional[int] = None,
        inbox_cap: Optional[int] = None,
        mesh=None,
    ):
        self.registry = ModelRegistry(capacity=capacity, mesh=mesh)
        self.batcher = MicroBatcher(
            self.registry,
            window_s=window_s,
            max_batch=max_batch,
            inbox_cap=inbox_cap,
        )

    def submit(self, path: str, rows):
        return self.batcher.submit(path, rows)

    def stats(self) -> dict:
        return {"registry": self.registry.stats(), **self.batcher.stats()}

    def close(self) -> None:
        self.batcher.close()


_GLOBAL: Optional[ServePlane] = None
_GLOBAL_LOCK = threading.Lock()


def global_serve_plane() -> ServePlane:
    """The process-wide plane every model_builder app shares (entries
    are keyed by absolute checkpoint path, so apps over different model
    volumes coexist)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = ServePlane()
        return _GLOBAL


__all__ = [
    "MicroBatcher",
    "ModelNotFoundError",
    "ModelRegistry",
    "SERVE_CLASS",
    "ServePlane",
    "artifact_rev",
    "global_serve_plane",
]
