"""Closed-loop load generator for the serving path.

Closed-loop (each client issues its next request only after the
previous one answered) is the honest shape for latency measurement: an
open-loop generator overruns a saturated server and measures its own
queue. ``bench.py``'s ``serve`` section drives this at 1 / 8 / 64
concurrent clients and reports p50/p99 latency, predictions/s, and the
achieved mean batch size — the number that proves micro-batching
actually coalesced concurrent singles into shared dispatches. The
``fleet`` section reuses the same loop against real sockets through
:func:`http_predict_sender` — either spread across replica targets or
aimed at the router (one target).
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Callable, Optional, Sequence
from urllib.parse import urlsplit

import numpy as np


def run_closed_loop(
    send: Callable,
    clients: int,
    requests_per_client: int,
    rows_per_request: int = 1,
    session_factory: Optional[Callable[[int], object]] = None,
) -> dict:
    """Run ``clients`` threads, each issuing ``requests_per_client``
    back-to-back calls to ``send(client_index)`` (which must perform one
    predict round-trip and raise on failure). Returns latency/throughput
    stats; any client error is re-raised after the loop drains.

    With ``session_factory``, each client builds its own session inside
    its thread, ``send(client_index, session)`` carries it, and the
    session is closed in ``finally`` — error paths included, so a
    failing client never leaks its connection. A client that dies
    before the start barrier aborts it rather than deadlocking the
    main thread.
    """
    latencies: list[list[float]] = [[] for _ in range(clients)]
    errors: list[Optional[BaseException]] = [None] * clients
    barrier = threading.Barrier(clients + 1)

    def client(index: int) -> None:
        mine = latencies[index]
        session = None
        try:
            if session_factory is not None:
                session = session_factory(index)
            barrier.wait()
            for _ in range(requests_per_client):
                started = time.perf_counter()
                if session_factory is not None:
                    send(index, session)
                else:
                    send(index)
                mine.append(time.perf_counter() - started)
        except BaseException as error:  # noqa: BLE001 — reported below
            errors[index] = error
            barrier.abort()
        finally:
            close = getattr(session, "close", None)
            if close is not None:
                close()

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(clients)
    ]
    for thread in threads:
        thread.start()
    try:
        barrier.wait()  # all clients release together: a real burst
    except threading.BrokenBarrierError:
        pass  # a client died during setup; its error re-raises below
    started = time.perf_counter()
    for thread in threads:
        thread.join()
    wall_s = time.perf_counter() - started
    for error in errors:
        # a setup failure breaks the barrier for every OTHER client too;
        # surface the root cause, not the collateral barrier errors
        if error is not None and not isinstance(
            error, threading.BrokenBarrierError
        ):
            raise error
    for error in errors:
        if error is not None:
            raise error
    flat = np.array([value for per in latencies for value in per])
    requests = int(flat.size)
    return {
        "clients": clients,
        "requests": requests,
        "wall_s": round(wall_s, 3),
        "p50_ms": round(float(np.percentile(flat, 50)) * 1000, 3),
        "p99_ms": round(float(np.percentile(flat, 99)) * 1000, 3),
        "mean_ms": round(float(flat.mean()) * 1000, 3),
        "requests_per_s": round(requests / wall_s, 1),
        "predictions_per_s": round(
            requests * rows_per_request / wall_s, 1
        ),
    }


def _host_port(target: str) -> tuple[str, int]:
    """``host:port`` from a target that may or may not carry a scheme."""
    parts = urlsplit(target if "//" in target else f"http://{target}")
    if parts.hostname is None or parts.port is None:
        raise ValueError(f"target needs host:port, got {target!r}")
    return parts.hostname, parts.port


class HttpSession:
    """One persistent HTTP connection to one target — the per-client
    session :func:`http_predict_sender` hands to the closed loop."""

    def __init__(self, target: str, timeout_s: float = 30.0):
        self.target = target
        host, port = _host_port(target)
        self._conn = http.client.HTTPConnection(host, port, timeout=timeout_s)

    def post_json(self, path: str, payload: dict) -> tuple[int, dict]:
        body = json.dumps(payload).encode()
        try:
            self._conn.request(
                "POST",
                path,
                body=body,
                headers={"Content-Type": "application/json"},
            )
            response = self._conn.getresponse()
            raw = response.read()
        except (http.client.HTTPException, OSError):
            # stale keep-alive (server closed between requests): one
            # reconnect, then let the caller see the failure
            self._conn.close()
            self._conn.request(
                "POST",
                path,
                body=body,
                headers={"Content-Type": "application/json"},
            )
            response = self._conn.getresponse()
            raw = response.read()
        try:
            decoded = json.loads(raw) if raw else {}
        except ValueError:
            decoded = {"raw": raw.decode(errors="replace")}
        return response.status, decoded

    def close(self) -> None:
        self._conn.close()


def http_predict_sender(
    targets: Sequence[str],
    model_name: str,
    rows,
    timeout_s: float = 30.0,
    on_response: Optional[Callable[[int, dict], None]] = None,
) -> tuple[Callable, Callable[[int], HttpSession]]:
    """``(send, session_factory)`` for :func:`run_closed_loop` against
    real sockets. Client ``i`` connects to ``targets[i % len(targets)]``
    — one target is router mode, several spread clients across replicas.
    ``on_response(status, body)`` observes every answer (chaos drills
    assert on it); without it any non-200 raises."""
    if not targets:
        raise ValueError("http_predict_sender needs at least one target")
    targets = list(targets)
    payload = {"rows": rows}
    path = f"/models/{model_name}/predict"

    def session_factory(index: int) -> HttpSession:
        return HttpSession(targets[index % len(targets)], timeout_s)

    def send(index: int, session: HttpSession) -> None:
        status, body = session.post_json(path, payload)
        if on_response is not None:
            on_response(status, body)
        elif status != 200:
            raise RuntimeError(
                f"predict via {session.target} failed: HTTP {status} {body}"
            )

    return send, session_factory
