"""Closed-loop load generator for the serving path.

Closed-loop (each client issues its next request only after the
previous one answered) is the honest shape for latency measurement: an
open-loop generator overruns a saturated server and measures its own
queue. ``bench.py``'s ``serve`` section drives this at 1 / 8 / 64
concurrent clients and reports p50/p99 latency, predictions/s, and the
achieved mean batch size — the number that proves micro-batching
actually coalesced concurrent singles into shared dispatches.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np


def run_closed_loop(
    send: Callable[[int], None],
    clients: int,
    requests_per_client: int,
    rows_per_request: int = 1,
) -> dict:
    """Run ``clients`` threads, each issuing ``requests_per_client``
    back-to-back calls to ``send(client_index)`` (which must perform one
    predict round-trip and raise on failure). Returns latency/throughput
    stats; any client error is re-raised after the loop drains."""
    latencies: list[list[float]] = [[] for _ in range(clients)]
    errors: list[Optional[BaseException]] = [None] * clients
    barrier = threading.Barrier(clients + 1)

    def client(index: int) -> None:
        mine = latencies[index]
        try:
            barrier.wait()
            for _ in range(requests_per_client):
                started = time.perf_counter()
                send(index)
                mine.append(time.perf_counter() - started)
        except BaseException as error:  # noqa: BLE001 — reported below
            errors[index] = error

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(clients)
    ]
    for thread in threads:
        thread.start()
    barrier.wait()  # all clients release together: a real burst
    started = time.perf_counter()
    for thread in threads:
        thread.join()
    wall_s = time.perf_counter() - started
    for error in errors:
        if error is not None:
            raise error
    flat = np.array([value for per in latencies for value in per])
    requests = int(flat.size)
    return {
        "clients": clients,
        "requests": requests,
        "wall_s": round(wall_s, 3),
        "p50_ms": round(float(np.percentile(flat, 50)) * 1000, 3),
        "p99_ms": round(float(np.percentile(flat, 99)) * 1000, 3),
        "mean_ms": round(float(flat.mean()) * 1000, 3),
        "requests_per_s": round(requests / wall_s, 1),
        "predictions_per_s": round(
            requests * rows_per_request / wall_s, 1
        ),
    }
