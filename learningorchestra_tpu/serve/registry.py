"""Device-resident model registry: fitted params pinned in device memory.

The batch pipeline loads a checkpoint from disk for every
checkpoint-predict job (``ml/builder.py`` ``predict_with_model``) — fine
for jobs that run minutes, fatal for a request that must answer in
milliseconds. This registry keeps predict-ready models (their parameter
arrays already on device) in a process-wide, byte-budgeted LRU, the same
shape as the data plane's ``core/devcache.py``:

- Entries are keyed by the checkpoint's absolute **path** and stamped
  with the artifact's **rev** — ``(st_ino, st_mtime_ns, st_size)`` of
  the file. ``write_checkpoint`` publishes atomically via ``os.replace``
  (new inode), so a rebuild that overwrites the artifact always moves
  the rev and the next lookup reloads: the registry can never serve
  stale HBM after a rebuild.
- The byte budget (``LO_SERVE_BYTES``) counts the models' device
  parameter bytes; past it the least-recently-used model is dropped.
  A budget of ``0`` (or a model bigger than the whole budget) degrades
  to the **host fallback**: the checkpoint is loaded fresh for that
  request and never cached — slower, still correct.
- Models load onto the process's **local** devices only
  (``local_mesh``): a serving forward must never enter a cross-host
  collective, because the batcher bypasses the scheduler's device queue
  and worker hosts run no batcher to meet it (docs/serving.md).

Import cost: stdlib only — jax and the checkpoint loader are imported
lazily inside :meth:`ModelRegistry.get`.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional


class ModelNotFoundError(KeyError):
    """No checkpoint artifact at the requested path (never built, or
    deleted between the route's existence check and the dispatch)."""


Rev = tuple  # (st_ino, st_mtime_ns, st_size)


def artifact_rev(path: str) -> Optional[Rev]:
    """The artifact's identity on disk, or None when it does not exist.
    ``os.replace`` publication gives a fresh inode per rebuild, so this
    triple moves even when mtime granularity would not."""
    try:
        stat = os.stat(path)
    except OSError:
        return None
    return (stat.st_ino, stat.st_mtime_ns, stat.st_size)


def local_mesh():
    """All devices addressable by THIS process on the data axis.

    Single-process: identical to ``default_mesh``. Multi-host: the
    serving forward stays host-local — the SPMD worker processes never
    see these dispatches, so a global mesh would deadlock its first
    collective."""
    import jax

    from learningorchestra_tpu.parallel.mesh import make_mesh

    return make_mesh(devices=jax.local_devices())


def _model_nbytes(model) -> int:
    return sum(int(leaf.nbytes) for leaf in model.device_state())


class _Entry:
    __slots__ = ("model", "rev", "nbytes", "kind")

    def __init__(self, model, rev: Rev, nbytes: int, kind: str):
        self.model = model
        self.rev = rev
        self.nbytes = nbytes
        self.kind = kind


class ModelRegistry:
    """Byte-budgeted LRU of predict-ready models keyed by artifact path.

    The lock guards the map only — checkpoint loads (disk unzip +
    host-to-device transfer, seconds for a big model) run OUTSIDE it,
    so a ``GET /models`` stats probe never stalls behind a load. The
    batcher's single worker thread is the only production loader, so
    two concurrent loads of one path cannot happen there; if test/
    library callers race, the second insert replaces the first — wasted
    work, never a wrong answer or a leaked byte count.
    """

    def __init__(self, capacity: Optional[int] = None, mesh=None):
        from learningorchestra_tpu.serve import config

        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self.capacity = config.serve_bytes() if capacity is None else capacity
        self._mesh = mesh
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        self._metrics = _serve_registry_metrics()

    def _resolve_mesh(self):
        if self._mesh is None:
            self._mesh = local_mesh()
        return self._mesh

    def _load(self, path: str):
        from learningorchestra_tpu.ml.checkpoint import load_model
        from learningorchestra_tpu.telemetry import span

        with span("serve:load_model", path=path):
            return load_model(path, mesh=self._resolve_mesh())

    def get(self, path: str):
        """The predict-ready model for ``path``; loads (and pins, budget
        permitting) on miss, reloads when the artifact rev moved.
        Raises :class:`ModelNotFoundError` when no artifact exists."""
        path = os.path.abspath(path)
        rev = artifact_rev(path)
        if rev is None:
            with self._lock:
                self._drop_locked(path, invalidation=True)
            raise ModelNotFoundError(path)
        from learningorchestra_tpu.telemetry import tracing

        with self._lock:
            entry = self._entries.get(path)
            if entry is not None and entry.rev == rev:
                self._entries.move_to_end(path)
                self.hits += 1
                self._metrics["hits"].inc()
                tracing.annotate(registry="hit")
                return entry.model
            if entry is not None:
                # a rebuild moved the artifact: never serve stale HBM
                self._drop_locked(path, invalidation=True)
            self.misses += 1
            self._metrics["misses"].inc()
            tracing.annotate(registry="miss")
        try:
            model = self._load(path)  # unlocked: probes stay O(us)
        except FileNotFoundError:
            # deleted between artifact_rev() and the open: the same
            # late-404 contract as a failed stat, not a 500
            raise ModelNotFoundError(path) from None
        nbytes = _model_nbytes(model)
        if 0 < nbytes <= self.capacity:
            with self._lock:
                if path in self._entries:  # a racing loader beat us
                    self._drop_locked(path)
                while self.bytes + nbytes > self.capacity and self._entries:
                    oldest = next(iter(self._entries))
                    self._drop_locked(oldest)
                    self.evictions += 1
                    self._metrics["evictions"].inc()
                self._entries[path] = _Entry(
                    model, rev, nbytes, type(model).__name__
                )
                self.bytes += nbytes
                self._metrics["bytes"].set(self.bytes)
                self._metrics["models"].set(len(self._entries))
        # over-budget (or capacity 0): host fallback — hand the
        # freshly loaded model through without pinning it
        return model

    def _drop_locked(self, path: str, invalidation: bool = False) -> None:
        entry = self._entries.pop(path, None)
        if entry is not None:
            self.bytes -= entry.nbytes
            if invalidation:
                self.invalidations += 1
                self._metrics["invalidations"].inc()
            self._metrics["bytes"].set(self.bytes)
            self._metrics["models"].set(len(self._entries))

    def release(self, path: str) -> None:
        """Unpin ``path`` (no-op when not resident): the fleet's
        replica agent returns the byte budget when a model's placement
        moves to another replica. Not an invalidation — the artifact
        is fine, this replica just no longer owns it."""
        with self._lock:
            self._drop_locked(os.path.abspath(path))

    def status(self, path: str) -> dict:
        """Residency info for ``GET /models/<name>`` — no load."""
        path = os.path.abspath(path)
        with self._lock:
            entry = self._entries.get(path)
            if entry is None:
                return {"resident": False}
            return {
                "resident": entry.rev == artifact_rev(path),
                "bytes": entry.nbytes,
                "kind": entry.kind,
            }

    def stats(self) -> dict:
        with self._lock:
            return {
                "models": len(self._entries),
                "bytes": self.bytes,
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
            }


_METRICS: Optional[dict] = None
_METRICS_LOCK = threading.Lock()


def _serve_registry_metrics() -> dict:
    """Registry counters/gauges, declared once per process. Counters
    increment eagerly (families are shared get-or-create, so several
    registries in one test process report into one family; production
    runs exactly one — docs/observability.md)."""
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            from learningorchestra_tpu.telemetry import global_registry

            registry = global_registry()
            _METRICS = {
                "hits": registry.counter(
                    "lo_serve_registry_hits_total",
                    "Predict dispatches served from a pinned model",
                ),
                "misses": registry.counter(
                    "lo_serve_registry_misses_total",
                    "Predict dispatches that loaded the checkpoint",
                ),
                "evictions": registry.counter(
                    "lo_serve_registry_evictions_total",
                    "Models dropped by the LRU byte budget",
                ),
                "invalidations": registry.counter(
                    "lo_serve_registry_invalidations_total",
                    "Models dropped because the artifact rev moved",
                ),
                "bytes": registry.gauge(
                    "lo_serve_registry_bytes",
                    "Device bytes of pinned model parameters",
                ),
                "models": registry.gauge(
                    "lo_serve_registry_models",
                    "Models resident in the serving registry",
                ),
            }
        return _METRICS
