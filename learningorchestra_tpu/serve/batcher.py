"""Request micro-batching: many waiting clients, one forward dispatch.

A flood of single-row predicts is the worst case for the batch
pipeline: each would pay its own dispatch (and on a remote-attached
chip, its own tunnel round-trip). The accelerator does not care whether
a forward pass carries 1 row or 64 — so the executor here collects
requests that arrive within a short window (``LO_SERVE_BATCH_WINDOW_MS``)
into ONE padded forward per model and scatters the outputs back to the
waiting request threads. This is the SPMD dispatch shape from the fit
path (matched in/out specs, mask-padded rows) applied at request
granularity.

Admission: the inbox is bounded (``LO_SERVE_QUEUE_CAP``). Past the cap
:meth:`MicroBatcher.submit` raises the scheduler's own
:class:`~learningorchestra_tpu.sched.scheduler.QueueFullError` with a
drain-rate Retry-After estimate, which the REST layer renders as the
same 429 contract the job queues use — the serving class bypasses the
scheduler's device queue (latency), not its admission discipline
(overload honesty).

Batches always dispatch with a fixed padded row count
(``LO_SERVE_MAX_BATCH`` rows minimum): XLA compiles one program per
shape, and letting every distinct batch size compile its own program
would turn the first traffic burst into a compile storm. Padding rows
are sliced off before scatter; the models' masked kernels make the
extra rows free.
"""

from __future__ import annotations

import math
import queue
import threading
import time
from typing import Optional

import numpy as np

from learningorchestra_tpu.sched.scheduler import QueueFullError
from learningorchestra_tpu.telemetry import tracing as _tracing
from learningorchestra_tpu.testing import faults as _faults
from learningorchestra_tpu.utils.shapegrid import grid_size, pad_axis0

SERVE_CLASS = "serve"

# One forward in TRACE_EVERY runs under its own trace, remembered in
# tracing's bounded in-process ring (remember_trace, 256 entries): the
# serving lane's timeline evidence without per-request trace cost.
TRACE_EVERY = 16

_CLOSE = object()  # inbox sentinel

_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)
# Shared by the queue-wait histogram here and the route's end-to-end
# lo_serve_request_seconds: serving latencies live in the millisecond
# range the job-oriented DEFAULT_BUCKETS (5 ms floor) cannot resolve.
LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0,
)


class PredictRequest:
    """One waiting client: input rows in, ``(labels, probs)`` or an
    exception out, handed across threads via the done event."""

    __slots__ = (
        "path", "rows", "labels", "probs", "error", "abandoned",
        "submitted_at", "_done",
    )

    def __init__(self, path: str, rows: np.ndarray):
        self.path = path
        self.rows = rows
        self.labels: Optional[np.ndarray] = None
        self.probs: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.abandoned = False
        self.submitted_at = time.monotonic()
        self._done = threading.Event()

    def finish(self) -> None:
        self._done.set()

    def abandon(self) -> None:
        """The waiting client gave up (route timeout → 503). Checked at
        dispatch: an overloaded batcher drains its dead backlog cheaply
        instead of burning device time on results nobody will read."""
        self.abandoned = True

    def wait(self, timeout: float) -> bool:
        return self._done.wait(timeout)


class MicroBatcher:
    """One daemon worker draining a bounded inbox into batched forwards.

    Single worker thread by design: one dispatch in flight per process
    keeps serving's device footprint bounded (the fit path's
    device-width-1 discipline, applied to the bypass lane), and while a
    forward runs the next burst piles into the inbox — which is exactly
    what makes the next dispatch a batch.
    """

    def __init__(
        self,
        registry,
        window_s: Optional[float] = None,
        max_batch: Optional[int] = None,
        inbox_cap: Optional[int] = None,
        trace_every: int = TRACE_EVERY,
    ):
        from learningorchestra_tpu.serve import config

        self.registry = registry
        # sample 1-in-N forwards into the bounded trace ring (0 = off;
        # tests pass 1 to trace every dispatch)
        self.trace_every = trace_every
        self.window_s = config.batch_window_s() if window_s is None else window_s
        self.max_batch = config.max_batch() if max_batch is None else max_batch
        cap = config.queue_cap() if inbox_cap is None else inbox_cap
        self._inbox: "queue.Queue" = queue.Queue(maxsize=cap)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # EWMA of batch service seconds, seeding Retry-After estimates
        self.avg_batch_s = 0.05
        self.batches = 0
        self.batched_requests = 0
        self.rejected = 0
        # worker-thread-private dispatch counter driving the 1-in-N
        # trace sampling: only the worker loop touches it, so it needs
        # no lock — unlike self.batches, which stats() reads under the
        # lock and must therefore also be WRITTEN under it (LO203)
        self._dispatches = 0
        self._metrics = _serve_batch_metrics()

    # --- submission (request threads) ----------------------------------------
    def submit(self, path: str, rows: np.ndarray) -> PredictRequest:
        """Enqueue one request; raises :class:`QueueFullError` when the
        inbox is at its cap (the 429 + Retry-After admission contract)
        and ``ValueError`` for a malformed ``rows`` — rejected HERE, on
        the caller's thread, so a bad submission can never poison the
        shared worker loop."""
        rows = np.asarray(rows)
        if rows.ndim != 2 or rows.shape[0] == 0:
            raise ValueError(
                f"rows must be a non-empty 2-D array, got shape {rows.shape}"
            )
        request = PredictRequest(path, rows)
        with self._lock:
            if self._closed:
                raise RuntimeError("serving batcher is closed")
            try:
                self._inbox.put_nowait(request)
            except queue.Full:
                self.rejected += 1
                self._metrics["rejected"].inc()
                depth = self._inbox.qsize()
                retry_after = max(
                    1,
                    min(
                        60,
                        math.ceil(
                            self.avg_batch_s * depth / max(1, self.max_batch)
                        ),
                    ),
                )
                raise QueueFullError(SERVE_CLASS, depth, retry_after) from None
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="lo-serve-batcher"
                )
                self._thread.start()
        return request

    # --- the batching loop (worker thread) ------------------------------------
    def _loop(self) -> None:
        while True:
            first = self._inbox.get()
            if first is _CLOSE:
                return
            batch = [first]
            # Belt-and-braces guard: _forward already owns per-group
            # errors, but a bug anywhere else in collection/grouping
            # must fail THIS batch's waiters and keep the lane alive —
            # this is the process's only serving thread, and a dead one
            # turns every future predict into a 503-until-restart.
            try:
                if self._collect(batch) == "closed":
                    self._run_batches(batch)
                    return
                self._run_batches(batch)
            except BaseException as error:  # noqa: BLE001
                import traceback

                traceback.print_exc()
                for request in batch:
                    if not request._done.is_set():  # already-delivered
                        # results stay delivered; only waiters fail
                        request.error = error
                        request.finish()

    def _collect(self, batch: list) -> Optional[str]:
        """Fill ``batch`` from the inbox until the window closes or the
        request/row budget is reached; returns "closed" on shutdown."""
        rows_total = len(batch[0].rows)
        deadline = time.monotonic() + self.window_s
        # max_batch bounds BOTH requests and accumulated rows per
        # dispatch: multi-row requests stop the collection early, so
        # a dispatch never exceeds max_batch + one request's rows
        # (itself capped by the route's LO_SERVE_MAX_ROWS)
        while len(batch) < self.max_batch and rows_total < self.max_batch:
            remaining = deadline - time.monotonic()
            try:
                # remaining <= 0 still drains an already-full inbox
                # without sleeping (window 0 = pure backlog batching)
                item = (
                    self._inbox.get_nowait()
                    if remaining <= 0
                    else self._inbox.get(timeout=remaining)
                )
            except queue.Empty:
                break
            if item is _CLOSE:
                return "closed"
            batch.append(item)
            rows_total += len(item.rows)
        return None

    def _run_batches(self, batch: list) -> None:
        started = time.monotonic()
        for request in batch:
            self._metrics["queue_wait"].observe(started - request.submitted_at)
        # one dispatch per (model, feature width): a request whose width
        # does not match its model's fails alone, not its batch-mates.
        # Abandoned requests (client already answered 503) are dropped
        # here — their forward would compute results nobody reads.
        groups: "dict[tuple, list]" = {}
        for request in batch:
            if request.abandoned:
                self._metrics["abandoned"].inc()
                request.error = TimeoutError("request abandoned by client")
                request.finish()
                continue
            groups.setdefault(
                (request.path, request.rows.shape[1]), []
            ).append(request)
        for group in groups.values():
            self._forward(group)
        with self._lock:
            self.avg_batch_s = (
                0.8 * self.avg_batch_s + 0.2 * (time.monotonic() - started)
            )

    def _forward(self, group: list) -> None:
        import contextlib

        from learningorchestra_tpu.telemetry import span

        # The worker thread runs outside any request context, so by
        # default span() is a no-op here. Sample 1-in-trace_every
        # forwards into their own trace, parked in the bounded
        # in-process ring (remember_trace) — the serving lane's
        # flight-recorder evidence: batch rows/bytes and the registry
        # hit/miss verdict ride the serve:forward span.
        trace = None
        if self.trace_every and self._dispatches % self.trace_every == 0:
            trace = _tracing.Trace(name=f"serve:{group[0].path}")
        self._dispatches += 1
        context = (
            _tracing.activate(trace)
            if trace is not None
            else contextlib.nullcontext()
        )
        try:
            with context:
                self._forward_traced(group, span)
        finally:
            if trace is not None:
                _tracing.remember_trace(trace)
                # sampled forwards also feed the fleet stitcher's
                # export buffer (GET /debug/spans)
                _tracing.export_trace(trace, service="serve")

    def _forward_traced(self, group: list, span) -> None:
        try:
            # chaos point: an injected error here must land as
            # per-request errors via the finish() path below, never a
            # dropped group (testing/faults.py)
            _faults.fire(
                "serve.forward", path=group[0].path, requests=len(group)
            )
            # the span covers the registry lookup too, so its
            # hit/miss verdict (registry.get annotates the ambient
            # span) and a miss's serve:load_model child both land here
            with span("serve:forward", requests=len(group)):
                model = self.registry.get(group[0].path)
                rows = np.concatenate([request.rows for request in group])
                total = len(rows)
                # fixed dispatch shape via the shared padded-shape grid
                # (utils/shapegrid.py, the coalescer rides it too):
                # every small batch runs the ONE compiled max_batch-row
                # program (padding rows sliced off below; zero rows are
                # finite through every model), and larger totals (a
                # multi-row request joined) round up to the
                # quarter-octave grid, which bounds distinct compiled
                # shapes logarithmically.
                rows = pad_axis0(rows, grid_size(total, self.max_batch))
                _tracing.annotate(
                    rows=total,
                    bytes=int(rows.nbytes),
                    dtype=str(rows.dtype),
                )
                labels, probs = model.predict_both(rows)
        except BaseException as error:  # noqa: BLE001 — delivered to the
            # waiting request threads; the route maps it to an HTTP error
            for request in group:
                request.error = error
                request.finish()
            return
        # published under the lock: stats() reads these two together
        # under self._lock, and a bare increment here could hand it a
        # mean_batch_size computed from a torn pair (LO203)
        with self._lock:
            self.batches += 1
            self.batched_requests += len(group)
        self._metrics["batch_size"].observe(len(group))
        self._metrics["batches"].inc()
        self._metrics["predictions"].inc(total)
        offset = 0
        for request in group:
            n = len(request.rows)
            request.labels = labels[offset : offset + n]
            request.probs = probs[offset : offset + n]
            offset += n
            request.finish()

    # --- lifecycle / stats -----------------------------------------------------
    def close(self) -> None:
        """Stop the worker and fail anything still queued (tests;
        production relies on the daemon thread dying with the process)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            thread = self._thread
        if thread is not None:
            self._inbox.put(_CLOSE)
            thread.join(timeout=10)
        while True:
            try:
                item = self._inbox.get_nowait()
            except queue.Empty:
                break
            if item is not _CLOSE:
                item.error = RuntimeError("serving batcher closed")
                item.finish()

    def depth(self) -> int:
        return self._inbox.qsize()

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": self._inbox.qsize(),
                "batches": self.batches,
                "batched_requests": self.batched_requests,
                "rejected": self.rejected,
                "mean_batch_size": (
                    round(self.batched_requests / self.batches, 3)
                    if self.batches
                    else None
                ),
            }


_METRICS: Optional[dict] = None
_METRICS_LOCK = threading.Lock()


def _serve_batch_metrics() -> dict:
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            from learningorchestra_tpu.telemetry import global_registry

            registry = global_registry()
            _METRICS = {
                "batch_size": registry.histogram(
                    "lo_serve_batch_size",
                    "Requests coalesced per forward dispatch",
                    buckets=_BATCH_BUCKETS,
                ),
                "queue_wait": registry.histogram(
                    "lo_serve_queue_wait_seconds",
                    "Seconds between request admission and dispatch start",
                    buckets=LATENCY_BUCKETS,
                ),
                "batches": registry.counter(
                    "lo_serve_batches_total",
                    "Batched forward dispatches run",
                ),
                "predictions": registry.counter(
                    "lo_serve_predictions_total",
                    "Rows predicted by the serving path",
                ),
                "rejected": registry.counter(
                    "lo_serve_rejected_total",
                    "Requests refused at the inbox cap (HTTP 429)",
                ),
                "abandoned": registry.counter(
                    "lo_serve_abandoned_total",
                    "Timed-out requests dropped before their forward ran",
                ),
            }
        return _METRICS
