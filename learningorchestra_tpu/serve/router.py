"""The fleet router: one URL in front of N serving replicas.

The reference scales serving by pointing clients at a Docker swarm VIP
(PAPER.md §1) — placement-blind round-robin, so a request for a model
usually lands on a replica that must cold-load it. Our router is
placement-AWARE: ``POST /models/<name>/predict`` resolves the model's
owners on the consistent-hash ring (serve/fleet.PlacementClient — the
same rev-cached map the replica agents pin by), orders them
healthy-first from the residency gossip (:class:`~learningorchestra_tpu.
serve.fleet.FleetView`), and returns an :class:`~learningorchestra_tpu.
utils.web.Upstream` — on the event-loop server the proxy rides the
loop itself (fd + memcpy, no thread held), failing over to the next
owner on connection death or a 5xx, with the client none the wiser.

Admission control extends the serving plane's 429 contract
(docs/serving.md): an optional per-model token bucket
(``LO_FLEET_MODEL_QPS``) answers ``429`` + ``Retry-After`` before any
socket is opened, so one hot model cannot starve its neighbours'
replicas. ``GET /models/<name>`` answers the fleet residency picture —
owners, per-replica heartbeat (pinned models/bytes, inflight, health)
and the placement rev — the operator's "where does this model live"
query.

Metric families (docs/observability.md): ``lo_router_requests_total``,
``lo_router_retries_total``, ``lo_router_rejected_total``,
``lo_router_request_seconds``.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from learningorchestra_tpu.serve import fleet as _fleet
from learningorchestra_tpu.testing import faults
from learningorchestra_tpu.utils.web import Upstream, WebApp

DEFAULT_TIMEOUT_S = 30.0


def _correlation_header() -> str:
    from learningorchestra_tpu.telemetry import tracing as _tracing

    return _tracing.CORRELATION_HEADER


class ModelQuota:
    """Per-model token bucket: ``rate`` requests/s refill, burst of one
    second's worth (min 1). ``rate=0`` disables admission control —
    :meth:`take` always admits."""

    def __init__(self, rate: float):
        self.rate = float(rate)
        self.burst = max(self.rate, 1.0)
        self._lock = threading.Lock()
        self._buckets: dict[str, tuple[float, float]] = {}

    def take(self, model: str) -> Optional[float]:
        """Admit one request for ``model``: ``None`` when admitted,
        else the seconds until a token is available (the Retry-After
        value)."""
        if self.rate <= 0:
            return None
        now = time.monotonic()
        with self._lock:
            tokens, stamp = self._buckets.get(model, (self.burst, now))
            tokens = min(self.burst, tokens + (now - stamp) * self.rate)
            if tokens >= 1.0:
                self._buckets[model] = (tokens - 1.0, now)
                return None
            self._buckets[model] = (tokens, now)
            return round((1.0 - tokens) / self.rate, 3)


def _raw_predict_request(model_name: str, body: bytes, correlation_id=None) -> bytes:
    """The request bytes replayed verbatim against each owner.
    ``Connection: close`` keeps the relay's response framing
    unambiguous (EOF terminates when the backend omits
    Content-Length) and means a failover never reuses a socket that
    already saw half a request."""
    head = (
        f"POST /models/{model_name}/predict HTTP/1.1\r\n"
        "Host: fleet\r\n"
        "Content-Type: application/json\r\n"
        f"Content-Length: {len(body)}\r\n"
        "Connection: close\r\n"
    )
    if correlation_id:
        from learningorchestra_tpu.telemetry import tracing as _tracing

        head += f"{_tracing.CORRELATION_HEADER}: {correlation_id}\r\n"
    return head.encode("ascii") + b"\r\n" + body


def create_app(
    store,
    placement: Optional[_fleet.PlacementClient] = None,
    view: Optional[_fleet.FleetView] = None,
    quota: Optional[ModelQuota] = None,
    timeout_s: float = DEFAULT_TIMEOUT_S,
) -> WebApp:
    """The router's WSGI app. ``store`` is the meta store carrying the
    ``__lo_placement__`` map and ``__lo_fleet__`` gossip; everything
    else defaults from the fleet knobs."""
    app = WebApp("router")
    placement = placement or _fleet.PlacementClient(store)
    view = view or _fleet.FleetView(store)
    quota = quota or ModelQuota(_fleet.model_qps())
    metrics = _router_metrics(app.registry)
    app.fleet_placement = placement
    app.fleet_view = view

    def ordered_targets(name: str) -> list[tuple[str, int]]:
        """The model's owners as connectable targets, healthy replicas
        first — a replica whose heartbeat went stale is still LAST
        resort (it may be alive with a wedged gossip thread), but
        never the first socket opened."""
        owners = placement.owners(name)
        ordered = [i for i in owners if view.healthy(i)]
        ordered += [i for i in owners if i not in ordered]
        targets = []
        for index in ordered:
            target = view.target(index)
            if target is not None:
                targets.append(target)
        return targets

    @app.route("/health")
    def health(request):
        return {
            "result": "ok",
            "service": app.name,
            # feature probe: client.py's Model detects a router base
            # URL by this field and routes predicts through the fleet
            "fleet_router": True,
            "replicas": placement.document()["replicas"],
            "degraded": app.slo_degraded(),
        }, 200

    @app.route("/models/<model_name>", methods=("GET",))
    def read_model_fleet(request, model_name):
        """The residency picture: who OWNS the model (placement), who
        actually HOLDS it right now (gossip), and the placement rev the
        answer was computed at."""
        owners = placement.owners(model_name)
        residency = view.residency()
        return {
            "result": {
                "model": model_name,
                "fleet": {
                    "owners": owners,
                    "rf": placement.document()["rf"],
                    "replicas": residency,
                    "placement_rev": placement.rev,
                },
            }
        }, 200

    @app.route("/models/<model_name>/predict", methods=("POST",))
    def route_predict(request, model_name):
        retry_after = quota.take(model_name)
        if retry_after is not None:
            metrics["rejected"].labels(model_name).inc()
            return app_quota_response(model_name, retry_after)
        try:
            faults.fire("serve.route", model=model_name)
        except faults.FaultInjected:
            # chaos parity with the store wire: an injected routing
            # fault answers a clean JSON 503, never a traceback
            return {"result": "routing_fault", "model": model_name}, 503
        targets = ordered_targets(model_name)
        if not targets:
            return {"result": "no_replicas", "model": model_name}, 503
        metrics["requests"].labels(model_name).inc()
        started = time.perf_counter()

        def on_attempt(index, target, _model=model_name):
            if index > 0:
                metrics["retries"].labels(_model).inc()

        def on_complete(status, _started=started):
            metrics["seconds"].observe(time.perf_counter() - _started)

        upstream = Upstream(
            targets,
            _raw_predict_request(
                model_name,
                request.get_data(),
                request.headers.get(_correlation_header()),
            ),
            timeout_s=timeout_s,
            on_attempt=on_attempt,
            on_exhausted=lambda: (
                {"result": "no_replicas", "model": model_name},
                503,
            ),
        )
        upstream.on_complete = on_complete
        return upstream

    return app


def app_quota_response(model_name: str, retry_after_s: float):
    """429 + Retry-After, the serving plane's admission-control shape
    (utils/web.too_many_requests) with the quota's drain estimate."""
    from werkzeug.wrappers import Response

    response = Response(
        json.dumps(
            {
                "result": "quota_exceeded",
                "model": model_name,
                "retry_after_s": retry_after_s,
            }
        ),
        mimetype="application/json",
        status=429,
    )
    response.headers["Retry-After"] = str(retry_after_s)
    return response


_METRICS: Optional[dict] = None
_METRICS_LOCK = threading.Lock()


def _router_metrics(registry) -> dict:
    """Router families, declared once per process
    (docs/observability.md)."""
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            _METRICS = {
                "requests": registry.counter(
                    "lo_router_requests_total",
                    "Predict requests admitted and proxied",
                    labels=("model",),
                ),
                "retries": registry.counter(
                    "lo_router_retries_total",
                    "Failover attempts past a model's first owner",
                    labels=("model",),
                ),
                "rejected": registry.counter(
                    "lo_router_rejected_total",
                    "Predict requests rejected by the per-model quota",
                    labels=("model",),
                ),
                "seconds": registry.histogram(
                    "lo_router_request_seconds",
                    "Routed predict wall-clock, admission to relay",
                ),
            }
        return _METRICS
