"""Replicated serving fleet: placement, residency gossip, replica agent.

The reference's only scale story is ``docker service scale
microservice_sparkworker=N`` (PAPER.md §1); our predict path was one
process, one MicroBatcher worker — a hard ceiling on predictions/s and
on aggregate pinned-model bytes. This module is the control plane that
lets N serving replicas act as one fleet (docs/serving.md "Fleet"):

- **Placement** — models are placed on replicas by consistent hash of
  the MODEL NAME on the shardmap's 64-vnode blake2b ring
  (core/shardmap.py), with ``LO_FLEET_RF`` distinct owners per model.
  The ``(replicas, rf)`` geometry is one document in the
  ``__lo_placement__`` collection on the meta store — seeded through
  the atomic ``create_collection`` claim and cached client-side with
  TTL + rev revalidation, exactly like ``__lo_shardmap__``: the map is
  authoritative, so a router and its replicas can never disagree on
  geometry.
- **Residency gossip** — each replica heartbeats one rev-bumped row in
  ``__lo_fleet__`` (its url, pinned models, pinned bytes, batcher
  inflight). The router's :class:`FleetView` reads the whole
  collection the same TTL + rev way; a replica whose heartbeat is
  older than ``LO_FLEET_DOWN_S`` is routed AROUND before a TCP timeout
  would notice it died.
- **The replica agent** — each serving replica runs one
  :class:`ReplicaAgent`: every tick it resolves the placement map,
  pins exactly its assigned checkpoints inside its ``LO_SERVE_BYTES``
  budget, fires the publish-time AOT warmup (compile/warmup.py) at the
  serve shape on NEW assignments — a placement change never costs a
  first-request compile — releases models it no longer owns, and
  writes its heartbeat.

Knob table (validated by deploy/run.sh's preflight, plumbed
cluster-wide by deploy/cluster.py's manifest ``fleet`` section):

=======================  =======  ====================================
env var                  default  meaning
=======================  =======  ====================================
``LO_FLEET_REPLICAS``    1        serving replicas in the fleet
``LO_FLEET_RF``          1        owners per model (replication
                                  factor, clamped to the replica
                                  count)
``LO_FLEET_MODEL_QPS``   0        per-model admission quota at the
                                  router (token bucket, requests/s;
                                  ``0`` = off)
``LO_FLEET_DOWN_S``      3.0      heartbeat age past which the router
                                  routes around a replica
``LO_FLEET_REPLICA``     unset    THIS process's replica index (set by
                                  the supervisor, not operators; arms
                                  the replica agent)
=======================  =======  ====================================
"""

from __future__ import annotations

import bisect
import os
import threading
import time
import traceback
from typing import Optional
from urllib.parse import urlsplit

from learningorchestra_tpu.core.shardmap import _ring_hash

PLACEMENT_COLLECTION = "__lo_placement__"
PLACEMENT_DOC_ID = 1
HEARTBEAT_COLLECTION = "__lo_fleet__"

DEFAULT_REPLICAS = 1
DEFAULT_RF = 1
DEFAULT_MODEL_QPS = 0.0
DEFAULT_DOWN_S = 3.0
# placement/heartbeat client cache windows: rev revalidation makes a
# short TTL cheap (one collection_rev probe), and failover recovery is
# bounded by one placement refresh — keep it snappy
DEFAULT_PLACEMENT_TTL_S = 2.0
DEFAULT_VIEW_TTL_S = 0.5
_RING_VNODES = 64


# ---------------------------------------------------------------------------
# Knobs


def replicas() -> int:
    """``LO_FLEET_REPLICAS`` validated (deploy/run.sh preflights this):
    serving replicas in the fleet, strictly integral >= 1. Only the
    SEEDING process's value matters — every later client adopts the
    placement document's geometry."""
    # lo: allow[LO305] this IS the validated accessor preflight calls
    raw = os.environ.get("LO_FLEET_REPLICAS", "").strip()
    if not raw:
        return DEFAULT_REPLICAS
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"LO_FLEET_REPLICAS must be an integer, got {raw!r}"
        ) from None
    if value < 1:
        raise ValueError(f"LO_FLEET_REPLICAS must be >= 1, got {value}")
    return value


def replication_factor() -> int:
    """``LO_FLEET_RF`` validated (deploy/run.sh preflights this): how
    many distinct replicas own each model, strictly integral >= 1. A
    value past the replica count is clamped at placement time — every
    replica owning every model is the degenerate maximum."""
    # lo: allow[LO305] this IS the validated accessor preflight calls
    raw = os.environ.get("LO_FLEET_RF", "").strip()
    if not raw:
        return DEFAULT_RF
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"LO_FLEET_RF must be an integer, got {raw!r}"
        ) from None
    if value < 1:
        raise ValueError(f"LO_FLEET_RF must be >= 1, got {value}")
    return value


def model_qps() -> float:
    """``LO_FLEET_MODEL_QPS`` validated (deploy/run.sh preflights
    this): per-model admission quota at the router in requests/s
    (token bucket, burst of one second's worth); ``0`` disables the
    quota entirely."""
    # lo: allow[LO305] this IS the validated accessor preflight calls
    raw = os.environ.get("LO_FLEET_MODEL_QPS", "").strip()
    if not raw:
        return DEFAULT_MODEL_QPS
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(
            f"LO_FLEET_MODEL_QPS must be requests/s >= 0, got {raw!r}"
        ) from None
    if value < 0 or value != value:  # NaN included
        raise ValueError(
            f"LO_FLEET_MODEL_QPS must be >= 0, got {value}"
        )
    return value


def down_after_s() -> float:
    """``LO_FLEET_DOWN_S`` validated (deploy/run.sh preflights this):
    heartbeat age in seconds past which the router treats a replica as
    down and routes around it. Strictly > 0 — the gossip clock needs a
    real window."""
    # lo: allow[LO305] this IS the validated accessor preflight calls
    raw = os.environ.get("LO_FLEET_DOWN_S", "").strip()
    if not raw:
        return DEFAULT_DOWN_S
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(
            f"LO_FLEET_DOWN_S must be seconds > 0, got {raw!r}"
        ) from None
    if not value > 0:
        raise ValueError(f"LO_FLEET_DOWN_S must be > 0, got {value}")
    return value


def replica_index() -> Optional[int]:
    """``LO_FLEET_REPLICA`` validated: THIS process's replica index,
    set per-process by the supervisor (deploy/stack.py), never by
    operators. ``None`` when unset — the process is not a fleet
    member and runs no replica agent."""
    # lo: allow[LO305] this IS the validated accessor preflight calls
    raw = os.environ.get("LO_FLEET_REPLICA", "").strip()
    if not raw:
        return None
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"LO_FLEET_REPLICA must be an integer, got {raw!r}"
        ) from None
    if value < 0:
        raise ValueError(f"LO_FLEET_REPLICA must be >= 0, got {value}")
    return value


def validate_env() -> dict:
    """Read every fleet knob (raising on malformed values) and return
    the resolved configuration — run.sh preflight and the runner's
    boot-print. A replica index outside the fleet refuses bring-up:
    the supervisor mis-wired the process."""
    config = {
        "LO_FLEET_REPLICAS": replicas(),
        "LO_FLEET_RF": replication_factor(),
        "LO_FLEET_MODEL_QPS": model_qps(),
        "LO_FLEET_DOWN_S": down_after_s(),
        "LO_FLEET_REPLICA": replica_index(),
    }
    index = config["LO_FLEET_REPLICA"]
    if index is not None and index >= config["LO_FLEET_REPLICAS"]:
        raise ValueError(
            f"LO_FLEET_REPLICA {index} is outside the fleet "
            f"(LO_FLEET_REPLICAS={config['LO_FLEET_REPLICAS']})"
        )
    return config


# accessor aliases for call sites whose natural parameter name shadows
# the module-level function
_env_replicas = replicas
_env_rf = replication_factor


# ---------------------------------------------------------------------------
# Placement: model name -> owning replicas


class PlacementRing:
    """Consistent-hash placement of model names on replicas: the
    shardmap's 64-vnode blake2b ring, keyed by MODEL NAME (not stripe
    index). :meth:`owners` walks the ring clockwise collecting ``rf``
    DISTINCT replicas, so losing one replica moves only its models and
    adding a replication factor never reshuffles the primary."""

    def __init__(self, replicas: int):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.replicas = replicas
        points = []
        for replica in range(replicas):
            for vnode in range(_RING_VNODES):
                points.append(
                    (_ring_hash(f"replica:{replica}:{vnode}"), replica)
                )
        points.sort()
        self._ring_points = [point for point, _ in points]
        self._ring_replicas = [replica for _, replica in points]

    def owners(self, model_name: str, rf: int = 1) -> list[int]:
        """The ``min(rf, replicas)`` distinct replicas owning
        ``model_name``, primary first, in ring order — the router's
        failover order."""
        rf = max(1, min(rf, self.replicas))
        if self.replicas == 1:
            return [0]
        point = _ring_hash(f"model:{model_name}")
        index = bisect.bisect_right(self._ring_points, point)
        owners: list[int] = []
        for step in range(len(self._ring_replicas)):
            replica = self._ring_replicas[
                (index + step) % len(self._ring_replicas)
            ]
            if replica not in owners:
                owners.append(replica)
                if len(owners) == rf:
                    break
        return owners


class PlacementClient:
    """The client half of the placement service: one document on the
    meta store, seeded through the atomic collection claim, cached with
    TTL + rev revalidation — ``__lo_shardmap__``'s exact contract
    (core/shardmap.ShardMapClient), so the fleet can never run two
    geometries."""

    def __init__(
        self,
        meta_store,
        replicas: Optional[int] = None,
        rf: Optional[int] = None,
        ttl_s: float = DEFAULT_PLACEMENT_TTL_S,
    ):
        self._meta = meta_store
        self._replicas = _env_replicas() if replicas is None else replicas
        self._rf = _env_rf() if rf is None else rf
        self._ttl_s = ttl_s
        self._lock = threading.Lock()
        self._doc: Optional[dict] = None
        self._doc_rev = -1
        self._checked_at = 0.0
        self._ring: Optional[PlacementRing] = None

    @property
    def rev(self) -> int:
        """The placement collection's last observed rev (the
        ``lo_fleet_placement_rev`` gauge's source)."""
        with self._lock:
            return self._doc_rev

    def document(self) -> dict:
        """The live placement document, seeding it on first contact."""
        now = time.monotonic()
        with self._lock:
            if (
                self._doc is not None
                and now - self._checked_at < self._ttl_s
            ):
                return self._doc
            live_rev = self._meta.collection_rev(PLACEMENT_COLLECTION)
            if self._doc is not None and live_rev == self._doc_rev:
                self._checked_at = now
                return self._doc
            doc = self._meta.find_one(
                PLACEMENT_COLLECTION, {"_id": PLACEMENT_DOC_ID}
            )
            if doc is None:
                # first contact: claim-then-seed; a lost claim means a
                # concurrent seeder won — read their document instead
                if self._meta.create_collection(PLACEMENT_COLLECTION):
                    doc = {
                        "_id": PLACEMENT_DOC_ID,
                        "replicas": self._replicas,
                        "rf": self._rf,
                    }
                    self._meta.insert_one(PLACEMENT_COLLECTION, doc)
                else:
                    doc = self._meta.find_one(
                        PLACEMENT_COLLECTION, {"_id": PLACEMENT_DOC_ID}
                    )
                    if doc is None:  # claimed but not yet seeded: ours
                        doc = {
                            "_id": PLACEMENT_DOC_ID,
                            "replicas": self._replicas,
                            "rf": self._rf,
                        }
                        self._meta.insert_one(PLACEMENT_COLLECTION, doc)
            if doc["replicas"] != self._replicas:
                raise ValueError(
                    f"placement map says {doc['replicas']} replicas but "
                    f"this process is wired to {self._replicas} — "
                    "LO_FLEET_REPLICAS does not match the deployed fleet"
                )
            self._doc = doc
            self._doc_rev = self._meta.collection_rev(PLACEMENT_COLLECTION)
            self._checked_at = now
            _fleet_metrics()["placement_rev"].set(self._doc_rev)
            return doc

    def ring(self) -> PlacementRing:
        doc = self.document()
        with self._lock:
            if self._ring is None or self._ring.replicas != doc["replicas"]:
                self._ring = PlacementRing(doc["replicas"])
            return self._ring

    def owners(self, model_name: str) -> list[int]:
        """The model's owning replicas, primary first (the router's
        failover order, the agent's assignment test)."""
        doc = self.document()
        return self.ring().owners(model_name, doc["rf"])


# ---------------------------------------------------------------------------
# Residency gossip


def _parse_url(url: str) -> Optional[tuple[str, int]]:
    try:
        parts = urlsplit(url if "//" in url else f"http://{url}")
        if parts.hostname is None or parts.port is None:
            return None
        return parts.hostname, parts.port
    except ValueError:
        return None


class Heartbeat:
    """One replica's rev-bumped residency row in ``__lo_fleet__``: the
    write half of the gossip (:class:`FleetView` is the read half).
    Row ids are ``replica + 1`` (store ids start at 1)."""

    def __init__(self, store, index: int, url: str):
        self._store = store
        self.index = index
        self.url = url
        self._seeded = False

    def write(self, models: list[str], pinned_bytes: int, inflight: int) -> dict:
        row = {
            "replica": self.index,
            "url": self.url,
            "models": sorted(models),
            "pinned_bytes": int(pinned_bytes),
            "inflight": int(inflight),
            # wall clock, not monotonic: the router is another process
            # (gossip assumes fleet hosts share NTP discipline)
            "stamp": time.time(),
        }
        if not self._seeded:
            self._store.create_collection(HEARTBEAT_COLLECTION)
            existing = self._store.find_one(
                HEARTBEAT_COLLECTION, {"_id": self.index + 1}
            )
            if existing is None:
                self._store.insert_one(
                    HEARTBEAT_COLLECTION, {"_id": self.index + 1, **row}
                )
                self._seeded = True
                return row
            self._seeded = True
        # update_one bumps the collection rev, so every FleetView's
        # next TTL expiry sees the fresh stamp with one rev probe
        self._store.update_one(
            HEARTBEAT_COLLECTION, {"_id": self.index + 1}, row
        )
        return row


class FleetView:
    """The router's health/residency view: every ``__lo_fleet__`` row,
    cached TTL + rev like the placement map. A replica is HEALTHY when
    its heartbeat is younger than ``LO_FLEET_DOWN_S`` — the router
    orders owners healthy-first, so a dead replica is routed around
    before its TCP timeouts would surface."""

    def __init__(
        self,
        store,
        ttl_s: float = DEFAULT_VIEW_TTL_S,
        down_s: Optional[float] = None,
    ):
        self._store = store
        self._ttl_s = ttl_s
        self.down_s = down_after_s() if down_s is None else down_s
        self._lock = threading.Lock()
        self._rows: dict[int, dict] = {}
        self._rev = -1
        self._checked_at = 0.0

    def rows(self) -> dict[int, dict]:
        """Replica index -> latest heartbeat row."""
        now = time.monotonic()
        with self._lock:
            if self._rows and now - self._checked_at < self._ttl_s:
                return self._rows
            live_rev = self._store.collection_rev(HEARTBEAT_COLLECTION)
            if self._rev == live_rev:
                self._checked_at = now
                return self._rows
            rows = {}
            for row in self._store.find(HEARTBEAT_COLLECTION, {}):
                if "replica" in row:
                    rows[int(row["replica"])] = row
            self._rows = rows
            self._rev = live_rev
            self._checked_at = now
            return rows

    def healthy(self, index: int) -> bool:
        row = self.rows().get(index)
        return (
            row is not None
            and time.time() - row.get("stamp", 0.0) < self.down_s
        )

    def target(self, index: int) -> Optional[tuple[str, int]]:
        row = self.rows().get(index)
        if row is None:
            return None
        return _parse_url(row.get("url", ""))

    def residency(self) -> dict:
        """The ``GET /models/<name>`` "fleet" payload's replica half:
        per-replica url / pinned models / bytes / inflight / health."""
        now = time.time()
        out = {}
        for index, row in sorted(self.rows().items()):
            age_s = max(now - row.get("stamp", 0.0), 0.0)
            out[str(index)] = {
                "url": row.get("url", ""),
                "models": row.get("models", []),
                "pinned_bytes": row.get("pinned_bytes", 0),
                "inflight": row.get("inflight", 0),
                "age_s": round(age_s, 3),
                "healthy": age_s < self.down_s,
            }
        return out


# ---------------------------------------------------------------------------
# The replica agent


class ReplicaAgent:
    """One per serving replica: every tick, converge residency on the
    placement map and gossip a heartbeat.

    - newly-assigned models are pinned through the serve plane's
      registry AND warmed at the serve shape (compile/warmup.py) so a
      placement change never costs a first-request compile;
    - models this replica no longer owns are released (the byte budget
      belongs to the assignment);
    - the heartbeat row carries what the router needs: url, pinned
      models, pinned bytes, batcher inflight.

    ``refresh()`` is one synchronous tick (tests drive it directly);
    :meth:`start` runs it on a daemon thread every ``interval_s``
    (default: a third of the down window, so a healthy replica can
    miss two ticks before the router routes around it).
    """

    def __init__(
        self,
        store,
        models_dir: str,
        serve,
        index: Optional[int] = None,
        url: str = "",
        total: Optional[int] = None,
        rf: Optional[int] = None,
        interval_s: Optional[float] = None,
        placement_ttl_s: float = DEFAULT_PLACEMENT_TTL_S,
        warm: bool = True,
    ):
        resolved = replica_index() if index is None else index
        if resolved is None:
            raise ValueError(
                "ReplicaAgent needs a replica index "
                "(LO_FLEET_REPLICA or index=)"
            )
        self.index = resolved
        self.models_dir = models_dir
        self.serve = serve
        self.url = url
        self._warm = warm
        down_s = down_after_s()
        self.interval_s = (
            max(down_s / 3.0, 0.2) if interval_s is None else interval_s
        )
        self.placement = PlacementClient(
            store, replicas=total, rf=rf, ttl_s=placement_ttl_s
        )
        self.heartbeat = Heartbeat(store, self.index, url)
        self._assigned: set[str] = set()
        self._warmed: set[str] = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _checkpoint_names(self) -> list[str]:
        from learningorchestra_tpu.ml.checkpoint import CHECKPOINT_SUFFIX

        if not self.models_dir or not os.path.isdir(self.models_dir):
            return []
        return sorted(
            name[: -len(CHECKPOINT_SUFFIX)]
            for name in os.listdir(self.models_dir)
            if name.endswith(CHECKPOINT_SUFFIX)
        )

    def assigned_models(self) -> list[str]:
        """The checkpoints on disk this replica owns under the live
        placement map."""
        return [
            name
            for name in self._checkpoint_names()
            if self.index in self.placement.owners(name)
        ]

    def refresh(self) -> dict:
        """One tick: converge pins on the assignment, then heartbeat.
        Per-model failures are contained — one unloadable checkpoint
        must not take the whole replica out of the gossip."""
        from learningorchestra_tpu.ml.checkpoint import checkpoint_path

        assigned = set(self.assigned_models())
        pinned: list[str] = []
        warmed = 0
        errors = 0
        registry = self.serve.registry
        for name in sorted(assigned):
            path = checkpoint_path(self.models_dir, name)
            try:
                if self._warm and name not in self._warmed:
                    from learningorchestra_tpu.compile.warmup import (
                        warm_artifact,
                    )

                    # warm_artifact pins through the registry, then runs
                    # the serve-shaped forward under the AOT compile span
                    warm_artifact(path, serve=self.serve)
                    self._warmed.add(name)
                    warmed += 1
                else:
                    registry.get(path)
                pinned.append(name)
            except Exception:  # noqa: BLE001 — keep gossiping
                errors += 1
        for name in sorted(self._assigned - assigned):
            # assignment moved away: the byte budget follows it
            registry.release(checkpoint_path(self.models_dir, name))
            self._warmed.discard(name)
        self._assigned = assigned
        stats = registry.stats()
        metrics = _fleet_metrics()
        metrics["replicas"].set(self.placement.document()["replicas"])
        metrics["pinned_bytes"].set(stats["bytes"])
        self.heartbeat.write(
            pinned, stats["bytes"], self.serve.batcher.depth()
        )
        return {
            "replica": self.index,
            "assigned": sorted(assigned),
            "pinned": pinned,
            "warmed": warmed,
            "errors": errors,
            "pinned_bytes": stats["bytes"],
        }

    def start(self) -> "ReplicaAgent":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run,
                daemon=True,
                name=f"fleet-replica-{self.index}",
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.refresh()
            except Exception:  # noqa: BLE001 — the loop must survive a
                # store hiccup (the missed heartbeat IS the health
                # signal), but the operator still gets the traceback
                traceback.print_exc()
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# ---------------------------------------------------------------------------
# Telemetry

_METRICS: Optional[dict] = None
_METRICS_LOCK = threading.Lock()


def _fleet_metrics() -> dict:
    """Fleet gauges, declared once per process (docs/observability.md)."""
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            from learningorchestra_tpu.telemetry import global_registry

            registry = global_registry()
            _METRICS = {
                "replicas": registry.gauge(
                    "lo_fleet_replicas",
                    "Serving replicas in the placement geometry",
                ),
                "pinned_bytes": registry.gauge(
                    "lo_fleet_pinned_bytes",
                    "This replica's pinned model parameter bytes",
                ),
                "placement_rev": registry.gauge(
                    "lo_fleet_placement_rev",
                    "Last observed __lo_placement__ collection rev",
                ),
            }
        return _METRICS
