"""Serving knobs, env-configurable with validation.

Same posture as ``sched/config.py``: every knob is read at ServePlane
construction (not import) so tests monkeypatch the environment, and a
malformed value raises immediately with the offending text —
``deploy/run.sh`` preflights all of them so a typo fails bring-up
instead of silently serving at a default.

Knob table (documented in docs/serving.md):

==============================  =======  ==================================
env var                         default  meaning
==============================  =======  ==================================
``LO_SERVE_BYTES``              1e9      registry device-byte budget; past
                                         it LRU eviction; ``0`` = host-only
                                         fallback (load per request, no
                                         pinning)
``LO_SERVE_BATCH_WINDOW_MS``    1.0      micro-batch collection window in
                                         milliseconds (``0`` = dispatch
                                         immediately, still draining any
                                         backlog into one batch)
``LO_SERVE_MAX_BATCH``          64       max requests coalesced into one
                                         forward dispatch (also the row
                                         count small batches pad to, and
                                         the row budget past which
                                         collection stops early)
``LO_SERVE_MAX_ROWS``           4096     max rows in ONE predict request;
                                         past it the route answers 413 —
                                         bulk scoring belongs on the batch
                                         lane (``/predictions``)
``LO_SERVE_QUEUE_CAP``          256      bounded batcher inbox; past it
                                         submissions get HTTP 429 +
                                         ``Retry-After``
``LO_SERVE_TIMEOUT_S``          30       per-request wait bound before the
                                         route answers 503 (the batcher
                                         drops abandoned requests instead
                                         of running their forwards)
==============================  =======  ==================================
"""

from __future__ import annotations

# One env-parsing implementation for both knob families: count knobs
# are strictly integral (LO_SERVE_MAX_BATCH=1.5 silently truncating to
# 1 would disable micro-batching — the misconfiguration-by-typo this
# module exists to refuse, and what the manifest validation in
# deploy/cluster.py already rejects).
from learningorchestra_tpu.sched.config import _float_env, _int_env

DEFAULT_SERVE_BYTES = 1_000_000_000


def serve_bytes() -> int:
    """Registry capacity in bytes of pinned model parameters.
    ``0`` disables pinning entirely (host-only fallback: every predict
    loads the checkpoint fresh — correct, just slower). Scientific
    notation accepted (``1e9``), same as ``LO_DEVCACHE_BYTES``."""
    return int(_float_env("LO_SERVE_BYTES", DEFAULT_SERVE_BYTES, 0))


def batch_window_s() -> float:
    """The micro-batch collection window, converted to seconds."""
    return _float_env("LO_SERVE_BATCH_WINDOW_MS", 1.0, 0.0) / 1000.0


def max_batch() -> int:
    return _int_env("LO_SERVE_MAX_BATCH", 64, 1)


def max_rows() -> int:
    """Row cap per predict request. The online lane is for low-latency
    scoring; an uncapped body would let one request drive an unbounded
    H2D + device allocation on the latency path."""
    return _int_env("LO_SERVE_MAX_ROWS", 4096, 1)


def queue_cap() -> int:
    return _int_env("LO_SERVE_QUEUE_CAP", 256, 1)


def request_timeout_s() -> float:
    value = _float_env("LO_SERVE_TIMEOUT_S", 30.0, 0.0)
    if value <= 0:
        raise ValueError(f"LO_SERVE_TIMEOUT_S must be > 0, got {value}")
    return value


def validate_all() -> dict:
    """Read every serving knob once — the deploy preflight entry point.
    Returns the resolved values so callers can log them."""
    return {
        "serve_bytes": serve_bytes(),
        "batch_window_s": batch_window_s(),
        "max_batch": max_batch(),
        "max_rows": max_rows(),
        "queue_cap": queue_cap(),
        "request_timeout_s": request_timeout_s(),
    }
