"""Scale proof: the end-to-end product path at 10M+ rows on one host.

Round 3 capped the store at ~1M rows: dataset cells were boxed Python
objects (VERDICT r3 missing #1). With typed columnar blocks
(core/columns.py) and vec-typed probability writes (ml/builder.py), the
north-star dataset sizes (BASELINE.json configs[3-4] — Criteo-sample /
NYC-Taxi-class row counts) become loadable on a single host: this
script ingests ``rows`` synthetic rows, runs the full model-builder
pipeline (store read -> preprocessor -> 5 classifier fits -> evaluate ->
prediction write-back), and reports wall-clocks plus peak RSS against
the bytes actually stored. The reference handles beyond-RAM data only
because MongoDB owns disk and Spark reads it partitioned (reference
docker-compose.yml:335-340, model_builder.py:74-76); this is the
one-host TPU-native equivalent with the store in memory.

Usage: python scale.py [rows] [classifier,classifier,...]
Prints ONE JSON line. Not part of bench.py's budgeted run — invoke
explicitly (the 10M default takes ~10-20 min on one v5e chip).
"""

from __future__ import annotations

import json
import resource
import sys
import time

import numpy as np

FEATURES = 16


def _rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def stored_gb(store, collections: list[str]) -> float:
    """Live bytes held by the store's typed column blocks."""
    total = 0
    for name in collections:
        for column in store.read_column_arrays(name).values():
            total += column.nbytes()
    return total / 1e9


def run_scale(rows: int, classifiers: list[str]) -> dict:
    import os

    from learningorchestra_tpu.core.store import InMemoryStore
    from learningorchestra_tpu.ml.builder import build_model
    from learningorchestra_tpu.utils.jitcache import enable_compile_cache

    # One classifier's device working set at a time: five concurrent
    # 10M-row fits exceed a single chip's HBM (16 GB on v5e).
    os.environ.setdefault("LO_BUILD_WORKERS", "1")
    enable_compile_cache()

    rng = np.random.default_rng(0)
    X = rng.random((rows, FEATURES), dtype=np.float32) * 20.0
    y = (
        (X[:, 0] + X[:, 1] * 0.5 + rng.random(rows, dtype=np.float32) * 8) > 22
    ).astype(np.int32)

    store = InMemoryStore()
    start = time.perf_counter()
    for name in ("scale_train", "scale_test"):
        store.create_collection(name)
        store.insert_one(
            name,
            {
                "_id": 0,
                "filename": name,
                "finished": True,
                "fields": [f"f{i}" for i in range(FEATURES)] + ["label"],
            },
        )
        columns = {f"f{i}": X[:, i] for i in range(FEATURES)}
        columns["label"] = y
        store.insert_columns(name, columns)
    ingest_s = time.perf_counter() - start

    preprocessor = (
        "from pyspark.ml.feature import VectorAssembler\n"
        "feature_cols = [c for c in training_df.schema.names if c != 'label']\n"
        "assembler = VectorAssembler(inputCols=feature_cols, outputCol='features')\n"
        "features_training = assembler.transform(training_df)\n"
        "features_testing = assembler.transform(testing_df)\n"
        "features_evaluation = assembler.transform(testing_df)\n"
    )
    start = time.perf_counter()
    results = build_model(
        store, "scale_train", "scale_test", preprocessor, classifiers
    )
    build_s = time.perf_counter() - start

    outputs = [f"scale_test_prediction_{name}" for name in classifiers]
    data_gb = stored_gb(store, ["scale_train", "scale_test"] + outputs)
    peak_gb = _rss_gb()
    from learningorchestra_tpu.utils.jitcache import cache_stats

    return {
        "rows": rows,
        "jit_cache": cache_stats(),
        "classifiers": classifiers,
        "ingest_s": round(ingest_s, 2),
        "build_s": round(build_s, 2),
        "rows_per_sec": round(rows / (ingest_s + build_s), 1),
        "stored_gb": round(data_gb, 3),
        "peak_rss_gb": round(peak_gb, 2),
        "rss_over_stored": round(peak_gb / data_gb, 2) if data_gb else None,
        "accuracy": {
            r["classificator"]: float(r["accuracy"]) for r in results
        },
        "fit_s": {
            r["classificator"]: round(r["timings"]["fit"], 2) for r in results
        },
        "write_s": {
            r["classificator"]: round(r["timings"]["write"], 2)
            for r in results
        },
        # every recorded phase, summed across classifiers — the
        # difference between build_s and this total is frame prep +
        # preprocessor + store reads (untimed host work)
        "phase_totals_s": {
            phase: round(
                sum(r["timings"].get(phase, 0.0) for r in results), 2
            )
            for phase in sorted(
                {phase for r in results for phase in r["timings"]}
            )
        },
    }


def run_northstar(rows: int) -> dict:
    """BASELINE configs[4] on ONE chip: histogram + PCA at NYC-Taxi-class
    row counts (the reference provisions a 64-worker Spark swarm; its
    PCA path cannot run at all past driver RAM — toPandas() collapse,
    reference pca.py:75-80). Ingests ``rows`` synthetic rows into the
    typed store, runs the store's $group histogram pushdown, then the
    device PCA; t-SNE via the landmark path as a stretch measurement."""
    from learningorchestra_tpu.core.store import InMemoryStore
    from learningorchestra_tpu.ops.pca import pca_embedding
    from learningorchestra_tpu.ops.tsne import tsne_embedding
    from learningorchestra_tpu.utils.jitcache import enable_compile_cache

    enable_compile_cache()
    rng = np.random.default_rng(0)
    centers = rng.normal(size=(10, FEATURES)).astype(np.float32) * 8.0
    labels = rng.integers(0, 10, size=rows)
    # float32 end to end: float64 noise intermediates would double peak
    # RSS (~25 GB of transients for a 6.4 GB matrix at 100M rows)
    X = centers[labels]
    X += rng.standard_normal((rows, FEATURES), dtype=np.float32)

    store = InMemoryStore()
    store.create_collection("taxi")
    store.insert_one(
        "taxi",
        {
            "_id": 0,
            "filename": "taxi",
            "finished": True,
            "fields": [f"f{i}" for i in range(FEATURES)] + ["cluster"],
        },
    )
    start = time.perf_counter()
    columns = {f"f{i}": X[:, i] for i in range(FEATURES)}
    columns["cluster"] = labels.astype(np.int64)
    store.insert_columns("taxi", columns)
    ingest_s = time.perf_counter() - start

    start = time.perf_counter()
    groups = store.aggregate(
        "taxi",
        [{"$group": {"_id": "$cluster", "count": {"$sum": 1}}}],
    )
    histogram_s = time.perf_counter() - start

    start = time.perf_counter()
    pca_embedding(X)
    pca_e2e_s = time.perf_counter() - start

    out = {
        "rows": rows,
        "ingest_s": round(ingest_s, 2),
        "histogram_s": round(histogram_s, 3),
        "histogram_groups": len(groups),
        "pca_e2e_numpy_s": round(pca_e2e_s, 2),
        "stored_gb": round(stored_gb(store, ["taxi"]), 2),
        "peak_rss_gb": round(_rss_gb(), 2),
    }
    try:
        start = time.perf_counter()
        embedded = tsne_embedding(X)  # landmark path past 20k rows
        out["tsne_landmark_s"] = round(time.perf_counter() - start, 2)
        out["tsne_shape"] = list(embedded.shape)
    except Exception as error:  # noqa: BLE001 — stretch measurement
        out["tsne_landmark_error"] = f"{type(error).__name__}: {error}"
    out["peak_rss_gb"] = round(_rss_gb(), 2)
    return out


def run_pipeline(rows: int) -> dict:
    """BASELINE configs[2]: projection + data-type-handler over a
    synthetic CSV — the reference's Spark-projection / per-document
    pymongo-update path (reference projection.py:104-125,
    data_type_handler.py:47-77, one update RPC per document per field).
    Here: native C++ CSV parse into string columns, single columnar
    move for the projection, vectorized numeric cast."""
    import os
    import tempfile

    from learningorchestra_tpu.core.ingest import ingest_csv
    from learningorchestra_tpu.core.store import InMemoryStore
    from learningorchestra_tpu.ops.dtype import convert_field_types
    from learningorchestra_tpu.ops.projection import project

    rng = np.random.default_rng(0)
    fields = [f"f{i}" for i in range(FEATURES)]

    start = time.perf_counter()
    # LO_PIPELINE_CSV names a persistent CSV: reused when present,
    # GENERATED THERE when absent (and kept) — regenerating a 12 GB
    # file costs ~20 min of pure setup per run, and generating to a
    # throwaway temp path while the named file stays absent would leak
    # the full file every run
    reuse = os.environ.get("LO_PIPELINE_CSV")
    if reuse and os.path.exists(reuse):
        path = reuse
    else:
        with (
            open(reuse, "w")
            if reuse
            else tempfile.NamedTemporaryFile("w", suffix=".csv", delete=False)
        ) as handle:
            handle.write(",".join(fields) + "\n")
            # streamed generation: one 100k-row block live at a time, so
            # peak RSS measures the PIPELINE's working set, not setup
            for block_start in range(0, rows, 100_000):
                block = rng.random(
                    (min(100_000, rows - block_start), FEATURES),
                    dtype=np.float32,
                ) * 100
                lines = "\n".join(
                    ",".join(f"{v:.4f}" for v in row) for row in block
                )
                handle.write(lines + "\n")
            path = handle.name
    csv_write_s = time.perf_counter() - start  # setup, not measured work

    store = InMemoryStore()
    rss_after = {}
    try:
        store.create_collection("pipe")
        start = time.perf_counter()
        count = ingest_csv(store, "pipe", path)
        ingest_s = time.perf_counter() - start
        rss_after["ingest"] = round(_rss_gb(), 2)

        keep = fields[: FEATURES // 2]
        store.create_collection("pipe_slim")
        start = time.perf_counter()
        project(store, "pipe", "pipe_slim", keep)
        projection_s = time.perf_counter() - start
        rss_after["projection"] = round(_rss_gb(), 2)

        start = time.perf_counter()
        convert_field_types(
            store, "pipe_slim", {field: "number" for field in keep}
        )
        dtype_s = time.perf_counter() - start
        rss_after["dtype"] = round(_rss_gb(), 2)

        start = time.perf_counter()
        groups = store.aggregate(
            "pipe_slim",
            [{"$group": {"_id": f"${keep[0]}", "count": {"$sum": 1}}}],
        )
        histogram_s = time.perf_counter() - start

        spilled = sum(
            1
            for name in ("pipe", "pipe_slim")
            for column in store._collections[name].block_columns.values()
            if column.is_spilled()
        )
        stored = stored_gb(store, ["pipe", "pipe_slim"])
    finally:
        if not reuse:
            os.unlink(path)

    pipeline_s = ingest_s + projection_s + dtype_s
    peak = _rss_gb()
    return {
        "rows": count,
        "csv_bytes": rows * (FEATURES * 8),
        "csv_write_setup_s": round(csv_write_s, 2),
        "ingest_s": round(ingest_s, 2),
        "projection_s": round(projection_s, 2),
        "dtype_s": round(dtype_s, 2),
        "histogram_s": round(histogram_s, 3),
        "histogram_groups": len(groups),
        "pipeline_rows_per_sec": round(count / pipeline_s, 1),
        "stored_gb": round(stored, 2),
        "spilled_columns": spilled,
        "spill_budget_gb": round(
            float(os.environ.get("LO_SPILL_BYTES", "8e9") or 0) / 1e9, 2
        ),
        "peak_rss_gb": round(peak, 2),
        "peak_rss_after_phase_gb": rss_after,
        "rss_over_stored": round(peak / stored, 2) if stored else None,
    }


def main() -> None:
    flags = {a for a in sys.argv[1:] if a.startswith("--")}
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    unknown = flags - {"--northstar", "--pipeline"}
    if unknown:  # a typo must not silently launch the 20-minute default
        raise SystemExit(f"unknown flags {sorted(unknown)}")
    rows = int(args[0]) if args else 10_000_000
    if "--northstar" in flags:
        print(json.dumps(run_northstar(rows)))
        return
    if "--pipeline" in flags:
        print(json.dumps(run_pipeline(rows)))
        return
    classifiers = args[1].split(",") if len(args) > 1 else [
        "lr", "dt", "rf", "gb", "nb"
    ]
    print(json.dumps(run_scale(rows, classifiers)))


if __name__ == "__main__":
    main()
