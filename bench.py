"""Benchmark: Model-Builder rows/sec/chip (the BASELINE.json north-star).

Times the full five-classifier model-builder fit suite — lr, dt, rf, gb,
nb at their MLlib-default configurations (the reference's classifier set,
model_builder.py:151-157) — on 1M synthetic rows resident on device, and
reports aggregate throughput ``rows / suite_wall_clock``.

The reference's only published wall-clock anchor is the Titanic
NaiveBayes fit: 41.870062828063965 s for 891 rows (docs/
database_api.md:76-83) ≈ 21.28 rows/s for ONE classifier.
``vs_baseline`` compares our rows/sec for the whole FIVE-classifier
suite against that single-classifier anchor — conservative by 5x.

Data is placed on device once, outside the timed region: the
model-builder regime is one load feeding many fits (the reference fits
all requested classifiers on the same loaded dataframes). Prints exactly
one JSON line.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_ROWS_PER_SEC = 891 / 41.870062828063965  # reference anchor (1 clf)
ROWS = 1_000_000
FEATURES = 16
CLASSES = 2


def main() -> None:
    import jax
    import jax.numpy as jnp

    from learningorchestra_tpu.ml import logistic, naive_bayes, trees
    from learningorchestra_tpu.ml.base import prepare_xy, resolve_mesh
    from learningorchestra_tpu.ml.binning import apply_bins, make_thresholds

    rng = np.random.default_rng(0)
    X = rng.random((ROWS, FEATURES), dtype=np.float32) * 20.0
    y = (
        (X[:, 0] + X[:, 1] * 0.5 + rng.random(ROWS, dtype=np.float32) * 8) > 22
    ).astype(np.int32)

    mesh = resolve_mesh(None)
    thresholds = jnp.asarray(make_thresholds(X), jnp.float32)
    X_std = (X - X.mean(0)) / np.maximum(X.std(0), 1e-9)
    X_dev, y_dev, mask_b = prepare_xy(X, y, mesh)
    X_std_dev, _, _ = prepare_xy(X_std, y, mesh)
    mask = mask_b.astype(jnp.float32)
    key = jax.random.key(0)
    params0 = {
        "w": jnp.zeros((FEATURES, CLASSES), jnp.float32),
        "b": jnp.zeros((CLASSES,), jnp.float32),
    }

    def suite():
        bins = apply_bins(X_dev, thresholds)
        outs = []
        outs.append(
            logistic._fit(params0, X_std_dev, y_dev, mask, 100, jnp.float32(0.0))[0]["w"]
        )
        outs.append(naive_bayes._fit(X_dev, y_dev, mask, CLASSES, jnp.float32(1.0))[0])
        outs.append(trees._dt_fit(bins, y_dev, mask, CLASSES, 5, 32)[2])
        outs.append(
            trees._rf_fit(bins, y_dev, mask, key, CLASSES, 5, 32, 20, 4)[2]
        )
        outs.append(trees._gbt_fit(bins, y_dev, mask, 5, 32, 20, jnp.float32(0.1))[3])
        # Fetch to host: the fitted-model materialization a real caller
        # observes (and block_until_ready alone does not synchronize on
        # every remote-attached platform).
        for out in outs:
            np.asarray(out)

    suite()  # compile everything once
    times = []
    for _ in range(3):
        start = time.perf_counter()
        suite()
        times.append(time.perf_counter() - start)
    best = min(times)
    rows_per_sec = ROWS / best

    print(
        json.dumps(
            {
                "metric": "model_builder_5clf_rows_per_sec",
                "value": round(rows_per_sec, 1),
                "unit": "rows/s",
                "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
