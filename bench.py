"""Benchmark suite: the two BASELINE.json north-star metrics plus MFU.

Four sections, all on the visible chip(s):

1. **Kernel suite** (headline, comparable to earlier rounds): the five
   classifier fit kernels — lr, dt, rf, gb, nb at MLlib-default configs
   (the reference's classifier set, model_builder.py:151-157) — on
   synthetic rows resident on device; per-classifier wall-clocks and
   aggregate ``rows / suite_time``.
2. **Product path**: the same rows ingested into the columnar store and
   driven through ``ml.builder.build_model`` (store read → preprocessor
   → five fits → prediction write-back), with the per-phase timings the
   service persists (fit/evaluate/predict/write). This is what a user
   of the REST surface actually gets; the reference's analogue is the
   persisted ``fit_time`` (model_builder.py:198-203) plus its untimed
   ``collect()``+insert tail.
3. **Embeddings north-star**: PCA and t-SNE wall-clocks. Head-to-head
   vs sklearn (the reference's actual engine, pca.py:87-88 /
   tsne.py:87-88) at a size sklearn can finish, then our scaling sizes
   (100k / 1M rows) that the reference's single-host path cannot reach.
4. **MFU**: a peak bf16 matmul probe (the chip's demonstrated ceiling)
   and an analytic lower bound for the LR fit (its two matmuls per
   L-BFGS iteration — tabular fits are HBM-bound, so this is honest
   and small).
5. **Serve**: closed-loop load against the online predict lane
   (docs/serving.md) at 1 / 8 / 64 concurrent clients — p50/p99
   latency, predictions/s, achieved mean batch size
   (``LO_BENCH_SERVE_REQUESTS`` per client, default 100).
6. **Coalesce**: the job coalescer (docs/scheduler.md) under a burst of
   64 concurrent small builds — jobs/s with coalescing on vs
   ``LO_COALESCE_WINDOW_MS=0`` off, achieved mean batch size — plus a
   100-point λ sweep as ONE fused dispatch vs 100 sequential
   estimator fits.

Prints exactly ONE JSON line: the headline kernel metric (metric/value/
unit/vs_baseline, same name as previous rounds) with everything else
under ``"extra"``. The reference's only published wall-clock anchor is
the Titanic NaiveBayes fit: 41.87 s for 891 rows (docs/
database_api.md:76-83) ≈ 21.28 rows/s for ONE classifier;
``vs_baseline`` compares the FIVE-classifier suite against it.

Budgeted: the driver gives one bench invocation finite wall-clock, so
sections spend against ``LO_BENCH_BUDGET_S`` (default 540 s) — optional
measurements (sklearn head-to-heads, the largest scaling size, warm
repeats) are skipped with an explicit ``"skipped"`` note once the
budget runs low, and the headline JSON line ALWAYS prints (sections
that fail carry an ``"error"`` instead of silencing the run).

Env knobs (for smoke runs): ``LO_BENCH_ROWS`` (default 1M),
``LO_BENCH_PRODUCT_ROWS`` (default 100k), ``LO_BENCH_EMBED_ROWS``
(default 1M), ``LO_BENCH_SKLEARN`` (default 1), ``LO_BENCH_BUDGET_S``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

BASELINE_ROWS_PER_SEC = 891 / 41.870062828063965  # reference anchor (1 clf)
ROWS = int(os.environ.get("LO_BENCH_ROWS", 1_000_000))
PRODUCT_ROWS = int(os.environ.get("LO_BENCH_PRODUCT_ROWS", 100_000))
EMBED_ROWS = int(os.environ.get("LO_BENCH_EMBED_ROWS", 1_000_000))
BUDGET_S = float(os.environ.get("LO_BENCH_BUDGET_S", 540))
_START = time.monotonic()


def _budget_left() -> float:
    return BUDGET_S - (time.monotonic() - _START)
RUN_SKLEARN = os.environ.get("LO_BENCH_SKLEARN", "1") == "1"
HEAD_TO_HEAD_ROWS = 2_048  # size sklearn's exact/BH t-SNE finishes quickly
FEATURES = 16
CLASSES = 2

# bf16 peak FLOP/s per chip by device_kind substring (public specs).
TPU_PEAK_FLOPS = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5", 197e12),  # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def _synthetic(rows: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    X = rng.random((rows, FEATURES), dtype=np.float32) * 20.0
    y = (
        (X[:, 0] + X[:, 1] * 0.5 + rng.random(rows, dtype=np.float32) * 8) > 22
    ).astype(np.int32)
    return X, y


def _best_of(fn, repeats: int = 3) -> float:
    times = []
    for _ in range(repeats):
        start = time.perf_counter()
        fn()
        times.append(time.perf_counter() - start)
    return min(times)


def _make_kernel_suite(X, y, subset_k: int):
    """Device setup + the five fit-kernel closures and the suite runner,
    shared by the default-shape and wide-shape kernel sections (one
    definition, one configuration to keep in sync)."""
    import jax
    import jax.numpy as jnp

    from learningorchestra_tpu.ml import logistic, naive_bayes, trees
    from learningorchestra_tpu.ml.base import prepare_xy, resolve_mesh
    from learningorchestra_tpu.ml.binning import apply_bins, make_thresholds

    features = X.shape[1]
    mesh = resolve_mesh(None)
    thresholds = jnp.asarray(make_thresholds(X), jnp.float32)
    X_std = (X - X.mean(0)) / np.maximum(X.std(0), 1e-9)
    X_dev, y_dev, mask_b = prepare_xy(X, y, mesh)
    X_std_dev, _, _ = prepare_xy(X_std, y, mesh)
    mask = mask_b.astype(jnp.float32)
    key = jax.random.key(0)
    params0 = {
        "w": jnp.zeros((features, CLASSES), jnp.float32),
        "b": jnp.zeros((CLASSES,), jnp.float32),
    }
    bins = apply_bins(X_dev, thresholds)
    bins.block_until_ready()

    # Fetch to host: the fitted-model materialization a real caller
    # observes (block_until_ready alone does not synchronize on every
    # remote-attached platform).
    kernels = {
        "lr": lambda: np.asarray(
            logistic._fit(params0, X_std_dev, y_dev, mask, 100, jnp.float32(0.0))[0]["w"]
        ),
        "nb": lambda: np.asarray(
            naive_bayes._fit(X_dev, y_dev, mask, CLASSES, jnp.float32(1.0))[0]
        ),
        "dt": lambda: np.asarray(trees._dt_fit(bins, y_dev, mask, CLASSES, 5, 32)[2]),
        "rf": lambda: np.asarray(
            trees._rf_fit(bins, y_dev, mask, key, CLASSES, 5, 32, 20, subset_k)[2]
        ),
        "gb": lambda: np.asarray(
            trees._gbt_fit(bins, y_dev, mask, 5, 32, 20, jnp.float32(0.1))[3]
        ),
    }

    def suite():
        for kernel in kernels.values():
            kernel()

    return kernels, suite, bins, y_dev, mask


def _chained_roofline(make_body, analytic_bytes: int, note: str) -> dict:
    """Time ``iters`` CSE-broken repetitions of a kernel inside ONE jit
    (single host sync — on a remote-attached chip every sync costs
    ~0.3 s of tunnel latency) and report implied HBM traffic."""
    import jax
    import jax.numpy as jnp

    iters = 8

    @jax.jit
    def chained():
        def body(i, acc):
            return acc + make_body(i)

        return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

    float(chained())  # compile
    start = time.perf_counter()
    float(chained())
    elapsed = (time.perf_counter() - start) / iters
    return {
        "pass_s": round(elapsed, 5),
        "analytic_bytes": analytic_bytes,
        "implied_gb_per_s": round(analytic_bytes / elapsed / 1e9, 1),
        "note": note,
    }


def _lr_grad_roofline(X, y) -> dict:
    """One loss+gradient pass — the unit the L-BFGS iteration count
    multiplies. Traffic: X read in the forward AND the backward."""
    import jax
    import jax.numpy as jnp

    from learningorchestra_tpu.ml import logistic

    rows = len(X)
    X_dev = jnp.asarray(X)
    y_dev = jnp.asarray(y)
    mask = jnp.ones(rows, jnp.float32)
    params = {
        "w": jnp.zeros((FEATURES, CLASSES), jnp.float32),
        "b": jnp.zeros((CLASSES,), jnp.float32),
    }
    grad_fn = jax.value_and_grad(logistic._loss_fn)

    def body(i):
        scaled = {
            "w": params["w"] + i.astype(jnp.float32) * 1e-7,  # break CSE
            "b": params["b"],
        }
        value, grad = grad_fn(scaled, X_dev, y_dev, mask, jnp.float32(0.0))
        return value + grad["w"].sum()

    analytic = 2 * rows * FEATURES * 4 + 2 * rows * 4
    return _chained_roofline(body, analytic, "value_and_grad, X read fwd+bwd")


def _nb_fit_roofline(X, y) -> dict:
    """The whole NB fit: one (C, rows) x (rows, F) contraction."""
    import jax.numpy as jnp

    from learningorchestra_tpu.ml import naive_bayes

    rows = len(X)
    X_dev = jnp.asarray(X)
    y_dev = jnp.asarray(y)
    mask = jnp.ones(rows, jnp.float32)

    def body(i):
        # the perturbation must feed the HEAVY op (one_hot * mask before
        # the contraction) or XLA hoists the matmul out of the loop —
        # i*0.0 would constant-fold and leave it loop-invariant
        theta, prior = naive_bayes._fit(
            X_dev,
            y_dev,
            mask + i.astype(jnp.float32) * 1e-7,
            num_classes=CLASSES,
            smoothing=jnp.float32(1.0),
        )
        return theta.sum() + prior.sum()

    analytic = rows * (FEATURES * 4 + 4 + 4 + 2 * CLASSES * 4)
    return _chained_roofline(body, analytic, "X + y + mask read, one-hot written+read")


def _eval_forward_roofline(X, y) -> dict:
    """The evaluate/predict forward + on-device confusion metrics —
    the per-classifier tail's device portion."""
    import jax.numpy as jnp

    from learningorchestra_tpu.ml import naive_bayes
    from learningorchestra_tpu.ml.evaluation import masked_metrics

    rows = len(X)
    X_dev = jnp.asarray(X)
    y_dev = jnp.asarray(y)
    mask_b = jnp.ones(rows, bool)
    theta = jnp.ones((CLASSES, FEATURES), jnp.float32) * 0.1
    prior = jnp.zeros((CLASSES,), jnp.float32)

    def body(i):
        labels, probs = naive_bayes._forward(
            theta + i.astype(jnp.float32) * 1e-7, prior, X_dev
        )
        accuracy, weighted_f1 = masked_metrics(y_dev, labels, mask_b, CLASSES)
        return probs.sum() + accuracy + weighted_f1

    analytic = rows * (FEATURES * 4 + 2 * CLASSES * 4 + 4 + 4)
    return _chained_roofline(
        body, analytic, "forward probs written+read, labels+metrics"
    )


def bench_kernels(X, y) -> dict:
    """Section 1: jitted fit kernels on device-resident data."""
    kernels, suite, bins, y_dev, mask = _make_kernel_suite(X, y, subset_k=4)

    suite()  # compile everything once
    # Headline: best-of-2 of the WHOLE suite (same best-of methodology
    # as earlier rounds; one fewer repeat to fit the bench budget — a
    # min over fewer repeats can only read slower, never flatter).
    suite_time = _best_of(suite, repeats=2)
    # Attribution overhead: the SAME suite with timeline recording on
    # (an active trace + one span per kernel; sampler off). The flight
    # recorder's contract is <2% overhead on kernel throughput — this
    # measures it every round so a creeping instrumentation cost is a
    # flagged regression, not a silent tax (docs/profiling.md).
    from learningorchestra_tpu.telemetry import tracing as _tracing

    def suite_recording():
        trace_obj = _tracing.Trace(name="bench_kernels")
        with _tracing.activate(trace_obj):
            for name, kernel in kernels.items():
                with _tracing.span(f"kernel:{name}"):
                    kernel()

    recording_time = _best_of(suite_recording, repeats=2)
    # Diagnostics: one timed pass per kernel (these sum lower than the
    # suite — they lose cross-kernel async overlap; don't compare across
    # rounds).
    per_classifier = {
        name: round(_best_of(kernel, repeats=1), 4)
        for name, kernel in kernels.items()
    }
    rows = len(X)
    out = {
        "rows": rows,
        "suite_s": round(suite_time, 4),
        "rows_per_sec": round(rows / suite_time, 1),
        "per_classifier_s": per_classifier,
        "suite_recording_on_s": round(recording_time, 4),
        # positive = recording cost; small negatives are run-to-run noise
        "recording_overhead_pct": round(
            100.0 * (recording_time / suite_time - 1.0), 2
        ),
    }
    # Bytes-based rooflines for every kernel class: these tabular fits
    # are HBM-bound, so achieved GB/s against the chip's ceiling is the
    # honest utilization axis (a FLOPs MFU read misleadingly low here —
    # VERDICT r4 weak #7; the bf16 matmul probe in extra.mfu remains as
    # the chip's demonstrated FLOP ceiling, it is just not this
    # workload's roofline).
    for name, probe in (
        ("tree_histogram_roofline", lambda: _histogram_roofline(bins, y_dev, mask)),
        ("lr_grad_roofline", lambda: _lr_grad_roofline(X, y)),
        ("nb_fit_roofline", lambda: _nb_fit_roofline(X, y)),
        ("eval_forward_roofline", lambda: _eval_forward_roofline(X, y)),
    ):
        try:
            out[name] = probe()
        except Exception as error:  # noqa: BLE001
            out[name] = {"error": f"{type(error).__name__}: {error}"}
    return out


def _histogram_roofline(bins, y_dev, mask) -> dict:
    """Bytes-based utilization for the tree-split histogram pass — the
    hot loop of dt/rf/gb (ml/trees.py _level_histograms). Measures one
    deepest-level pass (16 nodes) and reports implied HBM traffic
    against the chip's ~819 GB/s (v5e) ceiling. The MXU matmul
    formulation is bandwidth-bound on its one-hot construction, not
    FLOP-bound, so bytes/s is the honest axis."""
    import jax
    import jax.numpy as jnp

    from learningorchestra_tpu.ml import trees

    n_nodes, max_bins = 16, 32
    rows = bins.shape[0]
    node = jnp.asarray(
        np.random.default_rng(3).integers(0, n_nodes, rows), jnp.int32
    )
    channels = jax.nn.one_hot(y_dev, CLASSES, dtype=jnp.float32) * mask[:, None]

    # Chain iterations inside ONE jit (single host sync): on a
    # remote-attached chip every sync costs ~0.3 s of tunnel latency,
    # comparable to the level itself — see _pca_timings.
    iters = 8

    @jax.jit
    def chained(bins, node, channels):
        def body(i, acc):
            ch = channels * (1.0 + i.astype(jnp.float32) * 1e-7)  # break CSE
            return acc + trees._level_histograms(
                bins, node, ch, n_nodes, max_bins
            ).sum()

        return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

    float(chained(bins, node, channels))  # compile
    start = time.perf_counter()
    float(chained(bins, node, channels))
    elapsed = (time.perf_counter() - start) / iters
    # Analytic traffic: node one-hot + fused (rows, nodes*K) product
    # written+read, bins read, per-feature bin one-hot written+read.
    k = CLASSES
    bytes_touched = 4 * rows * (
        2 * n_nodes + 2 * n_nodes * k + FEATURES * (2 * max_bins + n_nodes * k + 1)
    )
    return {
        "level_s": round(elapsed, 4),
        "analytic_bytes": bytes_touched,
        "implied_gb_per_s": round(bytes_touched / elapsed / 1e9, 1),
        "note": "deepest level (16 nodes), incl. one-hot construction traffic",
    }


def bench_kernels_wide() -> dict:
    """Criteo-like wide shape (64 features, same rows) so the kernel
    numbers stop flattering overhead-bound fits at 16 features. Same
    suite construction as the headline section (_make_kernel_suite);
    only the shape and the RF per-node feature subset (sqrt(64)=8)
    differ."""
    wide_features = 64
    rng = np.random.default_rng(11)
    rows = min(ROWS, 1_000_000)
    Xw = rng.random((rows, wide_features), dtype=np.float32) * 20.0
    yw = ((Xw[:, :8].sum(1) + rng.random(rows, dtype=np.float32) * 20) > 88).astype(
        np.int32
    )
    _, suite, _, _, _ = _make_kernel_suite(Xw, yw, subset_k=8)

    suite()
    suite_time = _best_of(suite, repeats=1)
    return {
        "rows": rows,
        "features": wide_features,
        "suite_s": round(suite_time, 4),
        "rows_per_sec": round(rows / suite_time, 1),
    }


def bench_product(X, y) -> dict:
    """Section 2: the store→builder→store path a service request takes.

    Runs at ``PRODUCT_ROWS`` (default 100k): the wall-clock here is
    dominated by the store/host sides (Python column conversion, JSON-
    shaped writes) which scale linearly — 100k gives the same per-phase
    shape as 1M at a fifth of the budget."""
    from learningorchestra_tpu.core.store import InMemoryStore
    from learningorchestra_tpu.ml.builder import build_model

    X, y = X[:PRODUCT_ROWS], y[:PRODUCT_ROWS]
    store = InMemoryStore()
    rows = len(X)
    start = time.perf_counter()
    for name in ("bench_train", "bench_test"):
        store.create_collection(name)
        store.insert_one(
            name,
            {
                "_id": 0,
                "filename": name,
                "finished": True,
                "fields": [f"f{i}" for i in range(FEATURES)] + ["label"],
            },
        )
        columns = {f"f{i}": X[:, i].tolist() for i in range(FEATURES)}
        columns["label"] = y.tolist()
        store.insert_columns(name, columns)
    ingest_s = time.perf_counter() - start

    preprocessor = (
        "from pyspark.ml.feature import VectorAssembler\n"
        "feature_cols = [c for c in training_df.schema.names if c != 'label']\n"
        "assembler = VectorAssembler(inputCols=feature_cols, outputCol='features')\n"
        "features_training = assembler.transform(training_df)\n"
        "features_testing = assembler.transform(testing_df)\n"
        "features_evaluation = assembler.transform(testing_df)\n"
    )
    def run():
        return build_model(
            store,
            "bench_train",
            "bench_test",
            preprocessor,
            ["lr", "dt", "rf", "gb", "nb"],
        )

    from learningorchestra_tpu.core.devcache import global_devcache

    def devcache_delta(before: dict) -> dict:
        after = global_devcache().stats()
        return {
            key: after[key] - before.get(key, 0)
            for key in ("hits", "misses", "evictions", "invalidations")
        } | {"bytes": after["bytes"], "entries": after["entries"]}

    from learningorchestra_tpu.telemetry import profile as _profile_flows

    before_cold = global_devcache().stats()
    start = time.perf_counter()
    results = run()
    cold_s = time.perf_counter() - start  # includes XLA compiles + the
    # one store read + H2D this collection revision ever pays
    devcache_cold = devcache_delta(before_cold)
    # Cache-warm section: the SAME build over the already-read
    # collection. The devcache hit counters prove the second run
    # skipped the wire read (host-table hits) and the H2D
    # (content-addressed device-matrix hits) — the per-revision
    # once-per-boundary contract docs/dataplane.md states.
    # Cache-warm section runs under an active trace: the flight
    # recorder's per-phase attribution (load/preprocess/h2d/fit/write
    # seconds + wire/H2D bytes) is reported per round, so `--compare`
    # can name the phase that moved when warm_s regresses.
    from learningorchestra_tpu.telemetry import profile as _profile
    from learningorchestra_tpu.telemetry import tracing as _tracing

    before_warm = global_devcache().stats()
    # Byte-flow deltas around the WARM build (wire bytes, decode
    # seconds, H2D bytes — the boundary bill the zero-copy wire PR
    # drives down): recorded per round and direction-gated by
    # --compare, so a copy creeping back into the read path fails the
    # round by name instead of hiding inside warm_s.
    flows_before = _profile_flows.flow_totals()
    warm_trace = _tracing.Trace(name="bench_product_warm")
    start = time.perf_counter()
    with _tracing.activate(warm_trace):
        results = run()
    warm_s = time.perf_counter() - start  # what a steady-state request costs
    flows_after = _profile_flows.flow_totals()
    warm_flows = {
        key: round(flows_after[key] - flows_before[key], 6)
        for key in ("wire_read_bytes", "shm_bytes", "decode_s", "h2d_bytes")
    }
    devcache_warm = devcache_delta(before_warm)
    warm_summary = _profile.trace_summary(warm_trace)
    warm_phases = {
        name: entry["seconds"]
        for name, entry in sorted(warm_summary["phases"].items())
    }
    phases = {
        r["classificator"]: r["timings"] for r in results
    }
    return {
        "rows": rows,
        "ingest_s": round(ingest_s, 2),
        "build_model_5clf_cold_s": round(cold_s, 2),
        "build_model_5clf_warm_s": round(warm_s, 2),
        "end_to_end_rows_per_sec": round(rows / (ingest_s + warm_s), 1),
        "product_rows_per_sec_cold": round(rows / cold_s, 1),
        "product_rows_per_sec_warm": round(rows / warm_s, 1),
        "warm_speedup_vs_cold": round(cold_s / warm_s, 2),
        "devcache_cold": devcache_cold,
        "devcache_warm": devcache_warm,
        "warm_flows": warm_flows,
        "warm_attribution_s": warm_phases,
        "per_classifier_phases_s": phases,
        "accuracy": {
            r["classificator"]: float(r["accuracy"]) for r in results
        },
    }


def bench_wire() -> dict:
    """Wire-transport section: the SAME dataset read through the binary
    store wire as v1 frames (per-column decode copies), v2 frames
    (aligned zero-copy views, one allocation per chunk), and the
    shared-memory ring (no HTTP body at all) — MB/s plus each
    transport's decode-seconds bill, the numbers the zero-copy data
    plane moves (docs/dataplane.md)."""
    from learningorchestra_tpu.core.store import InMemoryStore
    from learningorchestra_tpu.core.store_service import (
        RemoteStore,
        create_store_app,
    )
    from learningorchestra_tpu.telemetry import profile as _profile
    from learningorchestra_tpu.utils.web import ServerThread

    rows = int(os.environ.get("LO_BENCH_WIRE_ROWS", "400000"))
    rng = np.random.default_rng(13)
    store = InMemoryStore()
    server = ServerThread(
        create_store_app(store, shm=True), "127.0.0.1", 0
    ).start()
    url = f"http://127.0.0.1:{server.port}"
    try:
        # ingest server-side directly: this section measures the READ
        # transports, not ingest
        columns = {f"f{i}": rng.random(rows) for i in range(8)}
        columns["tag"] = np.array(
            [f"row{i % 997}" for i in range(rows)], dtype=object
        )
        store.create_collection("bench_wire")
        store.insert_columns(
            "bench_wire",
            {name: values.tolist() for name, values in columns.items()},
            start_id=1,
        )
        payload_mb = rows * 8 * 8 / 1e6  # the float payload alone

        clients = {
            "v1": RemoteStore(url, wire_v2=False, shm_bytes=0),
            "v2": RemoteStore(url, shm_bytes=0),
            "shm": RemoteStore(url, shm_bytes=256_000_000),
        }
        out: dict = {"rows": rows, "payload_mb": round(payload_mb, 1)}
        baseline = None
        for name, client in clients.items():
            read = lambda c=client: c.read_column_arrays("bench_wire")  # noqa: E731
            read()  # warm connections + negotiate
            before = _profile.flow_totals()
            elapsed = _best_of(read, repeats=2)
            after = _profile.flow_totals()
            entry = {
                "read_s": round(elapsed, 4),
                "mb_per_s": round(payload_mb / elapsed, 1),
                "decode_s": round(
                    (after["decode_s"] - before["decode_s"]) / 2, 5
                ),
                "wire_read_bytes": int(
                    (after["wire_read_bytes"] - before["wire_read_bytes"])
                    / 2
                ),
                "shm_bytes": int(
                    (after["shm_bytes"] - before["shm_bytes"]) / 2
                ),
            }
            out[name] = entry
            if name == "v1":
                baseline = entry
            client.close()
        if baseline:
            for name in ("v2", "shm"):
                out[f"{name}_read_speedup"] = round(
                    baseline["read_s"] / out[name]["read_s"], 2
                )
                decode = out[name]["decode_s"]
                out[f"{name}_decode_speedup"] = (
                    round(baseline["decode_s"] / decode, 1)
                    if decode > 0
                    else None
                )
        return out
    finally:
        server.stop()


def bench_shard() -> dict:
    """Horizontal-sharding section: the SAME ingest, warm scatter-gather
    read, and warm single-classifier build driven through ``connect()``
    at 1, 2, and 4 store groups — each group its own subprocess, its own
    GIL, so aggregate MB/s can actually scale (docs/dataplane.md). The
    headline is ``x4_ingest_scaling_ratio`` (near-linear is the claim);
    warm read rows/s and warm nb-build rows/s ride along so the fan-out
    client's merge overhead can never regress unnoticed. One group is
    the degenerate plain ``RemoteStore`` — the unsharded baseline every
    ratio divides by.

    The scaling ratio's ceiling is ``min(groups, cpu_cores)``: each
    group is one Python server saturating one core, so a 1-core CI box
    honestly reads ~1.0 where a real multi-core host reads near-linear
    — ``cpu_cores`` rides in the output so --compare diffs across
    machines stay interpretable."""
    import re
    import subprocess
    import sys

    from learningorchestra_tpu.core.columns import Column
    from learningorchestra_tpu.core.store_service import connect
    from learningorchestra_tpu.ml.builder import build_model

    rows = int(os.environ.get("LO_BENCH_SHARD_ROWS", "400000"))
    rng = np.random.default_rng(17)
    features = {
        f"f{i}": Column.from_numpy(rng.random(rows)) for i in range(8)
    }
    labels = Column.from_numpy((rng.random(rows) > 0.5).astype(np.int64))
    payload_mb = rows * 8 * 8 / 1e6  # the float feature payload alone

    def start_group():
        env = dict(os.environ)
        env["LO_STORE_PORT"] = "0"
        env["PYTHONUNBUFFERED"] = "1"
        # each group in-memory in its own process: the section measures
        # the wire + insert path and real multi-GIL scaling, not N WALs
        # contending for one bench disk
        for stale in ("LO_DATA_DIR", "LO_REPLICATE", "LO_PEERS",
                      "LO_ARBITERS", "LO_PRIMARY_URL", "LO_NODE_ID"):
            env.pop(stale, None)
        proc = subprocess.Popen(
            [sys.executable, "-m", "learningorchestra_tpu.core.store_service"],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            match = re.search(r"store server on [^:]+:(\d+)", line)
            if match:
                return proc, f"http://127.0.0.1:{match.group(1)}"
        proc.kill()
        raise RuntimeError("shard group store did not come up")

    preprocessor = (
        "from pyspark.ml.feature import VectorAssembler\n"
        "feature_cols = [c for c in training_df.schema.names if c != 'label']\n"
        "assembler = VectorAssembler(inputCols=feature_cols, outputCol='features')\n"
        "features_training = assembler.transform(training_df)\n"
        "features_testing = assembler.transform(testing_df)\n"
        "features_evaluation = assembler.transform(testing_df)\n"
    )

    out: dict = {
        "rows": rows,
        "payload_mb": round(payload_mb, 1),
        "cpu_cores": os.cpu_count(),
    }
    baseline: Optional[dict] = None
    for shards in (1, 2, 4):
        procs: list = []
        store = None
        try:
            urls = []
            for _ in range(shards):
                proc, url = start_group()
                procs.append(proc)
                urls.append(url)
            store = connect(";".join(urls))
            for name in ("bench_shard_train", "bench_shard_test"):
                store.create_collection(name)
                store.insert_one(
                    name,
                    {
                        "_id": 0,
                        "filename": name,
                        "finished": True,
                        "fields": [f"f{i}" for i in range(8)] + ["label"],
                    },
                )
            start = time.perf_counter()
            store.insert_column_arrays(
                "bench_shard_train", dict(features, label=labels), start_id=1
            )
            ingest_s = time.perf_counter() - start
            # the tiny test split rides outside the timed window
            store.insert_column_arrays(
                "bench_shard_test",
                {name: values.slice(0, 2048) for name, values in features.items()}
                | {"label": labels.slice(0, 2048)},
                start_id=1,
            )
            read = lambda: store.read_column_arrays("bench_shard_train")  # noqa: E731
            read()  # warm connections + the shard map
            warm_read_s = _best_of(read, repeats=2)
            build = lambda: build_model(  # noqa: E731
                store,
                "bench_shard_train",
                "bench_shard_test",
                preprocessor,
                ["nb"],
                write_outputs=False,
            )
            build()  # cold: XLA compile + devcache fill
            warm_build_s = _best_of(build, repeats=1)
            entry = {
                "ingest_s": round(ingest_s, 4),
                "ingest_mb_per_s": round(payload_mb / ingest_s, 1),
                "warm_read_rows_per_sec": round(rows / warm_read_s, 1),
                "warm_build_rows_per_sec": round(rows / warm_build_s, 1),
            }
            out[f"shards{shards}"] = entry
            if baseline is None:
                baseline = entry
            else:
                out[f"x{shards}_ingest_scaling_ratio"] = round(
                    entry["ingest_mb_per_s"] / baseline["ingest_mb_per_s"], 2
                )
                out[f"x{shards}_warm_build_ratio"] = round(
                    entry["warm_build_rows_per_sec"]
                    / baseline["warm_build_rows_per_sec"],
                    2,
                )
        finally:
            if store is not None:
                store.close()
            for proc in procs:
                proc.terminate()
            for proc in procs:
                try:
                    proc.wait(timeout=10)
                except Exception:  # noqa: BLE001
                    proc.kill()
    return out


def bench_serve() -> dict:
    """Serve section: closed-loop load against the online predict lane
    (docs/serving.md) at 1 / 8 / 64 concurrent clients — p50/p99
    latency, predictions/s, and the achieved mean batch size (the
    number that proves concurrent singles coalesce into shared
    dispatches)."""
    import tempfile

    from learningorchestra_tpu.core.store import InMemoryStore
    from learningorchestra_tpu.ml.base import make_classifier
    from learningorchestra_tpu.ml.checkpoint import checkpoint_path, save_model
    from learningorchestra_tpu.serve import ServePlane
    from learningorchestra_tpu.serve.loadgen import run_closed_loop
    from learningorchestra_tpu.services import model_builder

    import shutil

    X, y = _synthetic(2_048, seed=5)
    model = make_classifier("lr").fit(X, y)
    models_dir = tempfile.mkdtemp(prefix="lo_serve_bench_")
    name = "bench_serve_prediction_lr"
    save_model(model, checkpoint_path(models_dir, name))
    plane = ServePlane()
    app = model_builder.create_app(
        InMemoryStore(), models_dir=models_dir, serve=plane
    )
    requests_per_client = int(os.environ.get("LO_BENCH_SERVE_REQUESTS", "100"))
    row = X[:1].tolist()
    levels: dict = {}
    try:
        for clients in (1, 8, 64):
            if _budget_left() < 20:
                levels[str(clients)] = {"skipped": "budget"}
                continue
            handles = [app.test_client() for _ in range(clients)]

            def send(index, handles=handles):
                response = handles[index].post(
                    f"/models/{name}/predict", json={"rows": row}
                )
                if response.status_code != 200:
                    raise RuntimeError(
                        f"predict failed: HTTP {response.status_code}"
                    )

            before = plane.batcher.stats()
            stats = run_closed_loop(send, clients, requests_per_client)
            after = plane.batcher.stats()
            batches = after["batches"] - before["batches"]
            grouped = after["batched_requests"] - before["batched_requests"]
            stats["mean_batch_size"] = (
                round(grouped / batches, 2) if batches else None
            )
            levels[str(clients)] = stats
        return {
            "model": "lr",
            "rows_per_request": 1,
            "requests_per_client": requests_per_client,
            "levels": levels,
            "registry": plane.registry.stats(),
        }
    finally:
        plane.close()
        shutil.rmtree(models_dir, ignore_errors=True)


def bench_fleet() -> dict:
    """Fleet section: the replicated serving plane (docs/serving.md
    "Fleet") at 1 / 2 / 4 replicas with 2 models. Each replica is a REAL
    ``services.runner`` subprocess — its own GIL, its own XLA threadpool
    — pinning its placement-assigned checkpoints and gossiping residency
    through a store subprocess, exactly the production wiring.

    Two load modes per replica count, both closed-loop
    (serve/loadgen.py): **direct** spreads clients across the replica
    ports (the aggregate-capacity ceiling), **router** aims everything
    at one in-process fleet router (what clients actually see — placement
    resolution + proxy overhead included). ``LO_FLEET_RF`` = replica
    count (full replication), so aggregate pinned bytes must scale
    ~linearly with replicas and every replica can serve every model.
    The headlines are ``x2_predictions_scaling_ratio`` (>= 1.7 on a
    multi-core box is the claim) and ``x4_pinned_bytes_ratio`` (~4);
    ``cpu_cores`` rides in the output since the box caps scaling, same
    as the shard section."""
    import re
    import shutil
    import subprocess
    import sys
    import tempfile
    import threading

    from learningorchestra_tpu.core.store_service import connect
    from learningorchestra_tpu.ml.base import make_classifier
    from learningorchestra_tpu.ml.checkpoint import checkpoint_path, save_model
    from learningorchestra_tpu.serve import fleet as serve_fleet
    from learningorchestra_tpu.serve import router as serve_router
    from learningorchestra_tpu.serve.loadgen import (
        HttpSession,
        run_closed_loop,
    )
    from learningorchestra_tpu.utils.web import ServerThread

    X, y = _synthetic(2_048, seed=11)
    models = ["bench_fleet_alpha", "bench_fleet_beta"]
    models_dir = tempfile.mkdtemp(prefix="lo_fleet_bench_")
    for name in models:
        save_model(
            make_classifier("lr").fit(X, y), checkpoint_path(models_dir, name)
        )
    rows = X[:8].tolist()
    clients = int(os.environ.get("LO_BENCH_FLEET_CLIENTS", "16"))
    requests_per_client = int(os.environ.get("LO_BENCH_FLEET_REQUESTS", "50"))

    def start_store():
        env = dict(os.environ)
        env["LO_STORE_PORT"] = "0"
        env["PYTHONUNBUFFERED"] = "1"
        # in-memory, own process: the section measures serving scale-out,
        # not N WALs contending for one bench disk (bench_shard's rule)
        for stale in ("LO_DATA_DIR", "LO_REPLICATE", "LO_PEERS",
                      "LO_ARBITERS", "LO_PRIMARY_URL", "LO_NODE_ID"):
            env.pop(stale, None)
        proc = subprocess.Popen(
            [sys.executable, "-m", "learningorchestra_tpu.core.store_service"],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            match = re.search(r"store server on [^:]+:(\d+)", line)
            if match:
                _drain(proc)
                return proc, f"http://127.0.0.1:{match.group(1)}"
        proc.kill()
        raise RuntimeError("fleet store did not come up")

    def _drain(proc):
        # keep the child's stdout pipe from filling once we stop reading
        threading.Thread(
            target=lambda: all(True for _ in proc.stdout), daemon=True
        ).start()

    def start_replica(index: int, total: int, store_url: str):
        env = dict(os.environ)
        env.update(
            {
                "LO_SERVICE": "model_builder",
                "LO_HOST": "127.0.0.1",
                "LO_PORT": "0",
                "LO_STORE_URL": store_url,
                "LO_MODELS_DIR": models_dir,
                "LO_FLEET_REPLICAS": str(total),
                "LO_FLEET_RF": str(total),
                "LO_FLEET_REPLICA": str(index),
                "PYTHONUNBUFFERED": "1",
            }
        )
        env.pop("LO_DATA_DIR", None)
        proc = subprocess.Popen(
            [sys.executable, "-m", "learningorchestra_tpu.services.runner"],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            match = re.search(r"service model_builder on [\w.\-]+:(\d+)", line)
            if match:
                _drain(proc)
                return proc, f"127.0.0.1:{match.group(1)}"
        proc.kill()
        raise RuntimeError(f"fleet replica {index} did not come up")

    def wait_pinned(store, total: int) -> int:
        """Block until every replica's gossip row shows both models
        pinned AND warmed (the agent heartbeats only after its warmup
        pass), then return the aggregate pinned bytes."""
        deadline = time.monotonic() + 180
        want = set(models)
        while time.monotonic() < deadline:
            try:
                gossip = store.find(serve_fleet.HEARTBEAT_COLLECTION, {})
            except Exception:  # noqa: BLE001 — store still booting
                gossip = []
            ready = [
                row for row in gossip if want <= set(row.get("models", ()))
            ]
            if len(ready) >= total:
                return int(sum(row.get("pinned_bytes", 0) for row in ready))
            time.sleep(0.5)
        raise RuntimeError("fleet replicas did not pin within budget")

    def drive(targets: list) -> dict:
        """Closed loop over BOTH models: client i connects to
        targets[i % n] and requests models[i % m] — multi-target mode
        when targets are the replica ports, router mode when targets
        is the router's one URL."""

        def session_factory(index: int) -> HttpSession:
            return HttpSession(targets[index % len(targets)])

        def send(index: int, session: HttpSession) -> None:
            name = models[index % len(models)]
            status, body = session.post_json(
                f"/models/{name}/predict", {"rows": rows}
            )
            if status != 200:
                raise RuntimeError(
                    f"predict {name} via {session.target}: HTTP {status} "
                    f"{body}"
                )

        return run_closed_loop(
            send,
            clients,
            requests_per_client,
            rows_per_request=len(rows),
            session_factory=session_factory,
        )

    out: dict = {
        "models": len(models),
        "clients": clients,
        "requests_per_client": requests_per_client,
        "rows_per_request": len(rows),
        "cpu_cores": os.cpu_count(),
    }
    baseline: Optional[dict] = None
    try:
        for replicas in (1, 2, 4):
            if _budget_left() < 90:
                out[f"replicas{replicas}"] = {"skipped": "budget"}
                continue
            procs: list = []
            store = None
            router_server = None
            try:
                store_proc, store_url = start_store()
                procs.append(store_proc)
                targets = []
                for index in range(replicas):
                    proc, target = start_replica(index, replicas, store_url)
                    procs.append(proc)
                    targets.append(target)
                store = connect(store_url)
                pinned_bytes = wait_pinned(store, replicas)
                direct = drive(targets)
                router_app = serve_router.create_app(
                    store,
                    placement=serve_fleet.PlacementClient(
                        store, replicas=replicas, rf=replicas
                    ),
                )
                router_server = ServerThread(router_app, "127.0.0.1", 0)
                router_server.start()
                routed = drive([f"127.0.0.1:{router_server.port}"])
                entry = {
                    "aggregate_pinned_bytes": pinned_bytes,
                    "direct": direct,
                    "router": routed,
                }
                out[f"replicas{replicas}"] = entry
                if baseline is None:
                    baseline = entry
                else:
                    out[f"x{replicas}_predictions_scaling_ratio"] = round(
                        direct["predictions_per_s"]
                        / baseline["direct"]["predictions_per_s"],
                        2,
                    )
                    out[f"x{replicas}_pinned_bytes_ratio"] = round(
                        pinned_bytes
                        / max(baseline["aggregate_pinned_bytes"], 1),
                        2,
                    )
            finally:
                if router_server is not None:
                    router_server.stop()
                if store is not None:
                    store.close()
                for proc in procs:
                    proc.terminate()
                for proc in procs:
                    try:
                        proc.wait(timeout=10)
                    except Exception:  # noqa: BLE001
                        proc.kill()
        return out
    finally:
        shutil.rmtree(models_dir, ignore_errors=True)


def _rss_bytes() -> int:
    """Current resident set (bytes) from /proc — ru_maxrss is a peak,
    not a level, so it cannot see waiters RELEASING memory."""
    with open("/proc/self/statm") as handle:
        return int(handle.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")


def _waiter_job(release) -> str:
    """A tracked job that stays running until the bench releases it —
    the thing /wait waiters park on."""
    release.wait(180)
    return "released"


def bench_waiters() -> dict:
    """Waiters section: push job completion on the event-loop serving
    core (docs/web.md). Two claims, measured:

    - **capacity**: N idle ``GET /jobs/<name>/wait`` connections parked
      on the async core cost O(1) threads and bytes-per-waiter of
      marginal RSS; the threaded escape hatch holds a (much smaller) M
      at one blocked thread each for the per-waiter head-to-head. Both
      arms count the client sockets too (same process), so the DELTA
      between arms is the honest thread-stack bill.
    - **notify latency**: client-observed finish-to-notified p50/p99
      for the three waiting styles — reference-cadence metadata polling
      (3 s), ``/wait`` long-poll, ``/wait`` SSE. Trials run
      concurrently so the poll arm's expected ~1.5 s mean does not
      serialize into the budget.
    """
    import gc
    import socket as socket_mod
    import threading

    import requests

    from learningorchestra_tpu.core.jobs import JobManager
    from learningorchestra_tpu.sched.scheduler import Scheduler
    from learningorchestra_tpu.utils import webloop
    from learningorchestra_tpu.utils.web import WebApp

    n_async = int(os.environ.get("LO_BENCH_WAITERS", "1000"))
    n_threaded = min(64, n_async)
    trials = int(os.environ.get("LO_BENCH_WAIT_TRIALS", "24"))
    poll_trials = min(16, trials)
    poll_interval_s = 3.0  # the reference client's cadence
    app = WebApp("bench_waiters")
    jobs = JobManager(
        scheduler=Scheduler(host_width=trials + 4, queue_cap=4 * trials + 16)
    )
    app.register_job_routes(jobs)
    out: dict = {"capacity": {}, "notify": {}}

    def capacity(server_port, parked_check, count, job_name):
        """Park ``count`` /wait connections on a running job; read RSS
        and thread level before vs while-parked, then release the job
        and drain the notifications."""
        release = threading.Event()
        jobs.submit(job_name, _waiter_job, release)
        request_bytes = (
            f"GET /jobs/{job_name}/wait?timeout=55 HTTP/1.1\r\n"
            f"Host: bench\r\nConnection: close\r\n\r\n"
        ).encode()
        gc.collect()
        rss_before = _rss_bytes()
        threads_before = threading.active_count()
        socks = []
        try:
            for _ in range(count):
                sock = socket_mod.create_connection(
                    ("127.0.0.1", server_port), timeout=30
                )
                sock.settimeout(30)
                sock.sendall(request_bytes)
                socks.append(sock)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and not parked_check(
                count, threads_before
            ):
                time.sleep(0.05)
            gc.collect()
            rss_parked = _rss_bytes()
            threads_parked = threading.active_count()
            release.set()
            start = time.perf_counter()
            delivered = 0
            for sock in socks:
                try:
                    if sock.recv(1024):
                        delivered += 1
                except OSError:
                    pass
            drain_s = time.perf_counter() - start
        finally:
            release.set()
            for sock in socks:
                sock.close()
        return {
            "waiters": count,
            "delivered": delivered,
            "threads_before": threads_before,
            "threads_parked": threads_parked,
            "threads_added": threads_parked - threads_before,
            "rss_added_mb": round((rss_parked - rss_before) / 1e6, 2),
            "rss_per_waiter_bytes": max(
                0, round((rss_parked - rss_before) / count)
            ),
            "drain_s": round(drain_s, 4),
        }

    def measure_mode(base_url, mode, count):
        """``count`` concurrent waiters, one tracked job each; release
        the jobs one at a time and record client-observed latency."""
        releases = [threading.Event() for _ in range(count)]
        names = [f"bench-wait-{mode}-{i}" for i in range(count)]
        for name, release in zip(names, releases):
            jobs.submit(name, _waiter_job, release)
        observed: list = [None] * count
        errors: list = []

        def wait_poll(name):
            while True:
                response = requests.get(f"{base_url}/jobs/{name}", timeout=10)
                record = response.json()["result"]
                if record.get("state") in ("finished", "failed", "cancelled"):
                    return time.perf_counter()
                time.sleep(poll_interval_s)

        def wait_longpoll(name):
            while True:
                response = requests.get(
                    f"{base_url}/jobs/{name}/wait",
                    params={"timeout": "30"},
                    timeout=40,
                )
                payload = response.json()["result"]
                if payload != "timeout":
                    return time.perf_counter()

        def wait_sse(name):
            response = requests.get(
                f"{base_url}/jobs/{name}/wait",
                params={"timeout": "30"},
                headers={"Accept": "text/event-stream"},
                stream=True,
                timeout=40,
            )
            for line in response.iter_lines():
                if line.startswith(b"event:"):
                    return time.perf_counter()
            raise RuntimeError("SSE stream ended without an event")

        wait_fn = {"poll": wait_poll, "longpoll": wait_longpoll,
                   "sse": wait_sse}[mode]

        def client(index):
            try:
                observed[index] = wait_fn(names[index])
            except Exception as error:  # noqa: BLE001 — tallied below
                errors.append(f"{type(error).__name__}: {error}")

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(count)
        ]
        for thread in threads:
            thread.start()
        time.sleep(0.8)  # everyone parked / into their first poll sleep
        finished_at = []
        for release in releases:
            finished_at.append(time.perf_counter())
            release.set()
            time.sleep(0.01)
        for thread in threads:
            thread.join(timeout=60)
        latencies_ms = [
            (observed[i] - finished_at[i]) * 1000.0
            for i in range(count)
            if observed[i] is not None
        ]
        entry = {"trials": count, "failed": count - len(latencies_ms)}
        if errors:
            entry["first_error"] = errors[0]
        if latencies_ms:
            entry["notify_p50_ms"] = round(
                float(np.percentile(latencies_ms, 50)), 2
            )
            entry["notify_p99_ms"] = round(
                float(np.percentile(latencies_ms, 99)), 2
            )
        return entry

    # --- async arm: the product configuration -----------------------------
    server = webloop.LoopServer(app, "127.0.0.1", 0).start()
    base_url = f"http://127.0.0.1:{server.port}"
    try:
        out["capacity"]["async"] = capacity(
            server.port,
            lambda count, _level: server.waiter_count >= count,
            n_async,
            "bench-capacity-async",
        )
        for mode, count in (
            ("longpoll", trials), ("sse", trials), ("poll", poll_trials)
        ):
            if mode == "poll" and _budget_left() < 30:
                out["notify"][mode] = {"skipped": "budget"}
                continue
            out["notify"][mode] = measure_mode(base_url, mode, count)
        out["notify"]["poll_interval_s"] = poll_interval_s
    finally:
        server.stop()

    # --- threaded escape-hatch arm: a thread per parked waiter ------------
    if _budget_left() > 30:
        from werkzeug.serving import make_server

        threaded = make_server("127.0.0.1", 0, app, threaded=True)
        thread = threading.Thread(target=threaded.serve_forever, daemon=True)
        thread.start()
        try:
            out["capacity"]["threaded"] = capacity(
                threaded.server_port,
                # no parked counter on werkzeug: the handler threads it
                # spawned (one per blocked waiter) are the signal
                lambda count, level: threading.active_count()
                >= level + count,
                n_threaded,
                "bench-capacity-threaded",
            )
        finally:
            threaded.shutdown()
            thread.join(timeout=5)
        async_arm = out["capacity"]["async"]
        threaded_arm = out["capacity"]["threaded"]
        if threaded_arm["rss_per_waiter_bytes"]:
            out["capacity"]["rss_per_waiter_ratio"] = round(
                threaded_arm["rss_per_waiter_bytes"]
                / max(async_arm["rss_per_waiter_bytes"], 1),
                2,
            )
    else:
        out["capacity"]["threaded"] = {"skipped": "budget"}
    jobs.scheduler.close()
    return out


def bench_coalesce() -> dict:
    """Coalesce section: the scheduler's vmap-across-jobs stage
    (sched/coalesce.py) under the ISSUE's two workloads. Both flood
    arms run the SAME batched runner (ml/sweep.py) through real
    JobManager device jobs — the only difference is the window knob —
    while the sweep arm compares one fused grid dispatch against the
    honest baseline of 100 sequential product-estimator fits."""
    import threading

    from learningorchestra_tpu.core.jobs import JobManager
    from learningorchestra_tpu.ml import sweep as lo_sweep
    from learningorchestra_tpu.ml.base import resolve_mesh
    from learningorchestra_tpu.ml.logistic import LogisticRegression
    from learningorchestra_tpu.sched.coalesce import Coalescer
    from learningorchestra_tpu.sched.scheduler import DEVICE_CLASS, Scheduler

    rows = int(os.environ.get("LO_BENCH_COALESCE_ROWS", "1024"))
    max_iter = 25
    n_jobs = 64
    X, y = _synthetic(rows, seed=7)
    mesh = resolve_mesh(None)
    runner = lo_sweep.group_runner(mesh)
    key, payload = lo_sweep.prepare_member(
        "lr", X, y, X, y, [{"reg_param": 0.0}], mesh=mesh, max_iter=max_iter
    )

    # Warm both fused program shapes this section dispatches (the
    # 8-slot floor the window-0 arm runs and the 64-slot batch the
    # coalesced arm runs): every timed number in this suite is a warm
    # measurement (see main()'s compile-cache note), so compiles must
    # not decide the comparison — in production the shape grid means a
    # batch width compiles once, ever.
    lo_sweep.run_group([payload], mesh)
    lo_sweep.run_group([payload] * n_jobs, mesh)

    def flood(window_s: float) -> dict:
        jobs = JobManager(scheduler=Scheduler(queue_cap=2 * n_jobs))
        coalescer = Coalescer(window_s=window_s, max_jobs=n_jobs)
        barrier = threading.Barrier(n_jobs + 1)
        failures: list = []

        def client(index: int) -> None:
            member = coalescer.register(
                key, payload, runner, name=f"co-{index}"
            )
            barrier.wait()
            try:
                jobs.run_sync(
                    f"co-{window_s}-{index}",
                    coalescer.run_member,
                    member,
                    job_class=DEVICE_CLASS,
                )
            except Exception as error:  # noqa: BLE001 — surfaced below
                failures.append(error)

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(n_jobs)
        ]
        for thread in threads:
            thread.start()
        barrier.wait()
        start = time.perf_counter()
        for thread in threads:
            thread.join()
        elapsed = time.perf_counter() - start
        stats = coalescer.stats()
        jobs.scheduler.close()
        if failures:
            raise RuntimeError(
                f"{len(failures)}/{n_jobs} coalesced jobs failed: "
                f"{failures[0]!r}"
            )
        return {
            "jobs_per_s": round(n_jobs / elapsed, 2),
            "wall_s": round(elapsed, 4),
            "fused_dispatches": stats["fused_dispatches"],
            "mean_batch_size": stats["mean_batch_size"],
        }

    coalesced = flood(0.010)
    uncoalesced = flood(0.0)
    out: dict = {
        "jobs": n_jobs,
        "rows": rows,
        "coalesced": coalesced,
        "uncoalesced_window0": uncoalesced,
        "coalesce_speedup": round(
            coalesced["jobs_per_s"] / uncoalesced["jobs_per_s"], 2
        ),
    }

    if _budget_left() < 60:
        out["sweep_100"] = {"skipped": "budget"}
        return out
    # The sweep arm at small-build scale (its own knob): fit + evaluate
    # 100 λ points as ONE fused dispatch vs the STRICTEST sequential
    # baseline — 100 bare product-estimator fits, each evaluated, no
    # REST/store overhead charged to either side.
    sweep_rows = int(os.environ.get("LO_BENCH_SWEEP_ROWS", "256"))
    X_s, y_s = _synthetic(sweep_rows, seed=9)
    grid = [{"reg_param": float(v)} for v in np.linspace(0.0, 1.0, 100)]
    key100, payload100 = lo_sweep.prepare_member(
        "lr", X_s, y_s, X_s, y_s, grid, mesh=mesh, max_iter=max_iter
    )
    # warm both arms' programs (the grid's padded width for the fused
    # arm, the solo estimator's programs for the sequential arm)
    lo_sweep.run_group([payload100], mesh)
    LogisticRegression(
        max_iter=max_iter, reg_param=0.0, mesh=mesh
    ).fit(X_s, y_s).evaluate(X_s, y_s)
    fused_s = _best_of(lambda: lo_sweep.run_group([payload100], mesh))
    start = time.perf_counter()
    for point in grid:
        model = LogisticRegression(
            max_iter=max_iter, reg_param=point["reg_param"], mesh=mesh
        ).fit(X_s, y_s)
        model.evaluate(X_s, y_s)
    sequential_s = time.perf_counter() - start
    out["sweep_100"] = {
        "points": len(grid),
        "rows": sweep_rows,
        "fused_s": round(fused_s, 3),
        "sequential_s": round(sequential_s, 3),
        "sweep_speedup": round(sequential_s / fused_s, 2),
    }
    return out


def bench_obs(X, y) -> dict:
    """The fleet observability plane's own cost (docs/observability.md):
    the in-store TSDB's scrape+store+rollup wall at 8 members x 200
    families, the stitcher's merge latency for a 5-process trace, and
    the kernel suite's recording overhead re-measured with a LIVE
    collector — the <2% attribution contract now covers retention too,
    so a collector that starts taxing the device path is a flagged
    regression, not a silent one."""
    from learningorchestra_tpu.core.store import InMemoryStore
    from learningorchestra_tpu.telemetry import metrics as _metrics
    from learningorchestra_tpu.telemetry import stitch as _stitch
    from learningorchestra_tpu.telemetry import tracing as _tracing
    from learningorchestra_tpu.telemetry import tsdb as _tsdb

    members, families, ticks = 8, 200, 5

    def body(member: int, tick: int) -> str:
        # values move every tick so delta compression does real work;
        # one histogram family exercises the bucket-merge + p99 path
        lines = [
            f"lo_bench_family_{f}_total {tick * 10 + member + f}"
            for f in range(families - 1)
        ]
        for le, cum in (("0.1", 5 * tick), ("1.0", 9 * tick), ("+Inf", 10 * tick)):
            lines.append(
                f'lo_serve_request_seconds_bucket{{le="{le}"}} {cum}'
            )
        lines.append(f"lo_serve_request_seconds_sum {tick * 1.5}")
        lines.append(f"lo_serve_request_seconds_count {10 * tick}")
        return "\n".join(lines) + "\n"

    store = InMemoryStore()
    ring = _tsdb.TSDB(store)
    base_ts = 1_000_000.0
    start = time.perf_counter()
    for tick in range(ticks):
        for member in range(members):
            vals = _tsdb.parse_samples(body(member, tick + 1))
            ring.append(
                f"m{member}", "bench", vals, ts=base_ts + 60.0 * tick
            )
    ingest_s = time.perf_counter() - start
    start = time.perf_counter()
    rollups = _tsdb.window_rollups(
        store,
        "lo_serve_request_seconds",
        600.0,
        now=base_ts + 60.0 * ticks,
    )
    rollup_s = time.perf_counter() - start

    # stitch latency: 5 process rows (distinct service labels group
    # separately even in one process) under one correlation ID
    cid = "bench_stitch_cid"
    for index in range(5):
        trace_obj = _tracing.Trace(cid)
        with _tracing.activate(trace_obj):
            for _ in range(40):
                with _tracing.span("op"):
                    pass
        _tracing.export_trace(trace_obj, service=f"bench_proc{index}")
    start = time.perf_counter()
    stitched = _stitch.stitched_trace(cid)
    stitch_ms = (time.perf_counter() - start) * 1000.0

    # recording overhead with the collector LIVE: same suite + span
    # methodology as bench_kernels, plus a collector appending this
    # process's registry into a store during the run. 0.5 s interval:
    # 120x the production default (60 s), so the measured tax is a
    # conservative ceiling on what a deployment pays, without timing
    # the degenerate collect-continuously regime
    kernels, suite, _, _, _ = _make_kernel_suite(X, y, subset_k=4)
    suite()
    plain_s = _best_of(suite, repeats=2)

    def suite_recording():
        trace_obj = _tracing.Trace(name="bench_obs")
        with _tracing.activate(trace_obj):
            for name, kernel in kernels.items():
                with _tracing.span(f"kernel:{name}"):
                    kernel()

    collector = _tsdb.Collector(
        InMemoryStore(),
        _metrics.global_registry(),
        instance="bench",
        service="bench",
        interval_s=0.5,
    )
    collector.start()
    try:
        live_s = _best_of(suite_recording, repeats=2)
    finally:
        collector.stop()

    return {
        "members": members,
        "families": families,
        "ticks": ticks,
        "ingest_store_s": round(ingest_s, 4),
        "ingest_per_tick_ms": round(ingest_s / ticks * 1000.0, 2),
        "rollup_s": round(rollup_s, 4),
        # deterministic synthetic data -> a constant; its presence
        # proves the windowed-percentile path ran
        "rollup_p99": (rollups.get("m0") or {}).get("p99"),
        "stitch_processes": len(stitched["otherData"]["processes"]),
        "stitch_ms": round(stitch_ms, 2),
        "suite_s": round(plain_s, 4),
        "suite_collector_on_s": round(live_s, 4),
        "collector_overhead_pct": round(
            100.0 * (live_s / plain_s - 1.0), 2
        ),
        "collector_ticks": collector.ticks,
        "collector_errors": collector.errors,
    }


def bench_embeddings() -> dict:
    """Section 3: the PCA + t-SNE north-star wall-clocks."""
    from learningorchestra_tpu.ops.pca import pca_embedding
    from learningorchestra_tpu.ops.tsne import tsne_embedding

    out: dict = {}
    rng = np.random.default_rng(7)
    centers = rng.normal(size=(10, FEATURES)) * 8.0

    def blobs(rows: int) -> np.ndarray:
        labels = rng.integers(0, 10, size=rows)
        return (centers[labels] + rng.normal(size=(rows, FEATURES))).astype(
            np.float32
        )

    # Head-to-head vs sklearn at a size its t-SNE can finish.
    X_small = blobs(HEAD_TO_HEAD_ROWS)
    tsne_small = lambda: tsne_embedding(X_small, method="exact")  # noqa: E731
    tsne_small()  # compile
    ours_tsne_small = _best_of(tsne_small, repeats=2)
    head_to_head = {
        "rows": HEAD_TO_HEAD_ROWS,
        "tsne_ours_s": round(ours_tsne_small, 3),
    }
    if RUN_SKLEARN and _budget_left() > 120:
        import sklearn.manifold

        start = time.perf_counter()
        sklearn.manifold.TSNE(n_components=2).fit_transform(X_small)
        sk_tsne = time.perf_counter() - start
        head_to_head["tsne_sklearn_s"] = round(sk_tsne, 3)
        head_to_head["tsne_speedup"] = round(sk_tsne / ours_tsne_small, 1)
    elif RUN_SKLEARN:
        head_to_head["tsne_sklearn_s"] = "skipped_budget"
    out["head_to_head"] = head_to_head

    # Scaling sizes the reference's toPandas()+t-SNE path can't reach
    # (sklearn PCA on 16 features stays cheap at any size — it is
    # measured here too for honesty; t-SNE is the cliff). Runs BEFORE
    # the landmark-quality evidence: the 1M north-star wall-clocks must
    # not be the thing a tight budget drops.
    scaling = {}
    if EMBED_ROWS >= 100_000:
        sizes = sorted({100_000, EMBED_ROWS})
    else:  # smoke run: the knob shrinks everything
        sizes = [max(EMBED_ROWS, 1)]
    for rows in sizes:
        # The largest size needs roughly a landmark-t-SNE plus warm
        # repeat; skip (with a note) rather than blow the budget.
        if _budget_left() < 150 and rows == max(sizes) and len(sizes) > 1:
            scaling[str(rows)] = {"skipped": "budget"}
            continue
        X_big = blobs(rows)
        entry = _pca_timings(X_big)
        # Each landmark run records its own trace; the LAST run's phase
        # split (landmark_fit vs interpolate vs d2h, ops/tsne.py spans)
        # is reported so a regression localizes to the phase that moved
        # — the attribution BENCH_r03→r05's tsne_landmark delta lacked.
        from learningorchestra_tpu.telemetry import profile as _profile
        from learningorchestra_tpu.telemetry import tracing as _tracing

        traces: list = []

        def run_tsne():
            trace_obj = _tracing.Trace(name=f"tsne_{rows}")
            traces.append(trace_obj)
            with _tracing.activate(trace_obj):
                return tsne_embedding(X_big)

        start = time.perf_counter()
        run_tsne()
        tsne_cold = time.perf_counter() - start
        warm_affordable = _budget_left() > 1.5 * tsne_cold
        tsne_s = _best_of(run_tsne, repeats=1) if warm_affordable else tsne_cold
        entry["tsne_landmark_s"] = round(tsne_s, 3)
        phase_split = _profile.trace_summary(traces[-1])["phases"]
        entry["tsne_phases_s"] = {
            name.split(":", 1)[1]: phase["seconds"]
            for name, phase in sorted(phase_split.items())
            if name.startswith(("tsne:", "d2h:"))
        }
        if not warm_affordable:
            entry["tsne_landmark_note"] = "cold_incl_compile"
        if RUN_SKLEARN:
            import sklearn.decomposition

            start = time.perf_counter()
            sklearn.decomposition.PCA(n_components=2).fit_transform(X_big)
            entry["pca_sklearn_s"] = round(time.perf_counter() - start, 3)
        scaling[str(rows)] = entry
        del X_big
    out["scaling"] = scaling

    # Landmark-quality evidence at the auto-switch size (ops/tsne.py
    # cuts over past 20k rows): exact and landmark embeddings of the
    # SAME data, scored with sklearn's trustworthiness on a subsample —
    # the number that says the 1M-row "t-SNE" is still a t-SNE.
    if _budget_left() > 120:
        try:
            out["landmark_quality"] = _landmark_quality(blobs)
        except Exception as error:  # noqa: BLE001
            out["landmark_quality"] = {
                "error": f"{type(error).__name__}: {error}"
            }
    else:
        out["landmark_quality"] = {"skipped": "budget"}
    return out


def _pca_timings(X_big) -> dict:
    """PCA timings with an apples-to-apples split. sklearn's input sits
    in host RAM untimed; the device analogue is the table already
    resident in HBM (where the ingest pipeline parks it), so the
    steady-state number is the on-device fit. The one-off host→device
    transfer and the end-to-end numpy-in/numpy-out call are reported
    separately. Per-call device time is measured by chaining iterations
    inside one jit (one host sync total) because on a remote-attached
    chip EVERY sync costs ~0.3 s of tunnel latency, which would swamp a
    millisecond kernel."""
    import jax
    import jax.numpy as jnp

    from learningorchestra_tpu.ml.base import shard_matrix
    from learningorchestra_tpu.ops.pca import _pca, pca_embedding

    start = time.perf_counter()
    dm = shard_matrix(X_big)
    np.asarray(jnp.sum(dm.data))  # force the transfer to finish
    transfer_s = time.perf_counter() - start

    iters = 8

    @jax.jit
    def chain(X, mask):
        def body(i, acc):
            # scale breaks CSE between iterations; the extra pass over
            # X only adds honest HBM traffic
            scaled = X * (1.0 + i.astype(jnp.float32) * 1e-7)
            embedded, _, _ = _pca(scaled, mask, 2)
            return acc + embedded.sum()

        return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

    float(chain(dm.data, dm.mask))  # compile
    start = time.perf_counter()
    float(chain(dm.data, dm.mask))
    elapsed = time.perf_counter() - start
    per_call = elapsed / iters

    # end-to-end numpy→numpy (includes H2D + D2H over the tunnel)
    run_pca = lambda: pca_embedding(X_big)  # noqa: E731
    run_pca()
    e2e = _best_of(run_pca, repeats=1)
    return {
        "pca_s": round(per_call, 4),
        "pca_e2e_numpy_s": round(e2e, 3),
        "pca_h2d_transfer_s": round(transfer_s, 3),
        "pca_note": "pca_s = on-device fit per call (input resident in HBM)",
    }


def _landmark_quality(blobs) -> dict:
    from learningorchestra_tpu.ops.tsne import tsne_embedding

    rows = 20_000
    X = blobs(rows)
    start = time.perf_counter()
    exact = tsne_embedding(X, method="exact")
    exact_s = time.perf_counter() - start
    start = time.perf_counter()
    landmark = tsne_embedding(X, method="landmark")
    landmark_s = time.perf_counter() - start
    entry = {
        "rows": rows,
        "exact_s": round(exact_s, 2),
        "landmark_s": round(landmark_s, 2),
    }
    if RUN_SKLEARN:
        from sklearn.manifold import trustworthiness

        sample = np.random.default_rng(5).choice(rows, 4000, replace=False)
        entry["trustworthiness_exact"] = round(
            float(trustworthiness(X[sample], exact[sample], n_neighbors=10)), 4
        )
        entry["trustworthiness_landmark"] = round(
            float(
                trustworthiness(X[sample], landmark[sample], n_neighbors=10)
            ),
            4,
        )
        entry["n_neighbors"] = 10
        entry["subsample"] = 4000
    return entry


def bench_mfu() -> dict:
    """Section 4: peak bf16 matmul MFU probe (the demonstrated ceiling
    on this chip) — tabular fits are HBM-bound, so their MFU is far
    below it; the LR analytic lower bound lives in the kernel section."""
    import jax
    import jax.numpy as jnp

    kind = jax.devices()[0].device_kind
    peak = next(
        (flops for key, flops in TPU_PEAK_FLOPS if key in kind.lower()), None
    )
    n = 8192
    steps = 32
    a = jnp.full((n, n), 0.001, jnp.bfloat16)
    b = jnp.full((n, n), 0.001, jnp.bfloat16)

    # One jitted chain so host dispatch (notably over a remote-attached
    # chip) amortizes across all the matmuls; reduced to a scalar and
    # fetched because block_until_ready does not synchronize on every
    # remote-attached platform.
    @jax.jit
    def chain(a, b):
        out = jax.lax.fori_loop(0, steps, lambda i, acc: acc @ b, a)
        return out.sum()

    float(chain(a, b))
    start = time.perf_counter()
    float(chain(a, b))
    elapsed = time.perf_counter() - start
    achieved = 2 * n**3 * steps / elapsed
    return {
        "device_kind": kind,
        "peak_bf16_flops": peak,
        "matmul_achieved_flops": round(achieved / 1e12, 2) * 1e12,
        "matmul_mfu": round(achieved / peak, 3) if peak else None,
    }


def _coldstart_child() -> None:
    """Child entry for the coldstart section (run via ``python -c``).

    Enables the persistent jit cache at ``LO_COLDSTART_CACHE_DIR``,
    optionally pulls the fleet executable collection from
    ``LO_COLDSTART_STORE_URL`` first, then compiles one program per
    family (predict / build / sweep) off the shared manifest and prints
    ONE JSON line: per-program first-compile seconds plus this
    process's persistent-cache hit/miss counters. The parent decides
    what the numbers mean (cold vs warm vs fleet-fetched)."""
    cache_dir = os.environ["LO_COLDSTART_CACHE_DIR"]
    store_url = os.environ.get("LO_COLDSTART_STORE_URL")

    from learningorchestra_tpu.utils import jitcache

    jitcache.enable_compile_cache(cache_dir)

    fetch_stats = {"fetched": 0, "discarded": 0, "skipped": 0}
    if store_url:
        from learningorchestra_tpu.compile import fleetcache
        from learningorchestra_tpu.core.store_service import RemoteStore

        client = RemoteStore(store_url)
        try:
            fetch_stats = fleetcache.fetch(client, cache_dir)
        finally:
            client.close()

    from learningorchestra_tpu.compile import aot, manifest
    from learningorchestra_tpu.ml.base import resolve_mesh

    mesh = resolve_mesh(None)
    kept, _ = manifest.enumerate_programs(mesh)
    picks: dict = {}
    for spec in kept:
        if spec.program == "build:lr" and "build" not in picks:
            picks["build"] = spec
        elif spec.program == "predict:lr" and "predict" not in picks:
            picks["predict"] = spec
        elif spec.program == "sweep:lr" and "sweep" not in picks:
            picks["sweep"] = spec
    programs = {}
    for family, spec in sorted(picks.items()):
        start = time.perf_counter()
        aot.compile_spec(spec, source="jit")  # the request path's bill
        programs[f"first_{family}_s"] = round(
            time.perf_counter() - start, 4
        )
    print(
        json.dumps(
            {
                "programs": programs,
                "fetch": fetch_stats,
                "cache": jitcache.cache_stats(),
            }
        ),
        flush=True,
    )


def bench_coldstart() -> dict:
    """Coldstart section: what the AOT compile plane (docs/compile.md)
    buys a fresh process. Three child-process arms compile the same
    manifest programs: ``cold`` against an empty persistent cache (the
    pre-plane first-request bill), ``warm`` against the dir the cold
    arm just filled (same-machine restart), and ``fleet`` against a
    fresh dir after fetching the executables the cold arm's files were
    published to a store as (a brand-new runner joining a warmed
    fleet). The headline assertion: the fleet arm's compile-miss count
    is ~0 — a fresh runner never pays the grid's compile bill twice
    fleet-wide."""
    import subprocess
    import sys
    import tempfile

    import shutil

    from learningorchestra_tpu.compile import fleetcache
    from learningorchestra_tpu.core.store import InMemoryStore
    from learningorchestra_tpu.core.store_service import (
        RemoteStore,
        create_store_app,
    )
    from learningorchestra_tpu.utils.web import ServerThread

    here = os.path.dirname(os.path.abspath(__file__))

    def run_child(cache_dir: str, store_url: Optional[str] = None) -> dict:
        env = dict(os.environ, LO_COLDSTART_CACHE_DIR=cache_dir)
        env.pop("LO_JIT_CACHE", None)  # the child's dir must win
        if store_url:
            env["LO_COLDSTART_STORE_URL"] = store_url
        proc = subprocess.run(
            [sys.executable, "-c", "import bench; bench._coldstart_child()"],
            cwd=here,
            env=env,
            capture_output=True,
            text=True,
            timeout=300,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"coldstart child failed: {proc.stderr.strip()[-500:]}"
            )
        return json.loads(proc.stdout.strip().splitlines()[-1])

    cold_dir = tempfile.mkdtemp(prefix="lo_coldstart_cold_")
    fleet_dir = tempfile.mkdtemp(prefix="lo_coldstart_fleet_")
    store = InMemoryStore()
    server = ServerThread(create_store_app(store), "127.0.0.1", 0).start()
    out: dict = {}
    try:
        cold = run_child(cold_dir)
        out["cold"] = {
            **cold["programs"],
            "misses": cold["cache"]["persistent_cache_misses"],
        }

        if _budget_left() < 60:
            out["warm"] = out["fleet"] = {"skipped": "budget"}
            return out
        warm = run_child(cold_dir)  # same dir: the restart case
        out["warm"] = {
            **warm["programs"],
            "hits": warm["cache"]["persistent_cache_hits"],
        }
        for family in ("build", "predict", "sweep"):
            key = f"first_{family}_s"
            if key in cold["programs"] and key in warm["programs"]:
                out[f"cold_vs_warm_{family}_delta_s"] = round(
                    cold["programs"][key] - warm["programs"][key], 4
                )

        if _budget_left() < 60:
            out["fleet"] = {"skipped": "budget"}
            return out
        # publish the cold arm's cache files through the store, then a
        # THIRD process with an empty local dir fetches and replays
        client = RemoteStore(f"http://127.0.0.1:{server.port}")
        try:
            published = fleetcache.publish(client, cold_dir)
        finally:
            client.close()
        fleet = run_child(
            fleet_dir, store_url=f"http://127.0.0.1:{server.port}"
        )
        out["fleet"] = {
            **fleet["programs"],
            "fetched": fleet["fetch"]["fetched"],
            "published": published["published"],
            # the plane's contract: ~0 — every program came off the wire
            "compile_misses": fleet["cache"]["persistent_cache_misses"],
            "compile_hits": fleet["cache"]["persistent_cache_hits"],
        }
        return out
    finally:
        server.stop()
        shutil.rmtree(cold_dir, ignore_errors=True)
        shutil.rmtree(fleet_dir, ignore_errors=True)


# --- regression gate (--compare) ---------------------------------------------
# The machinery that would have caught and localized the tsne_landmark
# regression the day it happened: diff every reported metric and
# per-phase attribution against a prior run's record, flag any
# regression past the threshold WITH the metric/phase that moved, and
# exit non-zero so CI fails the round instead of archiving the loss.

# suffixes that say which direction is "worse" for a dotted metric path
_HIGHER_IS_BETTER = (
    "rows_per_sec", "per_s", "predictions_per_s", "speedup", "mfu",
    "gb_per_s", "vs_baseline", "accuracy", "trustworthiness",
    "mean_batch_size", "ratio",
)
# byte-flow totals that gate DOWN (checked before the generic "bytes"
# fact token below eats them): wire and H2D traffic for the same
# workload growing past threshold means a copy/transfer crept back
# into the data plane (the zero-copy wire PR's regression gate);
# rss_per_waiter is the event-loop core's marginal cost per parked
# /wait connection — growing past threshold means per-connection state
# crept back toward a thread stack (docs/web.md)
_LOWER_PRIORITY = (
    "wire_read_bytes", "wire_write_bytes", "h2d_bytes", "rss_per_waiter",
    # the live-collector attribution tax (bench_obs): unlike the
    # generic overhead_pct fact below, this one gates DOWN — retention
    # creeping into the device path is exactly the regression the
    # <2% contract exists to catch (docs/observability.md)
    "collector_overhead",
)
_LOWER_IS_BETTER = ("_s", "_ms", "seconds", "p50_ms", "p99_ms")
# numeric facts that are not performance (never gated, still diffed)
_UNGATED = (
    "rows", "bytes", "features", "budget", "hits", "misses", "entries",
    "evictions", "invalidations", "components", "n_neighbors",
    "subsample", "requests_per_client", "rows_per_request", "landmarks",
    "macro_rows", "count", "depth", "capacity", "models", "peak",
    "flops", "value", "rejected", "samples", "hz", "overhead_pct",
    # waiters facts: parked/delivered counts, thread levels, the
    # interval knob, and the 1000-notify drain (too fast and too
    # jittery at ~0.1 s to gate at a 25% threshold honestly)
    "waiters", "delivered", "threads", "drain", "trials", "failed",
    "poll_interval",
)
# absolute floor below which a time-like delta is timer noise, not a
# regression (0.011s "doubling" to 0.022s must not fail a round). The
# floor is applied in the metric's OWN unit: 50 ms for *_ms metrics
# (p50_ms jittering 1.2 -> 1.8 ms is the same noise class).
_SECONDS_FLOOR = 0.05


def _noise_floor(path: str) -> float:
    """The absolute delta a 'down' metric must move to count as a
    regression, in the metric's own unit (leaf-first, like direction)."""
    for segment in reversed(path.split(".")):
        if segment.endswith("_ms"):
            return _SECONDS_FLOOR * 1000.0
        if segment.endswith("_s") or segment.endswith("seconds"):
            return _SECONDS_FLOOR
    return _SECONDS_FLOOR


def _metric_direction(path: str):
    """'up' (higher better), 'down' (lower better), or None (ungated).

    Walks segments leaf-first so the most specific name wins: the leaf
    decides when it carries a unit (``warm_s`` → down,
    ``rows_per_sec`` → up, ``hits`` → ungated), and a unit-less leaf
    inherits from its container — ``per_classifier_phases_s.lr.fit``
    gates downward because the ``_s`` dict names the unit for every
    phase inside it."""
    for segment in reversed(path.split(".")):
        # rate names first: "rows_per_sec" must gate up, not be eaten
        # by the "rows" fact token below
        for token in _HIGHER_IS_BETTER:
            if token in segment:
                return "up"
        for token in _LOWER_PRIORITY:
            if token in segment:
                return "down"
        for token in _UNGATED:
            if (
                segment == token
                or segment.startswith(token + "_")
                or segment.endswith("_" + token)
            ):
                return None
        if segment.endswith(_LOWER_IS_BETTER):
            return "down"
    return None


def flatten_metrics(record, prefix: str = "") -> dict:
    """Every numeric leaf of a bench record as ``dotted.path: value``."""
    out: dict[str, float] = {}
    if isinstance(record, dict):
        for key, value in record.items():
            out.update(flatten_metrics(value, f"{prefix}{key}."))
    elif isinstance(record, (int, float)) and not isinstance(record, bool):
        out[prefix[:-1]] = float(record)
    return out


def load_bench_record(path: str) -> dict:
    """A bench record from any of the shapes this repo archives: the
    driver's ``{"tail": ...}`` capture (BENCH_rNN.json — the record is
    the last ``{"metric": ...}`` line), a raw bench stdout record, or a
    BENCH_EXTRA sidecar (wrapped as the record's ``extra``)."""
    with open(path) as handle:
        data = json.load(handle)
    if isinstance(data, dict) and "tail" in data and "metric" not in data:
        record = None
        for line in data["tail"].splitlines():
            line = line.strip()
            if line.startswith("{") and '"metric"' in line:
                try:
                    record = json.loads(line)
                except json.JSONDecodeError:
                    continue
        if record is None:
            raise ValueError(f"no bench record line in {path!r}")
        return record
    if isinstance(data, dict) and "metric" not in data:
        return {"extra": data}  # a BENCH_EXTRA sidecar
    return data


def compare_benchmarks(
    previous: dict, current: dict, threshold: float = 0.25
) -> dict:
    """Diff two bench records. Returns ``{"diffs", "regressions",
    "improvements"}``: diffs cover every shared numeric metric;
    regressions are direction-gated changes worse by more than
    ``threshold`` (relative) AND past the absolute noise floor for
    seconds-like metrics — each names the exact metric/phase that
    moved."""
    prev_flat = flatten_metrics(previous)
    cur_flat = flatten_metrics(current)
    diffs, regressions, improvements = [], [], []
    for path in sorted(prev_flat.keys() & cur_flat.keys()):
        prev_value, cur_value = prev_flat[path], cur_flat[path]
        if prev_value == cur_value:
            continue
        change = (
            (cur_value - prev_value) / abs(prev_value)
            if prev_value
            else float("inf") if cur_value else 0.0
        )
        entry = {
            "metric": path,
            "previous": prev_value,
            "current": cur_value,
            "change_pct": round(change * 100.0, 1),
        }
        diffs.append(entry)
        direction = _metric_direction(path)
        if direction is None:
            continue
        worse = change > threshold if direction == "down" else (
            change < -threshold
        )
        if worse and direction == "down":
            # timer-noise floor, in the metric's own unit (s vs ms)
            if abs(cur_value - prev_value) < _noise_floor(path):
                worse = False
        if worse:
            regressions.append(entry)
        elif (change < -threshold if direction == "down" else change > threshold):
            improvements.append(entry)
    return {
        "diffs": diffs,
        "regressions": regressions,
        "improvements": improvements,
        "threshold_pct": round(threshold * 100.0, 1),
    }


def print_comparison(result: dict, previous_path: str) -> None:
    """Human-readable per-metric report. Goes BEFORE the headline JSON
    line so the driver's last-line record stays parseable."""
    print(f"--- bench compare vs {previous_path} "
          f"(threshold {result['threshold_pct']}%) ---")
    for entry in result["diffs"]:
        marker = " "
        if entry in result["regressions"]:
            marker = "R"
        elif entry in result["improvements"]:
            marker = "+"
        print(
            f"{marker} {entry['metric']}: {entry['previous']} -> "
            f"{entry['current']} ({entry['change_pct']:+}%)"
        )
    if result["regressions"]:
        print(f"REGRESSIONS ({len(result['regressions'])}):")
        for entry in result["regressions"]:
            print(
                f"  {entry['metric']}: {entry['previous']} -> "
                f"{entry['current']} ({entry['change_pct']:+}%)"
            )
    else:
        print("no regressions past threshold")


def main(compare_path: Optional[str] = None, threshold: float = 0.25) -> int:
    # Persistent XLA compile cache (the product runs with it too,
    # services/runner.py): every timed number here is a warm best-of
    # measurement, so caching compiles only stops setup time from
    # starving the later sections' budget.
    from learningorchestra_tpu.utils.jitcache import enable_compile_cache

    enable_compile_cache()
    X, y = _synthetic(ROWS)
    kernels = bench_kernels(X, y)  # the headline; no guard — must run
    extra: dict = {"kernels": kernels, "budget_s": BUDGET_S}

    def section(name, fn):
        """Optional sections never silence the headline: failures and
        budget exhaustion are recorded, the JSON line still prints."""
        if _budget_left() < 30:
            extra[name] = {"skipped": "budget"}
            return None
        try:
            extra[name] = fn()
        except Exception as error:  # noqa: BLE001 — recorded, not fatal
            extra[name] = {"error": f"{type(error).__name__}: {error}"}
        return extra[name]

    section("mfu", bench_mfu)  # the chip's bf16 ceiling (evidence, not
    # this workload's roofline — the per-kernel GB/s numbers are)
    # North-star sections before the wide-shape extra: when compiles
    # eat the budget, the first casualty must be the diagnostic, not
    # the product-path or embeddings measurements.
    section("product_path", lambda: bench_product(X, y))
    product = extra.get("product_path")
    if isinstance(product, dict) and "product_rows_per_sec_warm" in product:
        # the kernel↔product gap, as ONE gated number: how much of the
        # hardware's fit throughput the warm REST-path build delivers
        # (ROADMAP's "close the host-boundary gap" metric; gates UP)
        product["warm_vs_kernel_ratio"] = round(
            product["product_rows_per_sec_warm"] / kernels["rows_per_sec"],
            4,
        )
    section("wire", bench_wire)  # transport head-to-head (v1/v2/shm)
    section("shard", bench_shard)  # scatter-gather scaling at 1/2/4 groups
    section("serve", bench_serve)  # the online predict lane's latency
    section("fleet", bench_fleet)  # scale-out serving at 1/2/4 replicas
    section("waiters", bench_waiters)  # push job completion (docs/web.md)
    section("coalesce", bench_coalesce)  # vmap-across-jobs dispatch
    section("obs", lambda: bench_obs(X, y))  # fleet plane's own cost
    section("coldstart", bench_coldstart)  # AOT plane's cold-start win
    section("embeddings", bench_embeddings)
    section("kernels_wide", bench_kernels_wide)

    from learningorchestra_tpu.utils.jitcache import cache_stats

    extra["jit_cache"] = cache_stats()
    # The official record is the captured FINAL line, and the driver's
    # tail buffer is finite: round 4's record was lost ("parsed: null")
    # because the one-line JSON with the full ``extra`` payload outgrew
    # it. The bulky payload now goes to a sidecar file; the last line
    # stays compact (a short summary only) and therefore parseable.
    extra_path = os.environ.get("LO_BENCH_EXTRA", "BENCH_EXTRA.json")
    try:
        with open(extra_path, "w") as handle:
            json.dump(extra, handle, indent=1)
    except OSError as error:
        extra_path = f"unwritable: {error}"
    rows_per_sec = kernels["rows_per_sec"]
    summary = {
        "suite_s": kernels.get("suite_s"),
        "per_classifier_s": kernels.get("per_classifier_s"),
        "jit_cache": {
            "hits": extra["jit_cache"]["persistent_cache_hits"],
            "misses": extra["jit_cache"]["persistent_cache_misses"],
        },
    }
    product = extra.get("product_path")
    if isinstance(product, dict):
        summary["product_rows_per_sec"] = product.get("end_to_end_rows_per_sec")
        summary["product_warm_s"] = product.get("build_model_5clf_warm_s")
        summary["product_rows_per_sec_warm"] = product.get(
            "product_rows_per_sec_warm"
        )
        summary["warm_speedup_vs_cold"] = product.get("warm_speedup_vs_cold")
        summary["warm_vs_kernel_ratio"] = product.get("warm_vs_kernel_ratio")
        warm_cache = product.get("devcache_warm")
        if isinstance(warm_cache, dict):
            summary["devcache_warm"] = {
                "hits": warm_cache.get("hits"),
                "misses": warm_cache.get("misses"),
            }
    serve = extra.get("serve")
    if isinstance(serve, dict):
        top = serve.get("levels", {}).get("64")
        if isinstance(top, dict) and "p99_ms" in top:
            summary["serve_64c"] = {
                "p50_ms": top.get("p50_ms"),
                "p99_ms": top.get("p99_ms"),
                "predictions_per_s": top.get("predictions_per_s"),
                "mean_batch_size": top.get("mean_batch_size"),
            }
    fleet = extra.get("fleet")
    if isinstance(fleet, dict):
        two = fleet.get("replicas2", {})
        direct = two.get("direct") if isinstance(two, dict) else None
        if isinstance(direct, dict) and "predictions_per_s" in direct:
            summary["fleet_2r"] = {
                "predictions_per_s": direct.get("predictions_per_s"),
                "p99_ms": direct.get("p99_ms"),
                "scaling_ratio": fleet.get("x2_predictions_scaling_ratio"),
                "pinned_bytes": two.get("aggregate_pinned_bytes"),
            }
    waiters = extra.get("waiters")
    if isinstance(waiters, dict):
        longpoll = waiters.get("notify", {}).get("longpoll", {})
        async_arm = waiters.get("capacity", {}).get("async", {})
        if isinstance(longpoll, dict) and isinstance(async_arm, dict):
            summary["waiters"] = {
                "notify_p99_ms": longpoll.get("notify_p99_ms"),
                "parked": async_arm.get("waiters"),
                "threads_added": async_arm.get("threads_added"),
                "rss_per_waiter_bytes": async_arm.get("rss_per_waiter_bytes"),
            }
    embeddings = extra.get("embeddings")
    if isinstance(embeddings, dict):
        at_scale = embeddings.get("scaling", {}).get(str(EMBED_ROWS), {})
        if isinstance(at_scale, dict):
            for key in ("pca_e2e_numpy_s", "tsne_landmark_s"):
                if key in at_scale:
                    summary[key] = at_scale[key]
    record = {
        "metric": "model_builder_5clf_rows_per_sec",
        "value": rows_per_sec,
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 1),
        "summary": summary,
        "extra_file": extra_path,
    }
    exit_code = 0
    if compare_path is not None:
        # the comparison sees the FULL extra payload (per-phase
        # attribution included), not just the compact summary line
        comparison = compare_benchmarks(
            load_bench_record(compare_path),
            {**record, "extra": extra},
            threshold=threshold,
        )
        print_comparison(comparison, compare_path)
        if comparison["regressions"]:
            exit_code = 1
    # headline record LAST: the driver parses the final stdout line
    print(json.dumps(record))
    return exit_code


def cli(argv: Optional[list] = None) -> int:
    """``python bench.py [--compare PREV.json [--current CUR.json]]``.

    ``--compare`` alone runs the benchmark and diffs its record (with
    the full per-phase attribution) against the prior run's archived
    JSON; with ``--current`` no benchmark runs — the two files are
    compared directly (the CI fixture mode the regression-gate tests
    drive). Exit status 1 when any gated metric regressed past
    ``--threshold`` (default 0.25 = 25%)."""
    import argparse

    parser = argparse.ArgumentParser(description=cli.__doc__)
    parser.add_argument("--compare", metavar="PREV_JSON", default=None)
    parser.add_argument("--current", metavar="CUR_JSON", default=None)
    parser.add_argument("--threshold", type=float, default=0.25)
    args = parser.parse_args(argv)
    if args.current is not None:
        if args.compare is None:
            parser.error("--current requires --compare")
        comparison = compare_benchmarks(
            load_bench_record(args.compare),
            load_bench_record(args.current),
            threshold=args.threshold,
        )
        print_comparison(comparison, args.compare)
        return 1 if comparison["regressions"] else 0
    return main(compare_path=args.compare, threshold=args.threshold)


if __name__ == "__main__":
    raise SystemExit(cli())
