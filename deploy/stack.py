#!/usr/bin/env python3
"""Supervise the microservice topology: store server + seven services.

The reference deploys this shape as a Docker-swarm stack: service
containers with ``restart_policy: condition: on-failure, delay: 5s``
(reference docker-compose.yml:14-15) that gate their start on their
dependencies being reachable (``dockerize -wait``, docker-compose.yml:145).
This supervisor is that stack without the swarm:

- starts the store server, then blocks until its ``GET /health``
  answers (the dockerize gate);
- starts one ``LO_SERVICE=<name>`` runner process per service, all
  pointed at the store via ``LO_STORE_URL``;
- restarts any child that exits non-zero after a delay (the
  restart_policy), indefinitely by default;
- writes ``<data_dir>/stack_ports.json`` (``{"ports": {service: port},
  "pids": {service: pid}}``, refreshed on restart) so clients, tests
  and operators can discover the stack regardless of ephemeral-port
  mode;
- forwards SIGTERM/SIGINT to the children and exits cleanly.

Usage::

    python deploy/stack.py [data_dir]

Environment (all optional):

- ``LO_DATA_DIR``       store WAL dir (default ./lo_data or argv[1])
- ``LO_HOST``           bind host (default 127.0.0.1 — model_builder
                        executes request-supplied code; see deploy/README.md)
- ``LO_STORE_PORT``     store port (default 27027; 0 = OS-assigned)
- ``LO_EPHEMERAL``      "1" = every service binds an OS-assigned port
                        (tests); default: reference ports 5000-5006
- ``LO_RESTART_DELAY``  seconds between failure and restart (default 5)
- ``LO_MAX_RESTARTS``   per-child cap (default: unlimited)
- ``LO_WORKERS``        N > 0 switches to the MULTI-HOST topology:
                        store + an all-services coordinator + N SPMD
                        worker processes in one jax.distributed
                        runtime; any runtime member dying restarts the
                        whole group (see _supervise_multihost)
- ``LO_COORD_PORT``     jax.distributed coordinator port (default 12355)
- ``LO_REPLICATION``    "1" = replicated store plane (docs/replication.md):
                        primary store + WAL-shipping follower + quorum
                        arbiter (the reference's Mongo replica set +
                        ``mongodbarbiter``, docker-compose.yml:27-91);
                        services get both store URLs and fail over
                        client-side. Requires fixed store ports.
- ``LO_FOLLOWER_PORT``  follower store port (default 27028)
- ``LO_ARBITER_PORT``   arbiter port (default 27029)
- ``LO_AUTO_PROMOTE_S`` follower takeover timer, quorum-gated (default 5)
- ``LO_FLEET_REPLICAS`` N >= 1 additionally launches the serving fleet
                        (docs/serving.md "Fleet"): N replica
                        model_builder processes (``LO_FLEET_REPLICA=i``,
                        ports 5010+i — NOT 5002+i, which would collide
                        with the reference ports) behind one
                        ``LO_SERVICE=router`` process on 5007; unset =
                        no fleet children. Single-host topology only.
- ``LO_STACK_EXIT_ON_STDIN_EOF``  "1" = shut the stack down when stdin
                        hits EOF. Set by deploy/cluster.py's ssh
                        transport: killing the ssh CLIENT never signals
                        the remote side (BatchMode allocates no pty, so
                        no SIGHUP) — watching the ssh channel's stdin is
                        what keeps a dead driver from stranding the old
                        stack and its runtime group on every machine.

Cross-MACHINE topologies run one stack.py per machine (driven by
``deploy/cluster.py up <manifest>``, the reference's ``run.sh`` +
``docker stack deploy`` analogue, run.sh:8-32):

- ``LO_TOTAL_PROCESSES``  total jax processes across ALL machines
                          (default: local workers + 1). When it exceeds
                          the local member count, a runtime member dying
                          EXITS the stack (rc=1) instead of restarting
                          locally — members on other machines are
                          poisoned too, so only the cluster driver can
                          restart the runtime coherently.
- ``LO_PROCESS_BASE``     first jax process id on this machine. > 0
                          means a WORKER-ONLY machine: no store, no
                          coordinator; requires ``LO_COORDINATOR`` and
                          ``LO_STORE_URL`` pointing at the head machine.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SERVICE_NAMES = (
    "database_api",
    "projection",
    "model_builder",
    "data_type_handler",
    "histogram",
    "tsne",
    "pca",
)

# The replicated serving fleet (docs/serving.md "Fleet"), opt-in via
# LO_FLEET_REPLICAS: N extra model_builder processes carrying
# LO_FLEET_REPLICA=<i> (each runs a ReplicaAgent pinning its
# placement-assigned models) behind one LO_SERVICE=router process. The
# replicas bind FLEET_PORT_BASE+i — a separate base, NOT 5002+i, which
# would collide with the reference ports 5003-5006.
ROUTER_PORT = 5007
FLEET_PORT_BASE = 5010

# "service <name> on <host>:<port>" (services/runner.py) and
# "store server on <host>:<port>" (core/store_service.py)
_PORT_LINE = re.compile(r"on [\w.\-]+:(\d+)")
_SERVICE_PORT_LINE = re.compile(r"service (\w+) on [\w.\-]+:(\d+)")
_WORKER_READY_LINE = "spmd worker: waiting for jobs"


class Child:
    """One supervised process with an on-failure restart policy."""

    def __init__(self, name: str, argv: list[str], env: dict, log):
        self.name = name
        self.argv = argv
        self.env = env
        self.log = log
        self.proc: subprocess.Popen | None = None
        self.port: int | None = None
        # all-in-one runners announce one port per service
        self.service_ports: dict[str, int] = {}
        self.restarts = 0
        self._port_event = threading.Event()
        self._ready_event = threading.Event()  # spmd worker readiness

    def start(self) -> None:
        self.proc = subprocess.Popen(
            self.argv,
            env=self.env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO_ROOT,
        )
        threading.Thread(target=self._pump, daemon=True).start()

    def _pump(self) -> None:
        proc = self.proc
        for line in proc.stdout:
            match = _SERVICE_PORT_LINE.search(line)
            if match:
                # per-service announcement: recorded by NAME only —
                # self.port stays unset so an all-in-one runner never
                # publishes an arbitrary service port under its own name
                self.service_ports[match.group(1)] = int(match.group(2))
                self._port_event.set()
            else:
                match = _PORT_LINE.search(line)
                if match:
                    self.port = int(match.group(1))
                    self._port_event.set()
            if _WORKER_READY_LINE in line:
                self._ready_event.set()
            self.log(f"[{self.name}] {line.rstrip()}")

    def wait_ready(self, timeout: float) -> None:
        if not self._ready_event.wait(timeout):
            raise TimeoutError(f"{self.name}: not ready within {timeout}s")

    def wait_port(self, timeout: float) -> int:
        if not self._port_event.wait(timeout):
            raise TimeoutError(f"{self.name}: no port line within {timeout}s")
        return self.port

    def terminate(self) -> None:
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()

    def poll(self):
        return self.proc.poll() if self.proc else None


def start_stdin_watchdog(stopping, log, stream=None):
    """Launcher-death watchdog (LO_STACK_EXIT_ON_STDIN_EOF=1): EOF on
    stdin means the ssh channel — and with it the cluster driver — is
    gone; set ``stopping`` so the stack shuts down instead of lingering
    to collide with the driver's relaunch (stale store/coordinator
    ports, briefly two writable stores). ``ssh -o BatchMode=yes``
    allocates no pty, so a dying driver never HUPs the remote process
    group — watching the channel's stdin is the reliable signal.
    Returns the watcher thread, or None when the knob is off."""
    if os.environ.get("LO_STACK_EXIT_ON_STDIN_EOF") != "1":
        return None
    if stream is None:
        stream = sys.stdin.buffer

    def _stdin_watch() -> None:
        try:
            while stream.read(65536):
                pass
        except Exception:
            pass
        if not stopping.is_set():
            log("[stack] stdin closed (launcher gone); shutting down")
            stopping.set()

    thread = threading.Thread(
        target=_stdin_watch, name="stdin-eof-watchdog", daemon=True
    )
    thread.start()
    return thread


def wait_health(url: str, timeout: float) -> None:
    """The dockerize -wait analogue: block until the store answers."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url + "/health", timeout=2) as resp:
                if resp.status == 200:
                    return
        except OSError:
            pass
        time.sleep(0.2)
    raise TimeoutError(f"store not healthy at {url} within {timeout}s")


def _start_store_plane(children, store, host, log) -> str:
    """Start the store child — plus the follower and arbiter when the
    replicated plane is configured (LO_REPLICATION=1), plus every
    additional shard group when LO_SHARDS>1 — and return the
    ``LO_STORE_URL`` services should use: per group a comma list naming
    the primary AND the follower (client-side failover,
    core/store_service.py), groups joined by ``;`` (the sharded
    scatter-gather client, core/shardstore.py). Group 0 (the plain
    ``store`` child) is the meta group."""
    store.start()
    store_live_port = store.wait_port(60)
    store_url = f"http://{host}:{store_live_port}"
    wait_health(store_url, 60)
    log(f"[stack] store healthy at {store_url}")
    urls = [store_url]
    for name in ("store-follower", "store-arbiter"):
        child = children.get(name)
        if child is None:
            continue
        child.start()
        child_port = child.wait_port(60)
        if name == "store-follower":
            urls.append(f"http://{host}:{child_port}")
    if len(urls) > 1:
        log(f"[stack] replicated store plane up: {','.join(urls)} + arbiter")
    group_urls = [",".join(urls)]
    index = 1
    while f"store-s{index}" in children:
        primary = children[f"store-s{index}"]
        primary.start()
        primary_port = primary.wait_port(60)
        primary_url = f"http://{host}:{primary_port}"
        wait_health(primary_url, 60)
        shard_urls = [primary_url]
        for suffix in ("follower", "arbiter"):
            child = children.get(f"store-s{index}-{suffix}")
            if child is None:
                continue
            child.start()
            child_port = child.wait_port(60)
            if suffix == "follower":
                shard_urls.append(f"http://{host}:{child_port}")
        group_urls.append(",".join(shard_urls))
        index += 1
    if len(group_urls) > 1:
        log(
            f"[stack] sharded store plane up: {len(group_urls)} groups "
            f"({';'.join(group_urls)})"
        )
    return ";".join(group_urls)


def main() -> int:
    # chaos-knob preflight (run.sh does the same): a typo'd LO_FAULT_*
    # must refuse bring-up here too — cluster.py launches stack.py
    # directly, never through run.sh
    sys.path.insert(0, REPO_ROOT)
    try:
        from learningorchestra_tpu.testing import faults

        faults.validate_env()
    except ValueError as error:
        print(f"[stack] LO_FAULT_* validation failed: {error}")
        return 2
    except ImportError:
        pass  # minimal checkout: the store-plane children validate too
    data_dir = os.path.abspath(
        sys.argv[1]
        if len(sys.argv) > 1
        # lo: allow[LO301] free-form path knob, no domain to preflight
        else os.environ.get("LO_DATA_DIR", os.path.join(os.getcwd(), "lo_data"))
    )
    # lo: allow[LO301] free-form bind address, no domain to preflight
    host = os.environ.get("LO_HOST", "127.0.0.1")
    store_port = os.environ.get("LO_STORE_PORT", "27027")
    ephemeral = os.environ.get("LO_EPHEMERAL") == "1"
    restart_delay = float(os.environ.get("LO_RESTART_DELAY", "5"))
    max_restarts = os.environ.get("LO_MAX_RESTARTS")
    max_restarts = int(max_restarts) if max_restarts else None
    os.makedirs(data_dir, exist_ok=True)
    ports_path = os.path.join(data_dir, "stack_ports.json")

    log_lock = threading.Lock()

    def log(line: str) -> None:
        with log_lock:
            print(line, flush=True)

    base_env = dict(os.environ)
    base_env["PYTHONPATH"] = REPO_ROOT + os.pathsep + base_env.get("PYTHONPATH", "")
    base_env["PYTHONUNBUFFERED"] = "1"
    base_env["LO_DATA_DIR"] = data_dir
    base_env["LO_HOST"] = host

    replication = os.environ.get("LO_REPLICATION") == "1"
    process_base_early = int(os.environ.get("LO_PROCESS_BASE", "0") or 0)
    store_env = dict(base_env)
    store_env["LO_STORE_PORT"] = store_port
    store = Child(
        "store",
        [sys.executable, "-m", "learningorchestra_tpu.core.store_service"],
        store_env,
        log,
    )

    children: dict[str, Child] = {"store": store}

    if replication and process_base_early == 0:
        # Replicated store plane: primary + WAL-shipping follower +
        # quorum arbiter, wired by fixed ports (peer/arbiter URLs must
        # be known before any of the three starts).
        if store_port == "0":
            log("[stack] LO_REPLICATION=1 needs a fixed LO_STORE_PORT")
            return 2
        follower_port = os.environ.get("LO_FOLLOWER_PORT", "27028")
        arbiter_port = os.environ.get("LO_ARBITER_PORT", "27029")
        auto_promote_s = os.environ.get("LO_AUTO_PROMOTE_S", "5")
        primary_url = f"http://{host}:{store_port}"
        follower_url = f"http://{host}:{follower_port}"
        arbiter_url = f"http://{host}:{arbiter_port}"
        store_env.update(
            {
                "LO_REPLICATE": "1",
                "LO_PEERS": follower_url,
                "LO_ARBITERS": arbiter_url,
                "LO_NODE_ID": "store-primary",
            }
        )
        follower_env = dict(base_env)
        follower_env.update(
            {
                "LO_STORE_PORT": follower_port,
                # its own WAL dir — two stores must never share a log
                "LO_DATA_DIR": os.path.join(data_dir, "follower"),
                "LO_PRIMARY_URL": primary_url,
                "LO_PEERS": primary_url,
                "LO_ARBITERS": arbiter_url,
                "LO_AUTO_PROMOTE_S": auto_promote_s,
                "LO_NODE_ID": "store-follower",
            }
        )
        arbiter_env = dict(base_env)
        arbiter_env["LO_ARBITER_PORT"] = arbiter_port
        children["store-follower"] = Child(
            "store-follower",
            [sys.executable, "-m", "learningorchestra_tpu.core.store_service"],
            follower_env,
            log,
        )
        children["store-arbiter"] = Child(
            "store-arbiter",
            [sys.executable, "-m", "learningorchestra_tpu.core.arbiter"],
            arbiter_env,
            log,
        )

    # Horizontal sharding (docs/dataplane.md): LO_SHARDS=N launches N-1
    # EXTRA store groups beyond the meta group above, each on a port
    # stride of 10 from LO_STORE_PORT (primary base+10i, its follower
    # +1, its arbiter +2 when LO_REPLICATION=1) with its own data dir —
    # N WALs is the whole point. run.sh preflights the knob; this parse
    # re-checks because cluster.py launches stack.py directly.
    shards_raw = os.environ.get("LO_SHARDS", "").strip() or "1"
    try:
        shards = int(shards_raw)
        if shards < 1:
            raise ValueError(shards_raw)
    except ValueError:
        log(f"[stack] LO_SHARDS must be an integer >= 1, got {shards_raw!r}")
        return 2
    # Replicated serving fleet (docs/serving.md "Fleet"): opt-in via
    # LO_FLEET_REPLICAS=N — N replica model_builder processes (each a
    # ReplicaAgent pinning its placement-assigned models) behind one
    # router. run.sh preflights the knob; this parse re-checks because
    # cluster.py launches stack.py directly.
    fleet_raw = os.environ.get("LO_FLEET_REPLICAS", "").strip()
    fleet_replicas = 0
    if fleet_raw:
        try:
            fleet_replicas = int(fleet_raw)
            if fleet_replicas < 1:
                raise ValueError(fleet_raw)
        except ValueError:
            log(
                "[stack] LO_FLEET_REPLICAS must be an integer >= 1, "
                f"got {fleet_raw!r}"
            )
            return 2
    if shards > 1 and process_base_early == 0:
        if store_port == "0":
            log("[stack] LO_SHARDS>1 needs a fixed LO_STORE_PORT")
            return 2
        shard_base_port = int(store_port)
        for index in range(1, shards):
            group_port = shard_base_port + 10 * index
            group_name = f"store-s{index}"
            group_dir = os.path.join(data_dir, f"shard{index}")
            group_env = dict(base_env)
            group_env["LO_STORE_PORT"] = str(group_port)
            group_env["LO_DATA_DIR"] = group_dir
            if replication:
                group_primary = f"http://{host}:{group_port}"
                group_follower = f"http://{host}:{group_port + 1}"
                group_arbiter = f"http://{host}:{group_port + 2}"
                group_env.update(
                    {
                        "LO_REPLICATE": "1",
                        "LO_PEERS": group_follower,
                        "LO_ARBITERS": group_arbiter,
                        "LO_NODE_ID": f"{group_name}-primary",
                    }
                )
                follower_env = dict(base_env)
                follower_env.update(
                    {
                        "LO_STORE_PORT": str(group_port + 1),
                        # its own WAL dir — two stores never share a log
                        "LO_DATA_DIR": os.path.join(group_dir, "follower"),
                        "LO_PRIMARY_URL": group_primary,
                        "LO_PEERS": group_primary,
                        "LO_ARBITERS": group_arbiter,
                        "LO_AUTO_PROMOTE_S": os.environ.get(
                            "LO_AUTO_PROMOTE_S", "5"
                        ),
                        "LO_NODE_ID": f"{group_name}-follower",
                    }
                )
                arbiter_env = dict(base_env)
                arbiter_env["LO_ARBITER_PORT"] = str(group_port + 2)
                children[f"{group_name}-follower"] = Child(
                    f"{group_name}-follower",
                    [
                        sys.executable,
                        "-m",
                        "learningorchestra_tpu.core.store_service",
                    ],
                    follower_env,
                    log,
                )
                children[f"{group_name}-arbiter"] = Child(
                    f"{group_name}-arbiter",
                    [sys.executable, "-m", "learningorchestra_tpu.core.arbiter"],
                    arbiter_env,
                    log,
                )
            children[group_name] = Child(
                group_name,
                [sys.executable, "-m", "learningorchestra_tpu.core.store_service"],
                group_env,
                log,
            )

    def write_ports() -> None:
        ports = {
            name: child.port
            for name, child in children.items()
            if child.port is not None
        }
        for name, child in children.items():  # all-in-one: per-service
            if name.startswith("replica") and child.service_ports:
                # fleet replicas all announce "service model_builder";
                # publish under replica<i> so they don't clobber the
                # reference model_builder's port (or each other's)
                ports[name] = next(iter(child.service_ports.values()))
            else:
                ports.update(child.service_ports)
        state = {
            "ports": ports,
            "pids": {
                name: child.proc.pid
                for name, child in children.items()
                if child.proc is not None and child.poll() is None
            },
        }
        tmp = ports_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, ports_path)

    # Handlers installed before the first child starts: a SIGTERM during
    # the multi-minute bring-up must still tear everything down (the
    # try/finally below owns cleanup for bring-up failures too).
    stopping = threading.Event()

    def shutdown(signum, frame):
        stopping.set()

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)

    start_stdin_watchdog(stopping, log)

    workers = int(os.environ.get("LO_WORKERS", "0") or 0)
    process_base = int(os.environ.get("LO_PROCESS_BASE", "0") or 0)
    total_processes = int(os.environ.get("LO_TOTAL_PROCESSES", "0") or 0)
    try:
        if process_base > 0:
            exit_code = _supervise_workers_only(
                children,
                base_env,
                restart_delay,
                write_ports,
                stopping,
                log,
                workers,
                process_base,
                data_dir,
            )
        elif workers > 0 or total_processes > 1:
            # total > 1 with no local workers = the head machine of a
            # cross-machine runtime whose workers all live elsewhere
            if fleet_replicas:
                log(
                    "[stack] LO_FLEET_REPLICAS ignored in the multi-host "
                    "topology (the coordinator serves predicts itself)"
                )
            exit_code = _supervise_multihost(
                children,
                store,
                base_env,
                host,
                ephemeral,
                restart_delay,
                max_restarts,
                write_ports,
                ports_path,
                stopping,
                log,
                workers,
                data_dir,
            )
        else:
            exit_code = _supervise(
                children,
                store,
                base_env,
                host,
                ephemeral,
                restart_delay,
                max_restarts,
                write_ports,
                ports_path,
                stopping,
                log,
                fleet_replicas=fleet_replicas,
            )
    finally:
        log("[stack] shutting down")
        for child in children.values():
            child.terminate()
        deadline = time.time() + 10
        for child in children.values():
            if child.proc:
                try:
                    child.proc.wait(max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    child.proc.kill()
    return exit_code


def _supervise(
    children,
    store,
    base_env,
    host,
    ephemeral,
    restart_delay,
    max_restarts,
    write_ports,
    ports_path,
    stopping,
    log,
    fleet_replicas: int = 0,
) -> int:
    service_store_url = _start_store_plane(children, store, host, log)
    # the META group's primary (first ';' group, first ',' replica) —
    # the url the store-restart re-point logic below tracks
    store_url = service_store_url.split(";")[0].split(",")[0]

    launch_names = list(SERVICE_NAMES)
    fleet_names = []
    if fleet_replicas:
        # the fleet children ride the same supervision loop as the
        # seven: named replica<i>/router in children, restarted on
        # failure, ports published in stack_ports.json
        fleet_names = [f"replica{i}" for i in range(fleet_replicas)]
        fleet_names.append("router")
        launch_names += fleet_names
    for name in launch_names:
        env = dict(base_env)
        env["LO_STORE_URL"] = service_store_url
        if name.startswith("replica"):
            index = int(name[len("replica"):])
            env["LO_SERVICE"] = "model_builder"
            env["LO_FLEET_REPLICA"] = str(index)
            env["LO_PORT"] = "0" if ephemeral else str(FLEET_PORT_BASE + index)
        elif name == "router":
            env["LO_SERVICE"] = "router"
            env["LO_PORT"] = "0" if ephemeral else str(ROUTER_PORT)
            env.pop("LO_FLEET_REPLICA", None)
        else:
            env["LO_SERVICE"] = name
            # replica membership is per-process: never inherited from
            # the supervisor's own environment
            env.pop("LO_FLEET_REPLICA", None)
            if ephemeral:
                env["LO_PORT"] = "0"
        child = Child(
            name,
            [sys.executable, "-m", "learningorchestra_tpu.services.runner"],
            env,
            log,
        )
        children[name] = child
        child.start()
    for name in launch_names:
        children[name].wait_port(120)
    write_ports()
    if fleet_names:
        log(
            f"[stack] serving fleet up: {fleet_replicas} replica(s) + "
            "router"
        )
    log(f"[stack] all services up; ports in {ports_path}")

    retired: set = set()
    exit_code = 0
    while not stopping.is_set():
        time.sleep(0.5)
        for name, child in children.items():
            code = child.poll()
            if code is None or name in retired or stopping.is_set():
                continue
            if code == 0:
                log(f"[stack] {name} exited cleanly; not restarting")
                retired.add(name)
                child.port = None
                child.service_ports.clear()
                write_ports()
                continue
            if max_restarts is not None and child.restarts >= max_restarts:
                log(
                    f"[stack] {name} failed (rc={code}) after "
                    f"{child.restarts} restarts; giving up"
                )
                stopping.set()
                exit_code = 1
                break
            child.restarts += 1
            log(
                f"[stack] {name} failed (rc={code}); restart "
                f"#{child.restarts} in {restart_delay}s"
            )
            time.sleep(restart_delay)
            child._port_event.clear()
            child.port = None
            child.service_ports.clear()
            if name == "store":
                child.start()
                new_port = child.wait_port(60)
                new_url = f"http://{host}:{new_port}"
                wait_health(new_url, 60)
                # Ephemeral store ports can move across restarts; the
                # services' LO_STORE_URL is fixed at their spawn, so
                # only restart-in-place topologies (fixed store port)
                # keep the wiring valid — the default.
                if new_url != store_url:
                    log(
                        "[stack] store moved to "
                        f"{new_url}; restarting services to rewire"
                    )
                    store_url = new_url
                    for svc_name in launch_names:
                        svc = children[svc_name]
                        svc.terminate()
                        svc.env["LO_STORE_URL"] = store_url
            else:
                child.start()
                try:
                    child.wait_port(120)
                except TimeoutError as error:
                    if name.startswith("store-") and name.endswith(
                        ("-follower", "-arbiter")
                    ):
                        # a redundancy component that cannot come back
                        # (port held by a lingering socket, crash loop)
                        # must not take down the healthy primary and
                        # services; leave it dead, retry next cycle
                        log(f"[stack] {name} restart stalled: {error}")
                        continue
                    raise
            write_ports()

    return exit_code


def _supervise_workers_only(
    children,
    base_env,
    restart_delay,
    write_ports,
    stopping,
    log,
    workers: int,
    process_base: int,
    data_dir: str,
) -> int:
    """A worker-only machine of a cross-machine runtime
    (``LO_PROCESS_BASE`` > 0): supervise ``LO_WORKERS`` SPMD worker
    processes with jax process ids ``base..base+N-1``, joined to the
    head machine's coordinator (``LO_COORDINATOR``) and store
    (``LO_STORE_URL``). The reference analogue is a machine running only
    ``sparkworker`` replicas (docker-compose.yml:133-163). Any member
    dying exits the stack (rc=1): the cross-machine collective cannot
    heal locally, the cluster driver relaunches every machine's group.
    """
    workers = workers or 1
    total = int(base_env.get("LO_TOTAL_PROCESSES", "0") or 0)
    missing = [
        knob
        for knob in ("LO_COORDINATOR", "LO_STORE_URL")
        if not base_env.get(knob)
    ]
    if missing or total <= 0:
        missing += ["LO_TOTAL_PROCESSES"] if total <= 0 else []
        log(f"[stack] worker-only mode requires {', '.join(missing)}")
        return 2

    def worker_env(process_id: int) -> dict:
        env = dict(base_env)
        env["LO_NUM_PROCESSES"] = str(total)
        env["LO_PROCESS_ID"] = str(process_id)
        env.setdefault("LO_MODELS_DIR", os.path.join(data_dir, "models"))
        env.pop("LO_SERVICE", None)
        return env

    names = [f"worker{process_base + i}" for i in range(workers)]
    for index, name in enumerate(names):
        child = Child(
            name,
            [sys.executable, "-m", "learningorchestra_tpu.services.runner"],
            worker_env(process_base + index),
            log,
        )
        children[name] = child
        child.start()
    for name in names:
        children[name].wait_ready(300)
    write_ports()
    log(
        f"[stack] worker group up: processes "
        f"{process_base}..{process_base + workers - 1} of {total}"
    )
    while not stopping.is_set():
        time.sleep(0.5)
        dead = [name for name in names if children[name].poll() is not None]
        if dead:
            log(
                f"[stack] runtime member(s) {dead} died in a "
                "cross-machine runtime; exiting for the cluster driver"
            )
            return 1
    return 0


def _supervise_multihost(
    children,
    store,
    base_env,
    host,
    ephemeral,
    restart_delay,
    max_restarts,
    write_ports,
    ports_path,
    stopping,
    log,
    workers: int,
    data_dir: str,
) -> int:
    """The multi-host topology (``LO_WORKERS=N``): store server +
    coordinator (all seven services, REST, SPMD dispatch) + N worker
    processes joined into ONE jax.distributed runtime — the reference's
    sparkmaster + N sparkworker overlay (docker-compose.yml:123-163) as
    process supervision.

    Restart semantics differ from the single-host loop on purpose: the
    collective runtime cannot heal per-process (a lost member poisons
    the collective stream — parallel/spmd.py), so ANY runtime-member
    death tears down and relaunches the WHOLE group, exactly like Spark
    restarting an application that lost executors. The store survives
    group restarts (it is outside the runtime).

    Cross-machine deployments run this same supervisor per machine:
    the coordinator machine with ``LO_WORKERS=0`` workers here and
    remote workers joining via ``LO_COORDINATOR``/``LO_PROCESS_ID`` —
    see deploy/README.md.
    """
    service_store_url = _start_store_plane(children, store, host, log)
    store_url = service_store_url.split(";")[0].split(",")[0]

    coord_port = os.environ.get("LO_COORD_PORT", "12355")
    num_processes = int(
        base_env.get("LO_TOTAL_PROCESSES", "0") or 0
    ) or (workers + 1)
    # more processes than this machine hosts = a cross-machine runtime:
    # a local group restart cannot heal it (remote members are poisoned
    # too), so member death exits the stack for the cluster driver
    cross_machine = num_processes > workers + 1

    def runtime_env(process_id: int) -> dict:
        env = dict(base_env)
        env["LO_STORE_URL"] = service_store_url
        env["LO_COORDINATOR"] = f"{host}:{coord_port}"
        env["LO_NUM_PROCESSES"] = str(num_processes)
        env["LO_PROCESS_ID"] = str(process_id)
        # checkpoints must land on a path every host shares; on one
        # machine the data dir IS that shared volume
        env.setdefault("LO_MODELS_DIR", os.path.join(data_dir, "models"))
        if ephemeral:
            env["LO_EPHEMERAL"] = "1"
        env.pop("LO_SERVICE", None)  # coordinator runs all-in-one
        return env

    # LOCAL members only: with LO_TOTAL_PROCESSES set, processes beyond
    # workers+1 live on other machines (their stacks run LO_PROCESS_BASE)
    group_names = ["coordinator"] + [f"worker{i}" for i in range(1, workers + 1)]
    group_restarts = 0

    def launch_group() -> None:
        # A bring-up can stall (e.g. a member hitting a stale
        # coordination socket); retry the whole group like any other
        # restart instead of giving up the stack.
        for attempt in range(3):
            for index, name in enumerate(group_names):
                child = Child(
                    name,
                    [sys.executable, "-m", "learningorchestra_tpu.services.runner"],
                    runtime_env(index),
                    log,
                )
                children[name] = child
                child.start()
            try:
                children["coordinator"].wait_port(180)
                # the all-in-one coordinator announces one port PER
                # service; wait for the full set before publishing
                deadline = time.time() + 60
                while (
                    len(children["coordinator"].service_ports) < len(SERVICE_NAMES)
                    and time.time() < deadline
                ):
                    time.sleep(0.2)
                if len(children["coordinator"].service_ports) < len(SERVICE_NAMES):
                    raise TimeoutError(
                        "coordinator announced only "
                        f"{sorted(children['coordinator'].service_ports)}"
                    )
                for name in group_names[1:]:
                    children[name].wait_ready(180)
            except TimeoutError as error:
                if attempt == 2:
                    raise
                log(f"[stack] group bring-up stalled ({error}); relaunching")
                stop_group()
                time.sleep(restart_delay)
                continue
            break
        write_ports()
        log(
            f"[stack] runtime up: coordinator + {workers} worker(s), "
            f"ports in {ports_path}"
        )

    def stop_group() -> None:
        for name in group_names:
            child = children.get(name)
            if child is None:
                continue
            child.terminate()
            if child.proc is not None:
                try:
                    child.proc.wait(10)
                except subprocess.TimeoutExpired:
                    child.proc.kill()

    launch_group()

    exit_code = 0
    retired: set = set()
    while not stopping.is_set():
        time.sleep(0.5)
        store_code = store.poll()
        if (
            store_code is not None
            and store_code != 0
            and "store" not in retired
            and not stopping.is_set()
        ):
            if max_restarts is not None and store.restarts >= max_restarts:
                log(
                    f"[stack] store failed (rc={store_code}) after "
                    f"{store.restarts} restarts; giving up"
                )
                exit_code = 1
                break
            store.restarts += 1
            log(f"[stack] store failed (rc={store_code}); restarting")
            time.sleep(restart_delay)
            store._port_event.clear()
            store.start()
            new_port = store.wait_port(60)
            new_url = f"http://{host}:{new_port}"
            wait_health(new_url, 60)
            if new_url != store_url:
                # ephemeral store port moved: the group's LO_STORE_URL
                # is stale — rewire by restarting the runtime group
                log(f"[stack] store moved to {new_url}; restarting group")
                store_url = new_url
                stop_group()
                launch_group()
            write_ports()
        elif store_code == 0 and "store" not in retired:
            log("[stack] store exited cleanly; not restarting")
            retired.add("store")
            store.port = None
            write_ports()
        # replicated-plane members restart independently (their fixed
        # ports keep the wiring valid; the primary's term fence handles
        # a follower coming back after a completed takeover)
        for plane_name in ("store-follower", "store-arbiter"):
            child = children.get(plane_name)
            if (
                child is None
                or child.poll() is None
                or plane_name in retired
                or stopping.is_set()
            ):
                continue
            if child.poll() == 0:
                log(f"[stack] {plane_name} exited cleanly; not restarting")
                retired.add(plane_name)
                continue
            child.restarts += 1
            log(
                f"[stack] {plane_name} failed (rc={child.poll()}); "
                f"restart #{child.restarts} in {restart_delay}s"
            )
            time.sleep(restart_delay)
            child._port_event.clear()
            child.port = None
            child.start()
            try:
                child.wait_port(60)
            except TimeoutError as error:
                # a redundancy component failing to come back (port
                # still held by a lingering socket, crash-looping)
                # must NOT take down the healthy primary + services +
                # runtime group: leave it dead, the next cycle retries
                log(f"[stack] {plane_name} restart stalled: {error}")
                continue
            write_ports()
        dead = [
            name
            for name in group_names
            if children[name].poll() is not None
        ]
        if dead and not stopping.is_set():
            if cross_machine:
                log(
                    f"[stack] runtime member(s) {dead} died in a "
                    "cross-machine runtime; exiting for the cluster "
                    "driver to relaunch every machine's group"
                )
                return 1
            if max_restarts is not None and group_restarts >= max_restarts:
                log(
                    f"[stack] runtime member(s) {dead} died after "
                    f"{group_restarts} group restarts; giving up"
                )
                exit_code = 1
                break
            group_restarts += 1
            log(
                f"[stack] runtime member(s) {dead} died — a lost member "
                "poisons the collective stream; restarting the WHOLE "
                f"group (#{group_restarts}) in {restart_delay}s"
            )
            stop_group()
            time.sleep(restart_delay)
            launch_group()

    return exit_code


if __name__ == "__main__":
    sys.exit(main())
