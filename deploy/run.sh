#!/usr/bin/env bash
# Single-host bring-up — the analogue of the reference's `sudo ./run.sh`
# (reference run.sh:1-108 builds seven images and `docker stack deploy`s
# them around a MongoDB replica set; here one process serves all seven
# APIs over a WAL-backed store, with JAX owning the accelerator).
#
# Usage:
#   ./deploy/run.sh [data_dir]
#
# Environment:
#   LO_HOST        bind address (default 127.0.0.1; set 0.0.0.0 to expose
#                  beyond localhost — model_builder executes request-
#                  supplied code, so only do that inside a sandbox)
#   LO_DATA_DIR    store WAL directory (default ./lo_data, or $1)
#   JAX_PLATFORMS  accelerator choice  (default: jax autodetect — TPU
#                  when libtpu is present)
#
# Scheduler knobs (docs/scheduler.md has the full table; values are
# validated at startup — a typo fails fast instead of silently running
# at a default width):
#   LO_JOB_WORKERS        host-class concurrency width   (default 8)
#   LO_SCHED_DEVICE_WIDTH device-class width             (default 1 —
#                         SPMD dispatches never contend for the mesh)
#   LO_SCHED_QUEUE_CAP    per-class queue cap; past it submissions get
#                         HTTP 429 + Retry-After         (default 64)
#   LO_COALESCE_WINDOW_MS job-coalescing collection window in ms; shape-
#                         compatible device jobs arriving within it fuse
#                         into ONE vmap-across-jobs dispatch (default 2;
#                         0 = passthrough, every job dispatches alone)
#   LO_COALESCE_MAX_JOBS  max member jobs per fused dispatch (default 32,
#                         strictly integral)
#
# Data-plane knobs (docs/dataplane.md has the full table):
#   LO_DEVCACHE_BYTES     rev-keyed device-cache capacity in bytes
#                         (default 2e9; 0 disables)
#   LO_STORE_COMPRESS     1 = zlib the binary store wire (worth it on
#                         narrow links; default 0)
#   LO_WRITE_OVERLAP      0 = synchronous prediction write-back
#                         (default 1: writes overlap the next fit)
#   LO_SHM_BYTES          shared-memory ring size for co-located
#                         store reads (bytes, 1e9 notation ok;
#                         default 0 = disabled — frames ride the
#                         HTTP body)
#   LO_DTYPE_POLICY       feature-matrix dtype: f32 (default) or bf16
#                         (halves H2D + HBM; must match on every host)
#   LO_WIRE_V2            0 = escape hatch back to v1 wire frames
#                         (default 1: aligned zero-copy frames)
#
# Serving knobs (docs/serving.md has the full table):
#   LO_SERVE_BYTES           device-byte budget for pinned models
#                            (default 1e9; 0 = host-only fallback —
#                            correct, just loads per request)
#   LO_SERVE_BATCH_WINDOW_MS micro-batch collection window (default 1)
#   LO_SERVE_MAX_BATCH       max requests per forward dispatch (64)
#   LO_SERVE_MAX_ROWS        max rows per predict request; past it the
#                            route answers 413         (default 4096)
#   LO_SERVE_QUEUE_CAP       bounded batcher inbox; past it predicts
#                            get HTTP 429 + Retry-After    (default 256)
#   LO_SERVE_TIMEOUT_S       per-request wait bound → 503  (default 30)
#
# Serving-fleet knobs (docs/serving.md "Fleet" has the full table; the
# fleet only launches under deploy/stack.py with LO_FLEET_REPLICAS set):
#   LO_FLEET_REPLICAS     replica model_builder processes behind the
#                         router (strictly integral >= 1; unset = no
#                         fleet — the single reference model_builder)
#   LO_FLEET_RF           placement copies per model on the consistent-
#                         hash ring (default 1; clamped to the replica
#                         count; strictly integral >= 1)
#   LO_FLEET_MODEL_QPS    router per-model token-bucket rate; past it
#                         predicts get 429 + Retry-After (default 0 =
#                         quota off; >= 0)
#   LO_FLEET_DOWN_S       heartbeat staleness after which the router
#                         routes AROUND a replica (default 3; > 0)
#   LO_FLEET_REPLICA      this process's replica index — set by
#                         stack.py per child, never by an operator
#
# Web-serving knobs (docs/web.md has the full table):
#   LO_WEB_ASYNC          1 = selectors event-loop serving core (idle
#                         keep-alive/long-poll connections cost no
#                         thread); 0 = threaded werkzeug escape hatch
#   LO_WEB_HANDLERS       handler-pool width: blocking route functions
#                         in flight at once (default 8, strictly
#                         integral >= 1)
#   LO_WEB_MAX_CONNS      open-connection cap; past it new connections
#                         get 503 + close          (default 10000)
#   LO_WEB_WAIT_CAP_S     ceiling on a /wait long-poll's requested
#                         timeout                  (default 60, > 0)
#
# Profiling knobs (docs/profiling.md has the full table):
#   LO_PROF_HZ            sampling-profiler rate for GET /debug/profile
#                         (default 47; 0 disables the endpoint — the
#                         sampler never runs outside an explicit request
#                         either way)
#   LO_PROF_WINDOW_S      longest window one /debug/profile request may
#                         sample (default 60; must be > 0)
#
# Replication / failover knobs (docs/replication.md has the full table):
#   LO_REPLICATION        1 = replicated store plane (primary + follower
#                         + quorum arbiter) when run under deploy/stack.py
#   LO_FOLLOWER_PORT      follower store port        (default 27028)
#   LO_ARBITER_PORT       arbiter port               (default 27029)
#   LO_AUTO_PROMOTE_S     follower takeover timer    (default 5)
#   LO_QUORUM_GRACE_S     primary write-suspension grace after losing
#                         its voter majority
#   LO_STORE_SYNC_REPL    1 = acks wait for a follower (zero lost
#                         acknowledged writes; LO_STORE_ACK_TIMEOUT_S)
#
# Horizontal sharding knobs (docs/dataplane.md has the full table):
#   LO_SHARDS             store groups stack.py launches (default 1 =
#                         unsharded, byte-identical wire traffic; N > 1
#                         strides ports by 10 per extra group and
#                         composes with LO_REPLICATION per group)
#   LO_SHARD_STRIPE_ROWS  rows per consistent-hash stripe (default 8192;
#                         strictly integral >= 1 — part of the shard-map
#                         placement contract, identical on every host)
#   LO_SHARDMAP_TTL_S     shard-map client cache TTL in seconds
#                         (default 5; 0 = revalidate rev on every read)
#
# Crash-resume knobs (docs/robustness.md has the full table):
#   LO_RESUME             1 = segment-checkpointed fits + resume-aware
#                         recovery (default 1; 0 = orphaned RUNNING
#                         jobs fail on restart, the pre-resume contract)
#   LO_RESUME_EVERY_SEGMENTS
#                         persist a progress artifact every Nth fit
#                         segment (default 1 = every segment; strictly
#                         integral >= 1 — larger N trades re-done work
#                         after a crash for fewer artifact writes)
#
# AOT compile-plane knobs (docs/compile.md has the full table):
#   LO_AOT                1 = boot-time background precompile of the
#                         program manifest into the persistent jit
#                         cache (default 0 — short-lived processes
#                         never amortize the pass)
#   LO_AOT_MAX_PROGRAMS   manifest-entry cap for the pass; everything
#                         past it lands on a LOGGED drop list (default
#                         64; strictly integral >= 0, 0 = enumerate
#                         only)
#   LO_AOT_PUBLISH        1 = publish compiled executables to the
#                         __lo_executables__ store collection so the
#                         fleet shares them (default 1)
#
# Fleet observability knobs (docs/observability.md has the full table):
#   LO_TSDB_POINTS        retained samples per metric family x instance
#                         in the store's __lo_metrics__ ring (default
#                         512; strictly integral >= 1)
#   LO_TSDB_COLLECT       0 = no in-process fallback collector (the
#                         cluster driver sets this and scrapes all
#                         members centrally); default 1
#   LO_METRICS_INTERVAL_S scrape cadence in seconds (shared with the
#                         cluster driver's summary loop; default 60)
#   LO_TRACE_RING         per-process trace/span-export ring size
#                         (default 256; strictly integral >= 1)
#   LO_PLANE_MEMBERS      comma list of member base URLs GET /traces/
#                         <cid> stitches across (unset = local only)
#   LO_SLO_WINDOW_S       SLO evaluation window     (default 600, > 0)
#   LO_SLO_SERVE_P99_S    serve p99 latency ceiling  (default 1.0)
#   LO_SLO_5XX_RATE       5xx responses/s ceiling    (default 0.5)
#   LO_SLO_QUEUE_DEPTH    sched queue-depth ceiling  (default 64)
#   LO_SLO_REPL_LAG       replication-lag ceiling    (default 1000)
#
# Fault injection (chaos drills ONLY — docs/replication.md):
#   LO_FAULT_*            named fault points (kill/delay/error/torn);
#                         validated below so a typo'd point or spec
#                         fails bring-up instead of silently not firing
set -euo pipefail

cd "$(dirname "$0")/.."
export LO_DATA_DIR="${1:-${LO_DATA_DIR:-$PWD/lo_data}}"
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"

# Fail fast on malformed scheduler/data-plane/replication/fault knobs
# before bringing up services.
python - <<'EOF'
import os
from learningorchestra_tpu.sched import config
config.host_width(); config.device_width(); config.queue_cap()
# the rest of the scheduler table (docs/scheduler.md promises "all
# knobs are validated at startup" — LO301 caught retries/backoff/
# jitter/deadline/history validating nowhere)
config.retry_budget(); config.backoff_base_s(); config.backoff_cap_s()
config.jitter_seed(); config.default_timeout_s()
config.job_history(); config.job_ttl_s()
# coalescing knobs: window >= 0 (0 = passthrough), max_jobs a strict
# integer >= 1 (1.5 silently truncating would halve every fused batch)
config.coalesce_window_s(); config.coalesce_max_jobs()
from learningorchestra_tpu.core import devcache
devcache.capacity_bytes()
# zero-copy wire knobs: shm ring size >= 0 (1e9 notation ok, 0 =
# disabled), dtype policy f32|bf16 (part of every devcache key and of
# SPMD dispatch shapes — must be identical on every host)
from learningorchestra_tpu.core import shmring
shmring.shm_bytes()
from learningorchestra_tpu.utils import dtypepolicy
dtypepolicy.validate_env()
# serving knobs: reject non-numeric / out-of-range before bring-up
# (window >= 0, bytes >= 0 with 0 = host-only fallback)
from learningorchestra_tpu.serve import config as serve_config
serve_config.validate_all()
# serving-fleet knobs: replica count / rf strictly integral >= 1,
# quota rate >= 0 (0 = off), down threshold > 0, replica index (set by
# stack.py, not operators) integral and < the replica count — a typo'd
# LO_FLEET_RF must refuse bring-up, never silently place single-copy
from learningorchestra_tpu.serve import fleet as serve_fleet
serve_fleet.validate_env()
# profiling knobs: HZ >= 0 (0 = /debug/profile disabled), window > 0
from learningorchestra_tpu.telemetry import profile as lo_profile
lo_profile.validate_env()
# web-serving knobs: LO_WEB_ASYNC strictly 0/1, handler-pool width and
# connection cap strictly integral >= 1, wait-timeout cap > 0 — a
# typo'd LO_WEB_HANDLERS must refuse bring-up, never silently serve
# one-wide
from learningorchestra_tpu.utils import webloop
webloop.validate_env()
# AOT compile-plane knobs: LO_AOT / LO_AOT_PUBLISH strictly 0/1,
# LO_AOT_MAX_PROGRAMS strictly integral >= 0 — a typo'd LO_AOT must
# refuse bring-up, never silently boot cold (or silently precompile)
from learningorchestra_tpu.compile import config as compile_config
compile_config.validate_env()
for knob in ("LO_STORE_COMPRESS", "LO_WRITE_OVERLAP", "LO_REPLICATION",
             "LO_STORE_SYNC_REPL", "LO_WIRE_V2", "LO_SHAPE_BUCKETS",
             "LO_EPHEMERAL", "LO_REPLICATE", "LO_STACK_EXIT_ON_STDIN_EOF",
             "LO_TSDB_COLLECT"):
    value = os.environ.get(knob, "").strip()
    if value and value not in ("0", "1"):
        raise SystemExit(f"{knob} must be 0 or 1, got {value!r}")
for knob in ("LO_FOLLOWER_PORT", "LO_ARBITER_PORT"):
    value = os.environ.get(knob, "").strip()
    if value:
        try:
            port = int(value)
        except ValueError:
            port = -1
        if not 1 <= port <= 65535:
            raise SystemExit(f"{knob} must be a port number, got {value!r}")
# service/store/coordinator ports additionally accept 0 = OS-assigned
for knob in ("LO_PORT", "LO_STORE_PORT", "LO_COORD_PORT"):
    value = os.environ.get(knob, "").strip()
    if value:
        try:
            port = int(value)
        except ValueError:
            port = -1
        if not 0 <= port <= 65535:
            raise SystemExit(f"{knob} must be a port number, got {value!r}")
for knob in ("LO_AUTO_PROMOTE_S", "LO_QUORUM_GRACE_S",
             "LO_STORE_ACK_TIMEOUT_S", "LO_FAILOVER_TIMEOUT_S",
             "LO_LANDED_OK_WINDOW_S", "LO_REPL_INTERVAL_S",
             "LO_STORE_MONITOR_TICK_S", "LO_SPMD_HEARTBEAT_S",
             "LO_METRICS_INTERVAL_S"):
    value = os.environ.get(knob, "").strip()
    if value:
        try:
            seconds = float(value)
        except ValueError:
            seconds = -1.0
        if seconds <= 0:
            raise SystemExit(f"{knob} must be seconds > 0, got {value!r}")
# 0 is meaningful here: no SPMD deadline / immediate supervisor restart
for knob in ("LO_SPMD_TIMEOUT_S", "LO_RESTART_DELAY"):
    value = os.environ.get(knob, "").strip()
    if value:
        try:
            seconds = float(value)
        except ValueError:
            seconds = -1.0
        if seconds < 0:
            raise SystemExit(f"{knob} must be seconds >= 0, got {value!r}")
# wire/build/process-topology counts: strictly integral with a floor —
# a float or typo refuses bring-up instead of silently clamping
for knob, floor in (("LO_WIRE_ROWS", 1), ("LO_WIRE_ROWS_BIN", 1),
                    ("LO_COMPACT_RECORDS", 1), ("LO_BUILD_WORKERS", 1),
                    ("LO_CHUNK_RETRIES", 0), ("LO_READ_RETRIES", 0),
                    ("LO_WORKERS", 0), ("LO_TOTAL_PROCESSES", 0),
                    ("LO_PROCESS_BASE", 0), ("LO_MAX_RESTARTS", 0),
                    ("LO_TRACE_RING", 1), ("LO_TSDB_POINTS", 1),
                    ("LO_SHARDS", 1)):
    value = os.environ.get(knob, "").strip()
    if value:
        try:
            count = int(value)
        except ValueError:
            count = floor - 1
        if count < floor:
            raise SystemExit(
                f"{knob} must be an integer >= {floor}, got {value!r}")
# byte budgets keep run.sh's 1e9 notation; 0 disables the feature
for knob in ("LO_INGEST_SLAB_BYTES", "LO_SPILL_BYTES"):
    value = os.environ.get(knob, "").strip()
    if value:
        try:
            amount = int(float(value))
        except ValueError:
            amount = -1
        if amount < 0:
            raise SystemExit(
                f"{knob} must be bytes >= 0 (1e9 notation ok), got {value!r}")
value = os.environ.get("LO_PROGRAM_ROW_STEPS", "").strip()
if value:
    try:
        scale = float(value)
    except ValueError:
        scale = -1.0
    if scale <= 0:
        raise SystemExit(
            f"LO_PROGRAM_ROW_STEPS must be a scale > 0, got {value!r}")
# sharding knobs: stripe rows strictly integral >= 1, shard-map TTL a
# float >= 0 — a typo'd LO_SHARD_STRIPE_ROWS must refuse bring-up, or
# every client would compute a different hash-ring placement
from learningorchestra_tpu.core import shardmap
shardmap.validate_env()
# crash-resume knobs: LO_RESUME strictly 0/1, checkpoint cadence a
# strict integer >= 1 — "0.5" silently becoming "never checkpoint"
# would void the whole crash-resume contract at the worst moment
config.resume_enabled(); config.resume_every_segments()
# SLO thresholds (docs/observability.md): a typo'd LO_SLO_* must
# refuse bring-up — silently alerting at the default threshold is as
# bad as silently never alerting
from learningorchestra_tpu.telemetry import slo as lo_slo
lo_slo.validate_env()
# chaos fault points: a typo'd LO_FAULT_* must fail bring-up loudly
from learningorchestra_tpu.testing import faults
try:
    armed = faults.validate_env()
except ValueError as error:
    raise SystemExit(f"LO_FAULT_* validation failed: {error}")
if armed:
    print(f"run.sh: FAULT INJECTION ARMED: {armed} (chaos drill?)")
EOF

# SPMD-safety + concurrency preflight (docs/analysis.md): refuse to
# serve a build that violates the cross-host invariants (LO1xx) or the
# lock-discipline invariants of the threaded serving stack (LO2xx) — a
# bug found here costs seconds; found in production it costs a poisoned
# runtime or a deadlocked lock and a supervisor restart.
# The LO30x deployment-contract pass (docs/analysis.md) rides the same
# invocation: knob/preflight/manifest/metric/fault-table parity over
# the whole project, so the very validations above cannot drift from
# the code that reads the knobs.
# LO_ANALYSIS_WARN=1 downgrades to log-and-warn for emergency hotfixes;
# LO_ANALYSIS_CHANGED=1 blocks only on findings NEW since the git
# merge-base (forks and feature branches carrying an upstream backlog);
# LO_ANALYSIS_FORMAT=json emits the machine-readable finding stream
# (stable {rule, path, line, message, suppressed} objects) for CI
# collectors while the human summary moves to stderr.
analysis_flags=()
if [ "${LO_ANALYSIS_FORMAT:-text}" = "json" ]; then
    analysis_flags+=(--format=json)
fi
if [ "${LO_ANALYSIS_CHANGED:-0}" = "1" ]; then
    python -m learningorchestra_tpu.analysis "${analysis_flags[@]}" \
        --changed learningorchestra_tpu
else
    python -m learningorchestra_tpu.analysis "${analysis_flags[@]}" \
        learningorchestra_tpu
fi

exec python -m learningorchestra_tpu.services.runner
