#!/usr/bin/env python3
"""One-command multi-MACHINE bring-up: the reference's ``run.sh`` +
``docker stack deploy`` + worker scaling (reference run.sh:8-32,
docker-compose.yml:1-340, README.md:94 ``docker service scale
microservice_sparkworker=N``) as a manifest-driven cluster driver.

Every machine runs the same per-machine supervisor (``deploy/stack.py``);
this driver computes the cross-machine wiring (store URL, jax
coordinator address, per-machine process-id ranges), launches one stack
per machine, health-gates bring-up, and — because a lost member poisons
the whole collective runtime — relaunches EVERY machine's runtime group
when any machine reports a death (the role swarm restart policies +
``dockerize -wait`` play in the reference, docker-compose.yml:14-15,145).

Usage::

    python deploy/cluster.py up <manifest.json>      # bring up + supervise
    python deploy/cluster.py render <manifest.json>  # print per-machine cmds

Manifest (JSON)::

    {
      "repo": "/opt/learningorchestra_tpu",  # checkout path on every machine
      "python": "python3",
      "transport": "ssh",          # "ssh" (default) or "local" (all
                                   # "machines" are processes on this one —
                                   # CI and single-box smoke)
      "head": {
        "host": "10.0.0.1",        # address workers + clients reach it at
        "bind": "0.0.0.0",         # LO_HOST on the head (see deploy/README.md
                                   # before exposing model_builder)
        "ssh": "user@10.0.0.1",
        "data_dir": "/var/lo_data",
        "workers": 0               # SPMD worker processes ON the head machine
      },
      "workers": [                 # one entry per worker machine
        {"host": "10.0.0.2", "ssh": "user@10.0.0.2",
         "data_dir": "/var/lo_data", "processes": 1}
      ],
      "models_dir": "/shared/models",  # volume mounted by ALL machines
      "store_port": 27027,
      "coord_port": 12355,
      "env": {},                   # extra env for every machine
      "sched": {                   # optional scheduler knobs, validated
        "job_workers": 8,          #   LO_JOB_WORKERS (host-class width)
        "device_width": 1,         #   LO_SCHED_DEVICE_WIDTH
        "queue_cap": 64            #   LO_SCHED_QUEUE_CAP (429 past it)
      },
      "dataplane": {               # optional data-plane knobs, validated
        "devcache_bytes": 2000000000,  # LO_DEVCACHE_BYTES (0 disables)
        "store_compress": 0,       #   LO_STORE_COMPRESS (1 = zlib wire)
        "write_overlap": 1         #   LO_WRITE_OVERLAP (0 = sync writes)
      },
      "wire": {                    # optional zero-copy wire knobs
        "shm_bytes": 0,            #   LO_SHM_BYTES (ring size; 0 = off)
        "dtype_policy": "f32"      #   LO_DTYPE_POLICY (f32 | bf16)
      },
      "coalescing": {              # optional job-coalescing knobs
        "window_ms": 2,            #   LO_COALESCE_WINDOW_MS (>= 0;
        "max_jobs": 32             #   0 = passthrough) / LO_COALESCE_
      },                           #   MAX_JOBS (integer >= 1)
      "serving": {                 # optional online-serving knobs
        "serve_bytes": 1000000000, #   LO_SERVE_BYTES (0 = host fallback)
        "batch_window_ms": 1,      #   LO_SERVE_BATCH_WINDOW_MS (>= 0)
        "max_batch": 64,           #   LO_SERVE_MAX_BATCH (>= 1)
        "max_rows": 4096,          #   LO_SERVE_MAX_ROWS (413 past it)
        "queue_cap": 256,          #   LO_SERVE_QUEUE_CAP (429 past it)
        "timeout_s": 30            #   LO_SERVE_TIMEOUT_S (> 0)
      },
      "fleet": {                   # optional replicated serving fleet
        "replicas": 2,             #   LO_FLEET_REPLICAS (N replica
        "rf": 1,                   #   model_builders + router, single-
        "model_qps": 0,            #   host stacks only) / LO_FLEET_RF /
        "down_s": 3                #   LO_FLEET_MODEL_QPS (0 = off) /
      },                           #   LO_FLEET_DOWN_S (docs/serving.md)
      "profiling": {               # optional flight-recorder knobs
        "prof_hz": 47,             #   LO_PROF_HZ (0 disables /debug/
        "prof_window_s": 60        #   profile); LO_PROF_WINDOW_S (> 0)
      },
      "web": {                     # optional web-serving knobs
        "async": 1,                #   LO_WEB_ASYNC (0 = threaded
        "handlers": 8,             #   escape hatch); LO_WEB_HANDLERS
        "max_conns": 10000,        #   (>= 1); LO_WEB_MAX_CONNS (503
        "wait_cap_s": 60           #   past it); LO_WEB_WAIT_CAP_S (> 0)
      },
      "resume": {                  # optional crash-resume knobs
        "enabled": 1,              #   LO_RESUME (0 = orphaned RUNNING
        "every_segments": 1        #   jobs fail on restart) / LO_RESUME_
      },                           #   EVERY_SEGMENTS (integer >= 1)
      "compile": {                 # optional AOT compile plane knobs
        "aot": 1,                  #   LO_AOT (1 = precompile the shape
        "max_programs": 64,        #   grid at boot) / LO_AOT_MAX_
        "publish": 1               #   PROGRAMS (integer >= 0) /
      },                           #   LO_AOT_PUBLISH (docs/compile.md)
      "replication": {             # optional replicated store plane
        "enabled": true,           #   (docs/replication.md): the head
        "follower_port": 27028,    #   runs primary + WAL-shipping
        "arbiter_port": 27029,     #   follower + quorum arbiter; every
        "auto_promote_s": 5,       #   machine's LO_STORE_URL names both
        "sync_repl": 0             #   stores. sync_repl=1 withholds acks
      },                           #   until a follower holds the write
      "sharding": {                # optional horizontal store sharding
        "shards": 4,               #   LO_SHARDS: store groups on the
        "stripe_rows": 8192,       #   head (port stride 10; composes
        "map_ttl_s": 5             #   with replication per group) /
      },                           #   LO_SHARD_STRIPE_ROWS /
                                   #   LO_SHARDMAP_TTL_S (docs/dataplane.md)
      "restart_delay": 5,
      "max_cluster_restarts": null # null = retry forever
    }

``render`` prints the exact per-machine command lines (env + stack.py)
so an operator can run or inspect them by hand; ``up`` is those commands
plus supervision. ssh transport sets ``LO_STACK_EXIT_ON_STDIN_EOF=1``
so the remote stack shuts itself down when the ssh channel closes —
``ssh -o BatchMode=yes`` allocates no pty, so a dying driver would
otherwise never HUP the remote process group and the stale stack would
linger holding the store/coordinator ports; ``Machine.stop`` ALSO
issues an explicit remote ``pkill`` before every whole-cluster
relaunch, so a relaunch never collides with a surviving old group.
"""

from __future__ import annotations

import http.client
import json
import os
import shlex
import signal
import subprocess
import sys
import threading
import time
import urllib.request

DEPLOY_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(DEPLOY_DIR)

HEAD_READY_MARKERS = ("[stack] runtime up", "[stack] all services up")
WORKER_READY_MARKER = "[stack] worker group up"


def load_manifest(path: str) -> dict:
    with open(path) as handle:
        manifest = json.load(handle)
    manifest.setdefault("python", "python3")
    manifest.setdefault("transport", "ssh")
    manifest.setdefault("store_port", 27027)
    manifest.setdefault("coord_port", 12355)
    manifest.setdefault("env", {})
    manifest.setdefault("workers", [])
    manifest.setdefault("restart_delay", 5)
    head = manifest.get("head")
    if not head or "host" not in head:
        raise SystemExit("manifest needs head.host")
    head.setdefault("bind", "127.0.0.1")
    head.setdefault("workers", 0)
    head.setdefault("data_dir", "lo_data")
    for worker in manifest["workers"]:
        worker.setdefault("processes", 1)
        worker.setdefault("data_dir", "lo_data")
    if manifest["transport"] not in ("ssh", "local"):
        raise SystemExit(f"unknown transport {manifest['transport']!r}")
    sched = manifest.setdefault("sched", {})
    for key in sched:
        if key not in _SCHED_KNOBS:
            raise SystemExit(
                f"unknown sched knob {key!r} (have: "
                f"{', '.join(sorted(_SCHED_KNOBS))})"
            )
        # bool is an int subclass: `"device_width": true` must fail
        # here at manifest load, not crash-loop every machine later
        if (
            not isinstance(sched[key], int)
            or isinstance(sched[key], bool)
            or sched[key] < 1
        ):
            raise SystemExit(f"sched.{key} must be a positive integer")
    dataplane = manifest.setdefault("dataplane", {})
    for key in dataplane:
        if key not in _DATAPLANE_KNOBS:
            raise SystemExit(
                f"unknown dataplane knob {key!r} (have: "
                f"{', '.join(sorted(_DATAPLANE_KNOBS))})"
            )
        value = dataplane[key]
        # same bool-is-int trap as the sched knobs
        if not isinstance(value, int) or isinstance(value, bool):
            raise SystemExit(f"dataplane.{key} must be an integer")
        if key == "devcache_bytes":
            if value < 0:
                raise SystemExit("dataplane.devcache_bytes must be >= 0")
        elif value not in (0, 1):
            raise SystemExit(f"dataplane.{key} must be 0 or 1")
    wire = manifest.setdefault("wire", {})
    for key in wire:
        if key not in _WIRE_KNOBS:
            raise SystemExit(
                f"unknown wire knob {key!r} (have: "
                f"{', '.join(sorted(_WIRE_KNOBS))})"
            )
        value = wire[key]
        if key == "shm_bytes":
            # same bool-is-int trap as the sched knobs: JSON true would
            # stringify to "True" and fail every preflight downstream
            if not isinstance(value, int) or isinstance(value, bool):
                raise SystemExit("wire.shm_bytes must be an integer")
            if value < 0:  # 0 = shared-memory transport off, valid
                raise SystemExit("wire.shm_bytes must be >= 0")
        elif key == "dtype_policy":
            if not isinstance(value, str) or value not in ("f32", "bf16"):
                raise SystemExit("wire.dtype_policy must be f32 or bf16")
    coalescing = manifest.setdefault("coalescing", {})
    for key in coalescing:
        if key not in _COALESCING_KNOBS:
            raise SystemExit(
                f"unknown coalescing knob {key!r} (have: "
                f"{', '.join(sorted(_COALESCING_KNOBS))})"
            )
        value = coalescing[key]
        # same bool-is-int trap as the sched/serving knobs: JSON true
        # would stringify to "True" and fail every preflight downstream
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise SystemExit(f"coalescing.{key} must be a number")
        if key == "max_jobs":
            if not isinstance(value, int) or value < 1:
                raise SystemExit(
                    "coalescing.max_jobs must be an integer >= 1"
                )
        elif value < 0:  # window_ms: 0 = passthrough, still valid
            raise SystemExit("coalescing.window_ms must be >= 0")
    serving = manifest.setdefault("serving", {})
    for key in serving:
        if key not in _SERVING_KNOBS:
            raise SystemExit(
                f"unknown serving knob {key!r} (have: "
                f"{', '.join(sorted(_SERVING_KNOBS))})"
            )
        value = serving[key]
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise SystemExit(f"serving.{key} must be a number")
        if key in ("serve_bytes", "max_batch", "max_rows", "queue_cap") and (
            not isinstance(value, int)
        ):
            raise SystemExit(f"serving.{key} must be an integer")
        if key == "serve_bytes":
            if value < 0:  # 0 = host-only fallback, still valid
                raise SystemExit("serving.serve_bytes must be >= 0")
        elif key == "batch_window_ms":
            if value < 0:
                raise SystemExit("serving.batch_window_ms must be >= 0")
        elif key == "timeout_s":
            if value <= 0:
                raise SystemExit("serving.timeout_s must be > 0")
        elif value < 1:
            raise SystemExit(f"serving.{key} must be >= 1")
    fleet = manifest.setdefault("fleet", {})
    for key in fleet:
        if key not in _FLEET_KNOBS:
            raise SystemExit(
                f"unknown fleet knob {key!r} (have: "
                f"{', '.join(sorted(_FLEET_KNOBS))})"
            )
        value = fleet[key]
        # same bool-is-int trap as the sched knobs: `"replicas": true`
        # would stringify to "True" and fail every preflight downstream
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise SystemExit(f"fleet.{key} must be a number")
        if key in ("replicas", "rf"):
            if not isinstance(value, int) or value < 1:
                raise SystemExit(f"fleet.{key} must be an integer >= 1")
        elif key == "model_qps":
            if value < 0:  # 0 = per-model quota off, still valid
                raise SystemExit("fleet.model_qps must be >= 0")
        elif value <= 0:  # down_s
            raise SystemExit("fleet.down_s must be > 0")
    profiling = manifest.setdefault("profiling", {})
    for key in profiling:
        if key not in _PROFILING_KNOBS:
            raise SystemExit(
                f"unknown profiling knob {key!r} (have: "
                f"{', '.join(sorted(_PROFILING_KNOBS))})"
            )
        value = profiling[key]
        # same bool-is-int trap as the sched knobs
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise SystemExit(f"profiling.{key} must be a number")
        if key == "prof_hz":
            if not isinstance(value, int) or value < 0:
                raise SystemExit(
                    "profiling.prof_hz must be an integer >= 0 "
                    "(0 disables /debug/profile)"
                )
        elif key == "prof_window_s" and value <= 0:
            raise SystemExit("profiling.prof_window_s must be > 0")
    web = manifest.setdefault("web", {})
    for key in web:
        if key not in _WEB_KNOBS:
            raise SystemExit(
                f"unknown web knob {key!r} (have: "
                f"{', '.join(sorted(_WEB_KNOBS))})"
            )
        value = web[key]
        # same bool-is-int trap as the sched knobs: `"async": true`
        # would stringify to "True" and fail every preflight downstream
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise SystemExit(f"web.{key} must be a number")
        if key == "async":
            if value not in (0, 1):
                raise SystemExit("web.async must be 0 or 1")
        elif key == "wait_cap_s":
            if value <= 0:
                raise SystemExit("web.wait_cap_s must be > 0")
        elif not isinstance(value, int) or value < 1:
            raise SystemExit(f"web.{key} must be an integer >= 1")
    resume = manifest.setdefault("resume", {})
    for key in resume:
        if key not in _RESUME_KNOBS:
            raise SystemExit(
                f"unknown resume knob {key!r} (have: "
                f"{', '.join(sorted(_RESUME_KNOBS))})"
            )
        value = resume[key]
        # same bool-is-int trap as the sched knobs: `"enabled": true`
        # would stringify to "True" and fail run.sh's strict-0/1
        # LO_RESUME preflight on every machine
        if isinstance(value, bool) or not isinstance(value, int):
            raise SystemExit(f"resume.{key} must be an integer")
        if key == "enabled":
            if value not in (0, 1):
                raise SystemExit("resume.enabled must be 0 or 1")
        elif value < 1:  # every_segments
            raise SystemExit("resume.every_segments must be >= 1")
    compile_knobs = manifest.setdefault("compile", {})
    for key in compile_knobs:
        if key not in _COMPILE_KNOBS:
            raise SystemExit(
                f"unknown compile knob {key!r} (have: "
                f"{', '.join(sorted(_COMPILE_KNOBS))})"
            )
        value = compile_knobs[key]
        # same bool-is-int trap as the sched knobs: `"aot": true`
        # would stringify to "True" and fail run.sh's strict-0/1
        # LO_AOT preflight on every machine
        if isinstance(value, bool) or not isinstance(value, int):
            raise SystemExit(f"compile.{key} must be an integer")
        if key in ("aot", "publish"):
            if value not in (0, 1):
                raise SystemExit(f"compile.{key} must be 0 or 1")
        elif value < 0:  # max_programs: 0 = enumerate-and-drop-all
            raise SystemExit("compile.max_programs must be >= 0")
    tsdb = manifest.setdefault("tsdb", {})
    for key in tsdb:
        if key not in _TSDB_KNOBS:
            raise SystemExit(
                f"unknown tsdb knob {key!r} (have: "
                f"{', '.join(sorted(_TSDB_KNOBS))})"
            )
        value = tsdb[key]
        if key == "interval_s":
            if (
                isinstance(value, bool)
                or not isinstance(value, (int, float))
                or value <= 0
            ):
                raise SystemExit("tsdb.interval_s must be > 0")
        # bool-is-int trap, same as the sched knobs: `"points": true`
        # would stringify to "True" and fail every preflight downstream
        elif isinstance(value, bool) or not isinstance(value, int) or value < 1:
            raise SystemExit(f"tsdb.{key} must be an integer >= 1")
    slo = manifest.setdefault("slo", {})
    for key in slo:
        if key not in _SLO_KNOBS:
            raise SystemExit(
                f"unknown slo knob {key!r} (have: "
                f"{', '.join(sorted(_SLO_KNOBS))})"
            )
        value = slo[key]
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise SystemExit(f"slo.{key} must be a number")
        if key in ("queue_depth", "replication_lag"):
            if not isinstance(value, int) or value < 1:
                raise SystemExit(f"slo.{key} must be an integer >= 1")
        elif key == "window_s":
            if value <= 0:
                raise SystemExit("slo.window_s must be > 0")
        elif value < 0:  # serve_p99_s / http_5xx_rate: 0 = alert always
            raise SystemExit(f"slo.{key} must be >= 0")
    replication = manifest.setdefault("replication", {})
    for key in replication:
        if key not in _REPLICATION_KNOBS:
            raise SystemExit(
                f"unknown replication knob {key!r} (have: "
                f"{', '.join(sorted(_REPLICATION_KNOBS))})"
            )
    if replication:
        enabled = replication.get("enabled", True)
        if not isinstance(enabled, bool):
            raise SystemExit("replication.enabled must be true/false")
        replication["enabled"] = enabled
        for key in ("follower_port", "arbiter_port"):
            value = replication.setdefault(
                key, 27028 if key == "follower_port" else 27029
            )
            if (
                not isinstance(value, int)
                or isinstance(value, bool)
                or not 1 <= value <= 65535
            ):
                raise SystemExit(f"replication.{key} must be a port number")
        ports = {
            manifest["store_port"],
            replication["follower_port"],
            replication["arbiter_port"],
        }
        if enabled and len(ports) != 3:
            raise SystemExit(
                "replication needs three DISTINCT ports (store_port, "
                "follower_port, arbiter_port)"
            )
        auto = replication.setdefault("auto_promote_s", 5)
        if isinstance(auto, bool) or not isinstance(auto, (int, float)) or auto <= 0:
            raise SystemExit("replication.auto_promote_s must be > 0")
        sync = replication.setdefault("sync_repl", 0)
        if isinstance(sync, bool) or sync not in (0, 1):
            raise SystemExit("replication.sync_repl must be 0 or 1")
    sharding = manifest.setdefault("sharding", {})
    for key in sharding:
        if key not in _SHARDING_KNOBS:
            raise SystemExit(
                f"unknown sharding knob {key!r} (have: "
                f"{', '.join(sorted(_SHARDING_KNOBS))})"
            )
        value = sharding[key]
        if key == "map_ttl_s":
            if (
                isinstance(value, bool)
                or not isinstance(value, (int, float))
                or value < 0
            ):
                raise SystemExit(
                    "sharding.map_ttl_s must be >= 0 (0 = revalidate "
                    "the shard map on every read)"
                )
        # bool-is-int trap, same as the sched knobs: `"shards": true`
        # would stringify to "True" and fail every preflight downstream
        elif (
            isinstance(value, bool)
            or not isinstance(value, int)
            or value < 1
        ):
            raise SystemExit(f"sharding.{key} must be an integer >= 1")
    shards = sharding.get("shards", 1)
    if shards > 1 and _replication_enabled(manifest):
        # each extra group claims store_port + 10*i (+1 follower,
        # +2 arbiter): the meta group's configured pair must not land
        # inside any group's stride window
        group_ports = set()
        for index in range(1, shards):
            base = manifest["store_port"] + 10 * index
            group_ports.update((base, base + 1, base + 2))
        replication = manifest["replication"]
        for key in ("follower_port", "arbiter_port"):
            if replication[key] in group_ports:
                raise SystemExit(
                    f"replication.{key} collides with a shard group "
                    "port (groups claim store_port + 10*i .. +2)"
                )
    return manifest


# manifest sched.<knob> -> the env var every machine receives
_SCHED_KNOBS = {
    "job_workers": "LO_JOB_WORKERS",
    "device_width": "LO_SCHED_DEVICE_WIDTH",
    "queue_cap": "LO_SCHED_QUEUE_CAP",
}

# manifest dataplane.<knob> -> the env var every machine receives
# (docs/dataplane.md). Cluster-wide like the sched knobs: a device
# cache sized differently per host would skew per-host HBM headroom.
_DATAPLANE_KNOBS = {
    "devcache_bytes": "LO_DEVCACHE_BYTES",
    "store_compress": "LO_STORE_COMPRESS",
    "write_overlap": "LO_WRITE_OVERLAP",
}

# manifest wire.<knob> -> the env var every machine receives
# (docs/dataplane.md). Cluster-wide NON-NEGOTIABLY for dtype_policy:
# it is part of every devcache key and of SPMD dispatch shapes, so a
# per-host skew would desynchronize multi-host dispatch. shm_bytes
# rides along for symmetric co-located topologies.
_WIRE_KNOBS = {
    "shm_bytes": "LO_SHM_BYTES",
    "dtype_policy": "LO_DTYPE_POLICY",
}

# manifest coalescing.<knob> -> the env var every machine receives
# (docs/scheduler.md). Cluster-wide: coalescing keys include the mesh
# signature, and a per-host window skew would make "the same flood"
# fuse on one machine and serialize on another.
_COALESCING_KNOBS = {
    "window_ms": "LO_COALESCE_WINDOW_MS",
    "max_jobs": "LO_COALESCE_MAX_JOBS",
}

# manifest serving.<knob> -> the env var every machine receives
# (docs/serving.md). Only the head serves REST today, but the knobs go
# cluster-wide like the others: a failover promotion or a future
# per-host serving lane must not inherit silently different budgets.
_SERVING_KNOBS = {
    "serve_bytes": "LO_SERVE_BYTES",
    "batch_window_ms": "LO_SERVE_BATCH_WINDOW_MS",
    "max_batch": "LO_SERVE_MAX_BATCH",
    "max_rows": "LO_SERVE_MAX_ROWS",
    "queue_cap": "LO_SERVE_QUEUE_CAP",
    "timeout_s": "LO_SERVE_TIMEOUT_S",
}

# manifest fleet.<knob> -> the env var every machine receives
# (docs/serving.md "Fleet"). Plumbed cluster-wide like the serving
# knobs so a promoted head inherits the same fleet shape, but only a
# single-host stack ACTS on LO_FLEET_REPLICAS — stack.py's multi-host
# topology logs it as ignored (the coordinator serves predicts itself).
_FLEET_KNOBS = {
    "replicas": "LO_FLEET_REPLICAS",
    "rf": "LO_FLEET_RF",
    "model_qps": "LO_FLEET_MODEL_QPS",
    "down_s": "LO_FLEET_DOWN_S",
}

# manifest profiling.<knob> -> the env var every machine receives
# (docs/profiling.md). Cluster-wide: a stall diagnosis must be able to
# hit /debug/profile on ANY member, so no machine may silently run with
# the profiler knocked out or a different window cap.
_PROFILING_KNOBS = {
    "prof_hz": "LO_PROF_HZ",
    "prof_window_s": "LO_PROF_WINDOW_S",
}

# manifest web.<knob> -> the env var every machine receives
# (docs/web.md). Cluster-wide like the serving knobs: a failover
# promotion must not flip a machine between the event-loop core and
# the threaded escape hatch, or change how many waiters it can hold.
_WEB_KNOBS = {
    "async": "LO_WEB_ASYNC",
    "handlers": "LO_WEB_HANDLERS",
    "max_conns": "LO_WEB_MAX_CONNS",
    "wait_cap_s": "LO_WEB_WAIT_CAP_S",
}

# manifest resume.<knob> -> the env var every machine receives
# (docs/robustness.md). Cluster-wide: recovery decisions must be
# uniform — a machine with resume off would fail the very jobs its
# peers checkpoint for, and a skewed cadence skews the re-done-work
# bound the chaos drill asserts on.
_RESUME_KNOBS = {
    "enabled": "LO_RESUME",
    "every_segments": "LO_RESUME_EVERY_SEGMENTS",
}

# manifest compile.<knob> -> the env var every machine receives
# (docs/compile.md). Cluster-wide: the fleet executable cache only
# pays off when every member enumerates the SAME manifest — a member
# with a different program cap would publish a different grid and
# peers would miss on programs they expected to fetch hot.
_COMPILE_KNOBS = {
    "aot": "LO_AOT",
    "max_programs": "LO_AOT_MAX_PROGRAMS",
    "publish": "LO_AOT_PUBLISH",
}

# manifest tsdb.<knob> -> the env var every machine receives
# (docs/observability.md). Cluster-wide: the retention cap and scrape
# cadence shape ONE shared ring in the head store, and trace_ring
# bounds every member's span export buffer the stitcher drains —
# a member with a smaller ring would silently drop the oldest spans
# out of stitched traces.
_TSDB_KNOBS = {
    "points": "LO_TSDB_POINTS",
    "interval_s": "LO_METRICS_INTERVAL_S",
    "trace_ring": "LO_TRACE_RING",
}

# manifest slo.<knob> -> the env var every machine receives
# (docs/observability.md). Cluster-wide: burn verdicts must come from
# ONE threshold set no matter which member's /debug/slo is asked.
_SLO_KNOBS = {
    "window_s": "LO_SLO_WINDOW_S",
    "serve_p99_s": "LO_SLO_SERVE_P99_S",
    "http_5xx_rate": "LO_SLO_5XX_RATE",
    "queue_depth": "LO_SLO_QUEUE_DEPTH",
    "replication_lag": "LO_SLO_REPL_LAG",
}

# manifest sharding.<knob> -> the env var every machine receives
# (docs/dataplane.md). Cluster-wide NON-NEGOTIABLY: shards and
# stripe_rows define the hash-ring placement every client computes
# locally, so a per-host skew would route the same _id to different
# groups; the shard-map doc pins them and clients refuse a mismatch.
_SHARDING_KNOBS = {
    "shards": "LO_SHARDS",
    "stripe_rows": "LO_SHARD_STRIPE_ROWS",
    "map_ttl_s": "LO_SHARDMAP_TTL_S",
}

# manifest replication.<knob> (docs/replication.md); the head machine
# runs the whole store plane, every machine's LO_STORE_URL names the
# primary AND the follower for client-side failover
_REPLICATION_KNOBS = (
    "enabled",
    "follower_port",
    "arbiter_port",
    "auto_promote_s",
    "sync_repl",
)


def _replication_enabled(manifest: dict) -> bool:
    replication = manifest.get("replication") or {}
    return bool(replication) and replication.get("enabled", True)


def total_processes(manifest: dict) -> int:
    return (
        1
        + manifest["head"]["workers"]
        + sum(w["processes"] for w in manifest["workers"])
    )


def machine_plans(manifest: dict) -> list[dict]:
    """Per-machine launch plans: name, env, ssh target, data_dir."""
    head = manifest["head"]
    total = total_processes(manifest)
    store_url = f"http://{head['host']}:{manifest['store_port']}"
    replication = manifest.get("replication") or {}
    if _replication_enabled(manifest):
        # workers and clients fail over between the pair client-side
        store_url += (
            f",http://{head['host']}:{replication['follower_port']}"
        )
    shards = manifest.get("sharding", {}).get("shards", 1)
    if shards > 1:
        # one `;`-separated segment per store group (core/shardmap.py):
        # group i lives at store_port + 10*i, its follower one above —
        # the exact ports stack.py's sharded store plane binds
        groups = [store_url]
        for index in range(1, shards):
            base = manifest["store_port"] + 10 * index
            group = f"http://{head['host']}:{base}"
            if _replication_enabled(manifest):
                group += f",http://{head['host']}:{base + 1}"
            groups.append(group)
        store_url = ";".join(groups)
    coordinator = f"{head['host']}:{manifest['coord_port']}"
    shared = dict(manifest["env"])
    shared["LO_TOTAL_PROCESSES"] = str(total)
    if manifest["transport"] == "ssh":
        # the ssh channel is the launcher's lifeline: EOF on it tells
        # the remote stack its driver is gone (see plan_command)
        shared["LO_STACK_EXIT_ON_STDIN_EOF"] = "1"
    # scheduler knobs apply cluster-wide: every machine's services
    # admit through the same widths/caps (docs/scheduler.md). .get():
    # callers may hand-build plans without load_manifest's defaults.
    for knob, env_var in _SCHED_KNOBS.items():
        if knob in manifest.get("sched", {}):
            shared[env_var] = str(manifest["sched"][knob])
    for knob, env_var in _DATAPLANE_KNOBS.items():
        if knob in manifest.get("dataplane", {}):
            shared[env_var] = str(manifest["dataplane"][knob])
    for knob, env_var in _WIRE_KNOBS.items():
        if knob in manifest.get("wire", {}):
            shared[env_var] = str(manifest["wire"][knob])
    for knob, env_var in _COALESCING_KNOBS.items():
        if knob in manifest.get("coalescing", {}):
            shared[env_var] = str(manifest["coalescing"][knob])
    for knob, env_var in _SERVING_KNOBS.items():
        if knob in manifest.get("serving", {}):
            shared[env_var] = str(manifest["serving"][knob])
    for knob, env_var in _FLEET_KNOBS.items():
        if knob in manifest.get("fleet", {}):
            shared[env_var] = str(manifest["fleet"][knob])
    for knob, env_var in _PROFILING_KNOBS.items():
        if knob in manifest.get("profiling", {}):
            shared[env_var] = str(manifest["profiling"][knob])
    for knob, env_var in _WEB_KNOBS.items():
        if knob in manifest.get("web", {}):
            shared[env_var] = str(manifest["web"][knob])
    for knob, env_var in _RESUME_KNOBS.items():
        if knob in manifest.get("resume", {}):
            shared[env_var] = str(manifest["resume"][knob])
    for knob, env_var in _COMPILE_KNOBS.items():
        if knob in manifest.get("compile", {}):
            shared[env_var] = str(manifest["compile"][knob])
    for knob, env_var in _TSDB_KNOBS.items():
        if knob in manifest.get("tsdb", {}):
            shared[env_var] = str(manifest["tsdb"][knob])
    for knob, env_var in _SLO_KNOBS.items():
        if knob in manifest.get("slo", {}):
            shared[env_var] = str(manifest["slo"][knob])
    for knob, env_var in _SHARDING_KNOBS.items():
        if knob in manifest.get("sharding", {}):
            shared[env_var] = str(manifest["sharding"][knob])
    # the driver scrapes every member centrally (up()'s scrape loop)
    # and pushes into the head store's TSDB ring, so the per-process
    # fallback collectors stay off; an explicit manifest env wins
    shared.setdefault("LO_TSDB_COLLECT", "0")
    # the fan-out list GET /traces/<cid> stitches across: the head
    # store plus the head's seven services (worker machines have no
    # REST surface to drain)
    plane = [f"http://{head['host']}:{manifest['store_port']}"] + [
        f"http://{head['host']}:{port}" for port in SERVICE_PORTS
    ]
    shared.setdefault("LO_PLANE_MEMBERS", ",".join(plane))
    if "models_dir" in manifest:
        shared["LO_MODELS_DIR"] = manifest["models_dir"]

    head_env = dict(shared)
    head_env.update(
        {
            "LO_HOST": head["bind"],
            "LO_STORE_PORT": str(manifest["store_port"]),
            "LO_COORD_PORT": str(manifest["coord_port"]),
            "LO_WORKERS": str(head["workers"]),
            "LO_DATA_DIR": head["data_dir"],
        }
    )
    if _replication_enabled(manifest):
        head_env.update(
            {
                "LO_REPLICATION": "1",
                "LO_FOLLOWER_PORT": str(replication["follower_port"]),
                "LO_ARBITER_PORT": str(replication["arbiter_port"]),
                "LO_AUTO_PROMOTE_S": str(replication["auto_promote_s"]),
            }
        )
        if replication.get("sync_repl"):
            head_env["LO_STORE_SYNC_REPL"] = "1"
    plans = [
        {
            "name": "head",
            "ssh": head.get("ssh"),
            "host": head["host"],
            "env": head_env,
            "ready_markers": HEAD_READY_MARKERS,
        }
    ]
    next_process_id = 1 + head["workers"]
    for index, worker in enumerate(manifest["workers"]):
        env = dict(shared)
        env.update(
            {
                "LO_PROCESS_BASE": str(next_process_id),
                "LO_WORKERS": str(worker["processes"]),
                "LO_COORDINATOR": coordinator,
                "LO_STORE_URL": store_url,
                "LO_DATA_DIR": worker["data_dir"],
            }
        )
        plans.append(
            {
                "name": f"machine{index + 1}",
                "ssh": worker.get("ssh"),
                "host": worker["host"],
                "env": env,
                "ready_markers": (WORKER_READY_MARKER,),
            }
        )
        next_process_id += worker["processes"]
    return plans


def plan_command(manifest: dict, plan: dict) -> list[str]:
    """argv for one machine's stack, through the configured transport."""
    if manifest["transport"] == "local":
        return [sys.executable, os.path.join(DEPLOY_DIR, "stack.py")]
    repo = manifest.get("repo", REPO_ROOT)
    env_prefix = " ".join(
        f"{key}={shlex.quote(value)}" for key, value in plan["env"].items()
    )
    remote = (
        f"cd {shlex.quote(repo)} && exec env {env_prefix} "
        f"{manifest['python']} deploy/stack.py"
    )
    # fall back to the machine's manifest host (the bind address in env
    # is 0.0.0.0/absent for workers — not an ssh target)
    target = plan["ssh"] or plan["host"]
    return ["ssh", "-o", "BatchMode=yes", target, remote]


class Machine:
    """One machine's supervised stack process (local or over ssh)."""

    def __init__(self, manifest: dict, plan: dict, log):
        self.manifest = manifest
        self.plan = plan
        self.log = log
        self.proc: subprocess.Popen | None = None
        self.ready = threading.Event()

    def start(self) -> None:
        self.ready.clear()
        env = None
        if self.manifest["transport"] == "local":
            env = dict(os.environ)
            env.update(self.plan["env"])
            env["PYTHONPATH"] = (
                REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
            )
            env["PYTHONUNBUFFERED"] = "1"
        # stdin is a PIPE the driver holds open for the machine's
        # lifetime: the remote stack watches the ssh channel's stdin for
        # EOF (LO_STACK_EXIT_ON_STDIN_EOF) — an inherited stdin would
        # hand it /dev/null's immediate EOF under nohup/systemd/CI and
        # tear every stack down at bring-up (or let several ssh clients
        # race for the operator's terminal keystrokes).
        self.proc = subprocess.Popen(
            plan_command(self.manifest, self.plan),
            env=env,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO_ROOT,
        )
        threading.Thread(target=self._pump, daemon=True).start()

    def _pump(self) -> None:
        proc = self.proc
        for line in proc.stdout:
            if any(marker in line for marker in self.plan["ready_markers"]):
                self.ready.set()
            self.log(f"[{self.plan['name']}] {line.rstrip()}")

    def poll(self):
        return self.proc.poll() if self.proc else None

    def terminate(self) -> None:
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()

    def _remote_kill(self) -> None:
        """Kill the REMOTE stack process group explicitly: terminating
        the local ``ssh -o BatchMode=yes`` client does NOT signal the
        remote side (no tty → no SIGHUP), so without this every cluster
        restart stranded the previous stack.py — and its whole runtime
        group — on the worker machine."""
        if self.manifest["transport"] != "ssh":
            return
        target = self.plan["ssh"] or self.plan["host"]
        try:
            subprocess.run(
                [
                    "ssh", "-o", "BatchMode=yes",
                    "-o", "ConnectTimeout=5",
                    target,
                    # the launched command is `exec ... python deploy/stack.py`
                    # (plan_command); match it, not every python on the box
                    "pkill -f deploy/stack.py || true",
                ],
                timeout=15,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                check=False,
            )
        except (OSError, subprocess.TimeoutExpired):
            pass  # machine unreachable: nothing left to kill from here

    def stop(self, timeout: float = 15.0) -> None:
        # closing stdin FIRST is the graceful path: the remote stack's
        # stdin-EOF watchdog shuts the whole process tree down cleanly;
        # terminate + the explicit remote pkill remain the backstop
        if self.proc is not None and self.proc.stdin is not None:
            try:
                self.proc.stdin.close()
            except OSError:
                pass
        self.terminate()
        self._remote_kill()
        if self.proc is not None:
            try:
                self.proc.wait(timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()


# services on their reference ports (learningorchestra_tpu/services);
# the driver stays import-free of the package so it runs on machines
# with only the deploy/ tree checked out. The names label the TSDB
# samples the driver pushes into the store (POST /metrics/ingest).
SERVICE_NAMES = {
    5000: "database_api",
    5001: "projection",
    5002: "model_builder",
    5003: "data_type_handler",
    5004: "histogram",
    5005: "tsne",
    5006: "pca",
}
SERVICE_PORTS = tuple(sorted(SERVICE_NAMES))

# the families the cluster summary line aggregates across members
SUMMARY_FAMILIES = (
    "lo_http_requests_total",
    "lo_http_requests_in_flight",
    "lo_jobs_running",
    "lo_jobs_total",
    "lo_spmd_jobs_total",
    "lo_spmd_watchdog_trips_total",
    "lo_store_collections",
    "lo_store_wal_bytes",
    "lo_store_spill_bytes",
    "lo_jitcache_persistent_hits",
    "lo_jitcache_persistent_misses",
)


def parse_prometheus(text: str, strict: bool = False) -> dict:
    """Family → summed sample value (labels collapsed; histogram bucket
    samples skipped — the driver's summary wants totals, not shape).

    ``strict=True`` raises ValueError on a non-comment line that is not
    a parseable sample: the per-member scrape uses it so a truncated or
    corrupted body surfaces as a counted skip, never as silently-wrong
    totals folded into the cluster summary."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            name_part, value_part = line.rsplit(" ", 1)
            value = float(value_part)
        except ValueError as error:
            if strict:
                raise ValueError(
                    f"unparseable sample line {line!r}"
                ) from error
            continue
        family = name_part.split("{", 1)[0]
        if family.endswith("_bucket"):
            continue
        out[family] = out.get(family, 0.0) + value
    return out


def scrape_member_metrics(urls: list[str]) -> tuple[dict, dict]:
    """Scrape each member's ``/metrics``; unreachable members (worker
    machines have no REST surface, loopback-bound services aren't
    visible from the driver) are skipped, not errors. A member that
    answers with a malformed or truncated body (mid-restart, a proxy
    error page, a cut connection) is ALSO a per-member skip — counted
    in ``_malformed`` for the summary line, never a scrape-thread
    crash. Returns ``(totals, texts)``: the summed families plus each
    healthy member's raw exposition text keyed by URL, for the central
    TSDB ingest push."""
    totals: dict[str, float] = {}
    texts: dict[str, str] = {}
    reachable = 0
    malformed = 0
    for url in urls:
        try:
            with urllib.request.urlopen(url + "/metrics", timeout=3) as resp:
                raw = resp.read()
        except (OSError, http.client.HTTPException):
            # http.client.HTTPException covers IncompleteRead: a member
            # dying mid-response truncates the body during read()
            continue
        try:
            text = raw.decode()
            families = parse_prometheus(text, strict=True)
        except (UnicodeDecodeError, ValueError):
            malformed += 1
            continue
        reachable += 1
        texts[url] = text
        for family, value in families.items():
            totals[family] = totals.get(family, 0.0) + value
    totals["_members"] = reachable
    totals["_malformed"] = malformed
    return totals, texts


def push_member_metrics(store_url: str, texts: dict, log=print) -> int:
    """Push each scraped member's raw exposition text into the head
    store's TSDB ring (``POST /metrics/ingest``) — the cluster-mode
    replacement for every runner's in-process fallback collector
    (which the driver disables via LO_TSDB_COLLECT=0). The store side
    parses and delta-compresses; the driver stays import-free."""
    pushed = 0
    for url, text in texts.items():
        instance = url.split("//", 1)[-1]
        port = instance.rsplit(":", 1)[-1]
        service = SERVICE_NAMES.get(
            int(port) if port.isdigit() else -1, "store"
        )
        body = json.dumps(
            {"instance": instance, "service": service, "text": text}
        ).encode()
        request = urllib.request.Request(
            store_url + "/metrics/ingest",
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(request, timeout=3) as resp:
                if resp.status == 200:
                    pushed += 1
        except (OSError, http.client.HTTPException) as error:
            log(f"[cluster] metrics ingest push failed for {instance}: "
                f"{error}")
    return pushed


def metrics_summary_line(totals: dict) -> str:
    parts = [f"members={int(totals.get('_members', 0))}"]
    if totals.get("_malformed"):
        parts.append(f"malformed={int(totals['_malformed'])}")
    for family in SUMMARY_FAMILIES:
        if family in totals:
            value = totals[family]
            short = family[len("lo_"):]
            parts.append(
                f"{short}={int(value) if value == int(value) else value}"
            )
    return "[cluster] metrics: " + " ".join(parts)


def wait_store_health(url: str, timeout: float) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url + "/health", timeout=2) as resp:
                if resp.status == 200:
                    return
        except OSError:
            pass
        time.sleep(0.3)
    raise TimeoutError(f"store not healthy at {url} within {timeout}s")


def up(manifest: dict, log=print) -> int:
    plans = machine_plans(manifest)
    machines = [Machine(manifest, plan, log) for plan in plans]
    head = machines[0]
    store_url = (
        f"http://{manifest['head']['host']}:{manifest['store_port']}"
    )
    stopping = threading.Event()
    for signum in (signal.SIGTERM, signal.SIGINT):
        signal.signal(signum, lambda *_: stopping.set())

    max_restarts = manifest.get("max_cluster_restarts")
    restart_delay = manifest["restart_delay"]
    restarts = 0

    def launch_all() -> None:
        head.start()
        # the dockerize -wait gate: workers join only once the head's
        # store answers (their stacks would otherwise crash-loop on a
        # half-up head)
        wait_store_health(store_url, 180)
        log(f"[cluster] store healthy at {store_url}")
        for machine in machines[1:]:
            machine.start()
        deadline = time.time() + 600
        for machine in machines:
            remaining = max(1.0, deadline - time.time())
            if not machine.ready.wait(remaining):
                raise TimeoutError(
                    f"{machine.plan['name']} not ready within budget"
                )
        state = {
            "head": manifest["head"]["host"],
            "store_url": store_url,
            "total_processes": total_processes(manifest),
            "machines": [m.plan["name"] for m in machines],
        }
        with open("cluster_state.json", "w") as handle:
            json.dump(state, handle)
        log(
            f"[cluster] up: {len(machines)} machine(s), "
            f"{total_processes(manifest)} runtime process(es)"
        )

    def stop_all() -> None:
        for machine in machines:
            machine.terminate()
        for machine in machines:
            machine.stop()

    # the head's scrape surface: store server + the seven services (the
    # latter answer only when LO_HOST exposes them beyond loopback — the
    # scraper skips silently otherwise). On its OWN thread: a member
    # dropping packets makes each URL eat the full connect timeout, and
    # ~24 s of scrape stall inside the supervision loop would delay
    # dead-machine detection — and the whole-cluster relaunch — by that
    # much every interval.
    scrape_urls = [store_url] + [
        f"http://{manifest['head']['host']}:{port}" for port in SERVICE_PORTS
    ]
    scrape_interval = float(os.environ.get("LO_METRICS_INTERVAL_S", "60"))

    def scrape_loop() -> None:
        while not stopping.wait(scrape_interval):
            totals, texts = scrape_member_metrics(scrape_urls)
            if totals.get("_members") or totals.get("_malformed"):
                log(metrics_summary_line(totals))
            if texts:
                # retention lives IN the store: each healthy member's
                # raw text lands in the head store's __lo_metrics__
                # ring, where /metrics/history and the SLO engine read
                push_member_metrics(store_url, texts, log)

    if scrape_interval > 0:
        threading.Thread(
            target=scrape_loop, name="metrics-scrape", daemon=True
        ).start()

    exit_code = 0
    try:
        launch_all()
        while not stopping.is_set():
            time.sleep(0.5)
            dead = [m for m in machines if m.poll() is not None]
            if not dead:
                continue
            if max_restarts is not None and restarts >= max_restarts:
                log(
                    f"[cluster] {[m.plan['name'] for m in dead]} exited "
                    f"after {restarts} cluster restarts; giving up"
                )
                exit_code = 1
                break
            restarts += 1
            log(
                f"[cluster] {[m.plan['name'] for m in dead]} exited — "
                "restarting the whole cluster (a lost member poisons "
                f"the collective runtime), #{restarts} in {restart_delay}s"
            )
            stop_all()
            time.sleep(restart_delay)
            try:
                launch_all()
            except Exception as error:  # noqa: BLE001
                # a slow recovery (long WAL replay, stalled member) is a
                # restartable condition, not the end of supervision: the
                # loop sees the dead members next tick and retries under
                # the same max_restarts budget
                log(f"[cluster] relaunch failed ({error}); will retry")
    finally:
        log("[cluster] shutting down")
        stop_all()
    return exit_code


def render(manifest: dict) -> None:
    for plan in machine_plans(manifest):
        print(f"# {plan['name']}")
        if manifest["transport"] == "local":
            env = " ".join(
                f"{k}={shlex.quote(v)}" for k, v in plan["env"].items()
            )
            print(f"env {env} {sys.executable} deploy/stack.py")
        else:
            print(" ".join(shlex.quote(a) for a in plan_command(manifest, plan)))
        print()


def main() -> int:
    if len(sys.argv) != 3 or sys.argv[1] not in ("up", "render"):
        print(__doc__)
        return 2
    manifest = load_manifest(sys.argv[2])
    if sys.argv[1] == "render":
        render(manifest)
        return 0
    return up(manifest)


if __name__ == "__main__":
    sys.exit(main())
