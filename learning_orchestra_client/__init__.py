"""Drop-in replacement for the reference's PyPI client package.

The reference ships `learning-orchestra-client` (reference:
learning_orchestra_client/setup.py:1-22, __init__.py:1-370); user
scripts begin with ``from learning_orchestra_client import *`` and use
``Context`` plus the per-service classes. This shim re-exports the
byte-compatible client (learningorchestra_tpu/client.py — same class
names, banners, ports, poll loop, including the reference's
``AsyncronousWait``/``READE`` spellings), so the documented walkthrough
runs against the TPU framework with only the cluster IP changed.

Beyond the reference surface, ``Model`` additionally exposes the online
serving lane (``Model.predict(model_name, rows)`` /
``Model.list_models()`` → ``POST /models/<name>/predict`` — synchronous
labels + probabilities, no polling; docs/serving.md) and hyperparameter
sweeps (``Model.sweep(..., grid, sweep_name)`` → ``POST /models/sweep``
— a λ/depth grid fitted as ONE fused device dispatch, per-point metrics
plus the argmax checkpoint; docs/model_builder.md). ``Model.predict``
also rides a replicated serving fleet transparently: pointed at a fleet
router URL (``Context("host:5007")``), it detects the router by its
``/health`` feature probe and honors per-model-quota 429 + Retry-After
(docs/serving.md "Fleet").
"""

from learningorchestra_tpu.client import (  # noqa: F401
    AsyncronousWait,
    Context,
    DatabaseApi,
    DataTypeHandler,
    Histogram,
    Model,
    Pca,
    Projection,
    ResponseTreat,
    Tsne,
)

__all__ = [
    "AsyncronousWait",
    "Context",
    "DatabaseApi",
    "DataTypeHandler",
    "Histogram",
    "Model",
    "Pca",
    "Projection",
    "ResponseTreat",
    "Tsne",
]
