"""Assemble SCALE_r05.json from this round's recorded scale runs.

Each section is a verbatim scale.py output captured during round 5 on
the bench host (one axon-tunneled v5e + 1 CPU core, 125 GB RAM), plus
the honest context a single number cannot carry: per-run variance on
the shared tunnel is 2-10x (see notes), so phase walls are evidence of
behavior, not precise costs.

Usage: python make_scale_record.py <product_json> <pipeline_json>
       [northstar_json] > SCALE_r05.json
"""

import json
import sys


def load(path):
    with open(path) as handle:
        return json.load(handle)


def main() -> None:
    record = {
        "round": 5,
        "product_10m": load(sys.argv[1]),
        "pipeline_100m_outofcore": load(sys.argv[2]),
        "notes": {
            "variance": (
                "Phase wall-clocks on the axon-tunneled chip vary 2-10x "
                "run to run (an NB fit measured at 10M rows: 4.5 s in an "
                "isolated process vs 11-115 s inside full-suite runs; a "
                "scalar fetch RTT measured 2.2 s). The product_10m "
                "section is a single run, not a best-of; treat phase "
                "splits as behavioral evidence."
            ),
            "outofcore": (
                "pipeline_100m_outofcore ran with LO_SPILL_BYTES=2e9 — a "
                "2 GB column-payload RAM budget against ~30 GB stored "
                "(both collections): the store spilled column payloads "
                "to disk-backed mappings and streamed ingest appends "
                "straight to the files. A dataset that cannot fit in "
                "RAM at all is not demonstrable on this host (125 GB "
                "RAM, 79 GB free disk: disk is the smaller resource), "
                "so the budget stands in: stored bytes exceed the "
                "configured RAM budget 15x."
            ),
            "compile": (
                "Padded shapes snap to a quarter-octave grid "
                "(LO_SHAPE_BUCKETS), so any two dataset sizes within "
                "25% share every compiled program; cache hits/misses "
                "are recorded per run under jit_cache. One caveat the "
                "counters exposed: a fully-warm 10M run (55 hits, 0 "
                "misses) still recorded ~247 s of backend-compile time "
                "— the axon serving layer pays a per-executable load "
                "cost on cache HITS that the client-side persistent "
                "cache cannot remove, and it scales with congestion."
            ),
        },
    }
    if len(sys.argv) > 3:
        record["northstar_100m"] = load(sys.argv[3])
    print(json.dumps(record, indent=1))


if __name__ == "__main__":
    main()
