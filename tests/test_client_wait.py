"""Client-side resilience: the SDK's wait path across a service restart.

The crash-resume tentpole makes the SERVER survive a kill -9 mid-build;
these tests pin the CLIENT half of that story — a long-poll that dies
with the server must reconnect (seeded-jitter backoff, one capability
re-probe) and resolve against the restarted server, not hang or crash
the caller. Pure unit tests: requests and time are scripted, no HTTP.
"""

import pytest
import requests as requests_lib

import learningorchestra_tpu.client as lo_client
from learningorchestra_tpu.client import AsyncronousWait


class _Response:
    def __init__(self, status_code=200, body=None, headers=None):
        self.status_code = status_code
        self._body = body if body is not None else {}
        self.headers = headers or {}

    def json(self):
        if isinstance(self._body, Exception):
            raise self._body
        return self._body


def _health(job_wait=True):
    return _Response(200, {"status": "ok", "job_wait": job_wait})


def _terminal(state="finished"):
    return _Response(200, {"result": {"state": state}})


class _Script:
    """Scripted requests.get: pops the next step; a step that is an
    exception instance raises (the connection reset)."""

    def __init__(self, steps):
        self.steps = list(steps)
        self.calls = []

    def __call__(self, url, params=None, timeout=None, **kwargs):
        self.calls.append({"url": url, "params": params, "timeout": timeout})
        step = self.steps.pop(0)
        if isinstance(step, Exception):
            raise step
        return step


class _Reader:
    url_base = "http://127.0.0.1:5000/files"

    def _url(self, filename):
        return f"{self.url_base}/{filename}"


@pytest.fixture()
def waiter(monkeypatch):
    AsyncronousWait._push_probe_cache.clear()
    sleeps = []
    monkeypatch.setattr(lo_client.time, "sleep", sleeps.append)
    instance = AsyncronousWait()
    instance.recorded_sleeps = sleeps
    yield instance
    AsyncronousWait._push_probe_cache.clear()


def _run_push(monkeypatch, waiter, steps):
    script = _Script(steps)
    monkeypatch.setattr(lo_client.requests, "get", script)
    outcome = waiter._wait_push(_Reader(), "titanic_test")
    return outcome, script


class TestWaitPushReconnect:
    def test_connection_reset_reconnects_and_resolves(
        self, monkeypatch, waiter
    ):
        # park → reset (server killed) → re-probe health → park again →
        # the RESUMED job finishes and resolves the wait
        outcome, script = _run_push(
            monkeypatch,
            waiter,
            [
                requests_lib.ConnectionError("peer reset"),
                _health(job_wait=True),
                _terminal("finished"),
            ],
        )
        assert outcome is True
        # backed off once, with a bounded delay
        assert len(waiter.recorded_sleeps) == 1
        assert 0 < waiter.recorded_sleeps[0] <= AsyncronousWait.MAX_WAIT_TIME
        # call 2 was the health RE-probe (the cached capability was
        # invalidated — the restarted server may be an older build)
        assert script.calls[1]["url"].endswith("/health")
        assert script.calls[2]["url"].endswith("/jobs/titanic_test/wait")

    def test_restart_without_push_falls_back_to_polling(
        self, monkeypatch, waiter
    ):
        outcome, script = _run_push(
            monkeypatch,
            waiter,
            [
                requests_lib.ConnectionError("peer reset"),
                _health(job_wait=False),
            ],
        )
        assert outcome is False  # wait() then polls metadata

    def test_unreachable_after_reset_falls_back(self, monkeypatch, waiter):
        outcome, _ = _run_push(
            monkeypatch,
            waiter,
            [
                requests_lib.ConnectionError("peer reset"),
                requests_lib.ConnectionError("still down"),
            ],
        )
        assert outcome is False

    def test_repeated_resets_back_off_increasingly(self, monkeypatch, waiter):
        outcome, _ = _run_push(
            monkeypatch,
            waiter,
            [
                requests_lib.ConnectionError("reset 1"),
                _health(job_wait=True),
                requests_lib.ConnectionError("reset 2"),
                _health(job_wait=True),
                _terminal("failed"),
            ],
        )
        assert outcome is True  # failed is terminal too: wait resolves
        assert len(waiter.recorded_sleeps) == 2

    def test_reconnect_resets_the_backoff_clock(self, monkeypatch, waiter):
        # reset → reconnect → long-poll timeout (job alive) → reset
        # again: attempt restarts at 1, so the second reset's delay is
        # the FIRST-attempt delay again, not a deeper backoff
        outcome, _ = _run_push(
            monkeypatch,
            waiter,
            [
                requests_lib.ConnectionError("reset 1"),
                _health(job_wait=True),
                _Response(200, {"result": "timeout"}),
                requests_lib.ConnectionError("reset 2"),
                _health(job_wait=True),
                _terminal(),
            ],
        )
        assert outcome is True
        assert waiter.recorded_sleeps[0] == waiter.recorded_sleeps[1]

    def test_404_still_means_poll_fallback(self, monkeypatch, waiter):
        outcome, _ = _run_push(
            monkeypatch, waiter, [_Response(404, {"result": "not_found"})]
        )
        assert outcome is False

    def test_429_honors_retry_after_without_reprobe(
        self, monkeypatch, waiter
    ):
        outcome, script = _run_push(
            monkeypatch,
            waiter,
            [
                _Response(429, {}, headers={"Retry-After": "0.2"}),
                _terminal(),
            ],
        )
        assert outcome is True
        assert waiter.recorded_sleeps == [0.2]
        # backpressure is not a restart: no health re-probe in between
        assert all("/health" not in c["url"] for c in script.calls)

    def test_every_request_carries_a_timeout(self, monkeypatch, waiter):
        # LO206's contract, end to end: a wait that outlives a dead
        # server by one socket timeout instead of forever
        _, script = _run_push(
            monkeypatch,
            waiter,
            [
                requests_lib.ConnectionError("peer reset"),
                _health(job_wait=True),
                _terminal(),
            ],
        )
        assert all(c["timeout"] is not None for c in script.calls)
