"""Zero-copy columnar wire: frame v2, shm ring, negotiation, dtype policy.

The contract under test (docs/dataplane.md):

- golden encode/decode round-trips across every column kind and mask
  combination, v2 bit-identical to v1;
- v2 decode hands out 64-byte-aligned read-only VIEWS over one backing
  buffer (one FrameOwner per frame), and a caller mutating a decoded
  column copies first — the pinned frame can never be corrupted;
- v1↔v2 negotiation against a live store server (old client, old
  server, both simulated through the Accept header / wire_v2 flag);
- the shared-memory ring serves co-located reads without an HTTP body,
  and falls back to the body transparently when the segment is absent;
- a full histogram→build→predict pipeline returns identical results
  over every transport.
"""

from __future__ import annotations

import numpy as np
import pytest

from learningorchestra_tpu.core import shmring, wire
from learningorchestra_tpu.core import devcache
from learningorchestra_tpu.core.columns import MISSING, Column
from learningorchestra_tpu.core.store import InMemoryStore
from learningorchestra_tpu.core.store_service import (
    RemoteStore,
    create_store_app,
)
from learningorchestra_tpu.utils.web import ServerThread


def same_cells(a: list, b: list) -> bool:
    """Cell equality with NaN == NaN (bit-preservation is the contract;
    Python's ``==`` would call equal NaNs different)."""
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if isinstance(x, float) and isinstance(y, float):
            if x != y and not (np.isnan(x) and np.isnan(y)):
                return False
        elif isinstance(x, list) and isinstance(y, list):
            if not same_cells(x, y):
                return False
        elif x != y:
            return False
    return True


def golden_columns() -> dict[str, Column]:
    """Every kind, every mask: f8 (NaN-as-null), i8, num (+intm, none,
    miss), bool, str (unicode, none, miss), vec (+NaN row), obj (+miss),
    empty (all-pads)."""
    vec = np.arange(8.0).reshape(4, 2)
    vec[2, 1] = np.nan  # NaN cell nulls the row vector (f8 parity)
    return {
        "f8": Column.from_values([1.0, None, float("nan"), 3.5]),
        "i8": Column.from_values([1, -2, 3, 4]),
        "num": Column.from_values([1, 2.5, None, MISSING]),
        "bool": Column.from_values([True, False, True, False]),
        "str": Column.from_values(["a", None, "日本語", MISSING]),
        "vec": Column.from_numpy(vec),
        "obj": Column.from_values([{"x": 1}, None, [1, 2], MISSING]),
        "empty": Column.pads(4),
    }


class TestFrameV2Golden:
    def test_v2_roundtrip_matches_v1_and_source(self):
        cols = golden_columns()
        v1 = wire.decode_frame(wire.encode_frame(cols, {"rev": 3}))
        v2 = wire.decode_frame(wire.encode_frame(cols, {"rev": 3}, version=2))
        assert v1[1] == v2[1] == {"rev": 3}
        for name, column in cols.items():
            want = column.tolist(pad_as_none=False)
            assert same_cells(v1[0][name].tolist(pad_as_none=False), want), name
            assert same_cells(v2[0][name].tolist(pad_as_none=False), want), name

    def test_v2_numeric_buffers_bit_identical_to_v1(self):
        cols = golden_columns()
        v1, _ = wire.decode_frame(wire.encode_frame(cols))
        v2, _ = wire.decode_frame(wire.encode_frame(cols, version=2))
        for name in ("f8", "i8", "num", "bool", "vec"):
            a, b = v1[name], v2[name]
            # bit-level equality, NaN slots included
            assert (
                np.asarray(a.data).tobytes() == np.asarray(b.data).tobytes()
            ), name
            for slot in ("none", "miss", "intm"):
                ma, mb = getattr(a, slot), getattr(b, slot)
                assert (ma is None) == (mb is None), (name, slot)
                if ma is not None:
                    assert np.array_equal(ma, mb), (name, slot)

    def test_v2_views_are_aligned_and_share_one_owner(self):
        cols = golden_columns()
        decoded, _ = wire.decode_frame(wire.encode_frame(cols, version=2))
        owners = set()
        for name in ("f8", "i8", "num", "bool", "str", "vec"):
            column = decoded[name]
            assert column.owner is not None, name
            owners.add(id(column.owner))
            assert column.data.ctypes.data % wire.ALIGN == 0, name
            if column.offsets is not None:
                assert column.offsets.ctypes.data % wire.ALIGN == 0, name
        assert len(owners) == 1  # ONE backing buffer for the frame

    def test_zero_rows_roundtrip(self):
        cols = {
            "f": Column.from_values([]),
            "s": Column.from_strings([]),
        }
        for version in (1, 2):
            decoded, _ = wire.decode_frame(
                wire.encode_frame(cols, version=version)
            )
            assert decoded["f"].tolist() == []
            assert decoded["s"].tolist() == []

    def test_unknown_magic_rejected(self):
        with pytest.raises(ValueError):
            wire.decode_frame(b"LOCB9\n" + b"\0" * 32)

    @pytest.mark.parametrize("version", (1, 2))
    def test_zero_dimension_vec_roundtrip(self, version):
        # (0, w) buffers come from beyond-the-end paged chunks (the
        # speculative terminal fetch); (n, 0) from width-0 vectors —
        # memoryview.cast rejects zero-in-shape views, so encode must
        # short-circuit them, and decode must still CONSUME the empty
        # data buffer or every following mask lands on the wrong slot
        empty_rows = Column.from_numpy(np.empty((0, 3), dtype=np.float64))
        width_zero = Column.from_numpy(np.empty((2, 0), dtype=np.float64))
        width_zero = width_zero.set(0, None)  # a mask AFTER the data slot
        frame = wire.encode_frame(
            {"a": empty_rows, "b": width_zero}, version=version
        )
        decoded, _ = wire.decode_frame(frame)
        assert decoded["a"].tolist() == []
        assert decoded["b"].tolist() == [None, []]

    def test_width_zero_vec_mask_survives_wal_roundtrip(self):
        # the WAL/replication path (to_json_record) for a width-0 vec
        # with a null mask: the mask must round-trip exactly, not be
        # rebuilt from the adjacent (empty) data buffer
        column = Column.from_numpy(np.empty((3, 0), dtype=np.float64))
        column = column.set(0, None)
        column = column.set(2, None)
        back = Column.from_json_record(column.to_json_record())
        assert back.tolist() == [None, [], None]
        assert np.array_equal(back.none, [True, False, True])

    @pytest.mark.parametrize("version", (1, 2))
    def test_truncated_frame_raises_never_decodes_short(self, version):
        # v2's aligned layout can land a truncation on a dtype-size
        # boundary: a torn frame must RAISE (the chunk-retry machinery
        # re-fetches), never hand back silently short columns
        cols = {"a": Column.from_values(list(range(100)))}
        frame = wire.encode_frame(cols, version=version)
        for cut in (len(frame) // 2, len(frame) - 8):
            with pytest.raises(ValueError):
                wire.decode_frame(frame[:cut])


class TestMutationSafety:
    def test_set_copies_instead_of_corrupting_the_frame(self):
        frame = wire.encode_frame(golden_columns(), version=2)
        decoded, _ = wire.decode_frame(frame)
        column = decoded["f8"]
        owner = column.owner
        before = bytes(owner.base)
        mutated = column.set(0, 99.0)
        assert mutated.get(0) == 99.0
        assert bytes(owner.base) == before  # frame untouched
        assert mutated.owner is None  # the copy no longer pins it

    def test_direct_write_through_the_view_raises(self):
        decoded, _ = wire.decode_frame(
            wire.encode_frame(golden_columns(), version=2)
        )
        with pytest.raises(ValueError):
            decoded["f8"].data[0] = 5.0

    def test_append_after_zero_copy_decode(self):
        # the paged-read loop appends chunk columns (including a
        # terminal empty chunk) into zero-copy columns
        first, _ = wire.decode_frame(
            wire.encode_frame({"s": Column.from_values(["x", "y"])}, version=2)
        )
        second, _ = wire.decode_frame(
            wire.encode_frame({"s": Column.from_values(["z"])}, version=2)
        )
        empty, _ = wire.decode_frame(
            wire.encode_frame({"s": Column.from_values([])}, version=2)
        )
        merged = (
            first["s"].append_column(second["s"]).append_column(empty["s"])
        )
        assert merged.tolist() == ["x", "y", "z"]

    def test_to_float64_view_is_read_only_but_consumable(self):
        decoded, _ = wire.decode_frame(
            wire.encode_frame(
                {"x": Column.from_values([1.0, 2.0, 3.0])}, version=2
            )
        )
        out = decoded["x"].to_float64()
        assert not out.flags.writeable  # mask-free f8: the view itself
        assert np.stack([out], axis=1).flags.writeable  # consumers copy

    def test_to_float64_isolated_from_later_column_writes(self):
        # zero-copy hand-off must keep the old copy semantics' ISOLATION:
        # mutating the column after taking the matrix view copies first
        # (COW), and writing into the "matrix" raises instead of
        # corrupting the store
        column = Column.from_values([1.0, 2.0, 3.0])
        matrix = column.to_float64()
        column = column.set(0, 99.0)
        assert matrix.tolist() == [1.0, 2.0, 3.0]
        assert column.get(0) == 99.0
        with pytest.raises(ValueError):
            matrix[1] = -1.0

    def test_append_zero_byte_string_chunk_onto_view(self):
        # a chunk with ROWS but zero string bytes (all-empty strings)
        # appended onto a read-only zero-copy STR column: the no-growth
        # path must not slice-assign into the read-only view
        base, _ = wire.decode_frame(
            wire.encode_frame({"s": Column.from_values(["x", "y"])}, version=2)
        )
        hollow, _ = wire.decode_frame(
            wire.encode_frame({"s": Column.from_values(["", ""])}, version=2)
        )
        merged = base["s"].append_column(hollow["s"])
        assert merged.tolist() == ["x", "y", "", ""]


@pytest.fixture()
def wire_server():
    devcache.reset_global_devcache()
    server = ServerThread(
        create_store_app(InMemoryStore(), shm=True), "127.0.0.1", 0
    ).start()
    yield server
    server.stop()
    devcache.reset_global_devcache()


def _seed(client: RemoteStore, rows: int = 5000) -> None:
    client.create_collection("wired")
    client.insert_columns(
        "wired",
        {
            "x": [float(i) for i in range(rows)],
            "y": [None if i % 97 == 0 else i * 0.5 for i in range(rows)],
            "tag": [f"t{i % 13}" for i in range(rows)],
        },
        start_id=1,
    )


class TestNegotiation:
    def test_v1_and_v2_clients_read_identically(self, wire_server):
        url = f"http://127.0.0.1:{wire_server.port}"
        writer = RemoteStore(url, shm_bytes=0)
        _seed(writer)
        v2 = RemoteStore(url, shm_bytes=0)
        v1 = RemoteStore(url, wire_v2=False, shm_bytes=0)  # old client
        a = v2.read_column_arrays("wired")
        b = v1.read_column_arrays("wired")
        for name in a:
            assert a[name].tolist() == b[name].tolist(), name

    def test_server_health_advertises_bin2(self, wire_server):
        from learningorchestra_tpu.core.store_service import probe_health

        health = probe_health(f"http://127.0.0.1:{wire_server.port}")
        assert health["columns_wire"] == "bin2"
        assert health["shm"] is True

    def test_old_server_still_understood(self, wire_server):
        # an old server never emits v2: simulated by a client that does
        # not advertise (wire_v2=False) — the decode dispatches on the
        # magic, so the v1 body round-trips
        url = f"http://127.0.0.1:{wire_server.port}"
        client = RemoteStore(url, wire_v2=False, shm_bytes=0)
        _seed(client, rows=100)
        assert client._upload_version() == 1
        got = client.read_column_arrays("wired")
        assert got["x"].tolist()[:3] == [0.0, 1.0, 2.0]

    def test_v2_upload_after_health_probe(self, wire_server):
        url = f"http://127.0.0.1:{wire_server.port}"
        client = RemoteStore(url, shm_bytes=0)
        assert client._upload_version() == 2
        _seed(client, rows=100)
        assert client.read_column_arrays("wired")["tag"].tolist()[:2] == [
            "t0",
            "t1",
        ]

    def test_upload_version_reprobes_after_failover(self):
        # a rolling upgrade can fail a bin2 primary over onto an older
        # peer: the cached upload version must be re-probed at the new
        # server, never carried across the re-point
        first = ServerThread(
            create_store_app(InMemoryStore()), "127.0.0.1", 0
        ).start()
        second = ServerThread(
            create_store_app(InMemoryStore()), "127.0.0.1", 0
        ).start()
        try:
            client = RemoteStore(
                f"http://127.0.0.1:{first.port},"
                f"http://127.0.0.1:{second.port}",
                shm_bytes=0,
                failover_timeout=10,
            )
            assert client._upload_version() == 2
            first.stop()
            client.insert_one("ds", {"_id": 1, "x": 1})  # rides failover
            assert client.base_url.endswith(str(second.port))
            assert client._upload_version_cache is None  # re-probe due
            assert client._upload_version() == 2  # probed at the peer
            client.insert_columns("ds", {"y": [1.0, 2.0]}, start_id=2)
            assert client.count("ds") == 3
        finally:
            second.stop()


class TestShmRing:
    def test_shm_read_equals_body_read(self, wire_server):
        url = f"http://127.0.0.1:{wire_server.port}"
        writer = RemoteStore(url, shm_bytes=0)
        _seed(writer)
        shm = RemoteStore(url, shm_bytes=8_000_000)
        plain = RemoteStore(url, shm_bytes=0)
        try:
            a = shm.read_column_arrays("wired")
            b = plain.read_column_arrays("wired")
            for name in b:
                assert a[name].tolist() == b[name].tolist(), name
            stats = shm.shm_stats()
            assert stats["frames"] >= 1 and stats["bytes"] > 0
        finally:
            shm.close()

    def test_absent_segment_falls_back_to_body(self, wire_server):
        url = f"http://127.0.0.1:{wire_server.port}"
        writer = RemoteStore(url, shm_bytes=0)
        _seed(writer, rows=500)
        client = RemoteStore(url, shm_bytes=8_000_000)
        try:
            ring = client._ring()
            ring.name = "lo_bogus_segment_gone"  # server cannot attach
            got = client.read_column_arrays("wired")
            assert got["x"].tolist()[:3] == [0.0, 1.0, 2.0]
            assert client.shm_stats()["frames"] == 0  # body road taken
        finally:
            client.close()

    def test_shm_disabled_server_side(self):
        # LO_SHM_BYTES=0 on the server: the client advertises, the
        # server ignores, bytes ride the body
        devcache.reset_global_devcache()
        server = ServerThread(
            create_store_app(InMemoryStore(), shm=False), "127.0.0.1", 0
        ).start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            client = RemoteStore(url, shm_bytes=8_000_000)
            _seed(client, rows=500)
            got = client.read_column_arrays("wired")
            assert got["x"].tolist()[:3] == [0.0, 1.0, 2.0]
            assert (client.shm_stats() or {"frames": 0})["frames"] == 0
            client.close()
        finally:
            server.stop()

    def test_oversized_frame_falls_back(self, wire_server):
        # ring smaller than one frame: every read takes the body road
        url = f"http://127.0.0.1:{wire_server.port}"
        writer = RemoteStore(url, shm_bytes=0)
        _seed(writer)
        client = RemoteStore(url, shm_bytes=4096)
        try:
            got = client.read_column_arrays("wired")
            assert len(got["x"]) == 5000
            assert client.shm_stats()["frames"] == 0
        finally:
            client.close()

    def test_torn_slot_detected(self):
        # ring sized so the SECOND place wraps onto the first slot: the
        # stale coordinates must refuse, not hand back other data
        ring = shmring.ClientRing(1 << 12)
        try:
            rings = shmring.ServerRings()
            frame = wire.encode_frame(
                {"x": Column.from_values([float(i) for i in range(300)])},
                version=2,
            )
            assert len(frame) * 2 > ring.nbytes  # forces the wrap
            offset, length, generation = rings.place(
                ring.name, ring.nbytes, frame
            )
            fresh = rings.place(ring.name, ring.nbytes, frame)
            assert fresh[0] == offset  # wrapped onto the first slot
            got = ring.read(*fresh)
            assert len(got) == len(frame)
            with pytest.raises(shmring.ShmTornError):
                ring.read(offset, length, generation)
            rings.close()
        finally:
            ring.close()

    def test_path_shaped_segment_names_rejected(self, tmp_path):
        # a request header must never point the server's mmap at an
        # arbitrary writable file (traversal / absolute paths)
        victim = tmp_path / "victim.bin"
        victim.write_bytes(b"\0" * 4096)
        rings = shmring.ServerRings()
        frame = b"x" * 64
        for name in (
            f"../..{victim}",
            str(victim),
            "a/b",
            "..",
            ".hidden",
            "",
        ):
            assert rings.place(name, 4096, frame) is None, name
        assert victim.read_bytes() == b"\0" * 4096  # untouched
        with pytest.raises(ValueError):
            shmring._Attachment("../etc/hosts")
        rings.close()

    def test_attach_cache_evicts_oldest_not_newest(self):
        # LRU contract: with the cache full, attaching one more evicts
        # the OLDEST segment; the newest stays served from cache
        rings = shmring.ServerRings()
        rings.MAX_SEGMENTS = 2
        clients = [shmring.ClientRing(1 << 14) for _ in range(3)]
        try:
            frame = b"y" * 32
            assert rings.place(clients[0].name, 1 << 14, frame)
            assert rings.place(clients[1].name, 1 << 14, frame)
            with rings._lock:
                assert list(rings._segments) == [
                    clients[0].name,
                    clients[1].name,
                ]
            assert rings.place(clients[2].name, 1 << 14, frame)
            with rings._lock:
                names = list(rings._segments)
            assert clients[0].name not in names  # oldest evicted
            assert clients[1].name in names and clients[2].name in names
        finally:
            rings.close()
            for client in clients:
                client.close()

    def test_shm_bytes_env_validation(self, monkeypatch):
        monkeypatch.setenv("LO_SHM_BYTES", "1e6")
        assert shmring.shm_bytes() == 1_000_000
        monkeypatch.setenv("LO_SHM_BYTES", "0")
        assert shmring.shm_bytes() == 0
        monkeypatch.setenv("LO_SHM_BYTES", "-5")
        with pytest.raises(ValueError):
            shmring.shm_bytes()
        monkeypatch.setenv("LO_SHM_BYTES", "lots")
        with pytest.raises(ValueError):
            shmring.shm_bytes()


PREPROCESSOR = (
    "from pyspark.ml.feature import VectorAssembler\n"
    "feature_cols = [c for c in training_df.schema.names if c != 'label']\n"
    "assembler = VectorAssembler(inputCols=feature_cols, "
    "outputCol='features')\n"
    "features_training = assembler.transform(training_df)\n"
    "features_testing = assembler.transform(testing_df)\n"
    "features_evaluation = assembler.transform(testing_df)\n"
)


class TestPipelineEquivalence:
    """Acceptance: a full histogram→build→predict pipeline over each
    transport returns identical results (zero-copy equivalence at the
    workload level, not just the frame level)."""

    @pytest.fixture()
    def seeded_server(self):
        devcache.reset_global_devcache()
        server = ServerThread(
            create_store_app(InMemoryStore(), shm=True), "127.0.0.1", 0
        ).start()
        url = f"http://127.0.0.1:{server.port}"
        rng = np.random.default_rng(3)
        rows = 400
        X = rng.random((rows, 4))
        y = (X[:, 0] + X[:, 1] > 1.0).astype(int)
        writer = RemoteStore(url, shm_bytes=0)
        for name in ("wtrain", "wtest"):
            writer.create_collection(name)
            writer.insert_one(
                name,
                {
                    "_id": 0,
                    "filename": name,
                    "finished": True,
                    "fields": [f"f{i}" for i in range(4)] + ["label"],
                },
            )
            columns = {f"f{i}": X[:, i].tolist() for i in range(4)}
            columns["label"] = y.tolist()
            writer.insert_columns(name, columns)
        yield url
        server.stop()
        devcache.reset_global_devcache()

    def test_identical_over_every_transport(self, seeded_server):
        from learningorchestra_tpu.ml.builder import build_model

        url = seeded_server
        outputs = {}
        for label, client in (
            ("v1", RemoteStore(url, wire_v2=False, shm_bytes=0)),
            ("v2", RemoteStore(url, shm_bytes=0)),
            ("shm", RemoteStore(url, shm_bytes=8_000_000)),
        ):
            # each client is its own devcache scope (fresh store
            # token), so no transport's read is served from another's
            # cache entry
            histogram = client.aggregate(
                "wtrain", [{"$group": {"_id": "$label", "count": {}}}]
            )
            results = build_model(
                client, "wtrain", "wtest", PREPROCESSOR, ["lr", "nb"]
            )
            predictions = {
                r["classificator"]: sorted(
                    (
                        (doc["_id"], doc["prediction"])
                        for doc in client.find(
                            f"wtest_prediction_{r['classificator']}"
                        )
                        if doc["_id"] != 0
                    )
                )
                for r in results
            }
            metrics = {
                r["classificator"]: (r["accuracy"], r["F1"])
                for r in results
            }
            outputs[label] = (
                sorted(
                    (entry["_id"], entry["count"]) for entry in histogram
                ),
                predictions,
                metrics,
            )
            client.close()
        assert outputs["v1"] == outputs["v2"] == outputs["shm"]
