"""Worker process for the SPMD-dispatch stack test (not a pytest file).

Two of these form a 2-host deployment against a shared store server:
process 0 plays the coordinator (submits a build_model job through the
SPMD dispatcher, exactly as the model_builder REST handler does in
multi-host mode), process 1 plays the worker host (run_worker_loop).
Both enter the same fit over the global 8-device mesh; only the
coordinator writes predictions to the store.
"""

import sys


def main() -> None:
    process_id = int(sys.argv[1])
    num_processes = int(sys.argv[2])
    coordinator = sys.argv[3]
    store_url = sys.argv[4]
    images_dir = sys.argv[5]

    import os

    os.environ["LO_COORDINATOR"] = coordinator
    os.environ["LO_NUM_PROCESSES"] = str(num_processes)
    os.environ["LO_PROCESS_ID"] = str(process_id)

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from learningorchestra_tpu.parallel.multihost import initialize_from_env

    assert initialize_from_env(), "multi-host runtime did not come up"

    from learningorchestra_tpu.core.store_service import connect
    from learningorchestra_tpu.services.runner import make_dispatcher

    store = connect(store_url)
    dispatcher = make_dispatcher(store, images_dir)

    if process_id == 0:
        dispatcher.submit(
            "build_model",
            {
                "training_filename": "spmd_train",
                "test_filename": "spmd_train",
                "preprocessor_code": PREPROCESSOR,
                "classificators_list": ["lr"],
            },
        )
        dispatcher.shutdown_workers()
        print("coordinator: job done", flush=True)
    else:
        dispatcher.run_worker_loop()
        print("worker: loop exited", flush=True)


PREPROCESSOR = """
from pyspark.ml.feature import VectorAssembler
assembler = VectorAssembler(inputCols=["f1", "f2"], outputCol="features")
features_training = assembler.transform(training_df)
features_testing = assembler.transform(testing_df)
features_evaluation = features_training
"""


if __name__ == "__main__":
    main()
