"""Job coalescer + batched-fit sweeps (sched/coalesce.py, ml/sweep.py).

The acceptance bar from the issue: a coalesced N-job batch produces
BIT-IDENTICAL fitted params and metrics to the same N jobs run
sequentially (window 0), including with a mid-batch cancelled member and
a mid-batch failing member — neighbors unaffected, the failed member's
record carrying its own error.
"""

import threading

import numpy as np
import pytest

from learningorchestra_tpu.sched.cancel import CancelToken, JobCancelledError
from learningorchestra_tpu.sched.coalesce import Coalescer


def _ok_runner(calls):
    """A fake group runner recording each fused batch's payloads."""

    def run(payloads):
        calls.append(list(payloads))
        outcomes = []
        for payload in payloads:
            if payload.get("poison"):
                outcomes.append(
                    ("error", ValueError(f"bad member {payload['v']}"))
                )
            else:
                outcomes.append(("ok", {"v": payload["v"] * 2}))
        return outcomes

    return run


class TestCoalescerStage:
    """The stage's semantics with a fake runner — no jax, no scheduler:
    run_member is called directly, so leadership is deterministic."""

    def test_fused_batch_delivers_every_member(self):
        calls = []
        co = Coalescer(window_s=0.05, max_jobs=8)
        runner = _ok_runner(calls)
        members = [
            co.register(("k",), {"v": i}, runner, name=f"m{i}")
            for i in range(3)
        ]
        results = [co.run_member(m) for m in members]
        assert [r["v"] for r in results] == [0, 2, 4]
        assert len(calls) == 1 and len(calls[0]) == 3  # ONE fused dispatch
        stats = co.stats()
        assert stats["fused_dispatches"] == 1
        assert stats["members"] == 3
        assert stats["mean_batch_size"] == 3.0
        assert stats["pending"] == 0

    def test_window_zero_is_passthrough(self):
        calls = []
        co = Coalescer(window_s=0.0, max_jobs=8)
        runner = _ok_runner(calls)
        members = [
            co.register(("k",), {"v": i}, runner, name=f"m{i}")
            for i in range(3)
        ]
        results = [co.run_member(m) for m in members]
        assert [r["v"] for r in results] == [0, 2, 4]
        # no coalescing: one dispatch per job, nothing ever pending
        assert [len(c) for c in calls] == [1, 1, 1]
        assert co.stats()["mean_batch_size"] == 1.0

    def test_max_jobs_caps_the_batch(self):
        calls = []
        co = Coalescer(window_s=0.02, max_jobs=2)
        runner = _ok_runner(calls)
        members = [
            co.register(("k",), {"v": i}, runner, name=f"m{i}")
            for i in range(5)
        ]
        for member in members:
            co.run_member(member)
        assert sorted(len(c) for c in calls) == [1, 2, 2]

    def test_incompatible_keys_never_fuse(self):
        calls = []
        co = Coalescer(window_s=0.02, max_jobs=8)
        runner = _ok_runner(calls)
        a = co.register(("wide",), {"v": 1}, runner, name="a")
        b = co.register(("narrow",), {"v": 2}, runner, name="b")
        assert co.run_member(a)["v"] == 2
        assert co.run_member(b)["v"] == 4
        assert [len(c) for c in calls] == [1, 1]

    def test_cancelled_member_is_masked_not_its_neighbors(self):
        calls = []
        co = Coalescer(window_s=0.02, max_jobs=8)
        runner = _ok_runner(calls)
        tokens = [CancelToken() for _ in range(3)]
        members = [
            co.register(("k",), {"v": i}, runner, token=tokens[i], name=f"m{i}")
            for i in range(3)
        ]
        tokens[1].cancel("user gave up")
        assert co.run_member(members[0])["v"] == 0  # leader
        with pytest.raises(JobCancelledError):
            co.run_member(members[1])
        assert co.run_member(members[2])["v"] == 4
        # the fused dispatch saw only the two live members
        assert len(calls) == 1 and [p["v"] for p in calls[0]] == [0, 2]
        assert co.stats()["masked"] == 1

    def test_failing_member_fails_alone(self):
        calls = []
        co = Coalescer(window_s=0.02, max_jobs=8)
        runner = _ok_runner(calls)
        members = [
            co.register(
                ("k",), {"v": i, "poison": i == 1}, runner, name=f"m{i}"
            )
            for i in range(3)
        ]
        assert co.run_member(members[0])["v"] == 0
        with pytest.raises(ValueError, match="bad member 1"):
            co.run_member(members[1])
        assert co.run_member(members[2])["v"] == 4
        assert len(calls) == 1 and len(calls[0]) == 3

    def test_runner_wholesale_failure_fails_every_live_member(self):
        def run(payloads):
            raise RuntimeError("fused program died")

        co = Coalescer(window_s=0.02, max_jobs=8)
        members = [
            co.register(("k",), {"v": i}, run, name=f"m{i}") for i in range(3)
        ]
        for member in members:
            with pytest.raises(RuntimeError, match="fused program died"):
                co.run_member(member)

    def test_outcome_count_mismatch_is_a_loud_failure(self):
        co = Coalescer(window_s=0.02, max_jobs=8)
        members = [
            co.register(("k",), {"v": i}, lambda p: [("ok", 1)], name=f"m{i}")
            for i in range(2)
        ]
        for member in members:
            with pytest.raises(RuntimeError, match="outcomes"):
                co.run_member(member)

    def test_malformed_outcome_entry_delivers_every_member(self):
        # right COUNT, one entry not a 2-tuple: members after the bad
        # entry must still be delivered (an undelivered member would
        # park its follower task forever on the width-1 device lane)
        def run(payloads):
            return [("ok", 1), None, ("ok", 3)]

        co = Coalescer(window_s=0.02, max_jobs=8)
        members = [
            co.register(("k",), {"v": i}, run, name=f"m{i}") for i in range(3)
        ]
        assert co.run_member(members[0]) == 1  # delivered before the bug
        for member in members[1:]:
            with pytest.raises(TypeError):
                co.run_member(member)
        assert all(m.delivered for m in members)

    def test_wholesale_failure_errors_are_per_member_instances(self):
        def run(payloads):
            raise RuntimeError("fused program died")

        co = Coalescer(window_s=0.02, max_jobs=8)
        members = [
            co.register(("k",), {"v": i}, run, name=f"m{i}") for i in range(3)
        ]
        for member in members:
            with pytest.raises(RuntimeError, match="fused program died"):
                co.run_member(member)
        # fresh instance per member: concurrent re-raises must not
        # fight over one shared __traceback__
        assert members[0].error is not members[1].error
        assert members[1].error is not members[2].error

    def test_all_masked_batch_is_not_a_fused_dispatch(self):
        calls = []
        co = Coalescer(window_s=0.02, max_jobs=8)
        runner = _ok_runner(calls)
        tokens = [CancelToken() for _ in range(2)]
        members = [
            co.register(("k",), {"v": i}, runner, token=tokens[i])
            for i in range(2)
        ]
        for token in tokens:
            token.cancel("all gone")
        for member in members:
            with pytest.raises(JobCancelledError):
                co.run_member(member)
        stats = co.stats()
        assert not calls and stats["fused_dispatches"] == 0
        assert stats["masked"] == 2 and stats["mean_batch_size"] is None

    def test_abandoned_member_is_not_collected(self):
        calls = []
        co = Coalescer(window_s=0.02, max_jobs=8)
        runner = _ok_runner(calls)
        keep = co.register(("k",), {"v": 1}, runner, name="keep")
        drop = co.register(("k",), {"v": 2}, runner, name="drop")
        co.abandon(drop)
        assert co.run_member(keep)["v"] == 2
        assert len(calls) == 1 and [p["v"] for p in calls[0]] == [1]
        assert co.stats()["pending"] == 0


class TestCoalescedJobsThroughScheduler:
    """Member jobs keep the full scheduler contract: their own
    JobRecord lifecycle, cancellation, and per-member terminal states."""

    def _manager(self):
        from learningorchestra_tpu.core.jobs import JobManager
        from learningorchestra_tpu.sched.scheduler import Scheduler

        return JobManager(scheduler=Scheduler(queue_cap=128))

    def test_concurrent_members_fuse_and_all_records_finish(self):
        from learningorchestra_tpu.sched.scheduler import DEVICE_CLASS

        calls = []
        co = Coalescer(window_s=0.25, max_jobs=64)
        runner = _ok_runner(calls)
        jobs = self._manager()
        n = 8
        barrier = threading.Barrier(n)
        errors = []

        def client(i):
            token = CancelToken()
            member = co.register(
                ("k",), {"v": i}, runner, token=token, name=f"job-{i}"
            )
            barrier.wait()
            try:
                jobs.run_sync(
                    f"job-{i}",
                    co.run_member,
                    member,
                    job_class=DEVICE_CLASS,
                    token=token,
                )
            except BaseException as error:  # noqa: BLE001
                errors.append(error)

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(n)
        ]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join(timeout=30)
        jobs.scheduler.close()
        assert not errors
        for i in range(n):
            assert jobs.get(f"job-{i}").state == "finished"
        # the width-1 device class + the window coalesced the burst:
        # strictly fewer dispatches than jobs, so the mean batch > 1
        assert len(calls) < n
        assert co.stats()["mean_batch_size"] > 1

    def test_per_member_terminal_states_cancel_and_fail(self):
        from learningorchestra_tpu.sched.scheduler import DEVICE_CLASS

        calls = []
        co = Coalescer(window_s=0.25, max_jobs=64)
        runner = _ok_runner(calls)
        jobs = self._manager()
        tokens = [CancelToken() for _ in range(3)]
        tokens[1].cancel("cancelled before dispatch")
        members = [
            co.register(
                ("k",),
                {"v": i, "poison": i == 2},
                runner,
                token=tokens[i],
                name=f"mix-{i}",
            )
            for i in range(3)
        ]
        outcomes = {}

        def client(i):
            try:
                jobs.run_sync(
                    f"mix-{i}",
                    co.run_member,
                    members[i],
                    job_class=DEVICE_CLASS,
                    token=tokens[i],
                )
                outcomes[i] = "ok"
            except JobCancelledError:
                outcomes[i] = "cancelled"
            except ValueError:
                outcomes[i] = "failed"

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(3)
        ]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join(timeout=30)
        jobs.scheduler.close()
        assert outcomes == {0: "ok", 1: "cancelled", 2: "failed"}
        assert jobs.get("mix-0").state == "finished"
        assert jobs.get("mix-1").state == "cancelled"
        record = jobs.get("mix-2").as_dict()
        assert record["state"] == "failed"
        assert "bad member 2" in record["error"]  # its OWN error
        # the cancelled member never reached a fused dispatch
        assert all(
            payload["v"] != 1 for call in calls for payload in call
        )


def _member_data(seed: int, rows: int = 100, features: int = 6):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, features))
    y = (X[:, 0] + 0.3 * rng.normal(size=rows) > 0).astype(np.int64)
    return X, y


class TestBatchedFitBitIdentity:
    """THE correctness bar: fused == sequential, bit for bit."""

    @pytest.fixture()
    def mesh(self):
        from learningorchestra_tpu.ml.base import resolve_mesh

        return resolve_mesh(None)

    def _solo_results(self, specs, mesh, max_iter=15):
        """Each member through the passthrough (window 0) path — the
        sequential baseline the issue names."""
        from learningorchestra_tpu.ml import sweep as lo_sweep

        solo = Coalescer(window_s=0.0, max_jobs=8)
        runner = lo_sweep.group_runner(mesh)
        results = []
        for i, (X, y, grid) in enumerate(specs):
            key, payload = lo_sweep.prepare_member(
                "lr", X, y, X, y, grid, mesh=mesh, max_iter=max_iter
            )
            member = solo.register(key, payload, runner, name=f"solo-{i}")
            results.append(solo.run_member(member))
        return results

    def test_coalesced_batch_is_bit_identical_to_sequential(self, mesh):
        from learningorchestra_tpu.ml import sweep as lo_sweep

        specs = [
            (*_member_data(i), [{"reg_param": l2}])
            for i, l2 in enumerate((0.0, 0.1, 0.01, 0.5, 0.0))
        ]
        fused = Coalescer(window_s=0.05, max_jobs=8)
        runner = lo_sweep.group_runner(mesh)
        members = []
        for i, (X, y, grid) in enumerate(specs):
            key, payload = lo_sweep.prepare_member(
                "lr", X, y, X, y, grid, mesh=mesh, max_iter=15
            )
            members.append(fused.register(key, payload, runner, name=f"f{i}"))
        fused_results = [fused.run_member(m) for m in members]
        assert fused.stats()["fused_dispatches"] == 1  # ONE dispatch
        for fused_result, solo_result in zip(
            fused_results, self._solo_results(specs, mesh)
        ):
            f_point, s_point = fused_result["points"][0], solo_result["points"][0]
            # metrics: bit-identical floats, not just close
            assert f_point["accuracy"] == s_point["accuracy"]
            assert f_point["weighted_f1"] == s_point["weighted_f1"]
            # fitted params: bit-identical arrays
            np.testing.assert_array_equal(
                fused_result["params"][0]["w"], solo_result["params"][0]["w"]
            )
            np.testing.assert_array_equal(
                fused_result["params"][0]["b"], solo_result["params"][0]["b"]
            )

    def test_mid_batch_cancel_and_failure_leave_neighbors_bit_identical(
        self, mesh
    ):
        from learningorchestra_tpu.ml import sweep as lo_sweep

        specs = [
            (*_member_data(10 + i), [{"reg_param": 0.05 * i}])
            for i in range(5)
        ]
        # member 3 is poisoned: NaN features must fail IT alone
        specs[3][0][7, 2] = np.nan
        fused = Coalescer(window_s=0.05, max_jobs=8)
        runner = lo_sweep.group_runner(mesh)
        tokens = [CancelToken() for _ in range(5)]
        members = []
        for i, (X, y, grid) in enumerate(specs):
            key, payload = lo_sweep.prepare_member(
                "lr", X, y, X, y, grid, mesh=mesh, max_iter=15
            )
            members.append(
                fused.register(
                    key, payload, runner, token=tokens[i], name=f"f{i}"
                )
            )
        tokens[1].cancel("mid-batch cancel")  # member 1 masked out
        fused_results = {}
        for i, member in enumerate(members):
            if i == 1:
                with pytest.raises(JobCancelledError):
                    fused.run_member(member)
            elif i == 3:
                with pytest.raises(ValueError, match="non-finite"):
                    fused.run_member(member)
            else:
                fused_results[i] = fused.run_member(member)
        assert fused.stats()["masked"] == 1
        survivors = [0, 2, 4]
        solo = self._solo_results([specs[i] for i in survivors], mesh)
        for solo_result, i in zip(solo, survivors):
            np.testing.assert_array_equal(
                fused_results[i]["params"][0]["w"],
                solo_result["params"][0]["w"],
            )
            assert (
                fused_results[i]["points"][0]["accuracy"]
                == solo_result["points"][0]["accuracy"]
            )

    def test_dt_fused_matches_passthrough_bitwise(self, mesh):
        from learningorchestra_tpu.ml import sweep as lo_sweep

        grid = [{"max_depth": 2}, {"max_depth": 3}]
        specs = [(*_member_data(20 + i), grid) for i in range(3)]
        runner = lo_sweep.group_runner(mesh)

        def run(window_s):
            co = Coalescer(window_s=window_s, max_jobs=8)
            members = []
            for i, (X, y, g) in enumerate(specs):
                key, payload = lo_sweep.prepare_member(
                    "dt", X, y, X, y, g, mesh=mesh
                )
                members.append(co.register(key, payload, runner, name=f"d{i}"))
            return [co.run_member(m) for m in members], co

        fused_results, fused_co = run(0.05)
        solo_results, _ = run(0.0)
        assert fused_co.stats()["fused_dispatches"] == 1
        for fused_result, solo_result in zip(fused_results, solo_results):
            for f_params, s_params in zip(
                fused_result["params"], solo_result["params"]
            ):
                np.testing.assert_array_equal(
                    f_params["features_heap"], s_params["features_heap"]
                )
                np.testing.assert_array_equal(
                    f_params["leaf_probs"], s_params["leaf_probs"]
                )
            for f_point, s_point in zip(
                fused_result["points"], solo_result["points"]
            ):
                assert f_point["accuracy"] == s_point["accuracy"]

    def test_lr_sweep_tracks_the_solo_estimator(self, mesh):
        """Anchor the batched program to the product estimator: same
        data, λ=0, full iteration budget → near-identical params (the
        batched path skips the plateau exit, so compare with tol=0)."""
        from learningorchestra_tpu.ml import sweep as lo_sweep
        from learningorchestra_tpu.ml.logistic import LogisticRegression

        X, y = _member_data(42)
        key, payload = lo_sweep.prepare_member(
            "lr", X, y, X, y, [{"reg_param": 0.0}], mesh=mesh, max_iter=25
        )
        co = Coalescer(window_s=0.0, max_jobs=8)
        member = co.register(key, payload, lo_sweep.group_runner(mesh))
        result = co.run_member(member)
        solo = LogisticRegression(max_iter=25, tol=0.0, mesh=mesh).fit(X, y)
        np.testing.assert_allclose(
            result["params"][0]["w"],
            np.asarray(solo.params["w"]),
            rtol=1e-4,
            atol=1e-5,
        )
        accuracy, _ = solo.evaluate(X, y)
        assert abs(result["points"][0]["accuracy"] - accuracy) < 1e-6


PREPROCESSOR = (
    "from pyspark.ml.feature import VectorAssembler\n"
    "assembler = VectorAssembler(inputCols=['a', 'b'], outputCol='features')\n"
    "features_training = assembler.transform(training_df)\n"
    "features_testing = assembler.transform(testing_df)\n"
    "features_evaluation = None\n"
)


class TestSweepRoute:
    @pytest.fixture()
    def numeric_store(self, store):
        from learningorchestra_tpu.core.table import write_columns

        write_columns(
            store,
            "numbers",
            {
                "a": [float(i % 7) for i in range(240)],
                "b": [float((i * 3) % 5) for i in range(240)],
                "label": [float(i % 2) for i in range(240)],
            },
            {"filename": "numbers", "finished": True,
             "fields": ["a", "b", "label"]},
        )
        return store

    def _app(self, store, tmp_path, **kwargs):
        from learningorchestra_tpu.services import model_builder

        kwargs.setdefault("coalescer", Coalescer(window_s=0.0, max_jobs=8))
        return model_builder.create_app(
            store, models_dir=str(tmp_path), **kwargs
        )

    def _body(self, **overrides):
        body = {
            "training_filename": "numbers",
            "test_filename": "numbers",
            "preprocessor_code": PREPROCESSOR,
            "classificator": "lr",
            "grid": [{"reg_param": 0.0}, {"reg_param": 0.5}],
            "sweep_name": "numbers_sweep",
            "max_iter": 10,
        }
        body.update(overrides)
        return body

    def test_lr_sweep_metrics_checkpoint_and_serving_pickup(
        self, numeric_store, tmp_path
    ):
        import json
        import os

        client = self._app(numeric_store, tmp_path).test_client()
        response = client.post("/models/sweep", json=self._body())
        assert response.status_code == 201, response.get_data()
        result = json.loads(response.get_data())["result"]
        assert result["model"] == "numbers_sweep"
        assert len(result["points"]) == 2
        for point in result["points"]:
            assert 0.0 <= point["accuracy"] <= 1.0
            assert 0.0 <= point["weighted_f1"] <= 1.0
        assert result["best"] in (0, 1)
        # the argmax checkpoint is a real published artifact...
        assert os.path.isfile(os.path.join(str(tmp_path), "numbers_sweep.model"))
        listing = json.loads(client.get("/models").get_data())["result"]
        assert "numbers_sweep" in listing
        # ...the serving registry picks it up like any other build
        predict = client.post(
            "/models/numbers_sweep/predict", json={"rows": [[1.0, 2.0]]}
        )
        assert predict.status_code == 200, predict.get_data()
        # per-point metrics persisted as the sweep's collection
        document = numeric_store.find_one("numbers_sweep", {})
        assert document["finished"] is True
        assert document["best"] == result["best"]
        assert len(document["points"]) == 2
        # the record + trace surface every job gets
        jobs = json.loads(client.get("/jobs").get_data())["result"]
        assert any(
            job["name"] == "sweep:numbers_sweep"
            and job["state"] == "finished"
            for job in jobs
        )

    def test_dt_depth_sweep(self, numeric_store, tmp_path):
        import json

        client = self._app(numeric_store, tmp_path).test_client()
        body = self._body(
            classificator="dt",
            grid=[{"max_depth": 2}, {"max_depth": 3}],
            sweep_name="numbers_dt_sweep",
        )
        body.pop("max_iter")
        response = client.post("/models/sweep", json=body)
        assert response.status_code == 201, response.get_data()
        result = json.loads(response.get_data())["result"]
        assert [point["max_depth"] for point in result["points"]] == [2, 3]

    def test_validation_surface(self, numeric_store, tmp_path):
        client = self._app(numeric_store, tmp_path).test_client()
        cases = [
            ({"training_filename": "ghost"}, 406),
            ({"classificator": "svm"}, 406),
            ({"grid": []}, 406),
            ({"grid": [{"reg_param": -1.0}]}, 406),
            ({"grid": [{"reg_param": True}]}, 406),
            ({"grid": [{"max_depth": 3}]}, 406),  # wrong key for lr
            ({"sweep_name": "../escape"}, 406),
            ({"max_iter": 0}, 406),
            ({"max_iter": "ten"}, 406),
        ]
        for overrides, expected in cases:
            response = client.post("/models/sweep", json=self._body(**overrides))
            assert response.status_code == expected, (overrides, response.get_data())
        missing = self._body()
        del missing["grid"]
        assert client.post("/models/sweep", json=missing).status_code == 406

    def test_sweep_name_collision_is_409(self, numeric_store, tmp_path):
        client = self._app(numeric_store, tmp_path).test_client()
        assert (
            client.post("/models/sweep", json=self._body()).status_code == 201
        )
        assert (
            client.post("/models/sweep", json=self._body()).status_code == 409
        )

    def test_sdk_sweep_over_http(self, numeric_store, tmp_path):
        import learningorchestra_tpu.client as lo_client
        from learningorchestra_tpu.utils.web import ServerThread

        app = self._app(numeric_store, tmp_path)
        server = ServerThread(app, "127.0.0.1", 0).start()
        saved_port = lo_client.Model.MODEL_BUILDER_PORT
        try:
            lo_client.Model.MODEL_BUILDER_PORT = str(server.port)
            lo_client.Context("127.0.0.1")
            sdk = lo_client.Model()
            # sweep polls the database API for dataset readiness first
            # (create_model parity); no database_api runs in this test
            sdk._wait_finished = lambda *args, **kwargs: None
            result = sdk.sweep(
                "numbers",
                "numbers",
                PREPROCESSOR,
                "lr",
                [{"reg_param": 0.0}, {"reg_param": 0.3}],
                "sdk_sweep",
                max_iter=10,
                pretty_response=False,
            )
            assert result["result"]["model"] == "sdk_sweep"
            assert len(result["result"]["points"]) == 2
            # the reference-parity PyPI shim exposes the same surface
            from learning_orchestra_client import Model as ShimModel

            assert ShimModel.sweep is lo_client.Model.sweep
        finally:
            lo_client.Model.MODEL_BUILDER_PORT = saved_port
            server.stop()
