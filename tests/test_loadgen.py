"""The closed-loop load generator (serve/loadgen.py): per-client
sessions must be closed on EVERY path out of the client loop — error
paths included — targets parameterize (router mode is one target,
replica mode several), and a client that dies during setup aborts the
start barrier instead of deadlocking the run.
"""

import threading

import pytest

from learningorchestra_tpu.serve.loadgen import (
    HttpSession,
    http_predict_sender,
    run_closed_loop,
)
from learningorchestra_tpu.utils.web import ServerThread, WebApp


class _TrackingSession:
    def __init__(self, index):
        self.index = index
        self.closed = False

    def close(self):
        self.closed = True


class TestRunClosedLoop:
    def test_stats_shape_and_counts(self):
        calls = []

        def send(index):
            calls.append(index)

        stats = run_closed_loop(
            send, clients=3, requests_per_client=5, rows_per_request=4
        )
        assert len(calls) == 15
        assert stats["clients"] == 3 and stats["requests"] == 15
        assert stats["predictions_per_s"] == pytest.approx(
            stats["requests_per_s"] * 4, rel=0.02
        )
        for key in ("wall_s", "p50_ms", "p99_ms", "mean_ms"):
            assert stats[key] >= 0

    def test_sessions_closed_when_a_client_errors(self):
        """The leak the fleet bench would hit: one failing client must
        not strand ANY session — its own included — half open."""
        sessions = []

        def session_factory(index):
            session = _TrackingSession(index)
            sessions.append(session)
            return session

        def send(index, session):
            if index == 1:
                raise RuntimeError("replica gone")

        with pytest.raises(RuntimeError, match="replica gone"):
            run_closed_loop(
                send,
                clients=4,
                requests_per_client=3,
                session_factory=session_factory,
            )
        assert len(sessions) == 4
        assert all(session.closed for session in sessions)

    def test_setup_failure_aborts_the_barrier(self):
        """A session_factory that raises must surface ITS error (not a
        BrokenBarrierError) and never deadlock the start barrier."""
        created = []

        def session_factory(index):
            if index == 2:
                raise ConnectionRefusedError("nobody listening")
            session = _TrackingSession(index)
            created.append(session)
            return session

        finished = threading.Event()
        failure = {}

        def run():
            try:
                run_closed_loop(
                    lambda index, session: None,
                    clients=3,
                    requests_per_client=2,
                    session_factory=session_factory,
                )
            except BaseException as error:  # noqa: BLE001
                failure["error"] = error
            finished.set()

        thread = threading.Thread(target=run, daemon=True)
        thread.start()
        assert finished.wait(timeout=10), "closed loop deadlocked"
        assert isinstance(failure["error"], ConnectionRefusedError)
        assert all(session.closed for session in created)

    def test_session_is_passed_back_to_send(self):
        seen = {}

        def send(index, session):
            seen[index] = session

        run_closed_loop(
            send,
            clients=2,
            requests_per_client=1,
            session_factory=_TrackingSession,
        )
        assert {index: s.index for index, s in seen.items()} == {0: 0, 1: 1}


class TestHttpPredictSender:
    def test_clients_spread_across_targets(self):
        targets = ["127.0.0.1:5010", "http://127.0.0.1:5011"]
        _, session_factory = http_predict_sender(
            targets, "m_prediction_lr", [[1.0]]
        )
        # HTTPConnection connects lazily: inspecting placement is free
        spread = [session_factory(i).target for i in range(4)]
        assert spread == [targets[0], targets[1], targets[0], targets[1]]

    def test_needs_at_least_one_target(self):
        with pytest.raises(ValueError, match="at least one target"):
            http_predict_sender([], "m", [[1.0]])

    def test_non_200_raises_unless_observed(self):
        app = WebApp("stub")

        @app.route("/models/<model_name>/predict", methods=("POST",))
        def predict(request, model_name):
            return {"result": "no_replicas", "model": model_name}, 503

        server = ServerThread(app, "127.0.0.1", 0).start()
        try:
            target = f"127.0.0.1:{server.port}"
            send, factory = http_predict_sender(
                [target], "m_prediction_lr", [[1.0]], timeout_s=10.0
            )
            session = factory(0)
            try:
                with pytest.raises(RuntimeError, match="HTTP 503"):
                    send(0, session)
            finally:
                session.close()
            # an observer sees every answer and suppresses the raise —
            # the chaos drills assert on the collected statuses
            observed = []
            send, factory = http_predict_sender(
                [target],
                "m_prediction_lr",
                [[1.0]],
                timeout_s=10.0,
                on_response=lambda status, body: observed.append(
                    (status, body)
                ),
            )
            session = factory(0)
            try:
                send(0, session)
            finally:
                session.close()
            assert observed == [
                (503, {"result": "no_replicas", "model": "m_prediction_lr"})
            ]
        finally:
            server.stop()

    def test_session_reconnects_after_server_side_close(self):
        """A stale keep-alive (the server restarted between requests)
        costs one reconnect, not a failed client."""
        app = WebApp("stub")

        @app.route("/models/<model_name>/predict", methods=("POST",))
        def predict(request, model_name):
            return {"result": {"model": model_name}}, 200

        server = ServerThread(app, "127.0.0.1", 0).start()
        port = server.port
        session = HttpSession(f"127.0.0.1:{port}", timeout_s=10.0)
        try:
            status, _ = session.post_json(
                "/models/m/predict", {"rows": [[1.0]]}
            )
            assert status == 200
            # sever the server side; the session's socket goes stale
            server.stop()
            server = ServerThread(app, "127.0.0.1", port).start()
            status, body = session.post_json(
                "/models/m/predict", {"rows": [[1.0]]}
            )
            assert status == 200
            assert body == {"result": {"model": "m"}}
        finally:
            session.close()
            server.stop()
