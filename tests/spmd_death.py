"""Worker-death drill process (driven by tests/test_multihost.py).

Runs a 2-process jax.distributed runtime (gloo over localhost). The
coordinator submits one healthy SPMD job, then a job whose handler
makes the WORKER die abruptly mid-job (os._exit) while the coordinator
enters a cross-host collective — the situation a crashed host produces
in production. Asserted from the written results: the request errors
cleanly (watchdog timeout or a collective error — never a hang), and
the dispatcher refuses later jobs as poisoned. Recovery phase: a fresh
runtime (new process pair) runs the same job successfully — the
supervisor-restart story (deploy/stack.py restart policy; the reference
leans on swarm restart + Spark retry, docker-compose.yml:14-15,145).

argv: process_id num_processes coordinator_addr out_path phase
phase: "drill" or "recover"
"""

import json
import os
import sys

process_id, num_processes, coordinator, out_path, phase = sys.argv[1:6]
process_id = int(process_id)
num_processes = int(num_processes)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=coordinator,
    num_processes=num_processes,
    process_id=process_id,
)

import numpy as np  # noqa: E402
from jax.experimental import multihost_utils  # noqa: E402

from learningorchestra_tpu.parallel.spmd import (  # noqa: E402
    SpmdDispatcher,
    SpmdRuntimePoisonedError,
    SpmdTimeoutError,
)

dispatcher = SpmdDispatcher()


def fit(payload):
    """A cross-host collective job (stands in for a model fit)."""
    gathered = multihost_utils.process_allgather(
        np.array([jax.process_index() + 1], np.int32)
    )
    return int(np.sum(gathered))


def die_mid_job(payload):
    if jax.process_index() != 0:
        os._exit(17)  # the worker host "crashes" mid-job
    return fit(payload)  # coordinator enters a collective missing a peer


dispatcher.register("fit", fit)
dispatcher.register("die", die_mid_job)

if process_id != 0:
    try:
        dispatcher.run_worker_loop()
    finally:
        os._exit(0)

results = {}
results["fit_before"] = dispatcher.submit("fit", {}, timeout=60)

if phase == "drill":
    try:
        dispatcher.submit("die", {}, timeout=8)
        results["death_job"] = "no-error"
    except SpmdTimeoutError:
        results["death_job"] = "timeout"
    except Exception as error:  # gloo may surface the dead peer itself
        results["death_job"] = f"error:{type(error).__name__}"
    try:
        dispatcher.submit("fit", {}, timeout=8)
        results["after_death"] = "no-error"
    except SpmdRuntimePoisonedError:
        results["after_death"] = "poisoned"
    except Exception as error:
        results["after_death"] = f"error:{type(error).__name__}"
else:  # recover: healthy pair, clean shutdown
    dispatcher.shutdown_workers()

with open(out_path, "w") as handle:
    json.dump(results, handle)
os._exit(0)  # never attempt distributed teardown with a dead peer
