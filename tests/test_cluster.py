"""deploy/cluster.py brings up a cross-machine topology end to end.

The reference deploys its whole multi-node cluster with one command
(reference run.sh:8-32 + docker-compose.yml:1-340; worker scaling
README.md:94). This test drives OUR deploy artifact — not the test
harness — over the ``local`` transport: two "machines" (a head running
store + coordinator, and a worker-only machine contributing one SPMD
process) wired by the manifest into one 2-process jax.distributed
runtime, then a model build over the REST surface, then a worker-machine
death that the cluster driver heals by relaunching every machine's
runtime group.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TESTS_DIR)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _post(url, body, timeout=300):
    data = json.dumps(body).encode()
    request = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(request, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


@pytest.mark.integration
def test_cluster_two_machine_build_and_heal(tmp_path):
    csv_path = tmp_path / "cluster.csv"
    with open(csv_path, "w") as f:
        f.write("f1,f2,label\n")
        for i in range(120):
            lab = i % 2
            # non-negative features: the build includes nb, which keeps
            # MLlib's non-negativity contract
            f.write(
                f"{lab * 2 + (i % 7) * 0.1:.3f},"
                f"{2 - lab + (i % 5) * 0.1:.3f},{lab}\n"
            )

    head_data = tmp_path / "head_data"
    worker_data = tmp_path / "worker_data"
    manifest = {
        "transport": "local",
        "head": {
            "host": "127.0.0.1",
            "bind": "127.0.0.1",
            "data_dir": str(head_data),
            "workers": 0,
        },
        "workers": [{"host": "127.0.0.1", "data_dir": str(worker_data)}],
        "models_dir": str(tmp_path / "models"),
        "store_port": _free_port(),
        "coord_port": _free_port(),
        "restart_delay": 0.5,
        "env": {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "LO_EPHEMERAL": "1",
            "LO_RESTART_DELAY": "0.5",
        },
    }
    manifest_path = tmp_path / "manifest.json"
    manifest_path.write_text(json.dumps(manifest))

    # the deploy artifact can also just SHOW the wiring
    rendered = subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO_ROOT, "deploy", "cluster.py"),
            "render",
            str(manifest_path),
        ],
        capture_output=True,
        text=True,
        cwd=_REPO_ROOT,
    )
    assert rendered.returncode == 0
    assert "LO_PROCESS_BASE=1" in rendered.stdout
    assert "LO_TOTAL_PROCESSES=2" in rendered.stdout

    driver = subprocess.Popen(
        [
            sys.executable,
            os.path.join(_REPO_ROOT, "deploy", "cluster.py"),
            "up",
            str(manifest_path),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=str(tmp_path),
        start_new_session=True,
    )
    head_ports = head_data / "stack_ports.json"
    worker_ports = worker_data / "stack_ports.json"

    def wait_cluster_up(deadline_s: float) -> dict:
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            if driver.poll() is not None:
                raise AssertionError(
                    f"cluster driver died:\n{driver.stdout.read()}"
                )
            if (tmp_path / "cluster_state.json").exists() and (
                head_ports.exists()
            ):
                state = json.loads(head_ports.read_text())
                if len(state["ports"]) >= 7:
                    return state
            time.sleep(0.5)
        raise AssertionError("cluster never came up")

    def build_once(state: dict, name: str) -> None:
        db = state["ports"]["database_api"]
        mb = state["ports"]["model_builder"]
        dt = state["ports"]["data_type_handler"]
        status, _ = _post(
            f"http://127.0.0.1:{db}/files",
            {"filename": name, "url": str(csv_path)},
        )
        assert status == 201
        deadline = time.time() + 60
        while time.time() < deadline:
            status, body = _get(
                f"http://127.0.0.1:{db}/files/{name}?skip=0&limit=1&query={{}}"
            )
            if status == 200 and body["result"][0].get("finished"):
                break
            time.sleep(0.5)
        request = urllib.request.Request(
            f"http://127.0.0.1:{dt}/fieldtypes/{name}",
            data=json.dumps(
                {"f1": "number", "f2": "number", "label": "number"}
            ).encode(),
            headers={"Content-Type": "application/json"},
            method="PATCH",
        )
        with urllib.request.urlopen(request, timeout=60) as resp:
            assert resp.status == 200
        preprocessor = (
            "from pyspark.ml.feature import VectorAssembler\n"
            "va = VectorAssembler(inputCols=['f1', 'f2'],"
            " outputCol='features')\n"
            "features_training = va.transform(training_df)\n"
            "features_testing = va.transform(testing_df)\n"
            "features_evaluation = va.transform(testing_df)\n"
        )
        status, _ = _post(
            f"http://127.0.0.1:{mb}/models",
            {
                "training_filename": name,
                "test_filename": name,
                "preprocessor_code": preprocessor,
                "classificators_list": ["nb"],
            },
            timeout=600,
        )
        assert status == 201
        status, body = _get(
            f"http://127.0.0.1:{db}/files/{name}_prediction_nb"
            "?skip=0&limit=1&query={}"
        )
        assert status == 200
        assert float(body["result"][0]["accuracy"]) > 0.7

    try:
        state = wait_cluster_up(420)
        build_once(state, "c1")

        # kill the worker machine's runtime member: its stack exits,
        # the DRIVER relaunches every machine's group, and the rebuilt
        # cluster serves again — unattended
        worker_state = json.loads(worker_ports.read_text())
        victim = worker_state["pids"]["worker1"]
        os.kill(victim, signal.SIGKILL)
        deadline = time.time() + 420
        healed = None
        while time.time() < deadline:
            if driver.poll() is not None:
                raise AssertionError(
                    f"cluster driver died:\n{driver.stdout.read()}"
                )
            if worker_ports.exists():
                current = json.loads(worker_ports.read_text())
                pid = current["pids"].get("worker1")
                if pid and pid != victim and head_ports.exists():
                    head_state = json.loads(head_ports.read_text())
                    if len(head_state["ports"]) >= 7:
                        healed = head_state
                        break
            time.sleep(0.5)
        assert healed is not None, "cluster did not heal after worker death"
        time.sleep(2)  # let the coordinator finish publishing
        build_once(json.loads(head_ports.read_text()), "c2")
    finally:
        try:
            os.killpg(os.getpgid(driver.pid), signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            driver.wait(60)
        except subprocess.TimeoutExpired:
            os.killpg(os.getpgid(driver.pid), signal.SIGKILL)


# --- driver unit tests (no cluster bring-up) --------------------------------
def _load_cluster_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lo_deploy_cluster",
        os.path.join(_REPO_ROOT, "deploy", "cluster.py"),
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _manifest(transport="ssh", env=None):
    return {
        "repo": "/opt/my repo",  # space: the quoting under test
        "python": "python3",
        "transport": transport,
        "store_port": 27027,
        "coord_port": 12355,
        "env": env or {},
        "workers": [
            {
                "host": "10.0.0.2",
                "ssh": "user@10.0.0.2",
                "data_dir": "lo_data",
                "processes": 1,
            }
        ],
        "restart_delay": 5,
        "head": {
            "host": "10.0.0.1",
            "bind": "0.0.0.0",
            "ssh": "user@10.0.0.1",
            "data_dir": "/var/lo data",  # space again
            "workers": 0,
        },
    }


class TestPlanCommand:
    def test_ssh_quoting_and_env_propagation(self):
        cluster = _load_cluster_module()
        manifest = _manifest(
            env={"LO_EXTRA": "a b", "LO_QUOTE": "it's"}
        )
        plans = cluster.machine_plans(manifest)
        head_cmd = cluster.plan_command(manifest, plans[0])
        assert head_cmd[:3] == ["ssh", "-o", "BatchMode=yes"]
        assert head_cmd[3] == "user@10.0.0.1"
        remote = head_cmd[4]
        # repo path with a space survives the shell round-trip
        assert "cd '/opt/my repo' && exec env " in remote
        assert remote.endswith("python3 deploy/stack.py")
        # every env value shell-quoted exactly once
        assert "LO_EXTRA='a b'" in remote
        assert 'LO_QUOTE=\'it\'"\'"\'s\'' in remote
        assert "LO_DATA_DIR='/var/lo data'" in remote
        assert "LO_HOST=0.0.0.0" in remote
        # the worker plan carries the cross-machine wiring computed by
        # the driver — store URL, coordinator address, process base
        worker_cmd = cluster.plan_command(manifest, plans[1])
        worker_remote = worker_cmd[4]
        assert "LO_STORE_URL=http://10.0.0.1:27027" in worker_remote
        assert "LO_COORDINATOR=10.0.0.1:12355" in worker_remote
        assert "LO_PROCESS_BASE=1" in worker_remote
        assert "LO_EXTRA='a b'" in worker_remote
        # an ssh target falls back to the manifest host when absent
        plans[1]["ssh"] = None
        assert cluster.plan_command(manifest, plans[1])[3] == "10.0.0.2"

    def test_local_transport_runs_stack_directly(self):
        cluster = _load_cluster_module()
        manifest = _manifest(transport="local")
        plan = cluster.machine_plans(manifest)[0]
        command = cluster.plan_command(manifest, plan)
        assert command[0] == sys.executable
        assert command[1].endswith("stack.py")


class TestRemoteStop:
    def test_stop_issues_explicit_remote_kill(self, monkeypatch):
        cluster = _load_cluster_module()
        manifest = _manifest()
        plan = cluster.machine_plans(manifest)[1]
        machine = cluster.Machine(manifest, plan, log=lambda *_: None)
        calls = []
        monkeypatch.setattr(
            cluster.subprocess,
            "run",
            lambda argv, **kw: calls.append(argv),
        )
        machine.stop()  # never started: the remote kill must still fire
        assert len(calls) == 1
        argv = calls[0]
        assert argv[:3] == ["ssh", "-o", "BatchMode=yes"]
        assert argv[-2] == "user@10.0.0.2"
        assert "pkill -f deploy/stack.py" in argv[-1]

    def test_local_transport_skips_remote_kill(self, monkeypatch):
        cluster = _load_cluster_module()
        manifest = _manifest(transport="local")
        plan = cluster.machine_plans(manifest)[0]
        machine = cluster.Machine(manifest, plan, log=lambda *_: None)
        calls = []
        monkeypatch.setattr(
            cluster.subprocess,
            "run",
            lambda argv, **kw: calls.append(argv),
        )
        machine.stop()
        assert calls == []


class TestSshTransportLifecycle:
    """The exact ssh commands the driver issues, exercised through a
    PATH-shimmed fake `ssh` (ADVICE r5): the launch command must carry
    the stdin-EOF watchdog knob (BatchMode allocates no pty, so a dead
    driver can only signal the remote stack through its stdin), and
    Machine.stop must issue the explicit remote pkill BEFORE a
    whole-cluster relaunch so the old group never lingers holding the
    store/coordinator ports."""

    def _with_fake_ssh(self, tmp_path, monkeypatch):
        ssh_log = tmp_path / "ssh_calls.log"
        bin_dir = tmp_path / "bin"
        bin_dir.mkdir()
        fake_ssh = bin_dir / "ssh"
        fake_ssh.write_text(
            "#!/bin/sh\n"
            f'printf \'%s\\n\' "$*" >> {ssh_log}\n'
            "exec sleep 30\n"
        )
        fake_ssh.chmod(0o755)
        monkeypatch.setenv(
            "PATH", f"{bin_dir}{os.pathsep}{os.environ['PATH']}"
        )
        return ssh_log

    def test_launch_and_stop_issue_the_documented_commands(
        self, tmp_path, monkeypatch
    ):
        ssh_log = self._with_fake_ssh(tmp_path, monkeypatch)
        cluster = _load_cluster_module()
        manifest = _manifest()
        plan = cluster.machine_plans(manifest)[1]  # the worker machine
        machine = cluster.Machine(manifest, plan, log=lambda *_: None)
        machine.start()
        try:
            deadline = time.time() + 10
            while time.time() < deadline and not ssh_log.exists():
                time.sleep(0.05)
            assert ssh_log.exists(), "fake ssh never invoked"
            launch = ssh_log.read_text().splitlines()[0]
            # the exact remote command: BatchMode, target, exec env,
            # and the launcher-death watchdog armed
            assert "-o BatchMode=yes" in launch
            assert "user@10.0.0.2" in launch
            assert "LO_STACK_EXIT_ON_STDIN_EOF=1" in launch
            assert "deploy/stack.py" in launch
        finally:
            machine.stop()
        calls = ssh_log.read_text().splitlines()
        assert len(calls) >= 2, "stop issued no explicit remote kill"
        kill = calls[-1]
        assert "pkill -f deploy/stack.py" in kill
        assert "user@10.0.0.2" in kill
        # the supervised ssh client itself is gone too
        assert machine.proc.poll() is not None

    def test_local_transport_does_not_arm_watchdog(self):
        cluster = _load_cluster_module()
        manifest = _manifest(transport="local")
        plans = cluster.machine_plans(manifest)
        assert all(
            "LO_STACK_EXIT_ON_STDIN_EOF" not in plan["env"]
            for plan in plans
        )


class TestStackStdinWatchdog:
    """deploy/stack.py's side of the contract: with the knob armed, EOF
    on stdin (the ssh channel closing) triggers shutdown."""

    def _load_stack(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "lo_deploy_stack",
            os.path.join(_REPO_ROOT, "deploy", "stack.py"),
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        return module

    def test_eof_sets_stopping(self, monkeypatch):
        import io
        import threading

        stack = self._load_stack()
        monkeypatch.setenv("LO_STACK_EXIT_ON_STDIN_EOF", "1")
        read_fd, write_fd = os.pipe()
        stopping = threading.Event()
        lines = []
        thread = stack.start_stdin_watchdog(
            stopping, lines.append, stream=io.open(read_fd, "rb")
        )
        assert thread is not None
        assert not stopping.wait(0.2)  # channel open: keep running
        os.close(write_fd)  # the launcher dies → EOF
        assert stopping.wait(5), "EOF never triggered shutdown"
        assert any("stdin closed" in line for line in lines)

    def test_knob_off_means_no_watchdog(self, monkeypatch):
        import threading

        stack = self._load_stack()
        monkeypatch.delenv("LO_STACK_EXIT_ON_STDIN_EOF", raising=False)
        assert (
            stack.start_stdin_watchdog(threading.Event(), print) is None
        )


class TestReplicationManifest:
    def test_replication_section_plumbs_env_and_store_urls(self):
        cluster = _load_cluster_module()
        manifest = _manifest()
        manifest["replication"] = {
            "enabled": True,
            "follower_port": 27028,
            "arbiter_port": 27029,
            "auto_promote_s": 5,
            "sync_repl": 1,
        }
        plans = cluster.machine_plans(manifest)
        head_env = plans[0]["env"]
        assert head_env["LO_REPLICATION"] == "1"
        assert head_env["LO_FOLLOWER_PORT"] == "27028"
        assert head_env["LO_ARBITER_PORT"] == "27029"
        assert head_env["LO_AUTO_PROMOTE_S"] == "5"
        assert head_env["LO_STORE_SYNC_REPL"] == "1"
        # every worker's store URL names BOTH stores for client failover
        worker_env = plans[1]["env"]
        assert worker_env["LO_STORE_URL"] == (
            "http://10.0.0.1:27027,http://10.0.0.1:27028"
        )

    def test_replication_validation_rejects_bad_knobs(self, tmp_path):
        cluster = _load_cluster_module()

        def load(replication):
            manifest = _manifest()
            manifest["replication"] = replication
            path = tmp_path / "m.json"
            path.write_text(json.dumps(manifest))
            return cluster.load_manifest(str(path))

        loaded = load({"enabled": True})
        assert loaded["replication"]["follower_port"] == 27028
        with pytest.raises(SystemExit):
            load({"enabled": True, "follower_port": 27027})  # collides
        with pytest.raises(SystemExit):
            load({"enabled": True, "auto_promote_s": 0})
        with pytest.raises(SystemExit):
            load({"enabled": "yes"})
        with pytest.raises(SystemExit):
            load({"surprise_knob": 1})
        with pytest.raises(SystemExit):
            load({"enabled": True, "sync_repl": 2})


class TestShardingManifest:
    def test_sharding_section_plumbs_env_and_store_urls(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest()
        manifest["sharding"] = {
            "shards": 3,
            "stripe_rows": 4096,
            "map_ttl_s": 2,
        }
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        for plan in plans:
            # ring placement is computed client-side on every machine:
            # shards + stripe_rows must be cluster-wide identical
            env = plan["env"]
            assert env["LO_SHARDS"] == "3"
            assert env["LO_SHARD_STRIPE_ROWS"] == "4096"
            assert env["LO_SHARDMAP_TTL_S"] == "2"
        # the worker's store URL is the `;`-joined multi-group grammar,
        # one segment per group at store_port + 10*i
        assert plans[1]["env"]["LO_STORE_URL"] == (
            "http://10.0.0.1:27027;"
            "http://10.0.0.1:27037;"
            "http://10.0.0.1:27047"
        )

    def test_sharding_composes_with_replication(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest()
        manifest["replication"] = {"enabled": True}
        manifest["sharding"] = {"shards": 2}
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        # each `;` group keeps its own comma replica pair (primary at
        # stride base, follower one above) so per-group client failover
        # still works
        assert plans[1]["env"]["LO_STORE_URL"] == (
            "http://10.0.0.1:27027,http://10.0.0.1:27028;"
            "http://10.0.0.1:27037,http://10.0.0.1:27038"
        )

    def test_no_section_means_degenerate_single_group(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest()
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        assert plans[1]["env"]["LO_STORE_URL"] == "http://10.0.0.1:27027"
        for plan in plans:
            assert "LO_SHARDS" not in plan["env"]

    def test_sharding_validation_rejects_bad_knobs(self, tmp_path):
        cluster = _load_cluster_module()

        def load(sharding, extra=None):
            manifest = _manifest()
            manifest["sharding"] = sharding
            for key, value in (extra or {}).items():
                manifest[key] = value
            path = tmp_path / "m.json"
            path.write_text(json.dumps(manifest))
            return cluster.load_manifest(str(path))

        # shards 1 is the explicit degenerate form; ttl 0 = revalidate
        # the map on every read — both valid
        assert load({"shards": 1})["sharding"]["shards"] == 1
        assert load({"map_ttl_s": 0})["sharding"]["map_ttl_s"] == 0
        with pytest.raises(SystemExit):
            load({"surprise_knob": 1})
        with pytest.raises(SystemExit):
            load({"shards": True})  # bool-is-int trap
        with pytest.raises(SystemExit):
            load({"shards": 2.5})  # strictly integral
        with pytest.raises(SystemExit):
            load({"shards": "4"})
        with pytest.raises(SystemExit):
            load({"shards": 0})
        with pytest.raises(SystemExit):
            load({"stripe_rows": 0})
        with pytest.raises(SystemExit):
            load({"map_ttl_s": -1})
        with pytest.raises(SystemExit):
            load({"map_ttl_s": True})
        # a replication port landing inside a shard group's stride
        # window (group 1 claims 27037..27039 here) must refuse
        with pytest.raises(SystemExit):
            load(
                {"shards": 2},
                extra={
                    "replication": {
                        "enabled": True,
                        "follower_port": 27038,
                    }
                },
            )


class TestCoalescingManifest:
    def test_coalescing_section_plumbs_env_cluster_wide(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest()
        manifest["coalescing"] = {"window_ms": 5, "max_jobs": 16}
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        for plan in plans:  # every machine, like sched/serving knobs
            env = plan["env"]
            assert env["LO_COALESCE_WINDOW_MS"] == "5"
            assert env["LO_COALESCE_MAX_JOBS"] == "16"

    def test_coalescing_validation_rejects_bad_knobs(self, tmp_path):
        cluster = _load_cluster_module()

        def load(coalescing):
            manifest = _manifest()
            manifest["coalescing"] = coalescing
            path = tmp_path / "m.json"
            path.write_text(json.dumps(manifest))
            return cluster.load_manifest(str(path))

        # window 0 = passthrough: valid; fractional window: valid
        loaded = load({"window_ms": 0, "max_jobs": 1})
        assert loaded["coalescing"]["window_ms"] == 0
        assert load({"window_ms": 0.5})["coalescing"]["window_ms"] == 0.5
        with pytest.raises(SystemExit):
            load({"surprise_knob": 1})
        with pytest.raises(SystemExit):
            load({"window_ms": -1})
        with pytest.raises(SystemExit):
            load({"max_jobs": 0})
        with pytest.raises(SystemExit):
            load({"max_jobs": 1.5})  # strictly integral
        with pytest.raises(SystemExit):
            load({"max_jobs": True})  # bool-is-int trap
        with pytest.raises(SystemExit):
            load({"window_ms": "2"})


class TestFleetManifest:
    def test_fleet_section_plumbs_env_cluster_wide(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest()
        manifest["fleet"] = {
            "replicas": 4,
            "rf": 2,
            "model_qps": 10,
            "down_s": 1.5,
        }
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        for plan in plans:  # placement geometry must be cluster-uniform
            env = plan["env"]
            assert env["LO_FLEET_REPLICAS"] == "4"
            assert env["LO_FLEET_RF"] == "2"
            assert env["LO_FLEET_MODEL_QPS"] == "10"
            assert env["LO_FLEET_DOWN_S"] == "1.5"

    def test_no_section_means_no_fleet_env(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest()
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        for plan in plans:
            assert "LO_FLEET_REPLICAS" not in plan["env"]

    def test_fleet_validation_rejects_bad_knobs(self, tmp_path):
        cluster = _load_cluster_module()

        def load(fleet):
            manifest = _manifest()
            manifest["fleet"] = fleet
            path = tmp_path / "m.json"
            path.write_text(json.dumps(manifest))
            return cluster.load_manifest(str(path))

        # replicas 1 is the explicit degenerate fleet; qps 0 = quota
        # off; fractional down window — all valid
        assert load({"replicas": 1})["fleet"]["replicas"] == 1
        assert load({"model_qps": 0})["fleet"]["model_qps"] == 0
        assert load({"down_s": 0.5})["fleet"]["down_s"] == 0.5
        with pytest.raises(SystemExit):
            load({"surprise_knob": 1})
        with pytest.raises(SystemExit):
            load({"replicas": True})  # bool-is-int trap
        with pytest.raises(SystemExit):
            load({"replicas": 0})
        with pytest.raises(SystemExit):
            load({"replicas": 2.5})  # strictly integral
        with pytest.raises(SystemExit):
            load({"rf": 0})
        with pytest.raises(SystemExit):
            load({"rf": "2"})
        with pytest.raises(SystemExit):
            load({"model_qps": -1})
        with pytest.raises(SystemExit):
            load({"down_s": 0})
        with pytest.raises(SystemExit):
            load({"down_s": True})


class TestWireManifest:
    def test_wire_section_plumbs_env_cluster_wide(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest()
        manifest["wire"] = {"shm_bytes": 268435456, "dtype_policy": "bf16"}
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        for plan in plans:  # dtype_policy must match on EVERY host
            env = plan["env"]
            assert env["LO_SHM_BYTES"] == "268435456"
            assert env["LO_DTYPE_POLICY"] == "bf16"

    def test_wire_validation_rejects_bad_knobs(self, tmp_path):
        cluster = _load_cluster_module()

        def load(wire):
            manifest = _manifest()
            manifest["wire"] = wire
            path = tmp_path / "m.json"
            path.write_text(json.dumps(manifest))
            return cluster.load_manifest(str(path))

        # shm_bytes 0 = transport off: valid; f32 policy: valid
        loaded = load({"shm_bytes": 0, "dtype_policy": "f32"})
        assert loaded["wire"]["shm_bytes"] == 0
        with pytest.raises(SystemExit):
            load({"surprise_knob": 1})
        with pytest.raises(SystemExit):
            load({"shm_bytes": -1})
        with pytest.raises(SystemExit):
            load({"shm_bytes": True})  # bool-is-int trap
        with pytest.raises(SystemExit):
            load({"shm_bytes": "1e9"})  # bytes are integers
        with pytest.raises(SystemExit):
            load({"shm_bytes": 0.5})
        with pytest.raises(SystemExit):
            load({"dtype_policy": "f16"})  # only f32 | bf16
        with pytest.raises(SystemExit):
            load({"dtype_policy": 1})
        with pytest.raises(SystemExit):
            load({"dtype_policy": True})


class TestServingManifest:
    def test_serving_section_plumbs_env_cluster_wide(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest()
        manifest["serving"] = {
            "serve_bytes": 500_000_000,
            "batch_window_ms": 2,
            "max_batch": 32,
            "max_rows": 2048,
            "queue_cap": 128,
            "timeout_s": 10,
        }
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        for plan in plans:  # every machine, like sched/dataplane knobs
            env = plan["env"]
            assert env["LO_SERVE_BYTES"] == "500000000"
            assert env["LO_SERVE_BATCH_WINDOW_MS"] == "2"
            assert env["LO_SERVE_MAX_BATCH"] == "32"
            assert env["LO_SERVE_MAX_ROWS"] == "2048"
            assert env["LO_SERVE_QUEUE_CAP"] == "128"
            assert env["LO_SERVE_TIMEOUT_S"] == "10"

    def test_serving_validation_rejects_bad_knobs(self, tmp_path):
        cluster = _load_cluster_module()

        def load(serving):
            manifest = _manifest()
            manifest["serving"] = serving
            path = tmp_path / "m.json"
            path.write_text(json.dumps(manifest))
            return cluster.load_manifest(str(path))

        # 0 bytes = host-only fallback, 0 ms window: both valid
        loaded = load({"serve_bytes": 0, "batch_window_ms": 0})
        assert loaded["serving"]["serve_bytes"] == 0
        with pytest.raises(SystemExit):
            load({"surprise_knob": 1})
        with pytest.raises(SystemExit):
            load({"serve_bytes": -1})
        with pytest.raises(SystemExit):
            load({"serve_bytes": True})  # bool-is-int trap
        with pytest.raises(SystemExit):
            load({"serve_bytes": "1e9"})
        with pytest.raises(SystemExit):
            load({"batch_window_ms": -0.5})
        with pytest.raises(SystemExit):
            load({"max_batch": 0})
        with pytest.raises(SystemExit):
            load({"max_batch": 1.5})  # request counts are integers
        with pytest.raises(SystemExit):
            load({"max_rows": 0})
        with pytest.raises(SystemExit):
            load({"queue_cap": 0})
        with pytest.raises(SystemExit):
            load({"timeout_s": 0})


class TestProfilingManifest:
    def test_profiling_section_plumbs_env_cluster_wide(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest()
        manifest["profiling"] = {"prof_hz": 19, "prof_window_s": 30}
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        for plan in plans:  # a stall diagnosis must work on ANY member
            env = plan["env"]
            assert env["LO_PROF_HZ"] == "19"
            assert env["LO_PROF_WINDOW_S"] == "30"

    def test_profiling_validation_rejects_bad_knobs(self, tmp_path):
        cluster = _load_cluster_module()

        def load(profiling):
            manifest = _manifest()
            manifest["profiling"] = profiling
            path = tmp_path / "m.json"
            path.write_text(json.dumps(manifest))
            return cluster.load_manifest(str(path))

        # hz 0 = endpoint disabled: valid; fractional window: valid
        loaded = load({"prof_hz": 0, "prof_window_s": 0.5})
        assert loaded["profiling"]["prof_hz"] == 0
        with pytest.raises(SystemExit):
            load({"surprise_knob": 1})
        with pytest.raises(SystemExit):
            load({"prof_hz": -1})
        with pytest.raises(SystemExit):
            load({"prof_hz": True})  # bool-is-int trap
        with pytest.raises(SystemExit):
            load({"prof_hz": 9.5})  # rates are integers
        with pytest.raises(SystemExit):
            load({"prof_window_s": 0})


class TestWebManifest:
    def test_web_section_plumbs_env_cluster_wide(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest()
        manifest["web"] = {
            "async": 1,
            "handlers": 8,
            "max_conns": 10000,
            "wait_cap_s": 60,
        }
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        for plan in plans:  # every member serves HTTP — all get the knobs
            env = plan["env"]
            assert env["LO_WEB_ASYNC"] == "1"
            assert env["LO_WEB_HANDLERS"] == "8"
            assert env["LO_WEB_MAX_CONNS"] == "10000"
            assert env["LO_WEB_WAIT_CAP_S"] == "60"

    def test_web_validation_rejects_bad_knobs(self, tmp_path):
        cluster = _load_cluster_module()

        def load(web):
            manifest = _manifest()
            manifest["web"] = web
            path = tmp_path / "m.json"
            path.write_text(json.dumps(manifest))
            return cluster.load_manifest(str(path))

        # async 0 = threaded escape hatch: valid; fractional cap: valid
        loaded = load({"async": 0, "wait_cap_s": 0.5})
        assert loaded["web"]["async"] == 0
        with pytest.raises(SystemExit):
            load({"surprise_knob": 1})
        with pytest.raises(SystemExit):
            load({"async": True})  # bool-is-int trap
        with pytest.raises(SystemExit):
            load({"async": 2})
        with pytest.raises(SystemExit):
            load({"handlers": 0})
        with pytest.raises(SystemExit):
            load({"handlers": 9.5})  # widths are integers
        with pytest.raises(SystemExit):
            load({"wait_cap_s": 0})


class TestResumeManifest:
    def test_resume_section_plumbs_env_cluster_wide(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest()
        manifest["resume"] = {"enabled": 1, "every_segments": 4}
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        for plan in plans:  # recovery must agree on EVERY member
            env = plan["env"]
            assert env["LO_RESUME"] == "1"
            assert env["LO_RESUME_EVERY_SEGMENTS"] == "4"

    def test_resume_section_absent_sets_nothing(self, tmp_path):
        # absent section = runner defaults; the driver must not pin the
        # knobs to anything
        cluster = _load_cluster_module()
        path = tmp_path / "m.json"
        path.write_text(json.dumps(_manifest()))
        for plan in cluster.machine_plans(cluster.load_manifest(str(path))):
            assert "LO_RESUME" not in plan["env"]
            assert "LO_RESUME_EVERY_SEGMENTS" not in plan["env"]

    def test_resume_validation_rejects_bad_knobs(self, tmp_path):
        cluster = _load_cluster_module()

        def load(resume):
            manifest = _manifest()
            manifest["resume"] = resume
            path = tmp_path / "m.json"
            path.write_text(json.dumps(manifest))
            return cluster.load_manifest(str(path))

        # enabled 0 = the pre-resume contract: valid
        loaded = load({"enabled": 0, "every_segments": 1})
        assert loaded["resume"]["enabled"] == 0
        with pytest.raises(SystemExit):
            load({"surprise_knob": 1})
        with pytest.raises(SystemExit):
            load({"enabled": 2})
        with pytest.raises(SystemExit):
            # bool-is-int trap: str(True) is "True", which the runner's
            # strict 0/1 preflight would then refuse on every machine
            load({"enabled": True})
        with pytest.raises(SystemExit):
            load({"enabled": "1"})
        with pytest.raises(SystemExit):
            load({"every_segments": 0})
        with pytest.raises(SystemExit):
            load({"every_segments": 1.5})  # strictly integral
        with pytest.raises(SystemExit):
            load({"every_segments": True})


class TestCompileManifest:
    def test_compile_section_plumbs_env_cluster_wide(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest()
        manifest["compile"] = {"aot": 1, "max_programs": 128, "publish": 0}
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        for plan in plans:  # every member enumerates the SAME grid
            env = plan["env"]
            assert env["LO_AOT"] == "1"
            assert env["LO_AOT_MAX_PROGRAMS"] == "128"
            assert env["LO_AOT_PUBLISH"] == "0"

    def test_compile_section_absent_sets_nothing(self, tmp_path):
        cluster = _load_cluster_module()
        path = tmp_path / "m.json"
        path.write_text(json.dumps(_manifest()))
        for plan in cluster.machine_plans(cluster.load_manifest(str(path))):
            assert "LO_AOT" not in plan["env"]
            assert "LO_AOT_MAX_PROGRAMS" not in plan["env"]
            assert "LO_AOT_PUBLISH" not in plan["env"]

    def test_compile_validation_rejects_bad_knobs(self, tmp_path):
        cluster = _load_cluster_module()

        def load(compile_knobs):
            manifest = _manifest()
            manifest["compile"] = compile_knobs
            path = tmp_path / "m.json"
            path.write_text(json.dumps(manifest))
            return cluster.load_manifest(str(path))

        # max_programs 0 = enumerate-and-drop-all: valid (drops logged)
        loaded = load({"aot": 0, "max_programs": 0, "publish": 1})
        assert loaded["compile"]["max_programs"] == 0
        with pytest.raises(SystemExit):
            load({"surprise_knob": 1})
        with pytest.raises(SystemExit):
            load({"aot": 2})
        with pytest.raises(SystemExit):
            # bool-is-int trap: str(True) is "True", which the runner's
            # strict 0/1 preflight would then refuse on every machine
            load({"aot": True})
        with pytest.raises(SystemExit):
            load({"publish": True})
        with pytest.raises(SystemExit):
            load({"aot": "1"})
        with pytest.raises(SystemExit):
            load({"max_programs": -1})
        with pytest.raises(SystemExit):
            load({"max_programs": 64.0})  # strictly integral
        with pytest.raises(SystemExit):
            load({"max_programs": True})


class TestMetricsScrape:
    def test_parse_prometheus_sums_families(self):
        cluster = _load_cluster_module()
        text = (
            "# HELP lo_http_requests_total requests\n"
            "# TYPE lo_http_requests_total counter\n"
            'lo_http_requests_total{service="a",status="200"} 3\n'
            'lo_http_requests_total{service="b",status="500"} 2\n'
            'lo_http_request_duration_seconds_bucket{le="+Inf"} 5\n'
            "lo_jobs_running 1\n"
            "garbage line without value\n"
        )
        families = cluster.parse_prometheus(text)
        assert families["lo_http_requests_total"] == 5
        assert families["lo_jobs_running"] == 1
        # histogram buckets are shape, not totals — skipped
        assert "lo_http_request_duration_seconds_bucket" not in families

    def test_summary_line(self):
        cluster = _load_cluster_module()
        line = cluster.metrics_summary_line(
            {
                "_members": 2,
                "lo_http_requests_total": 7.0,
                "lo_jobs_running": 1.0,
            }
        )
        assert line.startswith("[cluster] metrics: members=2")
        assert "http_requests_total=7" in line
        assert "jobs_running=1" in line

    def test_parse_prometheus_strict_raises_on_garbage(self):
        cluster = _load_cluster_module()
        with pytest.raises(ValueError):
            cluster.parse_prometheus("garbage line without value\n", strict=True)
        # lenient default unchanged: the garbage line is skipped
        assert cluster.parse_prometheus("garbage line without value\n") == {}


def _metrics_member(payload: bytes, content_length=None):
    """A live /metrics member for scrape tests; returns (server, url).
    ``content_length`` larger than the payload simulates a member dying
    mid-response (the client sees a truncated body)."""
    import http.server
    import threading

    declared = len(payload) if content_length is None else content_length

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path != "/metrics":
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(declared))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *args):
            pass

    server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_port}"


class TestScrapeRobustness:
    def test_malformed_and_truncated_bodies_are_counted_skips(self):
        """Regression: a member answering garbage (mid-restart, a proxy
        error page) or a truncated body must be a per-member counted
        skip — the healthy members' totals land untouched and the
        scrape thread never crashes."""
        cluster = _load_cluster_module()
        healthy, healthy_url = _metrics_member(b"lo_jobs_running 2\n")
        garbage, garbage_url = _metrics_member(b"garbage line without value\n")
        binary, binary_url = _metrics_member(b"\x00\xff\xfe not text")
        truncated, truncated_url = _metrics_member(
            b"lo_jobs_running 9\n", content_length=4096
        )
        try:
            totals, texts = cluster.scrape_member_metrics([
                healthy_url, garbage_url, binary_url, truncated_url,
                "http://127.0.0.1:9",  # nothing listening
            ])
        finally:
            for server in (healthy, garbage, binary, truncated):
                server.shutdown()
                server.server_close()
        assert totals["_members"] == 1
        assert totals["_malformed"] == 2  # garbage + undecodable
        assert totals["lo_jobs_running"] == 2.0  # healthy member only
        assert list(texts) == [healthy_url]
        line = cluster.metrics_summary_line(totals)
        assert "members=1" in line and "malformed=2" in line

    def test_push_member_metrics_lands_in_store_ring(self):
        """Driver-side ingest push → the head store's retention ring:
        the cluster-mode path that replaces per-process collectors."""
        from learningorchestra_tpu.core.store import InMemoryStore
        from learningorchestra_tpu.telemetry import tsdb
        from learningorchestra_tpu.telemetry.metrics import MetricsRegistry
        from learningorchestra_tpu.utils.web import ServerThread, WebApp

        cluster = _load_cluster_module()
        store = InMemoryStore()
        app = WebApp("store", registry=MetricsRegistry())
        app.register_observability(store)
        server = ServerThread(app, "127.0.0.1", 0).start()
        try:
            store_url = f"http://127.0.0.1:{server.port}"
            texts = {
                "http://10.0.0.7:5002": "lo_jobs_total 4\n",
                "http://10.0.0.7:27027": "lo_store_docs 11\n",
            }
            logs = []
            pushed = cluster.push_member_metrics(
                store_url, texts, log=logs.append
            )
            assert pushed == 2 and logs == []
            history = tsdb.history(store, "lo_jobs_total")
            assert [v for _, v in history["10.0.0.7:5002"]] == [4.0]
            # the port → service map labels the instances
            assert tsdb.services_of(store) == {
                "10.0.0.7:5002": "model_builder",
                "10.0.0.7:27027": "store",
            }
            # a dead store head: logged per member, never a raise
            logs = []
            assert cluster.push_member_metrics(
                "http://127.0.0.1:9", {"http://h:5001": "lo_x 1\n"},
                log=logs.append,
            ) == 0
            assert len(logs) == 1 and "push failed" in logs[0]
        finally:
            server.stop()


class TestObservabilityManifest:
    def test_tsdb_and_slo_sections_plumb_env_cluster_wide(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest()
        manifest["tsdb"] = {
            "points": 128, "interval_s": 15, "trace_ring": 512,
        }
        manifest["slo"] = {
            "window_s": 300, "serve_p99_s": 0.25, "http_5xx_rate": 1,
            "queue_depth": 32, "replication_lag": 500,
        }
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        for plan in plans:  # every machine: one ring, one threshold set
            env = plan["env"]
            assert env["LO_TSDB_POINTS"] == "128"
            assert env["LO_METRICS_INTERVAL_S"] == "15"
            assert env["LO_TRACE_RING"] == "512"
            assert env["LO_SLO_WINDOW_S"] == "300"
            assert env["LO_SLO_SERVE_P99_S"] == "0.25"
            assert env["LO_SLO_5XX_RATE"] == "1"
            assert env["LO_SLO_QUEUE_DEPTH"] == "32"
            assert env["LO_SLO_REPL_LAG"] == "500"

    def test_driver_owns_collection_and_names_the_plane(self, tmp_path):
        cluster = _load_cluster_module()
        path = tmp_path / "m.json"
        path.write_text(json.dumps(_manifest()))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        for plan in plans:
            env = plan["env"]
            # the driver's scrape loop owns retention: fallback
            # collectors off everywhere unless the manifest says so
            assert env["LO_TSDB_COLLECT"] == "0"
            members = env["LO_PLANE_MEMBERS"].split(",")
            assert "http://10.0.0.1:27027" in members  # head store
            assert "http://10.0.0.1:5002" in members  # model_builder
            assert len(members) == 1 + len(cluster.SERVICE_PORTS)

    def test_manifest_env_wins_over_defaults(self, tmp_path):
        cluster = _load_cluster_module()
        manifest = _manifest(
            env={"LO_TSDB_COLLECT": "1", "LO_PLANE_MEMBERS": "http://x:1"}
        )
        path = tmp_path / "m.json"
        path.write_text(json.dumps(manifest))
        plans = cluster.machine_plans(cluster.load_manifest(str(path)))
        for plan in plans:
            assert plan["env"]["LO_TSDB_COLLECT"] == "1"
            assert plan["env"]["LO_PLANE_MEMBERS"] == "http://x:1"

    def test_tsdb_validation_rejects_bad_knobs(self, tmp_path):
        cluster = _load_cluster_module()

        def load(tsdb):
            manifest = _manifest()
            manifest["tsdb"] = tsdb
            path = tmp_path / "m.json"
            path.write_text(json.dumps(manifest))
            return cluster.load_manifest(str(path))

        # fractional scrape cadence is valid; integral knobs are strict
        assert load({"interval_s": 0.5})["tsdb"]["interval_s"] == 0.5
        with pytest.raises(SystemExit):
            load({"surprise_knob": 1})
        with pytest.raises(SystemExit):
            load({"points": 0})
        with pytest.raises(SystemExit):
            load({"points": 1.5})  # strictly integral
        with pytest.raises(SystemExit):
            load({"points": True})  # bool-is-int trap
        with pytest.raises(SystemExit):
            load({"trace_ring": 0})
        with pytest.raises(SystemExit):
            load({"interval_s": 0})
        with pytest.raises(SystemExit):
            load({"interval_s": True})

    def test_slo_validation_rejects_bad_knobs(self, tmp_path):
        cluster = _load_cluster_module()

        def load(slo):
            manifest = _manifest()
            manifest["slo"] = slo
            path = tmp_path / "m.json"
            path.write_text(json.dumps(manifest))
            return cluster.load_manifest(str(path))

        # 0 = alert on any breach: valid for the rate/latency objectives
        assert load({"serve_p99_s": 0})["slo"]["serve_p99_s"] == 0
        with pytest.raises(SystemExit):
            load({"surprise_knob": 1})
        with pytest.raises(SystemExit):
            load({"window_s": 0})
        with pytest.raises(SystemExit):
            load({"serve_p99_s": -0.1})
        with pytest.raises(SystemExit):
            load({"queue_depth": 0})
        with pytest.raises(SystemExit):
            load({"queue_depth": 1.5})  # strictly integral
        with pytest.raises(SystemExit):
            load({"queue_depth": True})  # bool-is-int trap
        with pytest.raises(SystemExit):
            load({"replication_lag": 0})
        with pytest.raises(SystemExit):
            load({"window_s": "600"})
