"""Multi-chip behaviour on the 8-device virtual CPU mesh: estimators
produce mesh-shape-independent results, shardings are real (rows
actually land on different devices), and the driver dry-run passes."""

import numpy as np
import pytest

import __graft_entry__ as graft_entry
from learningorchestra_tpu.ml.evaluation import accuracy_score
from learningorchestra_tpu.ml.logistic import LogisticRegression
from learningorchestra_tpu.ml.naive_bayes import NaiveBayes
from learningorchestra_tpu.ml.trees import GBTClassifier, RandomForestClassifier
from learningorchestra_tpu.parallel.mesh import make_mesh
from learningorchestra_tpu.parallel.sharding import shard_rows


@pytest.fixture()
def data(rng):
    X = rng.normal(size=(640, 6))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(int)
    return X, y


class TestShardingIsReal:
    def test_rows_split_across_devices(self, rng):
        mesh = make_mesh(data=8, model=1)
        X = rng.normal(size=(64, 4)).astype(np.float32)
        X_dev, mask = shard_rows(X, mesh)
        shards = X_dev.addressable_shards
        assert len(shards) == 8
        assert all(s.data.shape == (8, 4) for s in shards)
        devices = {s.device for s in shards}
        assert len(devices) == 8

    def test_model_axis_mesh(self):
        mesh = make_mesh(data=4, model=2)
        assert mesh.shape == {"data": 4, "model": 2}


class TestMeshShapeInvariance:
    """The same fit on 1, 8x1 and 4x2 meshes must give equal-quality
    models: sharding is a deployment knob, not a semantic one."""

    def test_nb_identical_probabilities(self, data):
        X, y = data
        X = np.abs(X)
        probs = []
        for mesh in (
            make_mesh(data=1, model=1),
            make_mesh(data=8, model=1),
            make_mesh(data=4, model=2),
        ):
            model = NaiveBayes(mesh=mesh).fit(X, y)
            probs.append(model.predict_proba(X))
        np.testing.assert_allclose(probs[0], probs[1], atol=1e-5)
        np.testing.assert_allclose(probs[0], probs[2], atol=1e-5)

    def test_lr_same_accuracy_with_tp(self, data):
        X, y = data
        accuracies = []
        for mesh in (make_mesh(data=1, model=1), make_mesh(data=4, model=2)):
            model = LogisticRegression(max_iter=30, mesh=mesh).fit(X, y)
            accuracies.append(accuracy_score(y, model.predict(X)))
        assert abs(accuracies[0] - accuracies[1]) < 0.02

    def test_rf_same_accuracy(self, data):
        X, y = data
        accuracies = []
        for mesh in (
            make_mesh(data=1, model=1),
            make_mesh(data=8, model=1),
            # trees sharded over the model axis (10 trees / 2 shards)
            make_mesh(data=4, model=2),
        ):
            model = RandomForestClassifier(num_trees=10, mesh=mesh).fit(X, y)
            accuracies.append(accuracy_score(y, model.predict(X)))
        # same seed, same binning; bootstrap draws are identical so the
        # forests match up to padded-row scatter order
        assert abs(accuracies[0] - accuracies[1]) < 0.02
        assert abs(accuracies[0] - accuracies[2]) < 0.02

    def test_rf_tree_axis_actually_sharded(self, data):
        from learningorchestra_tpu.parallel.mesh import MODEL_AXIS

        X, y = data
        mesh = make_mesh(data=4, model=2)
        model = RandomForestClassifier(num_trees=10, mesh=mesh).fit(X, y)
        sharding = model.features_heap.sharding
        assert sharding.spec[0] == MODEL_AXIS
        # 5 trees per model shard, on distinct device groups
        shard_rows = {s.data.shape[0] for s in model.features_heap.addressable_shards}
        assert shard_rows == {5}

    def test_gbt_same_accuracy(self, data):
        X, y = data
        accuracies = []
        for mesh in (make_mesh(data=1, model=1), make_mesh(data=8, model=1)):
            model = GBTClassifier(rounds=5, mesh=mesh).fit(X, y)
            accuracies.append(accuracy_score(y, model.predict(X)))
        assert abs(accuracies[0] - accuracies[1]) < 0.02

    def test_tsne_mesh_invariant(self, rng):
        # The affinity matrix is deterministic and must match across
        # mesh shapes (per-chip slabs + psum are just a different
        # reduction order). The optimized coordinates are chaotic —
        # float reassociation amplifies over iterations — so the
        # embedding itself is judged on cluster structure, not values.
        import jax.numpy as jnp

        from learningorchestra_tpu.ops.tsne import (
            _affinities,
            _pad_for_mesh,
            tsne_embedding,
        )

        centers = np.array([[10, 0], [0, 10], [5, -8]])
        labels = rng.integers(0, 3, size=120)
        X = (centers[labels] + rng.normal(size=(120, 2))).astype(np.float32)
        meshes = (
            make_mesh(data=1, model=1),
            make_mesh(data=8, model=1),
            make_mesh(data=4, model=2),
        )
        affinity_matrices = []
        for mesh in meshes:
            X_pad, valid, chunk = _pad_for_mesh(X, mesh, 1024)
            P = _affinities(
                mesh, jnp.asarray(X_pad), jnp.asarray(valid),
                jnp.float32(10.0), chunk,
            )
            affinity_matrices.append(np.asarray(P)[:120, :120])
        np.testing.assert_allclose(
            affinity_matrices[0], affinity_matrices[1], atol=1e-7
        )
        np.testing.assert_allclose(
            affinity_matrices[0], affinity_matrices[2], atol=1e-7
        )
        for mesh in meshes:
            embedded = tsne_embedding(X, iterations=250, seed=3, mesh=mesh)
            d = ((embedded[:, None, :] - embedded[None, :, :]) ** 2).sum(-1)
            np.fill_diagonal(d, np.inf)
            assert (labels[d.argmin(axis=1)] == labels).mean() > 0.9

    def test_pca_mesh_invariant(self, rng):
        from learningorchestra_tpu.ops.pca import pca_embedding

        X = rng.normal(size=(200, 5))
        results = [
            pca_embedding(X, mesh=mesh)
            for mesh in (make_mesh(data=1, model=1), make_mesh(data=8, model=1))
        ]
        np.testing.assert_allclose(results[0], results[1], atol=1e-3)


class TestDriverDryrun:
    def test_entry_compiles(self):
        import jax

        fn, args = graft_entry.entry()
        loss = jax.jit(fn)(*args)
        assert np.isfinite(float(loss))

    def test_dryrun_8(self):
        graft_entry.dryrun_multichip(8)

    def test_dryrun_2(self):
        graft_entry.dryrun_multichip(2)
