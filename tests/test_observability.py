"""Fleet observability plane (docs/observability.md "Fleet plane"):
in-store time-series retention (telemetry/tsdb.py + the store's trim
primitive), SLO rule evaluation and its chaos visibility
(telemetry/slo.py), and cross-process trace stitching
(telemetry/tracing.py export buffer + telemetry/stitch.py)."""

import json
import os
import time

import numpy as np
import pytest

from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.core.store import InMemoryStore
from learningorchestra_tpu.serve.batcher import LATENCY_BUCKETS, MicroBatcher
from learningorchestra_tpu.telemetry import slo, stitch, tracing, tsdb
from learningorchestra_tpu.telemetry.metrics import (
    MetricsRegistry,
    global_registry,
)
from learningorchestra_tpu.testing import faults
from learningorchestra_tpu.utils.web import WebApp


def body(response):
    return json.loads(response.get_data())


@pytest.fixture(autouse=True)
def _clean_plane(monkeypatch):
    # local-only stitching: no accidental HTTP fan-out from /traces/<cid>
    monkeypatch.delenv("LO_PLANE_MEMBERS", raising=False)
    yield
    faults.reset()
    with tracing._EXPORT_LOCK:
        tracing._EXPORT.clear()
        tracing._EXPORT_ORDER.clear()
    with slo._STATUS_LOCK:
        slo._STATUS_CACHE.clear()


# --- exposition parsing ------------------------------------------------------

class TestParseSamples:
    def test_counters_sum_across_label_sets(self):
        vals = tsdb.parse_samples(
            "# HELP lo_jobs_total jobs\n"
            "# TYPE lo_jobs_total counter\n"
            'lo_jobs_total{service="a"} 7\n'
            'lo_jobs_total{service="b"} 2\n'
            "lo_jobs_running 3\n"
        )
        assert vals["lo_jobs_total"] == 9.0
        assert vals["lo_jobs_running"] == 3.0

    def test_5xx_derived_from_status_labels(self):
        vals = tsdb.parse_samples(
            'lo_http_requests_total{service="a",route="/x",status="200"} 7\n'
            'lo_http_requests_total{service="a",route="/x",status="500"} 2\n'
            'lo_http_requests_total{service="b",route="/y",status="503"} 1\n'
        )
        assert vals["lo_http_requests_total"] == 10.0
        assert vals[tsdb.DERIVED_5XX] == 3.0

    def test_5xx_zero_when_no_errors(self):
        # the derived family must EXIST at 0.0 so the SLO rate rule has
        # a baseline, not a missing series
        vals = tsdb.parse_samples(
            'lo_http_requests_total{status="200"} 7\n'
        )
        assert vals[tsdb.DERIVED_5XX] == 0.0

    def test_histogram_family_merges_label_sets(self):
        vals = tsdb.parse_samples(
            'lo_x_seconds_bucket{route="a",le="0.1"} 3\n'
            'lo_x_seconds_bucket{route="a",le="+Inf"} 4\n'
            'lo_x_seconds_bucket{route="b",le="0.1"} 1\n'
            'lo_x_seconds_bucket{route="b",le="+Inf"} 1\n'
            "lo_x_seconds_sum 0.9\n"
            "lo_x_seconds_count 5\n"
        )
        assert vals["lo_x_seconds"] == {
            "buckets": {"0.1": 4.0, "+Inf": 5.0},
            "sum": 0.9,
            "count": 5.0,
        }

    def test_malformed_bodies_raise(self):
        with pytest.raises(ValueError):
            tsdb.parse_samples("garbage line without value")
        with pytest.raises(ValueError):
            tsdb.parse_samples("lo_x 1e")  # truncated value token
        with pytest.raises(ValueError):
            tsdb.parse_samples('lo_x_bucket{route="a"} 3')  # bucket, no le

    def test_comments_and_blanks_skipped(self):
        assert tsdb.parse_samples("# only comments\n\n") == {}


# --- store trim primitive ----------------------------------------------------

class TestTrimCollection:
    def test_oldest_first_and_rev_bump(self, store):
        for i in range(10):
            store.insert_one("ring", {"v": i})
        rev_before = store.collection_rev("ring")
        assert store.trim_collection("ring", 4) == 6
        assert sorted(doc["v"] for doc in store.find("ring")) == [6, 7, 8, 9]
        assert store.collection_rev("ring") > rev_before

    def test_noop_under_cap(self, store):
        for i in range(3):
            store.insert_one("ring", {"v": i})
        rev = store.collection_rev("ring")
        assert store.trim_collection("ring", 10) == 0
        assert store.collection_rev("ring") == rev
        assert store.trim_collection("missing", 5) == 0

    def test_rejects_bool_float_negative(self, store):
        for bad in (True, False, 2.0, -1):
            with pytest.raises(ValueError):
                store.trim_collection("ring", bad)

    def test_wal_replays_the_trim(self, tmp_path):
        durable = InMemoryStore(data_dir=str(tmp_path))
        for i in range(6):
            durable.insert_one("ring", {"v": i})
        assert durable.trim_collection("ring", 2) == 4
        reopened = InMemoryStore(data_dir=str(tmp_path))
        assert sorted(doc["v"] for doc in reopened.find("ring")) == [4, 5]


# --- TSDB retention + rollups ------------------------------------------------

class TestTSDB:
    def test_ring_cap_evicts_oldest_first(self, store):
        db = tsdb.TSDB(store, points=3)
        for i in range(5):
            db.append("m1", "svc", {"lo_g": float(i)}, ts=1000.0 + 60 * i)
        docs = sorted(store.find(tsdb.COLLECTION), key=lambda d: d["ts"])
        assert [doc["ts"] for doc in docs] == [1120.0, 1180.0, 1240.0]

    def test_budget_scales_with_instances(self, store):
        db = tsdb.TSDB(store, points=2)
        for i in range(3):
            db.append("m1", "a", {"x": float(i)}, ts=100.0 * i)
            db.append("m2", "b", {"x": float(i)}, ts=100.0 * i + 1)
        docs = list(store.find(tsdb.COLLECTION))
        assert len(docs) == 4  # 2 points x 2 instances
        per_instance = {
            inst: sorted(d["ts"] for d in docs if d["instance"] == inst)
            for inst in ("m1", "m2")
        }
        assert per_instance == {"m1": [100.0, 200.0], "m2": [101.0, 201.0]}

    def test_delta_compression_and_fold_forward(self, store):
        db = tsdb.TSDB(store)
        db.append("m1", "svc", {"a": 1.0, "b": 2.0}, ts=0.0)
        db.append("m1", "svc", {"a": 1.0, "b": 3.0}, ts=60.0)
        docs = sorted(store.find(tsdb.COLLECTION), key=lambda d: d["ts"])
        assert docs[0]["vals"] == {"a": 1.0, "b": 2.0}
        assert docs[1]["vals"] == {"b": 3.0}  # only the changed family
        # readers undo the compression: unchanged ticks carry the value
        assert tsdb.history(store, "a")["m1"] == [(0.0, 1.0), (60.0, 1.0)]
        assert tsdb.history(store, "b")["m1"] == [(0.0, 2.0), (60.0, 3.0)]

    def test_counter_rate_golden(self, store):
        db = tsdb.TSDB(store)
        for tick, total in ((0.0, 0.0), (60.0, 60.0), (120.0, 120.0)):
            db.append("m1", "svc", {"lo_c_total": total}, ts=tick)
        points = tsdb.history(store, "lo_c_total")["m1"]
        rolled = tsdb.rollup("lo_c_total", points, window_s=120.0, now=120.0)
        assert rolled["delta"] == 120.0
        assert rolled["rate"] == 1.0  # 120 increments over a 120 s span

    def test_counter_reset_falls_back_to_post_restart_totals(self, store):
        db = tsdb.TSDB(store)
        for tick, total in ((0.0, 100.0), (60.0, 120.0), (120.0, 5.0)):
            db.append("m1", "svc", {"lo_c_total": total}, ts=tick)
        points = tsdb.history(store, "lo_c_total")["m1"]
        rolled = tsdb.rollup("lo_c_total", points, window_s=120.0, now=120.0)
        assert rolled["delta"] == 5.0  # not -95

    def test_histogram_p99_golden(self, store):
        db = tsdb.TSDB(store)
        zero = {
            "buckets": {"0.1": 0.0, "1.0": 0.0, "+Inf": 0.0},
            "sum": 0.0,
            "count": 0.0,
        }
        later = {
            "buckets": {"0.1": 90.0, "1.0": 100.0, "+Inf": 100.0},
            "sum": 30.0,
            "count": 100.0,
        }
        db.append("m1", "svc", {"lo_h_seconds": zero}, ts=0.0)
        db.append("m1", "svc", {"lo_h_seconds": later}, ts=60.0)
        points = tsdb.history(store, "lo_h_seconds")["m1"]
        rolled = tsdb.rollup("lo_h_seconds", points, window_s=60.0, now=60.0)
        assert rolled["count"] == 100.0
        assert rolled["mean"] == 0.3
        # histogram_quantile interpolation: rank 99 lands in (0.1, 1.0]
        assert rolled["p99"] == pytest.approx(0.91)
        assert rolled["p50"] == pytest.approx(0.055556)

    def test_restart_reseeds_without_redump_and_revs_advance(self, store):
        first = tsdb.TSDB(store)
        first.append("m1", "svc", {"a": 1.0, "b": 2.0}, ts=0.0)
        rev_before = store.collection_rev(tsdb.COLLECTION)
        # a NEW appender over the same store = a restarted collector
        second = tsdb.TSDB(store)
        second.append("m1", "svc", {"a": 1.0, "b": 2.0}, ts=60.0)
        docs = sorted(store.find(tsdb.COLLECTION), key=lambda d: d["ts"])
        assert docs[1]["vals"] == {}  # reseeded: no spurious full redump
        assert store.collection_rev(tsdb.COLLECTION) > rev_before
        second.append("m1", "svc", {"a": 1.0, "b": 5.0}, ts=120.0)
        # fold-forward continuity across the restart boundary
        assert tsdb.history(store, "b")["m1"] == [
            (0.0, 2.0), (60.0, 2.0), (120.0, 5.0),
        ]

    def test_collector_scrapes_registry(self, store):
        registry = MetricsRegistry()
        jobs = registry.counter("lo_jobs_total", "jobs")
        jobs.inc(4)
        collector = tsdb.Collector(
            store, registry, instance="r1", service="runner",
            interval_s=3600,
        )
        collector.collect_once(ts=1000.0)
        jobs.inc(2)
        collector.collect_once(ts=1060.0)
        assert collector.ticks == 2 and collector.errors == 0
        assert tsdb.history(store, "lo_jobs_total")["r1"] == [
            (1000.0, 4.0), (1060.0, 6.0),
        ]

    def test_collector_counts_and_swallows_failures(self, store):
        class _BrokenRegistry:
            def render(self):
                raise RuntimeError("scrape exploded")

        collector = tsdb.Collector(
            store, _BrokenRegistry(), instance="r1", service="runner",
            interval_s=3600,
        )
        collector.collect_once(ts=1000.0)  # must not raise
        assert collector.ticks == 0 and collector.errors == 1


# --- SLO rules ---------------------------------------------------------------

class TestSLO:
    def test_scripted_burn_and_clear(self, store):
        db = tsdb.TSDB(store)
        db.append(
            "sched1", "runner", {"lo_sched_queue_depth": 100.0}, ts=1000.0
        )
        result = slo.evaluate(store, now=1000.0)
        assert result["burning"] == ["sched_queue_depth"]
        assert result["degraded"] is True
        entry = next(
            r for r in result["rules"] if r["rule"] == "sched_queue_depth"
        )
        assert entry["value"] == 100.0 and entry["instance"] == "sched1"
        db.append(
            "sched1", "runner", {"lo_sched_queue_depth": 3.0}, ts=1060.0
        )
        result = slo.evaluate(store, now=1060.0)
        assert result["burning"] == [] and result["degraded"] is False

    def test_fault_latency_flips_exactly_one_rule(self, store, monkeypatch):
        """The chaos-visibility loop: an injected serve.forward latency
        fault must surface as the serve_p99 rule burning — and ONLY that
        rule — then clear once the fault is disarmed and the window
        slides past the slow burst."""
        monkeypatch.setenv("LO_SLO_SERVE_P99_S", "0.02")

        class _FakeModel:
            def predict_both(self, X):
                return (
                    np.zeros(len(X), np.int64),
                    np.zeros((len(X), 2), np.float32),
                )

        class _InstantRegistry:
            def get(self, path):
                return _FakeModel()

        registry = MetricsRegistry()
        # the route-level histogram model_builder observes into
        serve_seconds = registry.histogram(
            "lo_serve_request_seconds", "test latency",
            buckets=LATENCY_BUCKETS,
        )
        collector = tsdb.Collector(
            store, registry, instance="serve1", service="model_builder",
            interval_s=3600,
        )
        batcher = MicroBatcher(
            _InstantRegistry(), window_s=0.0, max_batch=4, inbox_cap=8
        )
        try:
            faults.install("serve.forward", "delay:0.08")
            started = time.perf_counter()
            request = batcher.submit("m", np.zeros((1, 2)))
            assert request.wait(10.0) and request.error is None
            elapsed = time.perf_counter() - started
            assert elapsed >= 0.08  # the fault really delayed the forward
            serve_seconds.observe(elapsed)
            collector.collect_once(ts=1000.0)
            status = slo.evaluate(store, now=1000.0)
            assert status["burning"] == ["serve_p99"]  # exactly one rule
            # heal: disarm the fault, fast traffic, window slides on
            faults.reset()
            for _ in range(50):
                serve_seconds.observe(0.001)
            collector.collect_once(ts=1400.0)
            collector.collect_once(ts=2000.0)
            status = slo.evaluate(store, now=2000.0)
            assert status["burning"] == []
            assert status["degraded"] is False
        finally:
            batcher.close()

    def test_status_cached_per_rev(self, store):
        db = tsdb.TSDB(store)
        db.append("i1", "svc", {"lo_g": 1.0}, ts=100.0)
        first = slo.status(store)
        assert slo.status(store) is first  # same rev: cached verbatim
        db.append("i1", "svc", {"lo_g": 2.0}, ts=160.0)
        assert slo.status(store) is not first  # rev moved: re-evaluated

    def test_debug_slo_and_health_degraded_routes(self, store):
        app = WebApp("obs", registry=MetricsRegistry())
        app.register_job_routes(JobManager())
        app.register_observability(store)
        client = app.test_client()
        db = tsdb.TSDB(store)
        db.append(
            "sched1", "runner", {"lo_sched_queue_depth": 100.0}, ts=1000.0
        )
        payload = body(client.get("/debug/slo"))["result"]
        assert payload["degraded"] is True
        assert payload["burning"] == ["sched_queue_depth"]
        assert body(client.get("/health"))["degraded"] is True
        db.append(
            "sched1", "runner", {"lo_sched_queue_depth": 1.0}, ts=1060.0
        )
        assert body(client.get("/health"))["degraded"] is False

    def test_burning_gauge_published(self, store):
        db = tsdb.TSDB(store)
        db.append(
            "sched1", "runner", {"lo_sched_queue_depth": 100.0}, ts=1000.0
        )
        slo.publish(store, now=1000.0)

        def gauge_value():
            for line in global_registry().render().splitlines():
                if line.startswith("lo_slo_burning") and (
                    'rule="sched_queue_depth"' in line
                ):
                    return float(line.rsplit(" ", 1)[1])
            raise AssertionError("lo_slo_burning gauge not rendered")

        assert gauge_value() == 1.0
        db.append(
            "sched1", "runner", {"lo_sched_queue_depth": 1.0}, ts=1060.0
        )
        slo.publish(store, now=1060.0)
        assert gauge_value() == 0.0


# --- trace stitching ---------------------------------------------------------

def _export_one(cid, service, names=("alpha",)):
    trace = tracing.Trace(cid)
    with tracing.activate(trace):
        for name in names:
            with tracing.span(name):
                pass
    tracing.export_trace(trace, service=service)
    return trace


class TestStitch:
    def test_golden_stitched_fields(self):
        pid = os.getpid()
        trace = tracing.Trace("cid_golden_1")
        with tracing.activate(trace):
            with tracing.span("alpha"):
                with tracing.span("alpha:child"):
                    pass
        tracing.export_trace(trace, service="svc_a")
        _export_one("cid_golden_1", "svc_b", names=("beta",))
        out = stitch.stitched_trace("cid_golden_1", members=[])
        assert out["displayTimeUnit"] == "ms"
        assert out["otherData"]["correlation_id"] == "cid_golden_1"
        # deterministic layout: sorted group keys -> synthetic pids
        assert out["otherData"]["processes"] == {
            0: f"svc_a@{pid}", 1: f"svc_b@{pid}",
        }
        names = {
            event["args"]["name"]
            for event in out["traceEvents"]
            if event["ph"] == "M" and event["name"] == "process_name"
        }
        assert names == {f"svc_a@{pid}", f"svc_b@{pid}"}
        complete = [e for e in out["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in complete} == {
            "alpha", "alpha:child", "beta",
        }
        # all events anchored to one shared t0: the earliest span is 0
        assert min(e["ts"] for e in complete) == 0.0
        assert all(e["dur"] >= 0 for e in complete)

    def test_fanout_merges_remote_groups(self, monkeypatch):
        _export_one("cid_fanout", "svc_local")
        remote_group = {
            "svc_remote@9999": {
                "service": "svc_remote",
                "pid": 9999,
                "spans": [{
                    "name": "remote_work", "start_ts": 10.0,
                    "duration_s": 0.5, "tid": 1, "children": [],
                }],
            }
        }
        calls = []

        def fake_fetch(base_url, cid, since=None):
            calls.append((base_url, cid, since))
            return remote_group

        monkeypatch.setattr(stitch, "fetch_member_spans", fake_fetch)
        out = stitch.stitched_trace(
            "cid_fanout", members=["http://remote:1"]
        )
        assert calls == [("http://remote:1", "cid_fanout", None)]
        assert set(out["otherData"]["processes"].values()) == {
            f"svc_local@{os.getpid()}", "svc_remote@9999",
        }
        assert any(
            e["name"] == "remote_work" for e in out["traceEvents"]
        )

    def test_fanout_dedupes_own_group(self, monkeypatch):
        """A member list naming the serving process itself must replace
        the identical local group, not duplicate the row."""
        _export_one("cid_dedupe", "svc_self")
        key = f"svc_self@{os.getpid()}"
        local = tracing.exported_spans("cid_dedupe")["cid_dedupe"]

        monkeypatch.setattr(
            stitch, "fetch_member_spans",
            lambda base_url, cid, since=None: dict(local["groups"]),
        )
        out = stitch.stitched_trace("cid_dedupe", members=["http://me:1"])
        assert list(out["otherData"]["processes"].values()) == [key]

    def test_fetch_skips_down_member(self):
        # nothing listens on port 1: a partial stitch, never a raise
        assert stitch.fetch_member_spans("http://127.0.0.1:1", "cid") == {}

    def test_trace_ring_bounds_export_buffer(self, monkeypatch):
        monkeypatch.setenv("LO_TRACE_RING", "2")
        for i in range(3):
            _export_one(f"cid_ring_{i}", "svc")
        assert tracing.exported_spans("cid_ring_0") == {}  # evicted
        assert "cid_ring_2" in tracing.exported_spans()
        trace = tracing.Trace("cid_ring_many")
        with tracing.activate(trace):
            for _ in range(5):
                with tracing.span("s"):
                    pass
        tracing.export_trace(trace, service="svc")
        groups = tracing.exported_spans("cid_ring_many")[
            "cid_ring_many"]["groups"]
        assert len(groups[f"svc@{os.getpid()}"]["spans"]) == 2

    def test_trace_ring_knob_rejects_bad_values(self, monkeypatch):
        for bad in ("0", "-3", "1.5", "yes"):
            monkeypatch.setenv("LO_TRACE_RING", bad)
            with pytest.raises(ValueError):
                tracing.trace_ring()
        monkeypatch.setenv("LO_TRACE_RING", "7")
        assert tracing.trace_ring() == 7

    def test_debug_spans_route(self):
        _export_one("cid_route_1", "svc_r")
        app = WebApp("obs", registry=MetricsRegistry())
        client = app.test_client()
        payload = body(client.get("/debug/spans?cid=cid_route_1"))["result"]
        groups = payload["cid_route_1"]["groups"]
        assert f"svc_r@{os.getpid()}" in groups
        assert client.get("/debug/spans?since=nope").status_code == 400
        # a since in the future filters everything out
        future = time.time() + 3600
        assert body(
            client.get(f"/debug/spans?cid=cid_route_1&since={future}")
        )["result"] == {}

    def test_traces_route(self):
        app = WebApp("obs", registry=MetricsRegistry())
        client = app.test_client()
        assert client.get("/traces/unknown_cid").status_code == 404
        _export_one("cid_route_2", "svc_t")
        payload = body(client.get("/traces/cid_route_2"))
        assert payload["otherData"]["correlation_id"] == "cid_route_2"
        assert payload["otherData"]["processes"]

    def test_remember_ring_honours_knob(self, monkeypatch):
        monkeypatch.setenv("LO_TRACE_RING", "2")
        for i in range(3):
            tracing.remember_trace(tracing.Trace(f"cid_recall_{i}"))
        assert tracing.recall_trace("cid_recall_0") is None
        assert tracing.recall_trace("cid_recall_2") is not None


# --- /metrics/history + ingest -----------------------------------------------

class TestHistoryRoute:
    def _app(self, store):
        registry = MetricsRegistry()
        app = WebApp("obs", registry=registry)
        app.register_observability(store)
        return app, registry

    def test_p99_after_burst(self, store):
        """The acceptance-shaped read: a latency burst lands in the
        retention ring and GET /metrics/history answers a non-empty
        windowed p99 for lo_serve_request_seconds."""
        app, registry = self._app(store)
        serve_seconds = registry.histogram(
            "lo_serve_request_seconds", "t", buckets=LATENCY_BUCKETS
        )
        for _ in range(90):
            serve_seconds.observe(0.001)
        for _ in range(10):
            serve_seconds.observe(0.2)
        collector = tsdb.Collector(
            store, registry, instance="serve1", service="model_builder",
            interval_s=3600,
        )
        collector.collect_once(ts=1000.0)
        assert collector.ticks == 1 and collector.errors == 0
        payload = body(app.test_client().get(
            "/metrics/history?family=lo_serve_request_seconds"
        ))["result"]
        rolled = payload["rollup"]["serve1"]
        assert rolled["count"] == 100.0
        assert rolled["p99"] > 0.1  # the slow tail is visible
        assert payload["series"]["serve1"]
        assert payload["services"]["serve1"] == "model_builder"

    def test_since_filter_and_bad_args(self, store):
        app, _ = self._app(store)
        client = app.test_client()
        db = tsdb.TSDB(store)
        db.append("m1", "svc", {"lo_g": 1.0}, ts=100.0)
        db.append("m1", "svc", {"lo_g": 2.0}, ts=200.0)
        payload = body(client.get(
            "/metrics/history?family=lo_g&since=150"
        ))["result"]
        assert payload["series"]["m1"] == [[200.0, 2.0]]
        assert client.get("/metrics/history").status_code == 400
        assert client.get(
            "/metrics/history?family=lo_g&window=abc"
        ).status_code == 400

    def test_ingest_roundtrip(self, store):
        app, _ = self._app(store)
        client = app.test_client()
        response = client.post("/metrics/ingest", json={
            "instance": "10.0.0.7:5002", "service": "model_builder",
            "text": "lo_jobs_total 4\n", "ts": 1000.0,
        })
        assert response.status_code == 200
        assert body(response)["families"] == 1
        assert tsdb.history(store, "lo_jobs_total")["10.0.0.7:5002"] == [
            (1000.0, 4.0),
        ]
        assert tsdb.services_of(store) == {"10.0.0.7:5002": "model_builder"}

    def test_ingest_rejects_bad_bodies(self, store):
        app, _ = self._app(store)
        client = app.test_client()
        assert client.post(
            "/metrics/ingest", json={"text": "x 1\n"}
        ).status_code == 400
        response = client.post("/metrics/ingest", json={
            "instance": "i", "text": "garbage line without value\n",
        })
        assert response.status_code == 400
        assert body(response)["result"] == "unparseable"
        assert list(store.find(tsdb.COLLECTION)) == []  # nothing landed


# --- SDK correlation ---------------------------------------------------------

class TestClientCorrelation:
    def test_context_mints_one_cid_per_run(self):
        import learningorchestra_tpu.client as lo_client

        saved_cid = lo_client.correlation_id
        saved_url = getattr(lo_client, "cluster_url", None)
        try:
            first = lo_client.Context("10.0.0.1")
            assert first.correlation_id
            assert lo_client._correlation_headers() == {
                "X-Correlation-Id": first.correlation_id
            }
            second = lo_client.Context("10.0.0.1")
            assert second.correlation_id != first.correlation_id
        finally:
            lo_client.correlation_id = saved_cid
            if saved_url is not None:
                lo_client.cluster_url = saved_url

    def test_no_header_without_context(self):
        import learningorchestra_tpu.client as lo_client

        saved_cid = lo_client.correlation_id
        try:
            lo_client.correlation_id = None
            assert lo_client._correlation_headers() == {}
        finally:
            lo_client.correlation_id = saved_cid
