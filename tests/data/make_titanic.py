"""Regenerate a statistically faithful Titanic train/test pair.

The golden-parity test (tests/test_titanic_golden.py) needs the Kaggle
Titanic CSVs the reference's documented walkthrough uses
(reference: learning_orchestra_client/readme.md "usage example";
expected outputs in docs/database_api.md:76-83). This environment has
no network egress, so the datasets are REGENERATED from the real
dataset's published joint statistics rather than downloaded:

- 891 training rows; survival cross-tabulated EXACTLY by (Sex, Pclass):
  female 1st 91/94, 2nd 70/76, 3rd 72/144; male 1st 45/122, 2nd 17/108,
  3rd 47/347 (the canonical crosstab — total 342 survivors).
- Titles via Name (for the preprocessor's regexp_extract): Mr/Mrs/Miss/
  Master plus the rare titles at their real counts, consistent with sex
  and age (Master = young boys, Mrs = married women).
- Age: 177 missing (19.9%), class- and title-conditional normals
  matched to the real means (overall mean 29.7, std 14.5).
- SibSp/Parch marginals matched; Embarked S 644 / C 168 / Q 77 with 2
  missing; class-conditional fares (mean 84.15/20.66/13.68).
- 418 test rows with the same structure, no Survived column (the real
  Kaggle test.csv has none; the walkthrough fills label with lit(0)).

Deterministic: seed 1912. Run this file to rewrite the CSVs."""

from __future__ import annotations

import csv
import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))

# (sex, pclass) -> (count, survivors) — the real training crosstab
CROSSTAB = {
    ("female", 1): (94, 91),
    ("female", 2): (76, 70),
    ("female", 3): (144, 72),
    ("male", 1): (122, 45),
    ("male", 2): (108, 17),
    ("male", 3): (347, 47),
}
# title age means/stds from the real data (the preprocessor's
# imputation table uses 22/46/5/33/36 for Miss/Other/Master/Mr/Mrs)
TITLE_AGE = {
    "Master": (4.6, 3.6),
    "Miss": (21.8, 12.0),
    "Mr": (32.4, 12.7),
    "Mrs": (35.9, 11.4),
    "Other": (45.9, 12.0),
}
RARE_MALE = ["Dr", "Rev", "Major", "Col", "Capt", "Sir", "Don", "Jonkheer"]
RARE_FEMALE = ["Mlle", "Mme", "Ms", "Lady", "Countess"]
CLASS_FARE = {1: (84.15, 60.0), 2: (20.66, 10.0), 3: (13.68, 8.0)}

SURNAMES = [
    "Smith", "Andersson", "Johnson", "Brown", "Williams", "Kelly",
    "Svensson", "Olsen", "Murphy", "Jones", "Miller", "Davies",
    "Wilson", "Taylor", "Thomas", "Palsson", "Carter", "Goodwin",
    "Fortune", "Harris", "Becker", "Laroche", "Nilsson", "Hansen",
    "Moran", "Rice", "Flynn", "Sage", "Panula", "Skoog", "Ford",
    "Asplund", "Baclini", "Boulos", "Cacic", "Dean", "Elias",
]
FIRST_M = [
    "John", "William", "Charles", "George", "Thomas", "James", "Karl",
    "Johan", "Patrick", "Henry", "Edward", "Frederick", "Albert",
    "Arthur", "Richard", "Samuel", "Victor", "Ernest", "Oscar", "Nils",
]
FIRST_F = [
    "Mary", "Anna", "Margaret", "Elizabeth", "Bridget", "Catherine",
    "Alice", "Ellen", "Bertha", "Agnes", "Helen", "Ada", "Emily",
    "Hanora", "Maria", "Augusta", "Ellis", "Jessie", "Selma", "Hulda",
]


def _title_for(rng, sex: str, rare_pool: list) -> str:
    if rare_pool:
        return rare_pool.pop()
    if sex == "male":
        # 40 Masters among 577 males in the real data
        return "Master" if rng.random() < 40 / 560 else "Mr"
    # 125 Mrs / 182 Miss among 314 females (minus rares)
    return "Mrs" if rng.random() < 125 / 307 else "Miss"


def _age_for(rng, title: str, pclass: int):
    group = {
        "Master": "Master", "Miss": "Miss", "Mrs": "Mrs", "Mr": "Mr",
    }.get(title, "Other")
    mean, std = TITLE_AGE[group]
    mean += {1: 6.0, 2: 0.0, 3: -3.5}[pclass]  # 1st class skews older
    age = rng.normal(mean, std)
    age = float(np.clip(age, 0.42, 80.0))
    if age > 12:
        return float(int(round(age)))
    return round(age * 2) / 2  # children get half-year ages


def _family(rng, title: str, age, sex: str):
    """SibSp/Parch roughly matching the real marginals (0 dominates),
    with children carrying parents and Mrs carrying a spouse."""
    if title == "Master" or (age is not None and age < 15):
        sibsp = int(rng.choice([0, 1, 2, 3, 4], p=[0.25, 0.3, 0.2, 0.15, 0.1]))
        parch = int(rng.choice([1, 2], p=[0.55, 0.45]))
        return sibsp, parch
    if title == "Mrs":
        sibsp = int(rng.choice([0, 1, 2], p=[0.25, 0.65, 0.1]))
        parch = int(rng.choice([0, 1, 2, 3], p=[0.5, 0.25, 0.15, 0.1]))
        return sibsp, parch
    sibsp = int(rng.choice([0, 1, 2], p=[0.78, 0.18, 0.04]))
    parch = int(rng.choice([0, 1, 2], p=[0.85, 0.1, 0.05]))
    return sibsp, parch


def _embarked(rng, pclass: int) -> str:
    # S 644 / C 168 / Q 77; Cherbourg skews 1st class, Queenstown 3rd
    if pclass == 1:
        return rng.choice(["S", "C", "Q"], p=[0.60, 0.38, 0.02])
    if pclass == 2:
        return rng.choice(["S", "C", "Q"], p=[0.89, 0.09, 0.02])
    return rng.choice(["S", "C", "Q"], p=[0.72, 0.13, 0.15])


def _rows(rng, crosstab, with_survived: bool, start_id: int):
    rare_m = list(RARE_MALE)
    rare_f = list(RARE_FEMALE)
    rng.shuffle(rare_m)
    rng.shuffle(rare_f)
    people = []
    for (sex, pclass), (count, survivors) in crosstab.items():
        flags = [1] * survivors + [0] * (count - survivors)
        rng.shuffle(flags)
        for flag in flags:
            # rare titles only in 1st/2nd class, matching the real data
            pool = (
                (rare_m if sex == "male" else rare_f)
                if pclass <= 2 and rng.random() < 0.12
                else []
            )
            title = _title_for(rng, sex, pool)
            age = _age_for(rng, title, pclass)
            if rng.random() < 177 / 891:  # real missing-age rate
                age = None
            sibsp, parch = _family(rng, title, age, sex)
            first = rng.choice(FIRST_M if sex == "male" else FIRST_F)
            surname = rng.choice(SURNAMES)
            name = f"{surname}, {title}. {first}"
            fare_mean, fare_std = CLASS_FARE[pclass]
            fare = round(max(0.0, rng.normal(fare_mean, fare_std)), 4)
            ticket = f"{rng.integers(1000, 400000)}"
            cabin = (
                f"{rng.choice(list('ABCDE'))}{rng.integers(1, 130)}"
                if pclass == 1 and rng.random() < 0.7
                else ""
            )
            embarked = _embarked(rng, pclass)
            people.append(
                {
                    "Survived": flag,
                    "Pclass": pclass,
                    "Name": name,
                    "Sex": sex,
                    "Age": "" if age is None else age,
                    "SibSp": sibsp,
                    "Parch": parch,
                    "Ticket": ticket,
                    "Fare": fare,
                    "Cabin": cabin,
                    "Embarked": embarked,
                }
            )
    rng.shuffle(people)
    # two missing Embarked values, like the real training set
    if with_survived:
        people[100]["Embarked"] = ""
        people[400]["Embarked"] = ""
    for i, person in enumerate(people):
        person["PassengerId"] = start_id + i
        if not with_survived:
            person.pop("Survived")
    return people


def write(path: str, rows: list, fields: list) -> None:
    with open(path, "w", newline="") as handle:
        writer = csv.DictWriter(handle, fieldnames=fields)
        writer.writeheader()
        writer.writerows(rows)


def main() -> None:
    rng = np.random.default_rng(1912)
    train = _rows(rng, CROSSTAB, with_survived=True, start_id=1)
    assert len(train) == 891
    assert sum(r["Survived"] for r in train) == 342
    # test set: 418 rows, same structure scaled down, no Survived
    test_tab = {
        ("female", 1): (50, 0),
        ("female", 2): (30, 0),
        ("female", 3): (72, 0),
        ("male", 1): (57, 0),
        ("male", 2): (63, 0),
        ("male", 3): (146, 0),
    }
    test = _rows(rng, test_tab, with_survived=False, start_id=892)
    assert len(test) == 418
    write(
        os.path.join(HERE, "titanic_train.csv"),
        train,
        [
            "PassengerId", "Survived", "Pclass", "Name", "Sex", "Age",
            "SibSp", "Parch", "Ticket", "Fare", "Cabin", "Embarked",
        ],
    )
    write(
        os.path.join(HERE, "titanic_test.csv"),
        test,
        [
            "PassengerId", "Pclass", "Name", "Sex", "Age",
            "SibSp", "Parch", "Ticket", "Fare", "Cabin", "Embarked",
        ],
    )
    print("wrote titanic_train.csv (891 rows) and titanic_test.csv (418 rows)")


if __name__ == "__main__":
    main()
