"""AOT compile plane (docs/compile.md): manifest enumeration, the
boot precompile pass, executable serialization, the fleet cache's
trust boundary, and the restart drill.

The load-bearing claims, each pinned here:

- the program universe is finite, deterministic, and capped LOUDLY
  (dropped specs are returned and logged, never silently absent);
- an AOT compile writes the SAME persistent-cache entry the request
  path would read (compile → recompile is a cache hit);
- a serialized executable round-trips bit-identically;
- the fleet cache discards version-mismatched or corrupt artifacts
  WITHOUT deserializing them, and a half-published artifact (chunks,
  no meta row) is invisible;
- a runner restarted after kill -9 with an EMPTY local cache replays
  the published programs with ZERO compile misses (the whole plane's
  contract, end to end across real processes).
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from learningorchestra_tpu import compile as lo_compile
from learningorchestra_tpu.compile import config as compile_config
from learningorchestra_tpu.compile import fleetcache
from learningorchestra_tpu.compile.manifest import (
    ProgramSpec,
    enumerate_programs,
    lr_segment_iters,
    serve_row_buckets,
    specs_for_artifact,
)
from learningorchestra_tpu.utils import jitcache


@pytest.fixture()
def mesh():
    from learningorchestra_tpu.ml.base import resolve_mesh

    return resolve_mesh(None)


@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    """Point JAX's persistent compilation cache at a per-test dir.

    Bypasses enable_compile_cache()'s first-dir-wins global so tests
    stay hermetic, but applies the same config the product applies —
    including the xla-caches off switch that keeps keys portable."""
    import jax
    from jax._src import compilation_cache

    d = str(tmp_path / "jit_cache")
    os.makedirs(d, exist_ok=True)
    old_dir = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_enable_xla_caches", "")
    # earlier compiles in this process may have initialized (or
    # memoized away) the cache under the previous dir — start over
    compilation_cache.reset_cache()
    jitcache._register_listeners()
    monkeypatch.setattr(jitcache, "_ACTIVE_DIR", d)
    yield d
    jax.config.update("jax_compilation_cache_dir", old_dir)
    compilation_cache.reset_cache()


class TestConfig:
    def test_defaults(self, monkeypatch):
        for name in ("LO_AOT", "LO_AOT_MAX_PROGRAMS", "LO_AOT_PUBLISH"):
            monkeypatch.delenv(name, raising=False)
        assert compile_config.validate_env() == {
            "LO_AOT": 0,
            "LO_AOT_MAX_PROGRAMS": 64,
            "LO_AOT_PUBLISH": 1,
        }

    def test_happy_path(self, monkeypatch):
        monkeypatch.setenv("LO_AOT", "1")
        monkeypatch.setenv("LO_AOT_MAX_PROGRAMS", "0")
        monkeypatch.setenv("LO_AOT_PUBLISH", "0")
        assert compile_config.validate_env() == {
            "LO_AOT": 1,
            "LO_AOT_MAX_PROGRAMS": 0,
            "LO_AOT_PUBLISH": 0,
        }

    @pytest.mark.parametrize("value", ["2", "yes", "true", "1.0"])
    def test_bad_flag_rejected(self, monkeypatch, value):
        monkeypatch.setenv("LO_AOT", value)
        with pytest.raises(ValueError):
            compile_config.validate_env()

    @pytest.mark.parametrize("value", ["64.0", "-1", "many"])
    def test_bad_max_programs_rejected(self, monkeypatch, value):
        monkeypatch.setenv("LO_AOT_MAX_PROGRAMS", value)
        with pytest.raises(ValueError):
            compile_config.validate_env()

    @pytest.mark.parametrize("value", ["2", "on"])
    def test_bad_publish_rejected(self, monkeypatch, value):
        monkeypatch.setenv("LO_AOT_PUBLISH", value)
        with pytest.raises(ValueError):
            compile_config.validate_env()


class TestManifest:
    def test_universe_covers_every_program_family(self, mesh):
        kept, dropped = enumerate_programs(mesh, max_programs=10_000)
        assert not dropped
        families = {spec.program for spec in kept}
        assert families >= {
            "predict:lr", "predict:nb", "predict:dt", "predict:rf",
            "predict:gb", "build:lr", "build:nb", "sweep:lr",
        }

    def test_keys_unique_and_deterministic(self, mesh):
        kept, _ = enumerate_programs(mesh, max_programs=10_000)
        keys = [spec.key for spec in kept]
        assert len(keys) == len(set(keys))
        again, _ = enumerate_programs(mesh, max_programs=10_000)
        assert [s.key for s in again] == keys  # fleet-wide agreement

    def test_cap_returns_the_drop_list(self, mesh):
        full, _ = enumerate_programs(mesh, max_programs=10_000)
        kept, dropped = enumerate_programs(mesh, max_programs=3)
        assert len(kept) == 3
        # nothing silently vanishes: kept + dropped IS the universe
        assert [s.key for s in kept + dropped] == [s.key for s in full]
        # predicts sort first: cheapest compiles, costliest to miss
        assert all(s.program.startswith("predict:") for s in kept)

    def test_cap_zero_keeps_nothing(self, mesh):
        kept, dropped = enumerate_programs(mesh, max_programs=0)
        assert kept == [] and len(dropped) > 0

    def test_serve_buckets_collapse_to_fixed_dispatch_shape(self, mesh):
        # the batcher pads every request to grid_size(total, max_batch)
        # with floor=max_batch — ONE compiled predict program per model
        assert len(serve_row_buckets(mesh, max_batch=64)) == 1

    def test_lr_segment_iters_divides_the_budget(self):
        iters = lr_segment_iters(rows=64, features=8, max_iter=100)
        assert isinstance(iters, int) and iters >= 1
        assert 100 % iters == 0  # segments replay the exact fit chain

    def test_specs_for_artifact_reads_checkpoint_shapes(
        self, mesh, tmp_path
    ):
        from learningorchestra_tpu.ml.base import make_classifier
        from learningorchestra_tpu.ml.checkpoint import save_model

        rng = np.random.default_rng(0)
        X = rng.random((32, 5)).astype(np.float32)
        y = (X[:, 0] > 0.5).astype(np.int64)
        model = make_classifier("lr").fit(X, y)
        path = str(tmp_path / "m.model")
        save_model(model, path)
        specs = specs_for_artifact(path, mesh)
        assert specs and all(s.program == "predict:lr" for s in specs)
        assert all(s.features == 5 and s.num_classes == 2 for s in specs)


def _predict_spec(mesh) -> ProgramSpec:
    kept, _ = enumerate_programs(mesh, max_programs=10_000)
    return next(s for s in kept if s.program == "predict:lr")


class TestCompileSpec:
    def test_aot_compile_writes_then_hits_the_persistent_cache(
        self, mesh, cache_dir
    ):
        from learningorchestra_tpu.compile.aot import compile_spec

        spec = _predict_spec(mesh)
        before = jitcache.raw_stats()
        compile_spec(spec)
        assert os.listdir(cache_dir)  # the entry the fleet cache ships
        mid = jitcache.raw_stats()
        assert (
            mid["persistent_cache_misses"]
            == before["persistent_cache_misses"] + 1
        )
        # a recompile of the same spec never re-enters the compiler:
        # in-process jax satisfies it from memory (no second miss); the
        # cross-PROCESS cache load is TestRestartDrill's assertion
        compile_spec(spec)
        after = jitcache.raw_stats()
        assert (
            after["persistent_cache_misses"]
            == mid["persistent_cache_misses"]
        )

    def test_compile_source_attribution_scopes_and_restores(self):
        assert jitcache._COMPILE_SOURCE.get() == ("jit", None)
        with jitcache.compile_source("aot", "k1"):
            assert jitcache._COMPILE_SOURCE.get() == ("aot", "k1")
            with jitcache.compile_source("fleetcache"):
                assert jitcache._COMPILE_SOURCE.get() == (
                    "fleetcache",
                    None,
                )
            assert jitcache._COMPILE_SOURCE.get() == ("aot", "k1")
        assert jitcache._COMPILE_SOURCE.get() == ("jit", None)


class TestSerializeRoundTrip:
    def test_serialized_executable_executes_bit_identically(
        self, mesh, cache_dir
    ):
        import jax

        from learningorchestra_tpu.compile.aot import (
            compile_spec,
            deserialize_compiled,
            serialize_compiled,
        )
        from learningorchestra_tpu.compile.manifest import lower_args

        spec = _predict_spec(mesh)
        compiled = compile_spec(spec)
        blob = serialize_compiled(compiled)
        if blob is None:
            pytest.skip("jax lacks experimental executable serialization")
        restored = deserialize_compiled(blob)
        _, args, _ = lower_args(spec)
        rng = np.random.default_rng(7)
        concrete = jax.tree.map(
            lambda s: (rng.random(s.shape) + 0.5).astype(s.dtype), args
        )
        want = compiled(*concrete)
        got = restored(*concrete)
        for w, g in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(w), np.asarray(g))

    def test_corrupt_blob_raises_for_the_caller_to_discard(self):
        from learningorchestra_tpu.compile.aot import deserialize_compiled

        with pytest.raises(Exception):
            deserialize_compiled(b"not a pickled executable")


def _write_fake_entries(cache_dir: str, n: int = 3) -> dict:
    out = {}
    for i in range(n):
        name = f"jit_fake-{i}-cache"
        data = os.urandom(4096 + i)
        with open(os.path.join(cache_dir, name), "wb") as handle:
            handle.write(data)
        out[name] = data
    return out


class TestFleetCache:
    def test_publish_fetch_round_trip_byte_identity(self, store, tmp_path):
        src = str(tmp_path / "src")
        dst = str(tmp_path / "dst")
        os.makedirs(src)
        os.makedirs(dst)
        files = _write_fake_entries(src)
        stats = fleetcache.publish(store, src)
        assert stats["published"] == len(files)
        fetched = fleetcache.fetch(store, dst)
        assert fetched["fetched"] == len(files)
        for name, data in files.items():
            with open(os.path.join(dst, name), "rb") as handle:
                assert handle.read() == data

    def test_republish_skips_already_published(self, store, tmp_path):
        src = str(tmp_path / "src")
        os.makedirs(src)
        _write_fake_entries(src)
        fleetcache.publish(store, src)
        again = fleetcache.publish(store, src)
        assert again == {"published": 0, "skipped": 3}

    def test_rev_guard_makes_refetch_a_noop(self, store, tmp_path):
        src, dst = str(tmp_path / "src"), str(tmp_path / "dst")
        os.makedirs(src)
        os.makedirs(dst)
        _write_fake_entries(src)
        fleetcache.publish(store, src)
        assert fleetcache.fetch(store, dst)["fetched"] == 3
        assert fleetcache.fetch(store, dst) == {
            "fetched": 0,
            "discarded": 0,
            "skipped": 0,
        }

    def test_version_mismatch_discarded_without_decode(
        self, store, tmp_path, monkeypatch
    ):
        src, dst = str(tmp_path / "src"), str(tmp_path / "dst")
        os.makedirs(src)
        os.makedirs(dst)
        _write_fake_entries(src, n=1)
        monkeypatch.setattr(
            fleetcache,
            "_fingerprint_json",
            lambda: json.dumps({"jaxlib": "0.0.0-other-machine"}),
        )
        fleetcache.publish(store, src)
        monkeypatch.undo()
        stats = fleetcache.fetch(store, dst)
        assert stats["fetched"] == 0 and stats["discarded"] == 1
        assert os.listdir(dst) == []  # recompile, never deserialize

    def test_corrupt_chunks_discarded(self, store, tmp_path):
        import base64

        dst = str(tmp_path / "dst")
        os.makedirs(dst)
        payload = b"executable bytes"
        store.insert_one(
            fleetcache.COLLECTION,
            {
                "artifact": "jit_x-cache",
                "seq": 0,
                "data": base64.b64encode(payload).decode(),
            },
        )
        store.insert_one(
            fleetcache.COLLECTION,
            {
                "artifact": "jit_x-cache",
                "meta": 1,
                "chunks": 1,
                "size": len(payload),
                "sha256": "0" * 64,  # wrong digest
                "fingerprint": fleetcache._fingerprint_json(),
            },
        )
        stats = fleetcache.fetch(store, dst)
        assert stats["discarded"] == 1 and os.listdir(dst) == []

    def test_half_published_artifact_is_invisible(self, store, tmp_path):
        import base64

        dst = str(tmp_path / "dst")
        os.makedirs(dst)
        # chunks landed, meta row (written LAST by publish) did not:
        # the reader must see nothing at all
        store.insert_one(
            fleetcache.COLLECTION,
            {
                "artifact": "jit_partial-cache",
                "seq": 0,
                "data": base64.b64encode(b"half").decode(),
            },
        )
        stats = fleetcache.fetch(store, dst)
        assert stats == {"fetched": 0, "discarded": 0, "skipped": 0}
        assert os.listdir(dst) == []

    def test_path_traversal_artifact_rejected(self, store, tmp_path):
        import base64

        dst = str(tmp_path / "dst")
        os.makedirs(dst)
        evil = os.path.join("..", "evil-cache")
        payload = b"nope"
        store.insert_one(
            fleetcache.COLLECTION,
            {
                "artifact": evil,
                "seq": 0,
                "data": base64.b64encode(payload).decode(),
            },
        )
        store.insert_one(
            fleetcache.COLLECTION,
            {
                "artifact": evil,
                "meta": 1,
                "chunks": 1,
                "size": len(payload),
                "sha256": hashlib.sha256(payload).hexdigest(),
                "fingerprint": fleetcache._fingerprint_json(),
            },
        )
        stats = fleetcache.fetch(store, dst)
        assert stats["fetched"] == 0
        assert not os.path.exists(str(tmp_path / "evil-cache"))


class TestWarmup:
    def test_lr_warmup_derives_width_and_executes(self, tmp_path):
        from learningorchestra_tpu.compile.warmup import warm_artifact
        from learningorchestra_tpu.ml.base import make_classifier
        from learningorchestra_tpu.ml.checkpoint import save_model

        rng = np.random.default_rng(1)
        X = rng.random((32, 6)).astype(np.float32)
        y = (X[:, 0] > 0.5).astype(np.int64)
        path = str(tmp_path / "warm.model")
        save_model(make_classifier("lr").fit(X, y), path)
        assert warm_artifact(path) is True

    def test_tree_warmup_without_width_skips_honestly(self, tmp_path):
        from learningorchestra_tpu.compile.warmup import warm_artifact
        from learningorchestra_tpu.ml.base import make_classifier
        from learningorchestra_tpu.ml.checkpoint import save_model

        rng = np.random.default_rng(2)
        X = rng.random((32, 4)).astype(np.float32)
        y = (X[:, 0] > 0.5).astype(np.int64)
        path = str(tmp_path / "tree.model")
        save_model(make_classifier("dt").fit(X, y), path)
        # tree checkpoints don't record feature width: a guessed-width
        # warmup would compile a program serving never dispatches
        assert warm_artifact(path) is False


class TestPublishHook:
    def test_handler_registration_returns_previous(self):
        calls = []
        old = lo_compile.set_publish_handler(
            lambda path, features: calls.append((path, features))
        )
        try:
            lo_compile.checkpoint_published("/models/a.model", 7)
            assert calls == [("/models/a.model", 7)]
        finally:
            lo_compile.set_publish_handler(old)

    def test_raising_handler_never_fails_the_publication(self):
        def boom(path, features):
            raise RuntimeError("warmup exploded")

        old = lo_compile.set_publish_handler(boom)
        try:
            lo_compile.checkpoint_published("/models/b.model")
        finally:
            lo_compile.set_publish_handler(old)

    def test_default_is_a_noop(self):
        old = lo_compile.set_publish_handler(None)
        try:
            lo_compile.checkpoint_published("/models/c.model")
        finally:
            lo_compile.set_publish_handler(old)


_DRILL_CHILD = textwrap.dedent(
    """
    import hashlib, json, os, sys

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from learningorchestra_tpu.utils import jitcache
    from learningorchestra_tpu.compile.aot import AotPlane
    from learningorchestra_tpu.compile.manifest import (
        enumerate_programs, lower_args,
    )
    from learningorchestra_tpu.core.store_service import RemoteStore
    from learningorchestra_tpu.ml.base import resolve_mesh

    cache_dir = os.environ["DRILL_CACHE_DIR"]
    jitcache.enable_compile_cache(cache_dir)
    store = RemoteStore(os.environ["DRILL_STORE_URL"])
    plane = AotPlane(
        store=store, cache_dir=cache_dir,
        max_programs=int(os.environ["DRILL_MAX_PROGRAMS"]),
    )
    stats = plane.run()
    # execute the first predict program on a fixed input and report a
    # digest: the restarted runner must produce the SAME bits
    mesh = resolve_mesh(None)
    kept, _ = enumerate_programs(
        mesh, max_programs=int(os.environ["DRILL_MAX_PROGRAMS"])
    )
    spec = next(s for s in kept if s.program.startswith("predict:"))
    fn, args, statics = lower_args(spec)
    rng = np.random.default_rng(3)
    concrete = jax.tree.map(
        lambda s: (rng.random(s.shape) + 0.5).astype(s.dtype), args
    )
    out = fn.lower(*concrete, **statics).compile()(*concrete)
    digest = hashlib.sha256(
        b"".join(np.asarray(leaf).tobytes() for leaf in jax.tree.leaves(out))
    ).hexdigest()
    print(json.dumps({
        "stats": stats,
        "digest": digest,
        "raw": jitcache.raw_stats(),
    }), flush=True)
    if os.environ.get("DRILL_SELF_KILL") == "1":
        sys.stdout.flush()
        os.kill(os.getpid(), 9)  # the crash the fleet cache outlives
    """
)


def _run_drill_child(cache_dir, store_url, max_programs, self_kill):
    env = dict(
        os.environ,
        DRILL_CACHE_DIR=cache_dir,
        DRILL_STORE_URL=store_url,
        DRILL_MAX_PROGRAMS=str(max_programs),
        DRILL_SELF_KILL="1" if self_kill else "0",
        JAX_PLATFORMS="cpu",
    )
    env.pop("LO_JIT_CACHE", None)
    proc = subprocess.run(
        [sys.executable, "-c", _DRILL_CHILD],
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    lines = [
        line for line in proc.stdout.splitlines() if line.startswith("{")
    ]
    if not lines:
        raise AssertionError(
            f"drill child produced no record (rc={proc.returncode}): "
            f"{proc.stderr[-800:]}"
        )
    return json.loads(lines[-1]), proc.returncode


class TestRestartDrill:
    def test_restarted_runner_pays_zero_compile_misses(self, tmp_path):
        """kill -9 a runner that compiled + published the grid; a
        restarted runner with an EMPTY local cache fetches the fleet's
        executables and replays every published program with ZERO
        persistent-cache misses — and bit-identical outputs."""
        from learningorchestra_tpu.core.store import InMemoryStore
        from learningorchestra_tpu.core.store_service import create_store_app
        from learningorchestra_tpu.utils.web import ServerThread

        store = InMemoryStore()
        server = ServerThread(
            create_store_app(store), "127.0.0.1", 0
        ).start()
        url = f"http://127.0.0.1:{server.port}"
        first_dir = str(tmp_path / "first")
        restart_dir = str(tmp_path / "restart")
        os.makedirs(first_dir)
        os.makedirs(restart_dir)
        try:
            first, rc = _run_drill_child(
                first_dir, url, max_programs=2, self_kill=True
            )
            assert rc == -9  # it really died mid-flight
            assert first["stats"]["compiled"] == 2
            assert first["stats"]["published"] > 0
            assert store.find(fleetcache.COLLECTION, {"meta": 1})

            restarted, rc = _run_drill_child(
                restart_dir, url, max_programs=2, self_kill=False
            )
            assert rc == 0
            assert restarted["stats"]["fetched"] > 0
            # THE contract: every published program came off the wire
            assert restarted["raw"]["persistent_cache_misses"] == 0
            assert restarted["raw"]["persistent_cache_hits"] >= 2
            assert restarted["digest"] == first["digest"]
        finally:
            server.stop()
