"""Tree estimators vs sklearn oracles and invariants."""

import numpy as np
import pytest
import sklearn.ensemble
import sklearn.tree

from learningorchestra_tpu.ml.base import make_classifier
from learningorchestra_tpu.ml.binning import apply_bins, make_thresholds
from learningorchestra_tpu.ml.evaluation import accuracy_score
from learningorchestra_tpu.ml.trees import (
    DecisionTreeClassifier,
    GBTClassifier,
    RandomForestClassifier,
)


@pytest.fixture()
def nonlinear(rng):
    """XOR-ish data no linear model can fit: tests real tree splits."""
    n = 800
    X = rng.normal(size=(n, 6))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(int)
    return X, y


@pytest.fixture()
def three_class(rng):
    n = 900
    X = rng.normal(size=(n, 5))
    y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
    return X, y


class TestBinning:
    def test_bins_are_monotone_with_values(self, rng):
        X = rng.normal(size=(500, 3))
        thresholds = make_thresholds(X, 32)
        bins = np.asarray(apply_bins(X.astype(np.float32), thresholds.astype(np.float32)))
        for f in range(3):
            order = np.argsort(X[:, f])
            assert (np.diff(bins[order, f]) >= 0).all()
        assert bins.min() >= 0 and bins.max() < 32

    def test_threshold_semantics(self):
        # bin b holds thresholds[b-1] < x <= thresholds[b]
        X = np.array([[1.0], [2.0], [3.0], [4.0]])
        thresholds = np.array([[1.5, 2.5, 3.5]])
        bins = np.asarray(apply_bins(X.astype(np.float32), thresholds.astype(np.float32)))
        assert bins[:, 0].tolist() == [0, 1, 2, 3]


class TestDecisionTree:
    def test_solves_xor(self, nonlinear):
        X, y = nonlinear
        model = DecisionTreeClassifier().fit(X, y)
        assert accuracy_score(y, model.predict(X)) > 0.9

    def test_close_to_sklearn(self, three_class):
        X, y = three_class
        ours = DecisionTreeClassifier(max_depth=5).fit(X, y).predict(X)
        theirs = (
            sklearn.tree.DecisionTreeClassifier(max_depth=5, random_state=0)
            .fit(X, y)
            .predict(X)
        )
        assert np.mean(ours == theirs) > 0.9

    def test_proba_normalized(self, nonlinear):
        X, y = nonlinear
        probs = DecisionTreeClassifier().fit(X, y).predict_proba(X)
        np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-4)

    def test_pure_node_stops_splitting(self, rng):
        # Perfectly separable on one feature: tree must be exact.
        X = rng.normal(size=(200, 3))
        y = (X[:, 2] > 0).astype(int)
        model = DecisionTreeClassifier().fit(X, y)
        assert accuracy_score(y, model.predict(X)) == 1.0


class TestRandomForest:
    def test_solves_xor(self, nonlinear):
        X, y = nonlinear
        model = RandomForestClassifier().fit(X, y)
        assert accuracy_score(y, model.predict(X)) > 0.9

    def test_multiclass(self, three_class):
        X, y = three_class
        model = RandomForestClassifier().fit(X, y)
        assert accuracy_score(y, model.predict(X)) > 0.85

    def test_comparable_to_sklearn_generalization(self, rng):
        n = 1200
        X = rng.normal(size=(n, 6))
        y = ((X[:, 0] * X[:, 1] > 0) & (X[:, 2] > -0.5)).astype(int)
        X_train, X_test = X[:800], X[800:]
        y_train, y_test = y[:800], y[800:]
        ours = RandomForestClassifier().fit(X_train, y_train)
        theirs = sklearn.ensemble.RandomForestClassifier(
            n_estimators=20, max_depth=5, random_state=0
        ).fit(X_train, y_train)
        ours_acc = accuracy_score(y_test, ours.predict(X_test))
        theirs_acc = theirs.score(X_test, y_test)
        assert ours_acc > theirs_acc - 0.07


class TestGBT:
    def test_solves_xor(self, nonlinear):
        X, y = nonlinear
        model = GBTClassifier().fit(X, y)
        assert accuracy_score(y, model.predict(X)) > 0.9

    def test_rejects_multiclass(self, three_class):
        X, y = three_class
        with pytest.raises(ValueError):
            GBTClassifier().fit(X, y)

    def test_proba_binary_shape(self, nonlinear):
        X, y = nonlinear
        probs = GBTClassifier(rounds=5).fit(X, y).predict_proba(X)
        assert probs.shape == (len(X), 2)
        np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)

    def test_comparable_to_sklearn_generalization(self, rng):
        n = 1200
        X = rng.normal(size=(n, 6))
        y = ((X[:, 0] * X[:, 1] > 0) & (X[:, 2] > -0.5)).astype(int)
        X_train, X_test = X[:800], X[800:]
        y_train, y_test = y[:800], y[800:]
        ours = GBTClassifier().fit(X_train, y_train)
        theirs = sklearn.ensemble.GradientBoostingClassifier(
            n_estimators=20, max_depth=5, random_state=0
        ).fit(X_train, y_train)
        ours_acc = accuracy_score(y_test, ours.predict(X_test))
        theirs_acc = theirs.score(X_test, y_test)
        assert ours_acc > theirs_acc - 0.07


class TestSwitcher:
    def test_all_five_names(self, nonlinear):
        X, y = nonlinear
        for name in ("lr", "dt", "rf", "gb", "nb"):
            clf = make_classifier(name)
            model = clf.fit(np.abs(X) if name == "nb" else X, y)
            assert model.predict(X[:10]).shape == (10,)

    def test_unknown_name_raises(self):
        with pytest.raises(KeyError):
            make_classifier("svm")


class TestNaNRouting:
    def test_nan_rows_route_same_at_fit_and_predict(self, rng):
        # NaN in the split feature: training bins NaN into the last bin
        # (right); prediction must send it right too.
        n = 400
        X = rng.normal(size=(n, 2))
        X[: n // 4, 0] = np.nan
        y = np.where(np.isnan(X[:, 0]), 1, (X[:, 0] > 0).astype(int))
        model = DecisionTreeClassifier().fit(X, y)
        pred = model.predict(X)
        nan_rows = np.isnan(X[:, 0])
        assert (pred[nan_rows] == 1).mean() > 0.95
        assert accuracy_score(y, pred) > 0.95


def test_deep_tree_wide_level_routing():
    # depth > 6 exercises the _indicator_lookup gather fallback (a
    # (rows, 2^depth) indicator would dwarf the gather it replaces)
    import numpy as np

    from learningorchestra_tpu.ml.trees import DecisionTreeClassifier

    rng = np.random.default_rng(2)
    X = rng.normal(size=(2000, 6))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0) ^ (X[:, 2] > 0.5)).astype(np.int32)
    model = DecisionTreeClassifier(max_depth=8).fit(X, y)
    accuracy, _ = model.evaluate(X, y)
    assert accuracy > 0.95
