"""Estimators vs sklearn/numpy oracles: evaluation, logistic, naive bayes."""

import numpy as np
import pytest
import sklearn.linear_model
import sklearn.metrics
import sklearn.naive_bayes

from learningorchestra_tpu.ml import (
    LogisticRegression,
    NaiveBayes,
    accuracy_score,
    f1_score,
)


class TestEarlyExitPlateau:
    """The tol early-exit must stop on a genuine plateau and ONLY on
    one: a single floor-step Armijo iteration (one tiny loss delta
    inside an otherwise-descending run) used to satisfy the check and
    stop a fit mid-descent (ADVICE r5)."""

    def test_plateaued_requires_every_delta_and_the_total(self):
        from learningorchestra_tpu.ml.logistic import _plateaued

        tol = 1e-6
        # genuine plateau: stop
        assert _plateaued([0.5, 0.5, 0.5, 0.5], tol, 4)
        # momentary plateau (one tiny delta mid-descent): keep going
        assert not _plateaued([1.0, 0.9999999, 0.99, 0.98], tol, 4)
        assert not _plateaued([1.0, 0.99, 0.9899999, 0.97], tol, 4)
        # too little history: keep going
        assert not _plateaued([0.5, 0.5], tol, 4)
        # slow steady descent whose per-step deltas all sneak under a
        # loose tol but whose window total does not: keep going
        loose = 1.1e-2
        assert not _plateaued([1.03, 1.02, 1.01, 1.00], loose, 4)

    def _scripted_fit(self, monkeypatch, value_at):
        """Run logistic._fit with _fit_segment replaced by a scripted
        loss curve; returns how many segments were consumed."""
        from learningorchestra_tpu.ml import logistic

        calls = {"segments": 0, "cursor": 0}

        def fake_segment(params, opt_state, X, y, mask, iters, l2):
            calls["segments"] += 1
            start = calls["cursor"]
            calls["cursor"] += iters
            losses = np.asarray(
                [value_at(start + k) for k in range(iters)], np.float32
            )
            return params, opt_state, losses

        monkeypatch.setattr(logistic, "_fit_segment", fake_segment)
        X = np.zeros((4, 2), np.float32)
        y = np.zeros((4,), np.int32)
        logistic._fit(
            {"w": np.zeros((2, 2))},
            X,
            y,
            np.ones((4,), np.float32),
            max_iter=100,
            l2=0.0,
        )
        return calls["segments"]

    def test_momentary_plateau_does_not_terminate(self, monkeypatch):
        # strictly descending except ONE flat step at iteration 31
        def value_at(i):
            effective = i if i < 31 else i - 1  # v(31) == v(30)
            return 100.0 - effective * 0.1

        # all four 25-iteration segments run: no early exit
        assert self._scripted_fit(monkeypatch, value_at) == 4

    def test_genuine_plateau_terminates_early(self, monkeypatch):
        def value_at(i):
            return max(1.0, 100.0 - i * 2.0)  # flat from iteration 50

        # the segment covering iterations 50..74 ends on a real plateau
        assert self._scripted_fit(monkeypatch, value_at) == 3


@pytest.fixture()
def blobs(rng):
    """Linearly separable-ish 3-class data."""
    n, f, c = 600, 5, 3
    centers = rng.normal(size=(c, f)) * 3
    y = rng.integers(0, c, size=n)
    X = centers[y] + rng.normal(size=(n, f))
    return X, y


class TestEvaluation:
    def test_accuracy_matches_sklearn(self, rng):
        y_true = rng.integers(0, 4, size=500)
        y_pred = rng.integers(0, 4, size=500)
        assert accuracy_score(y_true, y_pred) == pytest.approx(
            sklearn.metrics.accuracy_score(y_true, y_pred)
        )

    def test_weighted_f1_matches_sklearn(self, rng):
        y_true = rng.integers(0, 4, size=500)
        y_pred = rng.integers(0, 4, size=500)
        assert f1_score(y_true, y_pred) == pytest.approx(
            sklearn.metrics.f1_score(y_true, y_pred, average="weighted"), abs=1e-6
        )

    def test_perfect_prediction(self):
        y = np.array([0, 1, 2, 1, 0])
        assert accuracy_score(y, y) == 1.0
        assert f1_score(y, y) == 1.0


class TestLogisticRegression:
    def test_separates_blobs(self, blobs):
        X, y = blobs
        model = LogisticRegression(max_iter=50).fit(X, y)
        assert accuracy_score(y, model.predict(X)) > 0.95

    def test_agrees_with_sklearn(self, blobs):
        X, y = blobs
        ours = LogisticRegression(max_iter=100).fit(X, y).predict(X)
        theirs = (
            sklearn.linear_model.LogisticRegression(C=1e6, max_iter=1000)
            .fit(X, y)
            .predict(X)
        )
        assert np.mean(ours == theirs) > 0.98

    def test_proba_shape_and_normalization(self, blobs):
        X, y = blobs
        probs = LogisticRegression(max_iter=20).fit(X, y).predict_proba(X)
        assert probs.shape == (len(X), 3)
        np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)

    def test_binary(self, rng):
        X = rng.normal(size=(300, 4))
        y = (X[:, 0] + X[:, 1] > 0).astype(int)
        model = LogisticRegression(max_iter=50).fit(X, y)
        assert accuracy_score(y, model.predict(X)) > 0.95


class TestNaiveBayes:
    def test_matches_sklearn_multinomial(self, rng):
        X = rng.integers(0, 20, size=(400, 8)).astype(float)
        y = rng.integers(0, 3, size=400)
        ours = NaiveBayes().fit(X, y)
        theirs = sklearn.naive_bayes.MultinomialNB(alpha=1.0).fit(X, y)
        assert np.array_equal(ours.predict(X), theirs.predict(X))
        np.testing.assert_allclose(
            ours.predict_proba(X), theirs.predict_proba(X), atol=1e-4
        )

    def test_rejects_negative_features(self, rng):
        X = rng.normal(size=(50, 3))
        y = rng.integers(0, 2, size=50)
        with pytest.raises(ValueError):
            NaiveBayes().fit(X, y)

    def test_padding_does_not_bias_fit(self, rng):
        # 7 rows on an 8-device mesh → 1 padding row; priors must use
        # only real rows.
        X = rng.integers(0, 5, size=(7, 3)).astype(float)
        y = np.array([0, 0, 0, 0, 1, 1, 1])
        ours = NaiveBayes().fit(X, y)
        theirs = sklearn.naive_bayes.MultinomialNB(alpha=1.0).fit(X, y)
        np.testing.assert_allclose(
            ours.predict_proba(X), theirs.predict_proba(X), atol=1e-4
        )
