"""Flight recorder (telemetry/profile.py): Chrome trace export golden,
byte-flow attribution, the sampling profiler under concurrency, the
/profile REST surface, PhaseTimer per-occurrence boundaries, and the
bench --compare regression gate."""

import json
import threading
import time

import pytest

import bench
from learningorchestra_tpu.core.devcache import reset_global_devcache
from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.ops.dtype import convert_field_types
from learningorchestra_tpu.services import model_builder
from learningorchestra_tpu.telemetry import profile, tracing
from learningorchestra_tpu.utils.profiling import PhaseTimer
from learningorchestra_tpu.utils.web import WebApp

NUMERIC_FIELDS = (
    "PassengerId", "Survived", "Pclass", "Age", "SibSp", "Parch", "Fare"
)

FIVE = ["lr", "dt", "rf", "gb", "nb"]


@pytest.fixture(scope="module")
def built_client(tmp_path_factory):
    """ONE 5-classifier build shared by every export test in this
    module. Module-scoped and fan-out-serialized (LO_BUILD_WORKERS=1)
    on purpose: XLA's CPU backend can rendezvous-deadlock when two
    already-compiled collective programs execute concurrently on the
    8 virtual devices (two evals each holding part of the device
    thread pool — a test-environment artifact, not a product path:
    real dispatches serialize through the device queue). One cold
    build with a serialized pool never hits it; the write-back worker
    still gives the timeline its second thread row."""
    import os

    from tests.conftest import TITANIC_LIKE_CSV
    from tests.test_frame import DOCUMENTED_PREPROCESSOR
    from learningorchestra_tpu.core.store import InMemoryStore

    csv_path = tmp_path_factory.mktemp("profile") / "titanic.csv"
    csv_path.write_text(TITANIC_LIKE_CSV)
    reset_global_devcache()  # the h2d spans below need a COLD cache
    store = InMemoryStore()
    for name in ("titanic_train", "titanic_test"):
        write_ingest_metadata(store, name, str(csv_path))
        ingest_csv(store, name, str(csv_path))
        convert_field_types(
            store, name, {f: "number" for f in NUMERIC_FIELDS}
        )
    client = model_builder.create_app(
        store, models_dir="", jobs=JobManager()
    ).test_client()
    previous = os.environ.get("LO_BUILD_WORKERS")
    os.environ["LO_BUILD_WORKERS"] = "1"
    try:
        response = client.post(
            "/models",
            json={
                "training_filename": "titanic_train",
                "test_filename": "titanic_test",
                "preprocessor_code": DOCUMENTED_PREPROCESSOR,
                "classificators_list": FIVE,
            },
        )
    finally:
        if previous is None:
            os.environ.pop("LO_BUILD_WORKERS", None)
        else:
            os.environ["LO_BUILD_WORKERS"] = previous
    assert response.status_code == 201
    return client


class TestChromeTraceExport:
    def test_five_classifier_build_profile_golden(self, built_client):
        """Acceptance: the completed 5-classifier build's /profile is
        valid Chrome trace-event JSON whose spans carry the required
        ph/ts/dur/tid fields, whose phase spans carry byte/row
        attribution, and whose byte counter tracks are present."""
        response = built_client.get(
            f"/jobs/build:titanic_test:{'+'.join(FIVE)}/profile"
        )
        assert response.status_code == 200
        trace = json.loads(response.data)  # valid JSON end to end
        events = trace["traceEvents"]
        complete = [e for e in events if e["ph"] == "X"]
        assert complete, "no span events exported"
        for event in complete:
            assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(event)
            assert event["dur"] >= 0 and event["ts"] >= 0
        names = {event["name"] for event in complete}
        assert {"load_data", "preprocess"} <= names
        for classifier in FIVE:
            assert f"train:{classifier}" in names
        # one row per thread: the 5-way classifier pool means >1 tid
        assert len({event["tid"] for event in complete}) > 1
        # byte counter tracks present and monotonically accumulating
        counters = [e for e in events if e["ph"] == "C"]
        assert counters, "no byte counter track"
        h2d_series = [c["args"]["h2d"] for c in counters]
        assert h2d_series == sorted(h2d_series)
        assert h2d_series[-1] > 0
        # phase spans carry byte/row attribution: the h2d transfers sum
        # to (at least) the rows actually moved — 8 CSV rows minus the
        # one NaN-age row, times features, times 4 bytes f32
        h2d_spans = [
            e for e in complete
            if e["name"].startswith("h2d:") and e.get("args")
        ]
        assert h2d_spans
        moved_rows = max(e["args"].get("rows", 0) for e in h2d_spans)
        assert moved_rows >= 7
        total = trace["otherData"]["bytes_total"]
        assert total["h2d_bytes"] >= moved_rows * 4
        # write phases carry bytes + rows of the persisted predictions
        writes = [e for e in complete if e["name"] == "phase:write"]
        assert len(writes) == len(FIVE)
        assert all(
            e["args"]["bytes"] > 0 and e["args"]["rows"] >= 7
            for e in writes
        )

    def test_profile_summary_format(self, built_client):
        response = built_client.get(
            f"/jobs/build:titanic_test:{'+'.join(FIVE)}/profile"
            "?format=summary"
        )
        assert response.status_code == 200
        summary = response.get_json()["result"]
        assert summary["job"]["state"] == "finished"
        phases = summary["phases"]
        assert phases["phase:fit"]["count"] == len(FIVE)
        assert phases["phase:fit"]["seconds"] > 0
        assert phases["phase:write"]["bytes"]["payload"] > 0
        # rows attribution yields rows/s for the fit phase
        assert phases["phase:fit"].get("rows_per_s", 0) > 0

    def test_profile_404_for_unknown_job(self, built_client):
        assert built_client.get("/jobs/nope/profile").status_code == 404
        assert (
            built_client.get(
                "/jobs/nope/profile?format=summary"
            ).status_code
            == 404
        )


class TestWireAttribution:
    def test_remote_read_span_carries_wire_bytes_and_decode(self):
        from learningorchestra_tpu.core.store import InMemoryStore
        from learningorchestra_tpu.core.store_service import (
            RemoteStore,
            create_store_app,
        )
        from learningorchestra_tpu.utils.web import ServerThread

        server = ServerThread(
            create_store_app(InMemoryStore()), "127.0.0.1", 0
        ).start()
        try:
            remote = RemoteStore(f"http://127.0.0.1:{server.port}")
            remote.create_collection("wired")
            rows = list(range(500))
            trace = tracing.Trace(name="wire")
            with tracing.activate(trace):
                remote.insert_columns(
                    "wired", {"x": rows, "y": rows}, start_id=1
                )
                arrays = remote.read_column_arrays("wired")
            assert len(arrays["x"]) == 500
            tree = trace.as_dict()
            spans = {s["name"]: s for s in tree["spans"]}
            write = spans["wire:write"]
            assert write["meta"]["rows"] == 500
            assert write["meta"]["wire_bytes"] > 500 * 8
            read = spans["wire:read"]
            assert read["meta"]["rows"] == 500
            assert read["meta"]["wire_bytes"] > 500 * 8
            assert read["meta"]["decode_s"] > 0
            assert read["meta"]["collection"] == "wired"
            # and the chrome export shows the wire counter moving
            chrome = profile.chrome_trace(trace)
            assert chrome["otherData"]["bytes_total"]["wire_bytes"] >= (
                read["meta"]["wire_bytes"]
            )
        finally:
            server.stop()


class TestPhaseTimerOccurrences:
    def test_reentrant_phase_keeps_boundaries_and_summed_metadata(self):
        timer = PhaseTimer()
        trace = tracing.Trace(name="phases")
        with tracing.activate(trace):
            with timer.phase("fit", rows=10):
                time.sleep(0.02)
            with timer.phase("fit", rows=20):
                time.sleep(0.03)
        # as_metadata keeps the summed contract
        assert timer.as_metadata()["fit"] == pytest.approx(0.05, abs=0.04)
        # but the boundaries survive: two occurrences, two spans
        fits = [o for o in timer.occurrences if o[0] == "fit"]
        assert len(fits) == 2
        (_, start1, dur1), (_, start2, dur2) = fits
        assert start2 >= start1 + dur1 * 0.5  # distinct windows
        spans = [s for s in trace.as_dict()["spans"] if s["name"] == "phase:fit"]
        assert len(spans) == 2
        assert spans[0]["meta"]["rows"] == 10
        assert spans[1]["meta"]["rows"] == 20
        assert spans[0]["start_ts"] + spans[0]["duration_s"] <= (
            spans[1]["start_ts"] + 0.01
        )
        # the timeline export keeps them as two events
        events = [
            e
            for e in profile.chrome_trace(trace)["traceEvents"]
            if e["ph"] == "X" and e["name"] == "phase:fit"
        ]
        assert len(events) == 2


class TestSampler:
    def test_sample_covers_named_threads(self):
        stop = threading.Event()

        def busy():
            while not stop.is_set():
                sum(range(1000))

        worker = threading.Thread(target=busy, name="lo-busy-worker")
        worker.start()
        try:
            stacks, samples = profile.sample_stacks(0.4, hz=97)
        finally:
            stop.set()
            worker.join()
        assert samples > 5
        assert any(
            stack.startswith("lo-busy-worker;") for stack in stacks
        ), stacks
        text = profile.folded_text(stacks)
        assert text.splitlines()[0].rsplit(" ", 1)[1].isdigit()

    def test_concurrent_requests_share_one_sampler_thread(self):
        """Bounded overhead: N concurrent /debug/profile requests must
        not spawn N sampling threads."""
        app = WebApp("prof_test")
        client_results = []
        max_samplers = []

        def hit():
            client = app.test_client()
            response = client.get("/debug/profile?seconds=0.4")
            client_results.append(
                (response.status_code, response.data.decode())
            )

        def watch():
            deadline = time.monotonic() + 2.0
            peak = 0
            while time.monotonic() < deadline:
                alive = sum(
                    1
                    for t in threading.enumerate()
                    if t.name == "lo-prof-sampler"
                )
                peak = max(peak, alive)
                time.sleep(0.01)
            max_samplers.append(peak)

        watcher = threading.Thread(target=watch)
        watcher.start()
        clients = [threading.Thread(target=hit) for _ in range(4)]
        for thread in clients:
            thread.start()
        for thread in clients:
            thread.join()
        watcher.join()
        assert all(status == 200 for status, _ in client_results)
        assert all(body for _, body in client_results)
        assert max_samplers[0] == 1  # shared, never one per request
        # and the sampler thread exits once the last window closes
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if not any(
                t.name == "lo-prof-sampler" for t in threading.enumerate()
            ):
                break
            time.sleep(0.02)
        else:
            pytest.fail("sampler thread did not stop after last release")

    def test_counts_cleared_after_last_release(self):
        profile.sample_stacks(0.1, hz=97)
        # the delta protocol reads before release; afterwards the
        # accumulated stacks are dead weight and must not persist
        # (one folded key per Thread-N name would leak forever)
        counts, samples = profile._SAMPLER.snapshot()
        assert not counts and samples == 0

    def test_malformed_knob_is_clean_json_500(self, monkeypatch):
        monkeypatch.setenv("LO_PROF_HZ", "abc")
        response = WebApp("prof_sick").test_client().get(
            "/debug/profile?seconds=1"
        )
        assert response.status_code == 500
        assert response.get_json()["result"] == "invalid_prof_config"

    def test_disabled_profiler_answers_403(self, monkeypatch):
        monkeypatch.setenv("LO_PROF_HZ", "0")
        client = WebApp("prof_off").test_client()
        response = client.get("/debug/profile?seconds=1")
        assert response.status_code == 403
        assert response.get_json() == {"result": "profiler_disabled"}

    def test_bad_seconds_400(self):
        client = WebApp("prof_bad").test_client()
        assert client.get("/debug/profile?seconds=abc").status_code == 400
        assert client.get("/debug/profile?seconds=-1").status_code == 400

    def test_knob_validation(self, monkeypatch):
        monkeypatch.setenv("LO_PROF_HZ", "-1")
        with pytest.raises(ValueError):
            profile.prof_hz()
        monkeypatch.setenv("LO_PROF_HZ", "abc")
        with pytest.raises(ValueError):
            profile.validate_env()
        monkeypatch.setenv("LO_PROF_HZ", "19")
        monkeypatch.setenv("LO_PROF_WINDOW_S", "0")
        with pytest.raises(ValueError):
            profile.validate_env()
        monkeypatch.setenv("LO_PROF_WINDOW_S", "30")
        profile.validate_env()


class TestServeForwardSpans:
    def test_sampled_forward_trace_carries_batch_attribution(
        self, tmp_path
    ):
        import numpy as np

        from learningorchestra_tpu.ml.base import make_classifier
        from learningorchestra_tpu.ml.checkpoint import (
            checkpoint_path,
            save_model,
        )
        from learningorchestra_tpu.serve.batcher import MicroBatcher
        from learningorchestra_tpu.serve.registry import ModelRegistry

        rng = np.random.default_rng(3)
        X = rng.random((64, 4), dtype=np.float32)
        y = (X[:, 0] > 0.5).astype(np.int32)
        model = make_classifier("nb").fit(X, y)
        artifact = checkpoint_path(str(tmp_path), "serve_prof_nb")
        save_model(model, artifact)
        batcher = MicroBatcher(
            ModelRegistry(capacity=10**9),
            window_s=0.0,
            max_batch=8,
            inbox_cap=32,
            trace_every=1,  # trace EVERY forward for the assertion
        )
        try:
            requests = [
                batcher.submit(artifact, X[i : i + 1]) for i in range(3)
            ]
            for request in requests:
                assert request.wait(10)
                assert request.error is None
        finally:
            batcher.close()
        # the forward ran under its own remembered trace with
        # rows/bytes + registry hit/miss attribution
        recent = [
            t
            for t in tracing._RECENT.values()
            if t.name == f"serve:{artifact}"
        ]
        assert recent
        spans = []
        for trace in recent:
            spans.extend(trace.as_dict()["spans"])
        forwards = [s for s in spans if s["name"] == "serve:forward"]
        assert forwards
        meta = forwards[0]["meta"]
        assert meta["registry"] in ("hit", "miss")
        assert meta["rows"] >= 1
        assert meta["bytes"] > 0
        total_rows = sum(s["meta"]["rows"] for s in forwards)
        assert total_rows == 3


class TestBenchCompare:
    PREV = {
        "metric": "model_builder_5clf_rows_per_sec",
        "value": 100000.0,
        "summary": {"suite_s": 2.0},
        "extra": {
            "kernels": {"rows_per_sec": 100000.0, "suite_s": 2.0, "rows": 10},
            "product_path": {
                "warm_attribution_s": {"phase:fit": 1.0, "store:read": 0.4},
            },
            "embeddings": {
                "scaling": {
                    "100000": {
                        "tsne_landmark_s": 1.1,
                        "tsne_phases_s": {
                            "landmark_fit": 0.6,
                            "interpolate": 0.5,
                        },
                    }
                }
            },
        },
    }

    def _current(self, **overrides):
        import copy

        current = copy.deepcopy(self.PREV)
        scaling = current["extra"]["embeddings"]["scaling"]["100000"]
        scaling.update(overrides)
        return current

    def test_no_regression_exits_zero(self, tmp_path, capsys):
        prev = tmp_path / "prev.json"
        cur = tmp_path / "cur.json"
        prev.write_text(json.dumps(self.PREV))
        cur.write_text(json.dumps(self._current(tsne_landmark_s=1.05)))
        rc = bench.cli(["--compare", str(prev), "--current", str(cur)])
        assert rc == 0
        assert "no regressions" in capsys.readouterr().out

    def test_regression_flags_the_phase_that_moved_and_exits_nonzero(
        self, tmp_path, capsys
    ):
        prev = tmp_path / "prev.json"
        cur = tmp_path / "cur.json"
        prev.write_text(json.dumps(self.PREV))
        cur.write_text(
            json.dumps(
                self._current(
                    tsne_landmark_s=9.4,
                    tsne_phases_s={"landmark_fit": 0.6, "interpolate": 8.8},
                )
            )
        )
        rc = bench.cli(["--compare", str(prev), "--current", str(cur)])
        assert rc == 1
        out = capsys.readouterr().out
        assert "REGRESSIONS" in out
        # the gate names the PHASE that moved, not just the headline
        assert "tsne_phases_s.interpolate" in out
        assert "tsne_phases_s.landmark_fit" not in out.split(
            "REGRESSIONS"
        )[1]

    def test_throughput_drop_is_a_regression(self):
        current = self._current()
        current["extra"]["kernels"]["rows_per_sec"] = 60000.0
        result = bench.compare_benchmarks(self.PREV, current)
        assert any(
            r["metric"] == "extra.kernels.rows_per_sec"
            for r in result["regressions"]
        )

    def test_seconds_noise_floor_and_fact_keys_never_gate(self):
        # 11ms -> 20ms "doubles" but is under the absolute floor
        prev = {"extra": {"kernels": {"suite_s": 0.011, "rows": 10}}}
        cur = {"extra": {"kernels": {"suite_s": 0.020, "rows": 99}}}
        assert not bench.compare_benchmarks(prev, cur)["regressions"]

    def test_noise_floor_scales_with_ms_unit(self):
        # the same physical jitter expressed in ms must not gate either
        prev = {"serve": {"c64": {"p50_ms": 11.0}}}
        cur = {"serve": {"c64": {"p50_ms": 22.0}}}
        assert not bench.compare_benchmarks(prev, cur)["regressions"]
        # a real latency regression past the 50ms floor still fails
        prev = {"serve": {"c64": {"p99_ms": 40.0}}}
        cur = {"serve": {"c64": {"p99_ms": 120.0}}}
        assert bench.compare_benchmarks(prev, cur)["regressions"]

    def test_loads_archived_driver_capture(self):
        record = bench.load_bench_record("BENCH_r05.json")
        assert record["metric"] == "model_builder_5clf_rows_per_sec"
        flat = bench.flatten_metrics(record)
        assert "value" in flat
