"""deploy/stack.py brings up the real topology: store server + seven
service processes, health-gated, with restart-on-failure.

This is the deployment story the reference gets from Docker swarm
(restart_policy docker-compose.yml:14-15, dockerize -wait :145,
services :173-330) — proven here with a live supervisor: the stack
comes up, serves the product path, and a killed service is restarted
and serves again."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TESTS_DIR)


def _get(url, timeout=5):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as error:
        return error.code, error.read()


@pytest.mark.integration
def test_stack_bringup_serve_and_restart(tmp_path):
    data_dir = tmp_path / "stack_data"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    env["LO_EPHEMERAL"] = "1"
    env["LO_STORE_PORT"] = "0"
    env["LO_RESTART_DELAY"] = "0.5"
    supervisor = subprocess.Popen(
        [sys.executable, os.path.join(_REPO_ROOT, "deploy", "stack.py"),
         str(data_dir)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=_REPO_ROOT,
    )
    ports_path = data_dir / "stack_ports.json"
    try:
        # Bring-up: all eight children publish ports (jax import per
        # process dominates; generous deadline).
        deadline = time.time() + 300
        state = None
        while time.time() < deadline:
            if supervisor.poll() is not None:
                out = supervisor.stdout.read()
                raise AssertionError(f"supervisor died:\n{out}")
            if ports_path.exists():
                state = json.loads(ports_path.read_text())
                if len(state["ports"]) == 8:
                    break
            time.sleep(0.5)
        assert state is not None and len(state["ports"]) == 8, state

        # The stack serves: database_api answers through the store.
        db_port = state["ports"]["database_api"]
        status, body = _get(f"http://127.0.0.1:{db_port}/files")
        assert status == 200
        assert body == {"result": []}

        # Kill a service ungracefully; the supervisor restarts it and
        # it serves again (possibly on a new ephemeral port).
        victim_pid = state["pids"]["histogram"]
        old_port = state["ports"]["histogram"]
        os.kill(victim_pid, signal.SIGKILL)
        deadline = time.time() + 120
        reborn = None
        while time.time() < deadline:
            state = json.loads(ports_path.read_text())
            pid = state["pids"].get("histogram")
            if pid and pid != victim_pid:
                reborn = state["ports"]["histogram"]
                break
            time.sleep(0.5)
        assert reborn is not None, "histogram was not restarted"
        status, body = _get(f"http://127.0.0.1:{reborn}/histograms")
        assert status in (200, 404, 405)  # reachable — route surface up
        # the store kept state across the service bounce
        status, body = _get(f"http://127.0.0.1:{db_port}/files")
        assert status == 200
        del old_port
    finally:
        supervisor.send_signal(signal.SIGTERM)
        try:
            supervisor.wait(30)
        except subprocess.TimeoutExpired:
            supervisor.kill()


@pytest.mark.integration
def test_stack_multihost_build_and_worker_death(tmp_path):
    """LO_WORKERS=1: the supervisor brings up store + coordinator + one
    SPMD worker as ONE jax.distributed runtime, a model build runs over
    the REST surface on the cross-process mesh, and killing the worker
    restarts the WHOLE group (a lost member poisons the collective
    stream) after which the next build succeeds — the swarm-restart +
    Spark-application-restart story in one supervisor."""
    data_dir = tmp_path / "mh_data"
    csv_path = tmp_path / "mh.csv"
    with open(csv_path, "w") as f:
        f.write("f1,f2,label\n")
        for i in range(120):
            lab = i % 2
            f.write(f"{lab * 2 + (i % 7) * 0.1:.3f},{-lab + (i % 5) * 0.1:.3f},{lab}\n")

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    env["LO_EPHEMERAL"] = "1"
    env["LO_STORE_PORT"] = "0"
    env["LO_RESTART_DELAY"] = "0.5"
    env["LO_WORKERS"] = "1"
    env["LO_COORD_PORT"] = "0"  # replaced below — needs a real free port
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        env["LO_COORD_PORT"] = str(s.getsockname()[1])
    supervisor = subprocess.Popen(
        [sys.executable, os.path.join(_REPO_ROOT, "deploy", "stack.py"),
         str(data_dir)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=_REPO_ROOT,
        start_new_session=True,  # one process group: no orphaned runners
    )
    ports_path = data_dir / "stack_ports.json"

    def wait_state(min_ports: int, deadline_s: float) -> dict:
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            if supervisor.poll() is not None:
                out = supervisor.stdout.read()
                raise AssertionError(f"supervisor died:\n{out}")
            if ports_path.exists():
                state = json.loads(ports_path.read_text())
                if len(state["ports"]) >= min_ports and "worker1" in state["pids"]:
                    return state
            time.sleep(0.5)
        raise AssertionError("stack never published the runtime ports")

    def post(url, body, timeout=300):
        data = json.dumps(body).encode()
        request = urllib.request.Request(
            url, data=data, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(request, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())

    def build_once(state, name: str) -> None:
        db = state["ports"]["database_api"]
        mb = state["ports"]["model_builder"]
        dt = state["ports"]["data_type_handler"]
        status, _ = post(
            f"http://127.0.0.1:{db}/files",
            {"filename": name, "url": str(csv_path)},
        )
        assert status == 201
        deadline = time.time() + 60
        while time.time() < deadline:
            status, body = _get(
                f"http://127.0.0.1:{db}/files/{name}?skip=0&limit=1&query={{}}"
            )
            if status == 200 and body["result"][0].get("finished"):
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"ingest of {name} never finished")
        request = urllib.request.Request(
            f"http://127.0.0.1:{dt}/fieldtypes/{name}",
            data=json.dumps(
                {"f1": "number", "f2": "number", "label": "number"}
            ).encode(),
            headers={"Content-Type": "application/json"},
            method="PATCH",
        )
        with urllib.request.urlopen(request, timeout=60) as resp:
            assert resp.status == 200
        pre = (
            "from pyspark.ml.feature import VectorAssembler\n"
            "assembler = VectorAssembler(inputCols=['f1', 'f2'],"
            " outputCol='features')\n"
            "features_training = assembler.transform(training_df)\n"
            "features_testing = assembler.transform(testing_df)\n"
            "features_evaluation = features_training\n"
        )
        status, _ = post(
            f"http://127.0.0.1:{mb}/models",
            {
                "training_filename": name,
                "test_filename": name,
                "preprocessor_code": pre,
                "classificators_list": ["lr"],
            },
        )
        assert status == 201
        status, body = _get(
            f"http://127.0.0.1:{db}/files/{name}_prediction_lr"
            "?skip=0&limit=1&query={}"
        )
        assert status == 200
        assert float(body["result"][0]["accuracy"]) > 0.8

    try:
        state = wait_state(8, 420)
        build_once(state, "mh_a")

        # kill the worker: the whole runtime group must restart
        os.kill(state["pids"]["worker1"], signal.SIGKILL)
        old_coord_pid = state["pids"]["coordinator"]
        deadline = time.time() + 420
        while time.time() < deadline:
            fresh = wait_state(8, 420)
            if fresh["pids"]["coordinator"] != old_coord_pid:
                state = fresh
                break
            time.sleep(0.5)
        else:
            raise AssertionError("group never restarted after worker death")

        build_once(state, "mh_b")
    finally:
        supervisor.send_signal(signal.SIGTERM)
        try:
            out, _ = supervisor.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            supervisor.kill()
            out, _ = supervisor.communicate()
        # a supervisor killed mid-bring-up can leave runner children
        # behind; sweep the whole process group
        try:
            os.killpg(supervisor.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
