"""deploy/stack.py brings up the real topology: store server + seven
service processes, health-gated, with restart-on-failure.

This is the deployment story the reference gets from Docker swarm
(restart_policy docker-compose.yml:14-15, dockerize -wait :145,
services :173-330) — proven here with a live supervisor: the stack
comes up, serves the product path, and a killed service is restarted
and serves again."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TESTS_DIR)


def _get(url, timeout=5):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as error:
        return error.code, error.read()


@pytest.mark.integration
def test_stack_bringup_serve_and_restart(tmp_path):
    data_dir = tmp_path / "stack_data"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    env["LO_EPHEMERAL"] = "1"
    env["LO_STORE_PORT"] = "0"
    env["LO_RESTART_DELAY"] = "0.5"
    supervisor = subprocess.Popen(
        [sys.executable, os.path.join(_REPO_ROOT, "deploy", "stack.py"),
         str(data_dir)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=_REPO_ROOT,
    )
    ports_path = data_dir / "stack_ports.json"
    try:
        # Bring-up: all eight children publish ports (jax import per
        # process dominates; generous deadline).
        deadline = time.time() + 300
        state = None
        while time.time() < deadline:
            if supervisor.poll() is not None:
                out = supervisor.stdout.read()
                raise AssertionError(f"supervisor died:\n{out}")
            if ports_path.exists():
                state = json.loads(ports_path.read_text())
                if len(state["ports"]) == 8:
                    break
            time.sleep(0.5)
        assert state is not None and len(state["ports"]) == 8, state

        # The stack serves: database_api answers through the store.
        db_port = state["ports"]["database_api"]
        status, body = _get(f"http://127.0.0.1:{db_port}/files")
        assert status == 200
        assert body == {"result": []}

        # Kill a service ungracefully; the supervisor restarts it and
        # it serves again (possibly on a new ephemeral port).
        victim_pid = state["pids"]["histogram"]
        old_port = state["ports"]["histogram"]
        os.kill(victim_pid, signal.SIGKILL)
        deadline = time.time() + 120
        reborn = None
        while time.time() < deadline:
            state = json.loads(ports_path.read_text())
            pid = state["pids"].get("histogram")
            if pid and pid != victim_pid:
                reborn = state["ports"]["histogram"]
                break
            time.sleep(0.5)
        assert reborn is not None, "histogram was not restarted"
        status, body = _get(f"http://127.0.0.1:{reborn}/histograms")
        assert status in (200, 404, 405)  # reachable — route surface up
        # the store kept state across the service bounce
        status, body = _get(f"http://127.0.0.1:{db_port}/files")
        assert status == 200
        del old_port
    finally:
        supervisor.send_signal(signal.SIGTERM)
        try:
            supervisor.wait(30)
        except subprocess.TimeoutExpired:
            supervisor.kill()
