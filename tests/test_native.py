"""Native C++ CSV loader vs the Python fallback: identical semantics."""

import numpy as np
import pytest

from learningorchestra_tpu.core.table import ColumnTable
from learningorchestra_tpu.native.loader import (
    NativeCsv,
    _python_read,
    native_available,
    read_csv_columns,
)

CSV = (
    'name,age,score,city\n'
    '"Brown, Mr. A",22,7.25,NY\n'
    '"Say ""hi""",35,,SF\n'
    'plain,,9.5,LA\n'
)


@pytest.fixture()
def csv_path(tmp_path):
    path = tmp_path / "data.csv"
    path.write_text(CSV)
    return str(path)


needs_native = pytest.mark.skipif(
    not native_available(), reason="g++ build unavailable"
)


@needs_native
class TestNativeParser:
    def test_dimensions_and_header(self, csv_path):
        with NativeCsv(csv_path) as parsed:
            assert parsed.num_rows == 3
            assert parsed.num_cols == 4
            assert parsed.header() == ["name", "age", "score", "city"]

    def test_quoted_cells(self, csv_path):
        with NativeCsv(csv_path) as parsed:
            assert parsed.cell(0, 0) == "Brown, Mr. A"
            assert parsed.cell(1, 0) == 'Say "hi"'

    def test_numeric_detection_and_fill(self, csv_path):
        with NativeCsv(csv_path) as parsed:
            assert not parsed.column_is_numeric(0)
            assert parsed.column_is_numeric(1)
            assert parsed.column_is_numeric(2)
            ages = parsed.numeric_column(1)
            np.testing.assert_allclose(ages[:2], [22, 35])
            assert np.isnan(ages[2])

    def test_matches_python_fallback(self, csv_path):
        native = read_csv_columns(csv_path)
        python = _python_read(csv_path)
        assert set(native) == set(python)
        for name in native:
            if native[name].dtype == object:
                assert list(native[name]) == list(python[name])
            else:
                np.testing.assert_allclose(
                    native[name], python[name], equal_nan=True
                )

    def test_crlf_and_trailing_newline(self, tmp_path):
        path = tmp_path / "crlf.csv"
        path.write_bytes(b"a,b\r\n1,x\r\n2,y\r\n")
        with NativeCsv(str(path)) as parsed:
            assert parsed.num_rows == 2
            assert parsed.cell(1, 1) == "y"

    def test_large_roundtrip(self, tmp_path, rng):
        path = tmp_path / "big.csv"
        values = rng.random(20_000)
        with open(path, "w") as handle:
            handle.write("x,tag\n")
            for i, value in enumerate(values):
                handle.write(f"{value:.17g},t{i % 7}\n")
        columns = read_csv_columns(str(path))
        np.testing.assert_allclose(columns["x"], values)
        assert columns["tag"][13] == "t6"


class TestFromCsv:
    def test_column_table_from_csv(self, csv_path):
        table = ColumnTable.from_csv(csv_path)
        assert table.num_rows == 3
        assert table.dtype_of("age") == "number"
        assert table.dtype_of("name") == "string"

    def test_ingest_native_path_matches_contract(self, store, csv_path):
        from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
        from learningorchestra_tpu.core.store import ROW_ID

        write_ingest_metadata(store, "d", csv_path)
        n = ingest_csv(store, "d", csv_path)
        assert n == 3
        row = next(store.find("d", {ROW_ID: 1}))
        # contract: values stay strings at ingest
        assert row["age"] == "22" and row["name"] == "Brown, Mr. A"
        meta = store.metadata("d")
        assert meta["finished"] is True
        assert meta["fields"] == ["name", "age", "score", "city"]


class TestReviewRegressions:
    def test_empty_strings_become_none(self, tmp_path):
        path = tmp_path / "m.csv"
        path.write_text("name,city\nBob,\nAmy,SF\n")
        table = ColumnTable.from_csv(str(path))
        assert table.columns["city"][0] is None
        assert table.dropna().num_rows == 1

    def test_hex_cells_stay_strings(self, tmp_path):
        path = tmp_path / "h.csv"
        path.write_text("x\n0x10\n0x20\n")
        columns = read_csv_columns(str(path))
        assert list(columns["x"]) == ["0x10", "0x20"]
        assert list(columns["x"]) == list(_python_read(str(path))["x"])

    def test_ragged_wide_falls_back(self, tmp_path):
        path = tmp_path / "r.csv"
        path.write_text("a,b\n1,2,3\n4,5\n")
        columns = read_csv_columns(str(path))
        assert len(columns["a"]) == 2

    def test_ragged_ingest_still_streams(self, store, tmp_path):
        from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata

        path = tmp_path / "r.csv"
        path.write_text("a,b\n1,2,3\n4,5\n")
        write_ingest_metadata(store, "r", str(path))
        assert ingest_csv(store, "r", str(path)) == 2
        assert store.metadata("r")["finished"] is True

    def test_underscore_cells_stay_strings(self, tmp_path):
        path = tmp_path / "u.csv"
        path.write_text("x\n1_000\n2_000\n")
        columns = read_csv_columns(str(path))
        assert list(columns["x"]) == ["1_000", "2_000"]
        assert list(columns["x"]) == list(_python_read(str(path))["x"])


class TestSlabbedIngest:
    """Big CSVs parse as bounded slabs (core/ingest._ingest_slabbed):
    row ids stay contiguous across slab boundaries and quoted embedded
    newlines never split a slab mid-record."""

    def test_slab_boundaries_preserve_rows_and_quotes(
        self, tmp_path, monkeypatch
    ):
        import learningorchestra_tpu.core.ingest as ingest
        from learningorchestra_tpu.core.store import InMemoryStore

        path = tmp_path / "big.csv"
        with open(path, "w", newline="") as f:
            f.write("a,b\n")
            for i in range(500):
                if i % 7 == 0:
                    # quoted cell with an embedded newline: a slab must
                    # not end between these two physical lines
                    f.write(f'"x{i}\ny",{i}\n')
                else:
                    f.write(f"v{i},{i}\n")
        monkeypatch.setattr(ingest, "_SLAB_BYTES", 256)  # many tiny slabs
        store = InMemoryStore()
        store.create_collection("big")
        count = ingest.ingest_csv(store, "big", str(path))
        assert count == 500
        rows = store.read_columns("big", ["a", "b"])
        assert rows["b"] == [str(i) for i in range(500)]
        assert rows["a"][0] == "x0\ny"
        assert rows["a"][7] == "x7\ny"
        assert rows["a"][1] == "v1"
