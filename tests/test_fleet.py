"""The replicated serving fleet (docs/serving.md "Fleet"): consistent-
hash placement, residency gossip, the replica agent, the placement-aware
router, and the kill-one-replica chaos drills.

Acceptance contract: placement is deterministic and distinct-owner
(losing one replica moves only its models), the gossip turns a missed
heartbeat into routing-around within ``LO_FLEET_DOWN_S``, the router
fails over in flight with ZERO wrong-model answers (every 200 names the
model the client asked for), the per-model quota answers 429 +
Retry-After before any socket opens, and the SDK rides the router
transparently off the ``/health`` feature probe. The fast drills run
in-process against real sockets; the subprocess drill (``slow`` tier)
SIGKILLs a real replica runner behind a real router runner.
"""

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from learningorchestra_tpu.core.store import InMemoryStore
from learningorchestra_tpu.ml.base import make_classifier
from learningorchestra_tpu.ml.checkpoint import (
    checkpoint_path,
    gather_model,
    write_checkpoint,
)
from learningorchestra_tpu.serve import ServePlane
from learningorchestra_tpu.serve import fleet
from learningorchestra_tpu.serve import router as fleet_router
from learningorchestra_tpu.serve.loadgen import (
    http_predict_sender,
    run_closed_loop,
)
from learningorchestra_tpu.services import model_builder
from learningorchestra_tpu.testing import faults
from learningorchestra_tpu.utils.web import ServerThread

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TESTS_DIR)

_FLEET_ENV = (
    "LO_FLEET_REPLICAS",
    "LO_FLEET_RF",
    "LO_FLEET_MODEL_QPS",
    "LO_FLEET_DOWN_S",
    "LO_FLEET_REPLICA",
)


@pytest.fixture(autouse=True)
def _clean_faults_and_env(monkeypatch):
    faults.reset()
    for name in _FLEET_ENV:
        monkeypatch.delenv(name, raising=False)
    yield
    faults.reset()


@pytest.fixture()
def data(rng):
    X = rng.normal(size=(200, 6))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(int)
    return X, y


def fit_and_checkpoint(name, X, y, models_dir):
    model = make_classifier("lr").fit(X, y)
    path = checkpoint_path(str(models_dir), name)
    write_checkpoint(gather_model(model), path)
    return model, path


# ---------------------------------------------------------------------------
# Placement


class TestPlacementRing:
    def test_owners_deterministic_across_instances(self):
        a = fleet.PlacementRing(4)
        b = fleet.PlacementRing(4)
        for name in ("alpha", "beta", "gamma", "titanic_prediction_lr"):
            assert a.owners(name, rf=2) == b.owners(name, rf=2)

    def test_owners_distinct_and_primary_stable_under_rf(self):
        ring = fleet.PlacementRing(4)
        for name in (f"model{i}" for i in range(32)):
            owners = ring.owners(name, rf=3)
            assert len(owners) == len(set(owners)) == 3
            # raising rf extends the walk, never reshuffles the primary
            assert owners[0] == ring.owners(name, rf=1)[0]
            assert owners[:2] == ring.owners(name, rf=2)

    def test_rf_clamps_to_replica_count(self):
        ring = fleet.PlacementRing(2)
        assert sorted(ring.owners("m", rf=9)) == [0, 1]
        assert len(ring.owners("m", rf=0)) == 1  # floor: one owner

    def test_single_replica_owns_everything(self):
        ring = fleet.PlacementRing(1)
        assert ring.owners("anything", rf=3) == [0]

    def test_primaries_spread_over_replicas(self):
        # 64 vnodes/replica: 200 names cannot all hash to one replica
        ring = fleet.PlacementRing(4)
        primaries = {ring.owners(f"m{i}")[0] for i in range(200)}
        assert primaries == {0, 1, 2, 3}

    def test_losing_a_replica_moves_only_its_models(self):
        before = fleet.PlacementRing(4)
        after = fleet.PlacementRing(3)
        moved = survivors = 0
        for i in range(200):
            name = f"m{i}"
            old = before.owners(name)[0]
            if old == 3:  # the removed replica's models must move
                moved += 1
            elif after.owners(name)[0] == old:
                survivors += 1
        kept_total = 200 - moved
        # consistent hashing: the overwhelming share of surviving
        # primaries stays put (modulo placement would reshuffle ~2/3)
        assert survivors / kept_total > 0.9

    def test_zero_replicas_rejected(self):
        with pytest.raises(ValueError):
            fleet.PlacementRing(0)


class TestPlacementClient:
    def test_first_contact_seeds_then_everyone_adopts(self):
        store = InMemoryStore()
        seeder = fleet.PlacementClient(store, replicas=3, rf=2)
        doc = seeder.document()
        assert doc["replicas"] == 3 and doc["rf"] == 2
        assert seeder.rev >= 0
        follower = fleet.PlacementClient(store, replicas=3, rf=2)
        assert follower.document()["rf"] == 2
        assert follower.owners("alpha") == seeder.owners("alpha")
        assert len(seeder.owners("alpha")) == 2

    def test_geometry_mismatch_refuses(self):
        store = InMemoryStore()
        fleet.PlacementClient(store, replicas=3, rf=1).document()
        wrong = fleet.PlacementClient(store, replicas=2, rf=1)
        with pytest.raises(ValueError, match="LO_FLEET_REPLICAS"):
            wrong.document()

    def test_document_is_ttl_cached(self):
        store = InMemoryStore()
        client = fleet.PlacementClient(store, replicas=2, rf=1, ttl_s=60.0)
        client.document()
        calls = {"rev": 0}
        original = store.collection_rev

        def counting(name):
            calls["rev"] += 1
            return original(name)

        store.collection_rev = counting
        for _ in range(5):
            client.document()
        assert calls["rev"] == 0  # inside the TTL: no store traffic

    def test_env_defaults_feed_the_client(self, monkeypatch):
        monkeypatch.setenv("LO_FLEET_REPLICAS", "4")
        monkeypatch.setenv("LO_FLEET_RF", "2")
        client = fleet.PlacementClient(InMemoryStore())
        doc = client.document()
        assert (doc["replicas"], doc["rf"]) == (4, 2)


class TestKnobValidation:
    def test_defaults(self):
        assert fleet.validate_env() == {
            "LO_FLEET_REPLICAS": 1,
            "LO_FLEET_RF": 1,
            "LO_FLEET_MODEL_QPS": 0.0,
            "LO_FLEET_DOWN_S": 3.0,
            "LO_FLEET_REPLICA": None,
        }

    def test_parses_configured_values(self, monkeypatch):
        monkeypatch.setenv("LO_FLEET_REPLICAS", "3")
        monkeypatch.setenv("LO_FLEET_RF", "2")
        monkeypatch.setenv("LO_FLEET_MODEL_QPS", "12.5")
        monkeypatch.setenv("LO_FLEET_DOWN_S", "0.5")
        monkeypatch.setenv("LO_FLEET_REPLICA", "2")
        config = fleet.validate_env()
        assert config["LO_FLEET_REPLICAS"] == 3
        assert config["LO_FLEET_MODEL_QPS"] == 12.5
        assert config["LO_FLEET_REPLICA"] == 2

    @pytest.mark.parametrize(
        "name,value",
        [
            ("LO_FLEET_REPLICAS", "zero"),
            ("LO_FLEET_REPLICAS", "0"),
            ("LO_FLEET_RF", "-1"),
            ("LO_FLEET_RF", "1.5"),
            ("LO_FLEET_MODEL_QPS", "-3"),
            ("LO_FLEET_MODEL_QPS", "nan"),
            ("LO_FLEET_DOWN_S", "0"),
            ("LO_FLEET_DOWN_S", "soon"),
            ("LO_FLEET_REPLICA", "-1"),
            ("LO_FLEET_REPLICA", "two"),
        ],
    )
    def test_malformed_knob_refuses(self, monkeypatch, name, value):
        monkeypatch.setenv(name, value)
        with pytest.raises(ValueError, match=name):
            fleet.validate_env()

    def test_replica_index_outside_fleet_refuses(self, monkeypatch):
        monkeypatch.setenv("LO_FLEET_REPLICAS", "2")
        monkeypatch.setenv("LO_FLEET_REPLICA", "2")
        with pytest.raises(ValueError, match="outside the fleet"):
            fleet.validate_env()


# ---------------------------------------------------------------------------
# Residency gossip


class TestGossip:
    def test_heartbeat_rows_feed_the_view(self):
        store = InMemoryStore()
        fleet.Heartbeat(store, 0, "http://127.0.0.1:5010").write(
            ["alpha"], 1024, 2
        )
        fleet.Heartbeat(store, 1, "http://127.0.0.1:5011").write(
            ["beta"], 2048, 0
        )
        view = fleet.FleetView(store, ttl_s=0.0, down_s=5.0)
        rows = view.rows()
        assert set(rows) == {0, 1}
        assert view.healthy(0) and view.healthy(1)
        assert view.target(0) == ("127.0.0.1", 5010)
        residency = view.residency()
        assert residency["1"]["models"] == ["beta"]
        assert residency["1"]["pinned_bytes"] == 2048
        assert residency["0"]["healthy"] is True

    def test_rewrite_updates_not_duplicates(self):
        store = InMemoryStore()
        beat = fleet.Heartbeat(store, 0, "http://127.0.0.1:5010")
        beat.write(["alpha"], 1, 0)
        beat.write(["alpha", "beta"], 2, 1)
        view = fleet.FleetView(store, ttl_s=0.0, down_s=5.0)
        assert len(view.rows()) == 1
        assert view.rows()[0]["models"] == ["alpha", "beta"]

    def test_stale_heartbeat_goes_unhealthy(self):
        store = InMemoryStore()
        fleet.Heartbeat(store, 0, "http://127.0.0.1:5010").write([], 0, 0)
        view = fleet.FleetView(store, ttl_s=0.0, down_s=0.15)
        assert view.healthy(0)
        time.sleep(0.2)
        assert not view.healthy(0)  # missed the down window
        assert view.residency()["0"]["healthy"] is False
        # the row (and its target) survive: stale is LAST resort, not gone
        assert view.target(0) == ("127.0.0.1", 5010)

    def test_unknown_replica_and_bad_url(self):
        store = InMemoryStore()
        fleet.Heartbeat(store, 0, "not a url").write([], 0, 0)
        view = fleet.FleetView(store, ttl_s=0.0, down_s=5.0)
        assert not view.healthy(7)
        assert view.target(7) is None
        assert view.target(0) is None  # unparseable url: no target


# ---------------------------------------------------------------------------
# The replica agent


class TestReplicaAgent:
    def _plane(self):
        return ServePlane(
            capacity=10**9, window_s=0.0, max_batch=8, inbox_cap=32
        )

    def test_rf1_partitions_models_exactly_once(self, data, tmp_path):
        X, y = data
        names = [f"agent{i}_prediction_lr" for i in range(4)]
        for name in names:
            fit_and_checkpoint(name, X, y, tmp_path)
        store = InMemoryStore()
        planes = [self._plane(), self._plane()]
        agents = [
            fleet.ReplicaAgent(
                store,
                str(tmp_path),
                planes[i],
                index=i,
                url=f"http://127.0.0.1:{5010 + i}",
                total=2,
                rf=1,
                warm=False,
            )
            for i in range(2)
        ]
        try:
            summaries = [agent.refresh() for agent in agents]
            pinned = [set(s["pinned"]) for s in summaries]
            # rf=1: every model pinned by EXACTLY one replica
            assert pinned[0] | pinned[1] == set(names)
            assert not pinned[0] & pinned[1]
            for agent, summary in zip(agents, summaries):
                assert summary["errors"] == 0
                assert set(summary["assigned"]) == set(
                    name
                    for name in names
                    if agent.placement.owners(name) == [agent.index]
                )
            view = fleet.FleetView(store, ttl_s=0.0, down_s=5.0)
            rows = view.rows()
            assert rows[0]["models"] == sorted(pinned[0])
            assert rows[0]["pinned_bytes"] > 0
        finally:
            for plane in planes:
                plane.close()

    def test_full_rf_pins_everything_and_warms_once(self, data, tmp_path):
        X, y = data
        names = [f"warm{i}_prediction_lr" for i in range(2)]
        for name in names:
            fit_and_checkpoint(name, X, y, tmp_path)
        store = InMemoryStore()
        plane = self._plane()
        agent = fleet.ReplicaAgent(
            store,
            str(tmp_path),
            plane,
            index=0,
            url="http://127.0.0.1:5010",
            total=1,
            rf=1,
            warm=True,
        )
        try:
            first = agent.refresh()
            assert sorted(first["pinned"]) == sorted(names)
            assert first["warmed"] == len(names)
            # warmup is per NEW assignment, not per tick
            assert agent.refresh()["warmed"] == 0
        finally:
            plane.close()

    def test_assignment_move_releases_the_budget(self, data, tmp_path):
        X, y = data
        names = [f"rel{i}_prediction_lr" for i in range(3)]
        for name in names:
            fit_and_checkpoint(name, X, y, tmp_path)
        store = InMemoryStore()
        plane = self._plane()
        agent = fleet.ReplicaAgent(
            store,
            str(tmp_path),
            plane,
            index=0,
            url="http://127.0.0.1:5010",
            total=1,
            rf=1,
            warm=False,
        )
        try:
            assert len(agent.refresh()["pinned"]) == 3
            full_bytes = plane.registry.stats()["bytes"]
            # the checkpoint vanishing IS an assignment move: the agent
            # must release the pin and return the bytes
            os.remove(checkpoint_path(str(tmp_path), names[0]))
            second = agent.refresh()
            assert names[0] not in second["pinned"]
            assert plane.registry.stats()["bytes"] < full_bytes
        finally:
            plane.close()

    def test_unloadable_checkpoint_keeps_gossiping(self, data, tmp_path):
        X, y = data
        fit_and_checkpoint("ok_prediction_lr", X, y, tmp_path)
        # a torn artifact: the agent must pin the good model, count the
        # error, and still write its heartbeat
        bad = checkpoint_path(str(tmp_path), "torn_prediction_lr")
        with open(bad, "wb") as handle:
            handle.write(b"not a checkpoint")
        store = InMemoryStore()
        plane = self._plane()
        agent = fleet.ReplicaAgent(
            store,
            str(tmp_path),
            plane,
            index=0,
            url="http://127.0.0.1:5010",
            total=1,
            rf=1,
            warm=False,
        )
        try:
            summary = agent.refresh()
            assert summary["pinned"] == ["ok_prediction_lr"]
            assert summary["errors"] == 1
            view = fleet.FleetView(store, ttl_s=0.0, down_s=5.0)
            assert view.rows()[0]["models"] == ["ok_prediction_lr"]
        finally:
            plane.close()

    def test_agent_requires_an_index(self, tmp_path):
        with pytest.raises(ValueError, match="replica index"):
            fleet.ReplicaAgent(InMemoryStore(), str(tmp_path), None)


# ---------------------------------------------------------------------------
# The router, over real sockets


class _InProcessFleet:
    """N model_builder replicas + their agents + the router, all on
    ephemeral ports in this process — the fast chaos topology."""

    def __init__(
        self,
        models_dir,
        replicas=2,
        rf=2,
        down_s=1.5,
        model_qps=0.0,
        timeout_s=10.0,
    ):
        self.down_s = down_s
        self.store = InMemoryStore()
        self.planes = []
        self.servers = []
        self.agents = []
        for index in range(replicas):
            plane = ServePlane(
                capacity=10**9, window_s=0.0, max_batch=16, inbox_cap=128
            )
            app = model_builder.create_app(
                self.store, models_dir=str(models_dir), serve=plane
            )
            server = ServerThread(app, "127.0.0.1", 0).start()
            agent = fleet.ReplicaAgent(
                self.store,
                str(models_dir),
                plane,
                index=index,
                url=f"http://127.0.0.1:{server.port}",
                total=replicas,
                rf=rf,
                interval_s=0.15,
                placement_ttl_s=0.05,
                warm=False,
            )
            agent.refresh()  # synchronous first pin: no bring-up race
            agent.start()
            self.planes.append(plane)
            self.servers.append(server)
            self.agents.append(agent)
        self.placement = fleet.PlacementClient(
            self.store, replicas=replicas, rf=rf, ttl_s=0.05
        )
        self.view = fleet.FleetView(self.store, ttl_s=0.1, down_s=down_s)
        self.app = fleet_router.create_app(
            self.store,
            placement=self.placement,
            view=self.view,
            quota=fleet_router.ModelQuota(model_qps),
            timeout_s=timeout_s,
        )
        self.router_server = ServerThread(self.app, "127.0.0.1", 0).start()
        self.router_target = f"127.0.0.1:{self.router_server.port}"

    @staticmethod
    def retries(model):
        return fleet_router._METRICS["retries"].value(model)

    @staticmethod
    def rejected(model):
        return fleet_router._METRICS["rejected"].value(model)

    def kill(self, index):
        """SIGKILL-equivalent for an in-process replica: server socket
        closed, agent stopped — its heartbeat row freezes in place."""
        self.agents[index].stop()
        self.servers[index].stop()

    def close(self):
        for stop in (
            [self.router_server.stop]
            + [agent.stop for agent in self.agents]
            + [server.stop for server in self.servers]
            + [plane.close for plane in self.planes]
        ):
            try:
                stop()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


def body(response):
    return json.loads(response.get_data())


class TestRouter:
    def test_quota_unit_semantics(self):
        quota = fleet_router.ModelQuota(2.0)
        assert quota.take("m") is None
        assert quota.take("m") is None  # burst = one second's worth
        delay = quota.take("m")
        assert delay is not None and 0 < delay <= 0.5
        assert quota.take("other") is None  # per-model buckets
        assert fleet_router.ModelQuota(0.0).take("m") is None  # off

    def test_predict_proxies_and_residency_reads(self, data, tmp_path):
        X, y = data
        model, _ = fit_and_checkpoint("rt_prediction_lr", X, y, tmp_path)
        flt = _InProcessFleet(tmp_path, replicas=2, rf=2)
        try:
            client = flt.app.test_client()
            rows = X[:5].astype(np.float32)
            response = client.post(
                "/models/rt_prediction_lr/predict",
                json={"rows": rows.tolist()},
            )
            assert response.status_code == 200
            result = body(response)["result"]
            assert result["model"] == "rt_prediction_lr"
            np.testing.assert_array_equal(
                np.array(result["predictions"]), model.predict(rows)
            )

            health = body(client.get("/health"))
            assert health["fleet_router"] is True
            assert health["replicas"] == 2

            picture = body(client.get("/models/rt_prediction_lr"))
            fleet_info = picture["result"]["fleet"]
            assert sorted(fleet_info["owners"]) == [0, 1]  # rf=2 of 2
            assert fleet_info["rf"] == 2
            assert fleet_info["placement_rev"] >= 0
            replicas = fleet_info["replicas"]
            assert set(replicas) == {"0", "1"}
            for row in replicas.values():
                assert row["healthy"] is True
                assert "rt_prediction_lr" in row["models"]

            # an unknown model relays the owner's 404 untouched
            response = client.post(
                "/models/never_built/predict", json={"rows": [[1.0] * 6]}
            )
            assert response.status_code == 404
            assert body(response) == {"result": "file_not_found"}
        finally:
            flt.close()

    def test_quota_answers_429_with_retry_after(self, data, tmp_path):
        X, y = data
        fit_and_checkpoint("q_prediction_lr", X, y, tmp_path)
        flt = _InProcessFleet(tmp_path, replicas=1, rf=1, model_qps=1.0)
        try:
            client = flt.app.test_client()
            rows = X[:2].tolist()
            rejected = flt.rejected("q_prediction_lr")
            first = client.post(
                "/models/q_prediction_lr/predict", json={"rows": rows}
            )
            assert first.status_code == 200
            second = client.post(
                "/models/q_prediction_lr/predict", json={"rows": rows}
            )
            assert second.status_code == 429
            payload = body(second)
            assert payload["result"] == "quota_exceeded"
            retry_after = float(second.headers["Retry-After"])
            assert 0 < retry_after <= 1.0
            assert payload["retry_after_s"] == pytest.approx(
                retry_after, abs=1e-9
            )
            assert flt.rejected("q_prediction_lr") == rejected + 1
        finally:
            flt.close()

    def test_route_fault_answers_clean_503(self, data, tmp_path):
        X, y = data
        fit_and_checkpoint("flt_prediction_lr", X, y, tmp_path)
        flt = _InProcessFleet(tmp_path, replicas=1, rf=1)
        try:
            client = flt.app.test_client()
            faults.install("serve.route", "error@1")
            response = client.post(
                "/models/flt_prediction_lr/predict",
                json={"rows": X[:2].tolist()},
            )
            assert response.status_code == 503
            assert body(response) == {
                "result": "routing_fault",
                "model": "flt_prediction_lr",
            }
            # budget spent: the very next request routes normally
            response = client.post(
                "/models/flt_prediction_lr/predict",
                json={"rows": X[:2].tolist()},
            )
            assert response.status_code == 200
        finally:
            flt.close()

    def test_no_heartbeats_means_503_no_replicas(self):
        store = InMemoryStore()
        app = fleet_router.create_app(
            store,
            placement=fleet.PlacementClient(store, replicas=2, rf=1),
            view=fleet.FleetView(store, ttl_s=0.0, down_s=1.0),
        )
        response = app.test_client().post(
            "/models/ghost/predict", json={"rows": [[1.0]]}
        )
        assert response.status_code == 503
        assert body(response) == {"result": "no_replicas", "model": "ghost"}


# ---------------------------------------------------------------------------
# SDK transparency


class TestSdkRouter:
    @pytest.fixture(autouse=True)
    def _fresh_probe_cache(self):
        from learningorchestra_tpu import client as sdk

        sdk.Model._router_probe_cache.clear()
        yield
        sdk.Model._router_probe_cache.clear()

    def test_predict_rides_the_router(self, data, tmp_path):
        from learningorchestra_tpu import client as sdk

        X, y = data
        model, _ = fit_and_checkpoint("sdk_prediction_lr", X, y, tmp_path)
        flt = _InProcessFleet(tmp_path, replicas=2, rf=2)
        try:
            sdk.Context(flt.router_target)
            wrapper = sdk.Model()
            assert wrapper._router_base() == f"http://{flt.router_target}"
            rows = X[:3].astype(np.float32)
            answer = wrapper.predict(
                "sdk_prediction_lr", rows.tolist(), pretty_response=False
            )
            result = answer["result"]
            assert result["model"] == "sdk_prediction_lr"
            np.testing.assert_array_equal(
                np.array(result["predictions"]), model.predict(rows)
            )
        finally:
            flt.close()

    def test_non_router_base_probes_none_once(self, data, tmp_path):
        from learningorchestra_tpu import client as sdk

        X, y = data
        fit_and_checkpoint("direct_prediction_lr", X, y, tmp_path)
        plane = ServePlane(
            capacity=10**9, window_s=0.0, max_batch=8, inbox_cap=32
        )
        app = model_builder.create_app(
            InMemoryStore(), models_dir=str(tmp_path), serve=plane
        )
        server = ServerThread(app, "127.0.0.1", 0).start()
        try:
            # a direct model_builder /health has no fleet_router field
            sdk.Context(f"127.0.0.1:{server.port}")
            wrapper = sdk.Model()
            assert wrapper._router_base() is None
            # ... and the verdict is cached: one probe per base URL
            assert sdk.Model._router_probe_cache == {
                f"http://127.0.0.1:{server.port}": False
            }
            assert wrapper._router_base() is None
        finally:
            server.stop()
            plane.close()


# ---------------------------------------------------------------------------
# The kill-one-replica chaos drills


class TestKillOneReplicaDrill:
    def _drive(self, flt, model, rows, responses, clients=4, requests=10):
        send, factory = http_predict_sender(
            [flt.router_target],
            model,
            rows,
            timeout_s=10.0,
            on_response=lambda status, payload: responses.append(
                (status, payload)
            ),
        )
        return run_closed_loop(
            send,
            clients=clients,
            requests_per_client=requests,
            rows_per_request=len(rows),
            session_factory=factory,
        )

    def test_failover_under_load_and_recovery(self, data, tmp_path):
        """The headline fast drill (docs/serving.md "Fleet"): kill the
        model's PRIMARY owner under closed-loop load. Every request
        still answers 200 for the right model (`lo_router_retries_total`
        proves failover did it), and once the dead replica misses its
        down window the fleet recovers: fresh requests route straight
        to the survivor, zero new retries."""
        X, y = data
        names = ["drill_a_prediction_lr", "drill_b_prediction_lr"]
        for name in names:
            fit_and_checkpoint(name, X, y, tmp_path)
        flt = _InProcessFleet(tmp_path, replicas=2, rf=2, down_s=1.5)
        try:
            model = names[0]
            primary = flt.placement.owners(model)[0]
            rows = X[:4].tolist()
            responses = []

            baseline = flt.retries(model)
            self._drive(flt, model, rows, responses)
            # a healthy fleet never fails over
            assert flt.retries(model) == baseline
            assert all(status == 200 for status, _ in responses)

            flt.kill(primary)
            # inside the down window the dead primary still orders
            # first (its frozen heartbeat looks fresh): every request
            # must fail over to the surviving owner, invisibly
            self._drive(flt, model, rows, responses)
            after_kill = flt.retries(model)
            assert after_kill > baseline
            assert len(responses) == 80
            assert all(status == 200 for status, _ in responses)
            # ZERO wrong-model answers: every 200 names the asked model
            assert all(
                payload["result"]["model"] == model
                for _, payload in responses
            )

            # recovery: one stale down window + a view TTL later the
            # router orders the survivor first — no more retries
            time.sleep(flt.down_s + 0.3)
            responses.clear()
            self._drive(flt, model, rows, responses)
            assert flt.retries(model) == after_kill
            assert all(status == 200 for status, _ in responses)
        finally:
            flt.close()

    def test_in_flight_failover_with_route_delay(self, data, tmp_path):
        """The `serve.route` delay fault holds one routing decision
        open while the primary dies under it — the request must still
        answer 200 from the survivor."""
        X, y = data
        fit_and_checkpoint("inflight_prediction_lr", X, y, tmp_path)
        flt = _InProcessFleet(tmp_path, replicas=2, rf=2, down_s=5.0)
        try:
            model = "inflight_prediction_lr"
            primary = flt.placement.owners(model)[0]
            baseline = flt.retries(model)
            faults.install("serve.route", "delay:0.4@1")
            outcome = {}

            def one_request():
                request = urllib.request.Request(
                    f"http://{flt.router_target}/models/{model}/predict",
                    data=json.dumps({"rows": X[:2].tolist()}).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(request, timeout=10) as resp:
                    outcome["status"] = resp.status
                    outcome["body"] = json.loads(resp.read())

            thread = threading.Thread(target=one_request)
            thread.start()
            time.sleep(0.15)  # the request is parked inside the delay
            flt.kill(primary)
            thread.join(timeout=15)
            assert not thread.is_alive()
            assert outcome["status"] == 200
            assert outcome["body"]["result"]["model"] == model
            assert flt.retries(model) >= baseline + 1
        finally:
            flt.close()


# ---------------------------------------------------------------------------
# The subprocess drill: real runners, real SIGKILL (slow tier)


class _Proc:
    """One subprocess with a parsed boot line and a drained stdout."""

    def __init__(self, args, env, boot_pattern, timeout_s=180):
        self.process = subprocess.Popen(
            args,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=_REPO_ROOT,
        )
        self.boot_lines: list[str] = []
        deadline = time.monotonic() + timeout_s
        pattern = re.compile(boot_pattern)
        self.port = None
        while time.monotonic() < deadline:
            line = self.process.stdout.readline()
            if not line:
                raise AssertionError(
                    "subprocess died during bring-up:\n"
                    + "".join(self.boot_lines)
                )
            self.boot_lines.append(line)
            match = pattern.search(line)
            if match:
                self.port = int(match.group(1))
                break
        if self.port is None:
            self.terminate()
            raise AssertionError(
                "subprocess never served:\n" + "".join(self.boot_lines)
            )
        threading.Thread(
            target=lambda: all(True for _ in self.process.stdout),
            daemon=True,
        ).start()

    def kill9(self):
        os.kill(self.process.pid, signal.SIGKILL)
        self.process.wait(timeout=30)

    def terminate(self):
        if self.process.poll() is None:
            self.process.terminate()
            try:
                self.process.wait(timeout=15)
            except subprocess.TimeoutExpired:
                self.process.kill()


def _fleet_child_env(extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    for stale in (
        "LO_DATA_DIR",
        "LO_REPLICATE",
        "LO_PEERS",
        "LO_ARBITERS",
        "LO_PRIMARY_URL",
        "LO_NODE_ID",
        "LO_FLEET_REPLICA",
        "LO_SERVICE",
        "LO_PORT",
    ):
        env.pop(stale, None)
    env.update(extra)
    return env


@pytest.mark.slow
@pytest.mark.integration
def test_subprocess_drill_sigkill_one_replica(tmp_path, rng):
    """The production wiring end to end: a store subprocess, two
    replica runners (their agents pinning by placement), a router
    runner — then SIGKILL one replica mid-deployment and assert the
    router keeps answering 200 for the right model, with
    `lo_router_retries_total` > 0 scraped off the router's /metrics."""
    X = rng.normal(size=(200, 6))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(int)
    models_dir = tmp_path / "models"
    models_dir.mkdir()
    names = ["sub_a_prediction_lr", "sub_b_prediction_lr"]
    for name in names:
        fit_and_checkpoint(name, X, y, models_dir)

    store_proc = _Proc(
        [sys.executable, "-m", "learningorchestra_tpu.core.store_service"],
        _fleet_child_env({"LO_STORE_PORT": "0"}),
        r"store server on [^:]+:(\d+)",
        timeout_s=60,
    )
    replicas: list = []
    router_proc = None
    try:
        store_url = f"http://127.0.0.1:{store_proc.port}"
        shared = {
            "LO_HOST": "127.0.0.1",
            "LO_PORT": "0",
            "LO_STORE_URL": store_url,
            "LO_MODELS_DIR": str(models_dir),
            "LO_FLEET_REPLICAS": "2",
            "LO_FLEET_RF": "2",
            "LO_FLEET_DOWN_S": "2.0",
        }
        for index in range(2):
            replicas.append(
                _Proc(
                    [
                        sys.executable,
                        "-m",
                        "learningorchestra_tpu.services.runner",
                    ],
                    _fleet_child_env(
                        {
                            **shared,
                            "LO_SERVICE": "model_builder",
                            "LO_FLEET_REPLICA": str(index),
                        }
                    ),
                    r"service model_builder on [\w.\-]+:(\d+)",
                )
            )
        router_proc = _Proc(
            [sys.executable, "-m", "learningorchestra_tpu.services.runner"],
            _fleet_child_env({**shared, "LO_SERVICE": "router"}),
            r"service router on [\w.\-]+:(\d+)",
        )
        router = f"http://127.0.0.1:{router_proc.port}"

        def predict(model, timeout=30):
            request = urllib.request.Request(
                f"{router}/models/{model}/predict",
                data=json.dumps({"rows": X[:4].tolist()}).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(request, timeout=timeout) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as error:
                return error.code, json.loads(error.read())

        # wait until BOTH replicas gossip both models (rf=2 = full
        # replication), so the kill provably leaves a serving copy
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            with urllib.request.urlopen(
                f"{router}/models/{names[0]}", timeout=10
            ) as response:
                picture = json.loads(response.read())["result"]["fleet"]
            rows = picture["replicas"]
            if len(rows) == 2 and all(
                set(names) <= set(row["models"]) for row in rows.values()
            ):
                break
            time.sleep(0.25)
        else:
            raise AssertionError(f"replicas never pinned: {picture}")

        status, payload = predict(names[0])
        assert status == 200
        assert payload["result"]["model"] == names[0]

        replicas[0].kill9()
        # inside the down window: every answer must still be a 200 for
        # the right model — failover, not error
        for _ in range(10):
            status, payload = predict(names[0])
            assert status == 200, payload
            assert payload["result"]["model"] == names[0]

        with urllib.request.urlopen(f"{router}/metrics", timeout=10) as r:
            metrics_text = r.read().decode()
        match = re.search(
            r'lo_router_retries_total\{model="%s"\} (\d+)' % names[0],
            metrics_text,
        )
        assert match and int(match.group(1)) > 0, metrics_text

        # after the down window the router marks the corpse unhealthy
        time.sleep(2.5)
        with urllib.request.urlopen(
            f"{router}/models/{names[0]}", timeout=10
        ) as response:
            picture = json.loads(response.read())["result"]["fleet"]
        health = {
            index: row["healthy"]
            for index, row in picture["replicas"].items()
        }
        assert health["0"] is False and health["1"] is True
        status, payload = predict(names[1])
        assert status == 200
        assert payload["result"]["model"] == names[1]
    finally:
        if router_proc is not None:
            router_proc.terminate()
        for proc in replicas:
            proc.terminate()
        store_proc.terminate()
