"""Deterministic synthetic classification dataset shared by the
multi-host worker and the in-process reference run (not a pytest file)."""

import numpy as np


def make_dataset(n: int = 400, features: int = 12, classes: int = 3):
    rng = np.random.RandomState(7)
    centers = rng.randn(classes, features) * 3.0
    y = rng.randint(0, classes, size=n)
    X = centers[y] + rng.randn(n, features)
    return X.astype(np.float64), y.astype(np.int64)
