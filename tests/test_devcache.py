"""Device-resident data plane: rev-keyed cache invalidation (local and
over the wire), LRU capacity bounds, streaming-read fault recovery,
wire compression, and the builder's overlapped write-back."""

import threading
import zlib

import numpy as np
import pytest
import requests

from learningorchestra_tpu.core import devcache
from learningorchestra_tpu.core.devcache import DeviceCache
from learningorchestra_tpu.core.store import InMemoryStore, ROW_ID
from learningorchestra_tpu.core.store_service import (
    RemoteStore,
    create_store_app,
)
from learningorchestra_tpu.core.wire import (
    ACCEPT_HEADER,
    CONTENT_TYPE,
    ENCODING_HEADER,
    decode_frame,
    encode_frame,
)
from learningorchestra_tpu.utils.web import ServerThread


@pytest.fixture(autouse=True)
def clean_global_devcache():
    devcache.reset_global_devcache()
    yield
    devcache.reset_global_devcache()


@pytest.fixture()
def remote_store():
    server = ServerThread(
        create_store_app(InMemoryStore()), "127.0.0.1", 0
    ).start()
    yield RemoteStore(f"http://127.0.0.1:{server.port}")
    server.stop()


def seed_dataset(store) -> None:
    store.create_collection("ds")
    store.insert_one("ds", {ROW_ID: 0, "filename": "ds", "finished": True})
    store.insert_columns("ds", {"a": [1.0, 2.0, 3.0], "b": [4.0, 5.0, 6.0]})


# Every mutating store op, as (name, mutate, expected_value_of_a).
# The drop case expects an empty reload instead of values.
MUTATIONS = [
    ("insert_one", lambda s: s.insert_one("ds", {ROW_ID: 99, "a": 9.0})),
    (
        "insert_many",
        lambda s: s.insert_many("ds", [{ROW_ID: 100, "a": 8.0}]),
    ),
    ("insert_columns", lambda s: s.insert_columns("ds", {"a": [7.0]})),
    ("set_column", lambda s: s.set_column("ds", "a", [9.0, 9.0, 9.0])),
    (
        "set_field_values",
        lambda s: s.set_field_values("ds", "a", {1: 42.0}),
    ),
    ("update_one", lambda s: s.update_one("ds", {ROW_ID: 1}, {"a": 5.5})),
    ("drop", lambda s: s.drop("ds")),
]


class TestRevInvalidation:
    @pytest.mark.parametrize("name,mutate", MUTATIONS)
    def test_local_mutation_bumps_rev_and_evicts(self, name, mutate):
        store = InMemoryStore()
        seed_dataset(store)
        cache = DeviceCache(capacity=10_000_000)
        first = devcache.dataset_table(store, "ds", cache=cache)
        assert devcache.dataset_table(store, "ds", cache=cache) is first
        rev_before = store.collection_rev("ds")
        invalidations_before = cache.stats()["invalidations"]

        mutate(store)
        rev_after = store.collection_rev("ds")
        assert rev_after != rev_before  # every mutating op bumps (or -1)

        reloaded = devcache.dataset_table(store, "ds", cache=cache)
        assert reloaded is not first  # the stale entry was evicted
        assert cache.stats()["invalidations"] > invalidations_before
        if name == "drop":
            assert rev_after == -1
            assert reloaded.num_rows == 0
            # unknown rev: nothing was re-cached
            assert cache.stats()["entries"] == 0
        else:
            # the reload sees the mutation and is cached under the new rev
            assert devcache.dataset_table(store, "ds", cache=cache) is reloaded

    def test_rev_is_store_monotonic_across_drop_recreate(self):
        """A dropped-and-recreated collection must never reissue a rev a
        cache still holds — revs come from a store-wide sequence."""
        store = InMemoryStore()
        seed_dataset(store)
        rev_first = store.collection_rev("ds")
        store.drop("ds")
        seed_dataset(store)
        assert store.collection_rev("ds") > rev_first

    @pytest.mark.parametrize("name,mutate", MUTATIONS)
    def test_remote_mutation_bumps_rev_and_evicts(
        self, remote_store, name, mutate
    ):
        """The same invariant over the wire: RemoteStore probes
        GET /c/<name>/rev, so a write through ANY client evicts cached
        readers everywhere at their next lookup."""
        seed_dataset(remote_store)
        cache = DeviceCache(capacity=10_000_000)
        first = devcache.dataset_table(remote_store, "ds", cache=cache)
        assert (
            devcache.dataset_table(remote_store, "ds", cache=cache) is first
        )
        rev_before = remote_store.collection_rev("ds")

        mutate(remote_store)
        assert remote_store.collection_rev("ds") != rev_before

        reloaded = devcache.dataset_table(remote_store, "ds", cache=cache)
        assert reloaded is not first
        if name == "set_column":
            assert reloaded.columns["a"].tolist() == [9.0, 9.0, 9.0]

    def test_unknown_backend_never_caches(self):
        class NoRevStore(InMemoryStore):
            collection_rev = None

        store = NoRevStore()
        seed_dataset(store)
        cache = DeviceCache(capacity=10_000_000)
        first = devcache.dataset_table(store, "ds", cache=cache)
        second = devcache.dataset_table(store, "ds", cache=cache)
        assert first is not second
        assert cache.stats()["entries"] == 0


class TestLruBounds:
    def test_eviction_under_cap(self):
        cache = DeviceCache(capacity=100)
        for i in range(5):
            cache.put("s", f"c{i}", ("k",), rev=1, value=i, nbytes=40)
        stats = cache.stats()
        assert stats["bytes"] <= 100
        assert stats["entries"] == 2
        assert stats["evictions"] == 3
        # LRU order: the newest entries survive
        assert cache.get("s", "c4", ("k",), 1) == 4
        assert cache.get("s", "c0", ("k",), 1) is None

    def test_lookup_refreshes_recency(self):
        cache = DeviceCache(capacity=100)
        cache.put("s", "a", ("k",), 1, "a", 40)
        cache.put("s", "b", ("k",), 1, "b", 40)
        assert cache.get("s", "a", ("k",), 1) == "a"  # a is now most recent
        cache.put("s", "c", ("k",), 1, "c", 40)  # evicts b, not a
        assert cache.get("s", "a", ("k",), 1) == "a"
        assert cache.get("s", "b", ("k",), 1) is None

    def test_oversized_entry_passes_through_uncached(self):
        cache = DeviceCache(capacity=100)
        value = cache.put("s", "a", ("k",), 1, "big", nbytes=1000)
        assert value == "big"
        assert cache.stats()["entries"] == 0

    def test_zero_capacity_disables(self):
        store = InMemoryStore()
        seed_dataset(store)
        cache = DeviceCache(capacity=0)
        first = devcache.dataset_table(store, "ds", cache=cache)
        assert devcache.dataset_table(store, "ds", cache=cache) is not first


class TestConcurrentReaders:
    def test_many_threads_one_entry(self):
        store = InMemoryStore()
        seed_dataset(store)
        cache = DeviceCache(capacity=10_000_000)
        results = []
        errors = []

        def read():
            try:
                for _ in range(20):
                    table = devcache.dataset_table(store, "ds", cache=cache)
                    results.append(table.columns["a"].tolist())
            except Exception as error:  # noqa: BLE001
                errors.append(error)

        threads = [threading.Thread(target=read) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert all(values == [1.0, 2.0, 3.0] for values in results)
        stats = cache.stats()
        assert stats["entries"] == 1
        # concurrent first loads may race (both load, last put wins) but
        # the steady state is all hits
        assert stats["hits"] > 100


class TestMidStreamFault:
    def test_retry_resumes_at_failed_chunk_and_purges_cache(
        self, remote_store
    ):
        """Regression: a mid-stream chunk failure must (a) invalidate any
        partially-populated devcache entry for the collection and (b)
        retry from the FAILED chunk — chunk 0 is never re-fetched."""
        remote_store.insert_columns(
            "ds", {"x": [float(i) for i in range(25)]}
        )
        remote_store.wire_rows_bin = 7

        # a resident entry for this collection under THIS store's scope,
        # standing in for a partially-populated one; an entry for a
        # same-named collection of a DIFFERENT store must survive the
        # purge
        cache = devcache.global_devcache()
        scope = devcache.store_token(remote_store)
        cache.put(scope, "ds", ("partial",), rev=1, value="stale", nbytes=8)
        cache.put("otherstore", "ds", ("k",), rev=1, value="keep", nbytes=8)
        assert cache.stats()["entries"] == 2

        calls = []
        failed = []
        original = remote_store._fetch_frame_bytes

        def faulty(path, body):
            if path.endswith("/read_columns_bin"):
                calls.append(body["start"])
                if body["start"] == 14 and not failed:
                    failed.append(True)
                    raise requests.ConnectionError("injected mid-stream")
            return original(path, body)

        remote_store._fetch_frame_bytes = faulty
        try:
            out = remote_store.read_column_arrays("ds", ["x"])
        finally:
            remote_store._fetch_frame_bytes = original

        assert out["x"].tolist() == [float(i) for i in range(25)]
        assert calls.count(0) == 1  # never restarted from chunk 0
        assert calls.count(14) == 2  # the failed chunk, retried in place
        assert cache.get(scope, "ds", ("partial",), 1) is None  # purged
        assert cache.get("otherstore", "ds", ("k",), 1) == "keep"

    def test_exhausted_retries_surface_the_error(self, remote_store):
        remote_store.insert_columns("ds", {"x": [1.0, 2.0, 3.0]})
        remote_store.wire_rows_bin = 2
        remote_store.chunk_retries = 1
        original = remote_store._fetch_frame_bytes

        def always_fails(path, body):
            if path.endswith("/read_columns_bin") and body["start"] == 2:
                raise requests.ConnectionError("injected, persistent")
            return original(path, body)

        remote_store._fetch_frame_bytes = always_fails
        try:
            with pytest.raises(requests.ConnectionError):
                remote_store.read_column_arrays("ds", ["x"])
        finally:
            remote_store._fetch_frame_bytes = original


class TestStreamingReads:
    def test_double_buffered_read_matches_single_frame(self, remote_store):
        rows = 100
        remote_store.insert_columns(
            "ds",
            {
                "x": [float(i) for i in range(rows)],
                "s": [str(i) for i in range(rows)],
            },
        )
        full = remote_store.read_column_arrays("ds")
        remote_store.wire_rows_bin = 9  # force the paged, prefetching loop
        paged = remote_store.read_column_arrays("ds")
        assert paged["x"].tolist() == full["x"].tolist()
        assert paged["s"].tolist() == full["s"].tolist()

    def test_rev_endpoint(self, remote_store):
        assert remote_store.collection_rev("missing") == -1
        remote_store.insert_columns("ds", {"x": [1.0]})
        rev = remote_store.collection_rev("ds")
        assert rev > 0
        remote_store.insert_columns("ds", {"x": [2.0]})
        assert remote_store.collection_rev("ds") > rev


class TestWireCompression:
    def make_app(self):
        store = InMemoryStore()
        store.insert_columns(
            "ds", {"x": [float(i % 17) for i in range(5000)]}
        )
        return store, create_store_app(store).test_client()

    def test_server_compresses_only_when_advertised(self):
        _, client = self.make_app()
        body = {"fields": ["x"], "start": 0, "limit": None}
        plain = client.post("/c/ds/read_columns_bin", json=body)
        assert plain.headers.get(ENCODING_HEADER) is None
        columns, _ = decode_frame(plain.data)
        assert len(columns["x"]) == 5000

        squeezed = client.post(
            "/c/ds/read_columns_bin",
            json=body,
            headers={ACCEPT_HEADER: "zlib"},
        )
        assert squeezed.headers.get(ENCODING_HEADER) == "zlib"
        assert len(squeezed.data) < len(plain.data)
        columns, _ = decode_frame(zlib.decompress(squeezed.data))
        assert columns["x"].tolist() == [float(i % 17) for i in range(5000)]

    def test_server_accepts_compressed_uploads(self):
        from learningorchestra_tpu.core.columns import Column

        store, client = self.make_app()
        frame = encode_frame(
            {"y": Column.from_values([float(i) for i in range(5000)])},
            extra={"start_id": 1},
        )
        response = client.post(
            "/c/up/insert_columns_bin",
            data=zlib.compress(frame, 1),
            headers={
                "Content-Type": CONTENT_TYPE,
                ENCODING_HEADER: "zlib",
            },
        )
        assert response.status_code == 200
        assert store.count("up") == 5000

    def test_remote_store_round_trip_compressed(self):
        server = ServerThread(
            create_store_app(InMemoryStore()), "127.0.0.1", 0
        ).start()
        try:
            remote = RemoteStore(
                f"http://127.0.0.1:{server.port}", compress=True
            )
            values = [float(i) for i in range(5000)]
            remote.insert_columns("ds", {"x": values})
            assert remote.read_column_arrays("ds", ["x"])["x"].tolist() == (
                values
            )
        finally:
            server.stop()


class TestContentAddressedDeviceCache:
    def test_same_bytes_reuse_one_device_copy(self):
        from learningorchestra_tpu.frame.dataframe import DataFrame

        X = np.arange(48, dtype=np.float64).reshape(12, 4)
        frame_a = DataFrame({"features": X.copy()})
        frame_b = DataFrame({"features": X.copy()})  # distinct frame, same bytes
        dm_a = frame_a.device_matrix("features")
        dm_b = frame_b.device_matrix("features")
        assert dm_a is dm_b  # one H2D served both frames
        changed = DataFrame({"features": X + 1.0})
        assert changed.device_matrix("features") is not dm_a

    def test_labels_cached_by_content(self):
        from learningorchestra_tpu.frame.dataframe import DataFrame

        y = np.array([0.0, 1.0, 0.0, 1.0])
        frame_a = DataFrame({"label": y.copy()})
        frame_b = DataFrame({"label": y.copy()})
        assert frame_a.device_labels("label") is frame_b.device_labels(
            "label"
        )

    def test_embedding_inputs_cached_atomically_and_rev_keyed(self):
        store = InMemoryStore()
        seed_dataset(store)
        cache = DeviceCache(capacity=100_000_000)
        encoded, vocab, dm = devcache.dataset_embedding_inputs(
            store, "ds", cache=cache
        )
        again = devcache.dataset_embedding_inputs(store, "ds", cache=cache)
        # one atomic entry: table, vocab and device matrix hit together
        assert again[0] is encoded and again[2] is dm
        assert len(dm) == encoded.num_rows
        store.set_column("ds", "a", [7.0, 7.0, 7.0])
        reloaded = devcache.dataset_embedding_inputs(
            store, "ds", cache=cache
        )
        assert reloaded[2] is not dm
        assert reloaded[0].columns["a"].tolist() == [7.0, 7.0, 7.0]


class TestEmbeddingDeviceInputs:
    def test_pca_accepts_device_matrix(self):
        from learningorchestra_tpu.ml.base import shard_matrix
        from learningorchestra_tpu.ops.pca import pca_embedding

        X = np.random.default_rng(0).random((64, 4)).astype(np.float32)
        from_host = pca_embedding(X)
        from_device = pca_embedding(shard_matrix(X))
        assert from_device.shape == (64, 2)
        np.testing.assert_allclose(from_host, from_device, atol=1e-4)

    def test_images_pipeline_hits_cache_on_second_embed(self, tmp_path):
        from learningorchestra_tpu.ops.images import create_embedding_image

        store = InMemoryStore()
        seed_dataset(store)
        create_embedding_image(
            store, "ds", None, "first", str(tmp_path), "pca", render=False
        )
        stats_after_first = devcache.global_devcache().stats()
        create_embedding_image(
            store, "ds", None, "second", str(tmp_path), "pca", render=False
        )
        stats_after_second = devcache.global_devcache().stats()
        # second embed: ONE atomic hit serves the encoded table + device
        # matrix together (the raw table read lives inside its loader,
        # which never runs again)
        assert (
            stats_after_second["hits"] >= stats_after_first["hits"] + 1
        )
        assert (
            stats_after_second["misses"] == stats_after_first["misses"]
        )


def _build_tiny(store, overlap: str, classifiers=("nb", "dt")):
    import os

    from learningorchestra_tpu.ml.builder import build_model

    preprocessor = (
        "from pyspark.ml.feature import VectorAssembler\n"
        "cols = [c for c in training_df.schema.names if c != 'label']\n"
        "assembler = VectorAssembler(inputCols=cols, outputCol='features')\n"
        "features_training = assembler.transform(training_df)\n"
        "features_testing = assembler.transform(testing_df)\n"
        "features_evaluation = assembler.transform(testing_df)\n"
    )
    previous = os.environ.get("LO_WRITE_OVERLAP")
    os.environ["LO_WRITE_OVERLAP"] = overlap
    try:
        return build_model(
            store,
            "train",
            "test",
            preprocessor,
            list(classifiers),
        )
    finally:
        if previous is None:
            os.environ.pop("LO_WRITE_OVERLAP", None)
        else:
            os.environ["LO_WRITE_OVERLAP"] = previous


def _seed_build_dataset(store):
    rng = np.random.default_rng(3)
    X = rng.random((80, 4))
    y = (X[:, 0] > 0.5).astype(float)
    for name in ("train", "test"):
        store.create_collection(name)
        store.insert_one(
            name, {ROW_ID: 0, "filename": name, "finished": True}
        )
        columns = {f"f{i}": X[:, i].tolist() for i in range(4)}
        columns["label"] = y.tolist()
        store.insert_columns(name, columns)


class TestOverlappedWriteBack:
    def test_overlapped_matches_synchronous(self):
        store_sync = InMemoryStore()
        _seed_build_dataset(store_sync)
        results_sync = _build_tiny(store_sync, overlap="0")

        store_async = InMemoryStore()
        _seed_build_dataset(store_async)
        results_async = _build_tiny(store_async, overlap="1")

        for sync_md, async_md in zip(results_sync, results_async):
            name = sync_md["classificator"]
            assert async_md["classificator"] == name
            assert async_md["accuracy"] == sync_md["accuracy"]
            # the barrier ran: timings are complete, write included
            assert "write" in async_md["timings"]
            out = f"test_prediction_{name}"
            sync_rows = store_sync.read_columns(out, ["prediction"])
            async_rows = store_async.read_columns(out, ["prediction"])
            assert sync_rows == async_rows
            # metadata document landed after the rows
            assert store_async.find_one(out, {ROW_ID: 0})["timings"]

    def test_write_failure_fails_the_build(self):
        class FailingWrites(InMemoryStore):
            def insert_columns(self, collection, columns, start_id=None):
                if "_prediction_" in collection:
                    raise RuntimeError("store full (injected)")
                super().insert_columns(collection, columns, start_id)

        store = FailingWrites()
        _seed_build_dataset(store)
        with pytest.raises(RuntimeError, match="store full"):
            _build_tiny(store, overlap="1", classifiers=("nb",))


class TestKnobPlumbing:
    def test_capacity_env_validation(self, monkeypatch):
        monkeypatch.setenv("LO_DEVCACHE_BYTES", "2e9")
        assert devcache.capacity_bytes() == 2_000_000_000
        monkeypatch.setenv("LO_DEVCACHE_BYTES", "0")
        assert devcache.capacity_bytes() == 0
        for bad in ("lots", "-1"):
            monkeypatch.setenv("LO_DEVCACHE_BYTES", bad)
            with pytest.raises(ValueError):
                devcache.capacity_bytes()

    def test_cluster_manifest_dataplane_section(self, tmp_path):
        import json
        import sys

        sys.path.insert(0, "deploy")
        try:
            import cluster
        finally:
            sys.path.pop(0)
        path = tmp_path / "manifest.json"
        path.write_text(
            json.dumps(
                {
                    "repo": ".",
                    "head": {"host": "127.0.0.1"},
                    "dataplane": {
                        "devcache_bytes": 123456,
                        "store_compress": 1,
                        "write_overlap": 0,
                    },
                }
            )
        )
        loaded = cluster.load_manifest(str(path))
        env = cluster.machine_plans(loaded)[0]["env"]
        assert env["LO_DEVCACHE_BYTES"] == "123456"
        assert env["LO_STORE_COMPRESS"] == "1"
        assert env["LO_WRITE_OVERLAP"] == "0"
        bad = tmp_path / "bad.json"
        for section in (
            {"devcache_bytes": -1},
            {"devcache_bytes": True},  # bool is an int subclass
            {"store_compress": 2},
            {"write_overlap": "1"},
            {"mystery_knob": 1},
        ):
            bad.write_text(
                json.dumps(
                    {
                        "repo": ".",
                        "head": {"host": "127.0.0.1"},
                        "dataplane": section,
                    }
                )
            )
            with pytest.raises(SystemExit):
                cluster.load_manifest(str(bad))


class TestBuilderCachedLoads:
    def test_second_build_skips_the_read(self):
        store = InMemoryStore()
        _seed_build_dataset(store)
        _build_tiny(store, overlap="1", classifiers=("nb",))
        stats_first = devcache.global_devcache().stats()
        _build_tiny(store, overlap="1", classifiers=("nb",))
        stats_second = devcache.global_devcache().stats()
        # warm build: train+test table reads hit; no new loads for them
        assert stats_second["hits"] >= stats_first["hits"] + 2
