"""Event-loop serving core (utils/webloop) + push job completion.

Covers the /wait route contract (immediate return, timeout hint, 404
parity, cancel wake, SSE framing golden), the raw-socket behaviours of
the loop server (keep-alive pipelining, slow-loris eviction, graceful
drain, connection cap, O(1) threads under many waiters), the
LO_WEB_ASYNC=0 escape hatch's byte parity, the web knobs' fail-fast
validation, and the client's push-first waiting (docs/web.md).
"""

import json
import socket
import threading
import time

import pytest

from learningorchestra_tpu import client as client_module
from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.sched import policy
from learningorchestra_tpu.utils import webloop
from learningorchestra_tpu.utils.web import ServerThread, WebApp


def body(response):
    return json.loads(response.get_data())


def make_app(jobs=None):
    jobs = jobs or JobManager()
    app = WebApp("waitsvc")
    app.register_job_routes(jobs)
    return app, jobs


def _quick():
    return "done"


def _blocked(release):
    release.wait(30)
    return "released"


def _cancellable(started):
    from learningorchestra_tpu.sched.cancel import check_cancelled

    started.set()
    while True:
        check_cancelled()
        time.sleep(0.005)


def _wait_state(record, state, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if record.state == state:
            return
        time.sleep(0.01)
    raise AssertionError(f"job never reached {state!r} (at {record.state!r})")


def _read_response(sock, buf=b""):
    """One HTTP response off a blocking socket: ``(head, body,
    leftover)`` — leftover carries pipelined bytes for the next call."""
    while b"\r\n\r\n" not in buf:
        data = sock.recv(65536)
        if not data:
            raise AssertionError("connection closed before headers")
        buf += data
    head, _, rest = buf.partition(b"\r\n\r\n")
    length = 0
    for line in head.split(b"\r\n")[1:]:
        key, _, value = line.partition(b":")
        if key.strip().lower() == b"content-length":
            length = int(value.strip())
    while len(rest) < length:
        data = sock.recv(65536)
        if not data:
            raise AssertionError("connection closed before body")
        rest += data
    return head, rest[:length], rest[length:]


def _read_until_close(sock):
    chunks = []
    while True:
        data = sock.recv(65536)
        if not data:
            break
        chunks.append(data)
    return b"".join(chunks)


@pytest.fixture()
def loop_app():
    app, jobs = make_app()
    server = webloop.LoopServer(app, "127.0.0.1", 0).start()
    yield app, jobs, server
    server.stop()


class TestWaitRoute:
    """The /jobs/<name>/wait contract via the blocking (test-client)
    resolution path — shared handler code with the event loop."""

    def test_already_finished_immediate(self):
        app, jobs = make_app()
        jobs.submit("quick", _quick)
        jobs.wait("quick", timeout=10)
        client = app.test_client()
        start = time.perf_counter()
        response = client.get("/jobs/quick/wait?timeout=20")
        elapsed = time.perf_counter() - start
        assert response.status_code == 200
        assert body(response)["result"]["state"] == "finished"
        assert elapsed < 2.0  # immediate, not the requested 20 s

    def test_timeout_is_a_clean_repoll_hint(self):
        app, jobs = make_app()
        release = threading.Event()
        jobs.submit("parked", _blocked, release)
        try:
            response = app.test_client().get("/jobs/parked/wait?timeout=0.05")
            assert response.status_code == 200
            payload = body(response)
            assert payload["result"] == "timeout"
            assert payload["job"] == "parked"
            assert payload["state"] in ("pending", "running")
        finally:
            release.set()

    def test_bad_timeout_400(self):
        app, jobs = make_app()
        client = app.test_client()
        for bad in ("abc", "-1", "nan"):
            response = client.get(f"/jobs/x/wait?timeout={bad}")
            assert response.status_code == 400
            assert body(response) == {"result": "bad_timeout"}

    def test_404_parity_with_job_read(self):
        app, jobs = make_app()
        client = app.test_client()
        plain = client.get("/jobs/nope")
        wait = client.get("/jobs/nope/wait?timeout=1")
        assert plain.status_code == wait.status_code == 404
        assert body(plain) == body(wait) == {"result": "not_found"}

    def test_bare_filename_resolves_to_collection_job(self):
        """Clients know dataset filenames; jobs carry prefixed names."""
        app, jobs = make_app()
        jobs.submit("ingest:titanic", _quick, collection="titanic")
        jobs.wait("ingest:titanic", timeout=10)
        response = app.test_client().get("/jobs/titanic/wait?timeout=5")
        assert response.status_code == 200
        assert body(response)["result"]["name"] == "ingest:titanic"

    def test_health_advertises_job_wait(self):
        app, jobs = make_app()
        response = app.test_client().get("/health")
        assert response.status_code == 200
        assert body(response)["job_wait"] is True

    def test_cancel_wakes_waiters(self):
        app, jobs = make_app()
        started = threading.Event()
        jobs.submit("doomed", _cancellable, started)
        assert started.wait(10)
        results = []

        def waiter():
            results.append(
                body(app.test_client().get("/jobs/doomed/wait?timeout=15"))
            )

        thread = threading.Thread(target=waiter)
        thread.start()
        time.sleep(0.3)  # the waiter is blocked in resolve_blocking
        start = time.perf_counter()
        cancel = app.test_client().delete("/jobs/doomed")
        assert cancel.status_code == 202
        thread.join(timeout=10)
        assert not thread.is_alive()
        assert results[0]["result"]["state"] == "cancelled"
        assert time.perf_counter() - start < 8.0  # woke, did not ride out 15 s


class TestSSE:
    def test_timeout_frame_golden(self):
        """The exact bytes an SSE /wait answers on timeout."""
        app, jobs = make_app()
        release = threading.Event()
        jobs.submit("sse-parked", _blocked, release)
        _wait_state(jobs.get("sse-parked"), "running")
        try:
            response = app.test_client().get(
                "/jobs/sse-parked/wait?timeout=0.05",
                headers={"Accept": "text/event-stream"},
            )
            assert response.status_code == 200
            assert response.content_type.startswith("text/event-stream")
            expected = webloop.SSE_PREAMBLE + webloop.sse_frame(
                "timeout",
                {"result": "timeout", "job": "sse-parked", "state": "running"},
            )
            assert response.get_data() == expected
        finally:
            release.set()

    def test_async_and_threaded_framing_byte_identical(self, loop_app):
        """The golden parity claim: the event loop's SSE stream (head at
        park, frame at resolve) concatenates to the same bytes the
        blocking server answers in one body."""
        app, jobs, server = loop_app
        release = threading.Event()
        jobs.submit("sse-parity", _blocked, release)
        _wait_state(jobs.get("sse-parity"), "running")
        try:
            threaded_body = app.test_client().get(
                "/jobs/sse-parity/wait?timeout=0.2",
                headers={"Accept": "text/event-stream"},
            ).get_data()
            sock = socket.create_connection(("127.0.0.1", server.port), 10)
            sock.settimeout(10)
            sock.sendall(
                b"GET /jobs/sse-parity/wait?timeout=0.2 HTTP/1.1\r\n"
                b"Host: t\r\nAccept: text/event-stream\r\n\r\n"
            )
            raw = _read_until_close(sock)
            sock.close()
            head, _, stream = raw.partition(b"\r\n\r\n")
            assert b"200 OK" in head.split(b"\r\n", 1)[0]
            assert b"text/event-stream" in head
            assert stream == threaded_body
        finally:
            release.set()

    def test_done_event_on_finished_job(self, loop_app):
        app, jobs, server = loop_app
        jobs.submit("sse-done", _quick)
        jobs.wait("sse-done", timeout=10)
        sock = socket.create_connection(("127.0.0.1", server.port), 10)
        sock.settimeout(10)
        sock.sendall(
            b"GET /jobs/sse-done/wait?timeout=5 HTTP/1.1\r\n"
            b"Host: t\r\nAccept: text/event-stream\r\n\r\n"
        )
        raw = _read_until_close(sock)
        sock.close()
        _, _, stream = raw.partition(b"\r\n\r\n")
        assert stream.startswith(webloop.SSE_PREAMBLE)
        assert b"event: done\n" in stream
        payload = json.loads(
            stream.split(b"data: ", 1)[1].split(b"\n", 1)[0]
        )
        assert payload["result"]["state"] == "finished"


class TestLoopServer:
    def test_keep_alive_pipelining(self, loop_app):
        """Two requests in ONE send, two responses on one connection."""
        app, jobs, server = loop_app
        request = b"GET /health HTTP/1.1\r\nHost: t\r\n\r\n"
        sock = socket.create_connection(("127.0.0.1", server.port), 10)
        sock.settimeout(10)
        sock.sendall(request + request)
        head1, body1, leftover = _read_response(sock)
        head2, body2, _ = _read_response(sock, leftover)
        sock.close()
        for head, payload in ((head1, body1), (head2, body2)):
            assert b"200 OK" in head.split(b"\r\n", 1)[0]
            assert b"Connection: keep-alive" in head
            assert json.loads(payload)["job_wait"] is True

    def test_slow_loris_eviction(self):
        app, jobs = make_app()
        server = webloop.LoopServer(
            app, "127.0.0.1", 0, header_timeout_s=0.3
        ).start()
        try:
            sock = socket.create_connection(("127.0.0.1", server.port), 10)
            sock.settimeout(10)
            sock.sendall(b"GET /health HTTP/1.1\r\nHost")  # never finishes
            raw = _read_until_close(sock)  # 408, then server closes
            sock.close()
            assert b"408" in raw.split(b"\r\n", 1)[0]
            assert json.loads(raw.partition(b"\r\n\r\n")[2]) == {
                "result": "request_timeout"
            }
        finally:
            server.stop()

    def test_graceful_stop_drains_parked_waiters(self):
        app, jobs = make_app()
        server = webloop.LoopServer(app, "127.0.0.1", 0).start()
        release = threading.Event()
        jobs.submit("drainee", _blocked, release)
        try:
            sock = socket.create_connection(("127.0.0.1", server.port), 10)
            sock.settimeout(10)
            sock.sendall(
                b"GET /jobs/drainee/wait?timeout=30 HTTP/1.1\r\nHost: t\r\n\r\n"
            )
            deadline = time.monotonic() + 10
            while server.waiter_count < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert server.waiter_count == 1
            server.stop()
            head, payload, _ = _read_response(sock)
            sock.close()
            assert b"200 OK" in head.split(b"\r\n", 1)[0]
            assert json.loads(payload)["result"] == "timeout"
        finally:
            release.set()

    def test_many_waiters_o1_threads(self):
        app, jobs = make_app()
        server = webloop.LoopServer(app, "127.0.0.1", 0, handlers=4).start()
        release = threading.Event()
        jobs.submit("crowd", _blocked, release)
        socks = []
        try:
            # warm the lazily-spawned handler pool so its threads do not
            # count against the parked-waiter delta
            sock = socket.create_connection(("127.0.0.1", server.port), 10)
            sock.settimeout(10)
            sock.sendall(b"GET /health HTTP/1.1\r\nHost: t\r\n\r\n")
            _read_response(sock)
            sock.close()
            threads_before = threading.active_count()
            for _ in range(30):
                sock = socket.create_connection(
                    ("127.0.0.1", server.port), 10
                )
                sock.settimeout(10)
                sock.sendall(
                    b"GET /jobs/crowd/wait?timeout=25 HTTP/1.1\r\n"
                    b"Host: t\r\nConnection: close\r\n\r\n"
                )
                socks.append(sock)
            deadline = time.monotonic() + 10
            while server.waiter_count < 30 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert server.waiter_count == 30
            # handler pool is capped at 4; parked connections hold none
            assert threading.active_count() - threads_before <= 4
            release.set()
            for sock in socks:
                head, payload, _ = _read_response(sock)
                assert json.loads(payload)["result"]["state"] == "finished"
        finally:
            release.set()
            for sock in socks:
                sock.close()
            server.stop()

    def test_connection_cap_503(self):
        app, jobs = make_app()
        server = webloop.LoopServer(app, "127.0.0.1", 0, max_conns=1).start()
        try:
            keeper = socket.create_connection(("127.0.0.1", server.port), 10)
            keeper.settimeout(10)
            keeper.sendall(b"GET /health HTTP/1.1\r\nHost: t\r\n\r\n")
            _read_response(keeper)  # keep-alive: still counted
            extra = socket.create_connection(("127.0.0.1", server.port), 10)
            extra.settimeout(10)
            raw = _read_until_close(extra)
            extra.close()
            keeper.close()
            assert b"503" in raw.split(b"\r\n", 1)[0]
            assert b"Retry-After: 1" in raw
        finally:
            server.stop()

    def test_metrics_families_visible(self, loop_app):
        app, jobs, server = loop_app
        release = threading.Event()
        jobs.submit("gauged", _blocked, release)
        try:
            sock = socket.create_connection(("127.0.0.1", server.port), 10)
            sock.settimeout(10)
            sock.sendall(
                b"GET /jobs/gauged/wait?timeout=20 HTTP/1.1\r\nHost: t\r\n\r\n"
            )
            deadline = time.monotonic() + 10
            while server.waiter_count < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            text = app.test_client().get("/metrics").get_data(as_text=True)
            assert 'lo_web_waiters{service="waitsvc"} 1' in text
            assert 'lo_web_connections{service="waitsvc",state="idle"}' in text
            assert (
                'lo_web_connections{service="waitsvc",state="active"}' in text
            )
            assert "lo_web_notify_seconds" in text
            release.set()
            _read_response(sock)
            sock.close()
        finally:
            release.set()


class TestEscapeHatch:
    def test_threaded_server_parity(self, monkeypatch):
        """LO_WEB_ASYNC=0 serves the same /wait bytes through werkzeug's
        thread-per-request server."""
        import requests

        app, jobs = make_app()
        jobs.submit("parity", _quick)
        jobs.wait("parity", timeout=10)
        bodies = {}
        for flag in ("1", "0"):
            monkeypatch.setenv("LO_WEB_ASYNC", flag)
            server = ServerThread(app, "127.0.0.1", 0).start()
            try:
                assert (server._loop is None) == (flag == "0")
                response = requests.get(
                    f"http://127.0.0.1:{server.port}/jobs/parity/wait",
                    params={"timeout": "5"},
                    timeout=10,
                )
                assert response.status_code == 200
                bodies[flag] = response.content
                health = requests.get(
                    f"http://127.0.0.1:{server.port}/health", timeout=10
                )
                assert health.json()["job_wait"] is True
            finally:
                server.stop()
        assert bodies["0"] == bodies["1"]


class TestKnobs:
    def test_async_flag_strict(self, monkeypatch):
        monkeypatch.setenv("LO_WEB_ASYNC", "2")
        with pytest.raises(ValueError, match="LO_WEB_ASYNC"):
            webloop.web_async_enabled()
        monkeypatch.setenv("LO_WEB_ASYNC", "0")
        assert webloop.web_async_enabled() is False
        monkeypatch.delenv("LO_WEB_ASYNC")
        assert webloop.web_async_enabled() is True

    def test_handlers_strictly_integral(self, monkeypatch):
        for bad in ("0", "2.0", "lots"):
            monkeypatch.setenv("LO_WEB_HANDLERS", bad)
            with pytest.raises(ValueError, match="LO_WEB_HANDLERS"):
                webloop.handler_pool_size()
        monkeypatch.setenv("LO_WEB_HANDLERS", "3")
        assert webloop.handler_pool_size() == 3

    def test_wait_cap_positive(self, monkeypatch):
        monkeypatch.setenv("LO_WEB_WAIT_CAP_S", "0")
        with pytest.raises(ValueError, match="LO_WEB_WAIT_CAP_S"):
            webloop.wait_cap_s()

    def test_validate_env_resolves_defaults(self, monkeypatch):
        for knob in (
            "LO_WEB_ASYNC", "LO_WEB_HANDLERS",
            "LO_WEB_MAX_CONNS", "LO_WEB_WAIT_CAP_S",
        ):
            monkeypatch.delenv(knob, raising=False)
        assert webloop.validate_env() == {
            "LO_WEB_ASYNC": 1,
            "LO_WEB_HANDLERS": 8,
            "LO_WEB_MAX_CONNS": 10_000,
            "LO_WEB_WAIT_CAP_S": 60.0,
        }

    def test_wait_timeout_capped_by_knob(self, monkeypatch):
        monkeypatch.setenv("LO_WEB_WAIT_CAP_S", "0.05")
        app, jobs = make_app()
        release = threading.Event()
        jobs.submit("capped", _blocked, release)
        try:
            start = time.perf_counter()
            response = app.test_client().get("/jobs/capped/wait?timeout=30")
            assert body(response)["result"] == "timeout"
            assert time.perf_counter() - start < 5.0
        finally:
            release.set()


class TestWaiterUnit:
    def test_notify_idempotent_first_instant_wins(self):
        waiter = webloop.Waiter(lambda: None, 1.0, lambda: ({}, 200))
        waiter.notify()
        first = waiter.notified_at
        time.sleep(0.01)
        waiter.notify()
        assert waiter.notified_at == first

    def test_resolve_blocking_kinds(self):
        ready = webloop.Waiter(lambda: ({"ok": 1}, 200), 1.0, lambda: ({}, 200))
        assert ready.resolve_blocking() == (({"ok": 1}, 200), "ready")
        timed = webloop.Waiter(
            lambda: None, 0.02, lambda: ({"late": 1}, 200)
        )
        assert timed.resolve_blocking() == (({"late": 1}, 200), "timeout")


class TestClientPush:
    @pytest.fixture()
    def fresh_probe_cache(self, monkeypatch):
        monkeypatch.setattr(
            client_module.AsyncronousWait, "_push_probe_cache", {}
        )

    def test_wait_prefers_push(self, monkeypatch, fresh_probe_cache):
        """With /health advertising job_wait, wait() resolves through
        /jobs/<name>/wait — the app has NO metadata route, so a poll
        fallback would fail loudly."""
        app, jobs = make_app()
        jobs.submit("ingest:titanic", _quick, collection="titanic")
        jobs.wait("ingest:titanic", timeout=10)
        server = webloop.LoopServer(app, "127.0.0.1", 0).start()
        try:
            monkeypatch.setattr(
                client_module.DatabaseApi,
                "DATABASE_API_PORT",
                str(server.port),
            )
            client_module.Context("127.0.0.1")
            start = time.perf_counter()
            client_module.AsyncronousWait().wait(
                "titanic", pretty_response=False
            )
            assert time.perf_counter() - start < 5.0
        finally:
            server.stop()

    def test_push_404_falls_back_to_metadata_poll(
        self, monkeypatch, fresh_probe_cache
    ):
        app, jobs = make_app()

        @app.route("/files/<filename>")
        def read_file(request, filename):
            return {"result": [{"filename": filename, "finished": True}]}, 200

        server = webloop.LoopServer(app, "127.0.0.1", 0).start()
        try:
            monkeypatch.setattr(
                client_module.DatabaseApi,
                "DATABASE_API_PORT",
                str(server.port),
            )
            monkeypatch.setattr(
                client_module.AsyncronousWait, "WAIT_TIME", 0.01
            )
            monkeypatch.setattr(
                client_module.AsyncronousWait, "MAX_WAIT_TIME", 0.02
            )
            client_module.Context("127.0.0.1")
            start = time.perf_counter()
            client_module.AsyncronousWait().wait(
                "untracked", pretty_response=False
            )
            assert time.perf_counter() - start < 5.0
        finally:
            server.stop()

    def test_retry_after_honored_and_clamped(self, monkeypatch):
        sleeps = []

        class FakeTime:
            @staticmethod
            def sleep(seconds):
                sleeps.append(seconds)

        # swap the module binding inside client.py only — patching the
        # real time.sleep would hijack background scheduler threads
        monkeypatch.setattr(client_module, "time", FakeTime())
        monkeypatch.setattr(client_module.AsyncronousWait, "WAIT_TIME", 3)

        class FakeResponse:
            def __init__(self, retry_after):
                self.headers = (
                    {"Retry-After": retry_after} if retry_after else {}
                )

        waiter = client_module.AsyncronousWait()
        waiter._sleep_retry_after(FakeResponse("7"))
        waiter._sleep_retry_after(FakeResponse("0.001"))  # clamped up
        waiter._sleep_retry_after(FakeResponse("9999"))  # clamped down
        waiter._sleep_retry_after(FakeResponse("soon"))  # malformed
        assert sleeps == [7.0, 0.1, 60.0, 3.0]

    def test_poll_backoff_jitter_deterministic_and_bounded(self):
        first = policy.backoff_delay("titanic", 1, base_s=3, cap_s=12)
        again = policy.backoff_delay("titanic", 1, base_s=3, cap_s=12)
        assert first == again  # seeded: restarts do not re-roll
        assert 0.75 * 3 <= first <= 1.25 * 3
        deep = policy.backoff_delay("titanic", 10, base_s=3, cap_s=12)
        assert deep <= 12 * 1.25  # capped at 4x the reference pace
        assert policy.backoff_delay(
            "other", 1, base_s=3, cap_s=12
        ) != pytest.approx(first)  # per-name de-synchronization
