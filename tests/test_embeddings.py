"""PCA and t-SNE embeddings: oracle comparisons and structure checks."""

import os

import numpy as np
import pytest
import sklearn.decomposition

from learningorchestra_tpu.core.table import ColumnTable, write_table
from learningorchestra_tpu.ops.images import create_embedding_image
from learningorchestra_tpu.ops.pca import pca_embedding
from learningorchestra_tpu.ops.tsne import tsne_embedding


@pytest.fixture()
def three_blobs(rng):
    centers = np.array([[10, 0, 0, 0], [0, 10, 0, 0], [0, 0, 10, 0]])
    labels = rng.integers(0, 3, size=240)
    X = centers[labels] + rng.normal(size=(240, 4))
    return X.astype(np.float64), labels


def _knn_label_agreement(embedded, labels):
    """Fraction of points whose nearest neighbour shares their label."""
    d = ((embedded[:, None, :] - embedded[None, :, :]) ** 2).sum(-1)
    np.fill_diagonal(d, np.inf)
    return (labels[d.argmin(axis=1)] == labels).mean()


class TestPca:
    def test_matches_sklearn_up_to_sign(self, three_blobs):
        X, _ = three_blobs
        ours = pca_embedding(X, n_components=2)
        theirs = sklearn.decomposition.PCA(n_components=2).fit_transform(X)
        for component in range(2):
            ratio = np.corrcoef(ours[:, component], theirs[:, component])[0, 1]
            assert abs(ratio) > 0.999

    def test_separates_blobs(self, three_blobs):
        X, labels = three_blobs
        embedded = pca_embedding(X)
        assert _knn_label_agreement(embedded, labels) > 0.95


class TestTsne:
    def test_separates_blobs(self, three_blobs):
        X, labels = three_blobs
        embedded = tsne_embedding(X, iterations=500, seed=0)
        assert embedded.shape == (len(X), 2)
        assert _knn_label_agreement(embedded, labels) > 0.9

    def test_small_n_perplexity_clamp(self, rng):
        X = rng.normal(size=(8, 3))
        embedded = tsne_embedding(X, iterations=50)
        assert embedded.shape == (8, 2)
        assert np.isfinite(embedded).all()


class TestImagePipeline:
    def test_creates_png_with_label_hue(self, store, three_blobs, tmp_path):
        X, labels = three_blobs
        table = ColumnTable.from_lists(
            {
                "a": X[:, 0].tolist(),
                "b": X[:, 1].tolist(),
                "c": X[:, 2].tolist(),
                "label": [("x", "y", "z")[l] for l in labels],
            }
        )
        write_table(store, "blobs", table, {"filename": "blobs", "finished": True})
        path = create_embedding_image(
            store, "blobs", "label", "blobs_pca", str(tmp_path), "pca"
        )
        assert os.path.exists(path)
        assert open(path, "rb").read(8).startswith(b"\x89PNG")


class TestReviewRegressions:
    def test_duplicate_rows_keep_max_affinity(self, rng):
        # label-encoded categorical tables routinely contain identical
        # rows; a duplicate must be its twin's highest-affinity
        # neighbour (self excluded by index, not by distance == 0).
        import jax.numpy as jnp

        from learningorchestra_tpu.ops.tsne import _affinities, _pad_for_mesh
        from learningorchestra_tpu.parallel.mesh import default_mesh

        mesh = default_mesh()
        base = rng.normal(size=(20, 3)).astype(np.float32)
        X = np.vstack([base, base[:1]])  # row 20 duplicates row 0
        X_pad, valid, chunk = _pad_for_mesh(X, mesh, 1024)
        P = np.asarray(
            _affinities(
                mesh, jnp.asarray(X_pad), jnp.asarray(valid),
                jnp.float32(5.0), chunk,
            )
        )
        assert P[0, :21].argmax() == 20 and P[20, :21].argmax() == 0
        assert P[0, 20] > 10 * np.median(P[0, :21])
        # padded rows/columns carry only the numerical floor, no mass
        assert (P[21:, :] <= 1e-12).all() and (P[:, 21:] <= 1e-12).all()

    def test_landmark_path_separates_blobs(self, rng):
        from learningorchestra_tpu.ops.tsne import tsne_embedding

        centers = np.array([[12, 0, 0], [0, 12, 0], [0, 0, 12]])
        labels = rng.integers(0, 3, size=900)
        X = centers[labels] + rng.normal(size=(900, 3))
        embedded = tsne_embedding(
            X, iterations=300, method="landmark", landmarks=200, seed=0
        )
        assert embedded.shape == (900, 2)
        assert np.isfinite(embedded).all()
        assert _knn_label_agreement(embedded, labels) > 0.85
